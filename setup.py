"""Build integration (reference ``setup.py:29-103`` built five CUDA
extension modules; here one C++ host-runtime library is compiled and the
device kernels are Pallas, needing no build step).

``pip install .`` / ``python setup.py build`` compiles
``csrc/apex_tpu_C.cpp`` into ``apex_tpu/_native/libapex_tpu_C.so``.  The
library also auto-builds on first import (``apex_tpu/_native/__init__.py``)
and has a pure-numpy fallback, so a "Python-only install" — the reference
build matrix's second axis — is simply an install without a toolchain.
"""

import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        try:
            subprocess.run(["make", "-C", "csrc"], check=True)
        except Exception as e:  # toolchain-less install: fallback path
            print(f"apex_tpu: native build skipped ({e}); "
                  "pure-numpy fallback will be used")
        super().run()


setup(cmdclass={"build_py": BuildWithNative},
      package_data={"apex_tpu._native": ["*.so"]})
