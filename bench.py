"""Headline benchmarks on one chip: ResNet-50 (amp O2 + FusedAdam, plus the
O3 "speed of light" config the reference documents in
``examples/imagenet/README.md``) and GPT-small causal-LM training.

Prints ONE JSON line.  Primary metric: best ResNet-50 img/s; ``mfu`` is
model-FLOPs utilisation for that config; the ``configs`` map carries every
measured config's throughput + MFU + HFU (incl. GPT tok/s) so
compute-efficiency regressions are visible, not just throughput ones.
``mfu`` counts MODEL FLOPs (6 attention passes, the PaLM convention);
``hfu`` counts EXECUTED FLOPs (7 passes where the fused one-pass
attention backward recomputes scores).

Regression gate: the output's ``regression_check`` compares every
config's throughput against the newest ``BENCH_r{N}.json`` next to this
script (or ``--compare PATH``); with ``--compare`` a >``--threshold``
(default 10%) per-config drop exits nonzero naming the configs.

Baseline derivation (BASELINE.json north star: "v5e-16 within 90% of
8xA100 images/sec"): 8xA100 ResNet-50 amp synthetic-data throughput
~2500 img/s/GPU => 20000 img/s; 90% over 16 v5e chips =>
1125 img/s/chip.  ``vs_baseline`` is measured img/s on this one chip
divided by that per-chip target (>1.0 beats the north star pro-rata).

MFU: FLOPs per step are taken from XLA's compiled cost analysis (the
compiler's own count for the whole train step: fwd + bwd + optimizer),
divided by wall time and chip peak.  Peak defaults to v5e bf16
(197 TFLOP/s); other TPU generations resolve via ``device_kind``.
"""

import argparse
import glob
import json
import os
import re
import sys
import time

import jax
import jax.numpy as jnp

BASELINE_IMG_PER_SEC_PER_CHIP = 1125.0

#: bf16 peak TFLOP/s by device kind substring (fallback: v5e).
PEAK_TFLOPS = {
    "v5litepod": 197.0, "v5e": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
    "v6e": 918.0, "trillium": 918.0,
}


def chip_peak_flops() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return 197.0e12


def step_flops(compiled, fallback: float) -> float:
    """XLA's own FLOP count for one compiled step; ``fallback`` (an
    analytic estimate) covers backends whose cost analysis is missing."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        f = float(cost.get("flops", 0.0))
        if f > 0:
            return f
    except Exception:
        pass
    return fallback


def _time_steps(step, state, args, warmup, iters, loss_key="loss"):
    # NB: a scalar fetch, not block_until_ready — the latter does not
    # drain the pipeline over tunneled device transports.
    for _ in range(warmup):
        state, metrics = step(state, *args)
    if warmup:
        float(metrics[loss_key])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, *args)
    float(metrics[loss_key])
    return time.perf_counter() - t0


def bench_resnet(opt_level: str, batch: int, size: int, warmup: int,
                 iters: int, peak: float, s2d: bool = False,
                 host_stream: bool = False):
    """``host_stream=True`` measures the overlapped input pipeline
    (apex_tpu.data.prefetch_to_device: uint8 numpy batches, H2D +
    on-device normalize in flight) against the device-resident number —
    the A/B the reference's data_prefetcher capability implies
    (VERDICT r3 #4: done = ≤3% loss at b256)."""
    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet50, ResNet50S2D
    from apex_tpu.optimizers import FusedAdam

    # s2d: the TPU-native space-to-depth stem (MXU-friendly C_in)
    model = ResNet50S2D() if s2d else ResNet50()
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, size, size, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
    variables = model.init(jax.random.PRNGKey(2), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # O3 speed-of-light per the reference README: pure half compute,
    # static scale, but --keep-batchnorm-fp32 True.
    kwargs = dict(keep_batchnorm_fp32=True, loss_scale=128.0) \
        if opt_level == "O3" else {}
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level=opt_level,
                       verbosity=0, **kwargs)
    state = a.init(params)

    def loss_fn(p, xb, yb):
        logits, _ = model.apply({"params": p, "batch_stats": batch_stats},
                                xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))
    compiled = step.lower(state, x, y).compile()
    if host_stream:
        dt = _time_host_stream(compiled, state, batch, size, warmup, iters)
    else:
        dt = _time_steps(compiled, state, (x, y), warmup, iters)

    img_per_sec = batch * iters / dt
    # analytic fallback: RN50 fwd ~4.09 GFLOP/img at 224px (scales with
    # spatial area), training ~3x fwd
    fwd = 4.09e9 * (size / 224.0) ** 2
    flops = step_flops(compiled, fallback=3.0 * fwd * batch)
    mfu = round(flops * iters / dt / peak, 4) if peak else None
    # no analytic-recompute correction on this path: XLA counts the
    # whole conv step itself, so model FLOPs == executed FLOPs
    return {"img_s": round(img_per_sec, 2), "mfu": mfu, "hfu": mfu,
            "batch": batch, "px": size}


def _time_host_stream(step, state, batch: int, size: int, warmup: int,
                      iters: int):
    """Training-loop wall time with batches streamed from HOST numpy
    through the overlapped prefetcher instead of device-resident.

    One generator spans warmup + timed iterations so the timing window
    measures the primed steady-state pipeline: the transform's jit
    trace/compile and the initial lookahead fill are warmup work, not
    pipeline cost."""
    import jax as _jax

    from apex_tpu.data import (host_synthetic_loader, normalize_uint8,
                               prefetch_to_device)

    normalize = _jax.jit(normalize_uint8)  # jitted ONCE for the run
    loader = host_synthetic_loader(warmup + iters, batch, size, seed=0)
    metrics = None
    t0 = None
    n = 0
    for xb, yb in prefetch_to_device(loader, lookahead=2,
                                     transform=normalize):
        if n == warmup:
            if metrics is not None:
                float(metrics["loss"])  # drain warmup before the clock
            t0 = time.perf_counter()
        state, metrics = step(state, xb, yb)
        n += 1
    float(metrics["loss"])
    return time.perf_counter() - (t0 if t0 is not None else 0.0)


def bench_gpt(batch: int, seq: int, warmup: int, iters: int, peak: float,
              tiny: bool, tpu_heads: "bool | str" = False,
              remat: bool = False, batch_fallbacks: tuple = ()):
    import dataclasses

    from apex_tpu import amp
    from apex_tpu.models.gpt import (
        GPTModel, gpt_medium_tpu, gpt_small, gpt_small_tpu, gpt_tiny,
        lm_loss)
    from apex_tpu.optimizers import FusedAdam

    # tpu_heads: same params/FLOPs with the TPU-native 6x128 head
    # geometry (full MXU lane width in the flash kernels); the string
    # "medium" selects gpt_medium_tpu (~368M, 8x128 heads) instead.
    if tiny:
        cfg = gpt_tiny()
    elif tpu_heads == "medium":
        cfg = gpt_medium_tpu()
    else:
        cfg = gpt_small_tpu() if tpu_heads else gpt_small()
    if remat:  # long-context configs recompute the layer body
        cfg = dataclasses.replace(cfg, remat=True)
    model = GPTModel(cfg)

    def run_at(b):
        ids = jax.random.randint(jax.random.PRNGKey(3), (b, seq), 0,
                                 cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(4), ids[:, :16])["params"]
        a = amp.initialize(optimizer=FusedAdam(lr=1e-4), opt_level="O2",
                           verbosity=0)
        state = a.init(params)

        def loss_fn(p, xb):
            logits = model.apply({"params": p}, xb)
            return lm_loss(logits[:, :-1], xb[:, 1:])

        step = jax.jit(amp.make_train_step(a, loss_fn),
                       donate_argnums=(0,))
        compiled = step.lower(state, ids).compile()
        dt = _time_steps(compiled, state, (ids,), warmup, iters)
        return _lm_result(compiled, cfg, params, b, seq, dt, iters, peak,
                          "tok_s", b * seq * iters / dt, causal=True,
                          remat=remat)

    # OOM batch ladder: the tunneled chip's usable HBM varies by day
    # (round 4: gpt-medium b8 — which fit in round 3 — OOM'd on OLD and
    # new code alike while a 14 GB probe buffer allocated fine).  An
    # OOM poisons the WHOLE process (server-side buffers from the
    # failed execution linger: a b4 run that succeeds from scratch
    # fails after a b8 OOM in-process, and round 4's pre-flight saw the
    # ladder's b8 attempt kill the L16384 config that ran later in the
    # same bench process), so when a ladder is configured EVERY attempt
    # — including the first — runs in a fresh subprocess; the main
    # bench process never executes the OOM-prone config at all.  A
    # degraded-batch record notes the fallback; the regression gate
    # skips batch-mismatched configs (tok/s is not comparable).
    if not batch_fallbacks:
        return run_at(batch)
    errs = []
    for i, b in enumerate((batch,) + tuple(batch_fallbacks)):
        res, err = _gpt_subprocess(batch=b, seq=seq, warmup=warmup,
                                   iters=iters, peak=peak, tiny=tiny,
                                   tpu_heads=tpu_heads, remat=remat)
        if res is not None:
            if i > 0:
                res["oom_fallback_from_batch"] = batch
            return res
        errs.append(f"b{b}: {err}")
        if err and "RESOURCE_EXHAUSTED" not in err \
                and "Out of memory" not in err \
                and "timeout" not in err:
            break   # non-OOM failure: laddering down won't help
    raise RuntimeError(
        f"gpt OOM ladder exhausted "
        f"(batches {(batch,) + tuple(batch_fallbacks)}): "
        + " | ".join(errs))


def _gpt_subprocess(**kw):
    """One bench_gpt run in a FRESH python process (post-OOM processes
    are poisoned — see the ladder note) -> (result dict | None, error
    string | None).  The parent keeps its device client open; the axon
    relay multiplexes clients, and a hung grant is bounded by the
    timeout."""
    import os
    import subprocess
    import sys as _sys

    code = ("import json,sys\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "import bench\n"
            "r = bench.bench_gpt(**json.loads(sys.argv[1]))\n"
            "print('BENCH_SUBPROC_JSON ' + json.dumps(r))\n")
    try:
        p = subprocess.run(
            [_sys.executable, "-c", code, json.dumps(kw),
             os.path.dirname(os.path.abspath(__file__))],
            capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        return None, "subprocess timeout (900s)"
    for line in p.stdout.splitlines():
        if line.startswith("BENCH_SUBPROC_JSON "):
            return json.loads(line[len("BENCH_SUBPROC_JSON "):]), None
    blob = (p.stderr or "") + "\n" + (p.stdout or "")
    tail = (p.stderr or p.stdout or "").strip().splitlines()
    msg = tail[-1][:200] if tail else f"rc={p.returncode}"
    # An OOM's final traceback line often lacks the literal marker
    # (wrapped XlaRuntimeError tails); surface it from ANYWHERE in the
    # captured output so the ladder keeps stepping down instead of
    # misreading the failure as non-OOM.
    for marker in ("RESOURCE_EXHAUSTED", "Out of memory"):
        if marker in blob and marker not in msg:
            msg = f"{marker}: {msg}"
            break
    return None, msg


#: analytic attention matmul passes per layer.  MODEL passes (the PaLM
#: MFU convention): forward 2 (QK^T, PV) + backward 4 (dq, dk, dv, dp)
#: = 6.  EXECUTED passes on the fused one-pass Pallas backward: the bwd
#: additionally recomputes the score matrix = 7 total; that extra pass
#: is hardware work, not model work, so it books under HFU only.
ATTN_MODEL_PASSES = 6
ATTN_FUSED_EXEC_PASSES = 7


def attention_pass_flops(cfg, batch: int, seq: int, causal: bool) -> float:
    """Analytic FLOPs of ONE attention matmul pass (``2*B*H*L^2*D``),
    summed over layers.  Callers scale by ``ATTN_MODEL_PASSES`` (MFU) or
    ``ATTN_FUSED_EXEC_PASSES`` (HFU on the fused-backward kernel path).

    XLA's cost analysis reports (near-)ZERO flops for custom calls
    (measured: 0.003 GF vs 12.9 GF analytic for one L2048 forward), so
    without this term every transformer MFU undercounts by the
    attention fraction — ~1% at L2048 but ~40% at L8192.

    A remat'd layer body would re-run the forward's 2 passes, but
    remat=True measures identical step time to remat=False here (XLA
    CSEs the recompute), so no remat term is counted — conservative if
    a future config genuinely recomputes.  Causal halves every pass
    (the kernels skip dead blocks)."""
    head_dim = cfg.hidden_size // cfg.num_heads
    one_pass = 2.0 * batch * cfg.num_heads * float(seq) ** 2 * head_dim
    return cfg.num_layers * one_pass * (0.5 if causal else 1.0)


#: substrings identifying the flash-attention pallas calls in compiled
#: HLO — the kernel wrappers' function names, which XLA records in the
#: custom-call's op_name metadata (e.g. ``jvp(jit(_flash_fwd))/
#: pallas_call``) and derives instruction names from.
_FLASH_KERNEL_MARKS = ("_flash_fwd", "_flash_bwd")


def _pallas_attn_compiled(compiled) -> "bool | None":
    """Whether the compiled step actually contains the flash-attention
    Pallas custom call — the analytic attention term must be gated on
    the path the executable TOOK, not on ``use_pallas()`` alone:
    flash_attention can still route to the jnp math under use_pallas
    (cross-attention shapes, interpret-mode under shard_map), where
    XLA's cost analysis already counts the einsums and adding the term
    would double count.  Matching the *attention* kernel names (not any
    ``tpu_custom_call``) matters for the same reason: other Pallas
    kernels (fused optimizers, layer norm) are in the step too and
    their custom calls must not vouch for the attention path.  Returns
    None when the HLO text is unavailable."""
    try:
        txt = compiled.as_text()
    except Exception:
        return None
    return any(mark in txt for mark in _FLASH_KERNEL_MARKS)


def _lm_result(compiled, cfg, params, batch, seq, dt, iters, peak,
               rate_key, rate, causal=True, remat=False):
    """Shared tail for the transformer benches: params count, FLOPs with
    the 6ND + attention analytic fallback, MFU + HFU.

    ``mfu`` counts model FLOPs (6 attention passes — the PaLM
    convention); ``hfu`` counts executed FLOPs (7 passes on the fused
    one-pass backward, which recomputes scores).  MFU is the headline
    number; HFU shows what the hardware actually ran."""
    del remat
    n_params = sum(int(p.size) for p in jax.tree.leaves(params))
    from apex_tpu.ops import use_pallas
    one_pass = attention_pass_flops(cfg, batch, seq, causal)
    dense_fb = 6.0 * n_params * batch * seq
    kernel_path = _pallas_attn_compiled(compiled)
    if kernel_path is None:
        kernel_path = use_pallas()
    if kernel_path:
        # step_flops covers everything XLA sees; the pallas attention
        # calls report ~0 there and are added analytically.
        base = step_flops(compiled, fallback=dense_fb)
        model_flops = base + ATTN_MODEL_PASSES * one_pass
        exec_flops = base + ATTN_FUSED_EXEC_PASSES * one_pass
    else:
        # jnp attention path: cost analysis counts the einsums itself
        # (and XLA's AD backward materializes rather than recomputes,
        # so model == executed); only the FALLBACK needs the term.
        model_flops = exec_flops = step_flops(
            compiled, fallback=dense_fb + ATTN_MODEL_PASSES * one_pass)
    mfu = round(model_flops * iters / dt / peak, 4) if peak else None
    hfu = round(exec_flops * iters / dt / peak, 4) if peak else None
    return {rate_key: round(rate, 2), "mfu": mfu, "hfu": hfu,
            "batch": batch, "seq": seq, "params": n_params}


def probe_devices(timeout_s: float = 240.0):
    """``jax.devices()`` under a watchdog: a wedged tunnel lease blocks
    PJRT client init forever (make_c_api_client) with no error.  Returns
    the device list, ``None`` on timeout; init *errors* re-raise
    immediately with their real traceback."""
    import threading
    done = threading.Event()
    out = {}

    def probe():
        try:
            out["devices"] = jax.devices()
        except BaseException as e:  # re-raised in the caller
            out["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        return None
    if "error" in out:
        raise out["error"]
    return out["devices"]


def _backend_or_die(timeout_s: float = 240.0):
    """Fail fast with a diagnosis rather than hang past the driver's
    timeout when the backend is wedged."""
    devices = probe_devices(timeout_s)
    if devices is None:
        import os
        import sys
        print(f"bench: TPU backend init blocked >{timeout_s:.0f}s "
              "(stale pool lease / dead relay — see "
              "make_c_api_client); no metrics can be measured",
              file=sys.stderr)
        os._exit(3)
    return devices


def bench_bert(batch: int, seq: int, warmup: int, iters: int, peak: float,
               tiny: bool, tpu_heads: bool = False):
    """BASELINE config 4: BERT-large MLM+NSP pretraining step with
    FusedLAMB + FusedLayerNorm + flash attention (amp O2)."""
    import dataclasses

    from apex_tpu import amp
    from apex_tpu.models.bert import (
        BertForPreTraining, bert_large, bert_large_tpu, bert_tiny,
        pretraining_loss)
    from apex_tpu.optimizers import FusedLAMB

    base = bert_large_tpu() if tpu_heads else bert_large()
    cfg = bert_tiny() if tiny else dataclasses.replace(base, remat=True)
    model = BertForPreTraining(cfg)
    k = jax.random.split(jax.random.PRNGKey(5), 4)
    ids = jax.random.randint(k[0], (batch, seq), 0, cfg.vocab_size)
    mlm_labels = jax.random.randint(k[1], (batch, seq), 0, cfg.vocab_size)
    mlm_mask = (jax.random.uniform(k[2], (batch, seq)) < 0.15)\
        .astype(jnp.float32)
    nsp_labels = jax.random.randint(k[3], (batch,), 0, 2)
    params = model.init(jax.random.PRNGKey(6), ids[:1, :8])["params"]

    a = amp.initialize(optimizer=FusedLAMB(lr=1e-4), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, ids, mlm_labels, nsp_labels, mlm_mask):
        mlm_logits, nsp_logits = model.apply({"params": p}, ids)
        return pretraining_loss(mlm_logits, nsp_logits, mlm_labels,
                                nsp_labels, mlm_mask)

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))
    args = (ids, mlm_labels, nsp_labels, mlm_mask)
    compiled = step.lower(state, *args).compile()
    dt = _time_steps(compiled, state, args, warmup, iters)

    return _lm_result(compiled, cfg, params, batch, seq, dt, iters, peak,
                      "seq_s", batch * iters / dt, causal=False)


#: v5e HBM peak (bytes/s) by device-kind substring — the decode bench's
#: roofline denominator (decode is bandwidth-bound, not FLOPs-bound).
HBM_BYTES_PER_S = {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9,
                   "v5p": 2765e9, "v6": 1640e9}


def chip_hbm_bytes_per_s() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, bw in HBM_BYTES_PER_S.items():
        if key in kind:
            return bw
    return 819e9


def bench_generate(batch: int, prefill: int, new_tokens: int, warmup: int,
                   iters: int, peak: float, tiny: bool = False,
                   kv_dtype=None):
    """KV-cached decode throughput (``apex_tpu.models.generate``):
    greedy generation of ``new_tokens`` after a ``prefill``-token prompt
    on gpt-small (TPU head geometry), bf16 params.

    Decode is HBM-bandwidth-bound, not MXU-bound: every generated token
    re-reads the full parameter set plus both KV caches.  The ceiling
    is derived through the shared roofline machinery
    (:func:`apex_tpu.analysis.cost.roofline_expectation` — the same
    physics the lint calibration audit holds floors to): static
    flops/bytes per step in, binding resource and ceiling rate out,
    recorded as ``hbm_tok_s_ceiling`` + ``bound`` alongside the
    measured rate and its ``hbm_frac`` fraction-of-ceiling (gated by
    ``DECODE_FLOORS`` the way MFU floors gate the train configs; the
    MFU of a well-formed decode is intrinsically ~1-2% —
    ``docs/source/models.rst`` carries the framing).  CAVEAT the byte
    model is the roofline FLOOR (params + cache, ideal fusion):
    ``DECODE_DECOMPOSE_r01.json`` decomposes where the b8 step's real
    traffic goes and attributes the measured 0.43 — the fraction is a
    tracked efficiency metric against a fixed bar, not a claim that
    0.57 of the bandwidth is idle.  ``tok_s`` counts NEW tokens only;
    the one prefill forward per call is amortized into the measured
    window exactly as a serving loop would pay it.

    ``kv_dtype="int8"`` selects the int8 KV cache
    (:mod:`apex_tpu.quant.int8`: per-position absmax scales, dequant
    fused into the attention read) and the byte model follows — 1
    byte/element for both caches plus 4 bytes/position/layer for each
    scale array instead of 2 bytes/element, so the ceiling this config
    is gated against (``gpt_small_tpu_decode_kv8``) is DERIVED from
    the int8 byte model through the same
    :func:`~apex_tpu.analysis.cost.roofline_expectation` call, never
    hand-written: decode is HBM-bound with kv_read the dominant term
    (DECODE_DECOMPOSE_r01), so halving cache bytes is a ~2x ceiling
    lift at long context."""
    from apex_tpu import amp
    from apex_tpu.models.generate import generate
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(7), (batch, prefill),
                                0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(8), prompt[:1, :16])["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)  # bf16, the serving layout

    import numpy as np
    out = generate(params, cfg, prompt, new_tokens, kv_dtype=kv_dtype)
    np.asarray(out[:, -1])  # compile + drain (scalar fetch, not BUR)
    for _ in range(warmup):
        out = generate(params, cfg, prompt, new_tokens, kv_dtype=kv_dtype)
    np.asarray(out[:, -1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = generate(params, cfg, prompt, new_tokens, kv_dtype=kv_dtype)
    np.asarray(out[:, -1])
    dt = time.perf_counter() - t0

    from apex_tpu.analysis import cost as cost_mod

    n_params = sum(int(p.size) for p in jax.tree.leaves(params))
    head_dim = cfg.hidden_size // cfg.num_heads
    m = prefill + new_tokens
    if kv_dtype == "int8":
        # int8 KV byte model: 1 byte/element per cache + one f32 scale
        # per cached position per layer for each of K and V
        cache_b = (2 * cfg.num_layers * batch * m * cfg.num_heads
                   * head_dim * 1
                   + 2 * cfg.num_layers * batch * m * 4)
    else:
        cache_b = (2 * cfg.num_layers * batch * m * cfg.num_heads
                   * head_dim * 2)
    bytes_per_step = 2 * n_params + cache_b   # bf16 params + k&v caches
    # dense-matmul flops of one step (2 flops/param/token x batch):
    # the numerator of the shared roofline — decode intensity is ~0.01
    # flop/byte, so the expectation resolves bandwidth-bound and the
    # ceiling rate reduces to batch x bw / bytes; a future config that
    # tips compute-bound (huge batch, int8 KV) is handled by the same
    # formula instead of silently overstating the bar
    flops_per_step = 2.0 * n_params * batch
    bw = chip_hbm_bytes_per_s()
    exp = cost_mod.roofline_expectation(
        flops_per_step, bytes_per_step,
        peak_flops=peak or float("inf"), peak_hbm_bytes_per_s=bw)
    ceiling = batch * exp["ceiling_flops_per_s"] / flops_per_step
    rec = {"tok_s": round(batch * new_tokens * iters / dt, 2),
           "batch": batch, "prefill": prefill, "new_tokens": new_tokens,
           "params": n_params, "bound": exp["bound"],
           "hbm_tok_s_ceiling": round(ceiling, 2),
           "hbm_frac": round(batch * new_tokens * iters / dt / ceiling,
                             4)}
    if kv_dtype is not None:
        rec["kv_dtype"] = kv_dtype
        rec["cache_bytes_per_step"] = int(cache_b)
    return rec


def bench_serve(warmup: int, iters: int, peak: float,
                num_slots: int = 8, prefill: int = 512,
                new_tokens: int = 128, tiny: bool = False):
    """Continuous-batching serve throughput+latency
    (:class:`apex_tpu.serve.ServeEngine`): an offered-load sweep over
    concurrency levels — 1 in-flight request (pure latency), then
    ``num_slots`` mixed-length requests streaming through the fixed
    slots (continuous batching over the paged KV cache, fused sampling
    epilogue).

    Per level: ``tok_s`` (generated tokens / wall), per-DECODE-STEP
    wall latency ``p50_ms``/``p99_ms`` — read from the engine's own
    ``serve_decode_step_seconds`` histogram
    (:mod:`apex_tpu.obs.metrics`), NOT a private list sort, so bench
    and a production scrape can never disagree on percentile math (the
    quantiles are bucket-interpolated the Prometheus way).  The
    headline record carries the full-load numbers (``tok_s`` rides the
    existing delta/ladder gates).  ``ab_ok`` is the latency-tail gate:
    p99 under ``20 x p50`` — the tail a mid-serve retrace or host sync
    produces is 100-1000x (far beyond bucket-interpolation error), so
    this catches the static-shape contract breaking at runtime without
    guessing an absolute latency bar before a chip round records
    one."""
    del peak, warmup
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    if tiny:
        num_slots, prefill, new_tokens = 2, 16, 8
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)

    block = 16 if not tiny else 4
    mb = -(-(prefill + new_tokens) // block)
    scfg = ServeConfig(
        num_slots=num_slots, block_size=block,
        num_blocks=num_slots * mb + 1, max_blocks_per_slot=mb,
        prefill_chunk=min(prefill, 128 if not tiny else 8))
    rng = np.random.RandomState(11)

    def make_reqs(n, tag):
        reqs = []
        for i in range(n):
            plen = int(prefill * (0.5 + 0.5 * (i % 2)))  # mixed lengths
            reqs.append(Request(
                uid=f"{tag}{i}",
                prompt=rng.randint(0, cfg.vocab_size, (plen,)),
                max_new_tokens=new_tokens))
        return reqs

    # ONE engine serves every load level: the decode/prefill programs
    # compile once (each ServeEngine re-jits, and the compile dominates
    # setup on chip), and the retraces==1 gate then spans the sweep.
    # A PRIVATE registry isolates the histogram from any other serving
    # in this process; per-level windows come from histogram snapshots.
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    step_hist = eng.metrics.histogram("serve_decode_step_seconds")
    tok_counter = eng.metrics.counter("serve_tokens_total")

    def drive(n, tag):
        for r in make_reqs(n, tag):
            eng.submit(r)
        eng.step()                       # admission + compile + 1 step
        mark = step_hist.state()         # window: steady-state steps
        tok0 = tok_counter.value
        t0 = time.perf_counter()
        while not eng.sched.idle():
            # admission/prefill is driven OUTSIDE the decode-step
            # sample the engine histogram records: p50/p99 are
            # DECODE-step latency (the retrace/host-sync tail this
            # gate watches), while admission cost still lands in the
            # wall-clock tok_s
            eng._admit_and_evict()
            if not eng.sched.active.any():
                raise RuntimeError("serve bench admission stall: "
                                   "queued requests but no active slot")
            eng.step()
        wall = time.perf_counter() - t0
        produced = tok_counter.value - tok0
        steps = step_hist.count - mark[2]
        p50 = step_hist.quantile(0.5, since=mark) * 1e3 if steps else 0.0
        p99 = step_hist.quantile(0.99, since=mark) * 1e3 if steps else 0.0
        return {"tok_s": round(produced / wall, 2) if wall else 0.0,
                "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
                "steps": int(steps), "retraces":
                    eng.trace_counts["decode"]}

    del iters  # the request stream sets the sample count
    solo = drive(1, "s")
    full = drive(num_slots, "f")
    tail_ok = full["p99_ms"] <= 20 * max(full["p50_ms"], 1e-6) \
        and full["retraces"] == 1
    return {"tok_s": full["tok_s"], "batch": num_slots,
            "prefill": prefill, "new_tokens": new_tokens,
            "p50_ms": full["p50_ms"], "p99_ms": full["p99_ms"],
            "offered_load": {"c1": solo, f"c{num_slots}": full},
            "ab_ok": bool(tail_ok)}


def bench_serve_spec(warmup: int, iters: int, peak: float,
                     num_slots: int = 8, prefill: int = 512,
                     new_tokens: int = 128, spec_k: int = 4,
                     draft_layers: int = 3, tiny: bool = False):
    """Speculative-vs-baseline serve A/B at EQUAL work
    (:class:`apex_tpu.serve.SpecEngine` vs
    :class:`~apex_tpu.serve.ServeEngine`): the SAME mixed-length
    greedy request stream served by the plain one-token-per-step
    engine and by the speculative engine (truncated layer-skip draft
    proposing ``spec_k`` tokens per round, the target verifying the
    whole block in one b×(k+1) step).

    The headline number is the speculative arm's ``tok_s``; the gate
    (``ab_ok``) is the latency win in machine-checked form —
    **tokens per decode dispatch strictly greater with speculation
    on** (every accepted token saves a full HBM sweep of params +
    KV, which is what converts the int8-KV bandwidth headroom into
    latency) — plus ``retraces == 1`` on BOTH arms (the speculation
    loop must not have broken the static-shape contract).  Latency
    percentiles come from each engine's own
    ``serve_decode_step_seconds`` histogram, like every serve
    config.

    Unlike the other serve configs, the model is BRIEFLY TRAINED
    (:func:`apex_tpu.models.gpt.train_toy_lm` — the ONE recipe the
    scenario tool and the spec tests share) and the prompts come
    from its training stream: acceptance rate — the entire
    speculative win — is a statement about how well the draft
    predicts the target, and a random-init model's near-uniform
    logits make it structurally ~1/vocab (the gate would fail by
    construction, measuring nothing).  The scenario-matrix artifact
    (``SCENARIO_r*.json``) carries the full per-scenario grid; this
    config is the chip-round headline cell."""
    del peak, warmup, iters
    import numpy as np

    from apex_tpu.models.gpt import gpt_small_tpu, gpt_tiny, \
        train_toy_lm
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import (Request, ServeConfig, ServeEngine,
                                SpecConfig, SpecEngine, truncated_draft)

    if tiny:
        num_slots, prefill, new_tokens, spec_k, draft_layers = \
            2, 16, 8, 2, 1
    cfg, params, ids = train_toy_lm(
        gpt_tiny() if tiny else gpt_small_tpu())
    draft_layers = min(draft_layers, cfg.num_layers - 1)
    dp, dcfg = truncated_draft(params, cfg, draft_layers)

    block = 16 if not tiny else 4
    mb = -(-(prefill + new_tokens) // block)
    scfg = ServeConfig(
        num_slots=num_slots, block_size=block,
        num_blocks=num_slots * mb + 1, max_blocks_per_slot=mb,
        prefill_chunk=min(prefill, 128 if not tiny else 8))
    ids_np = np.asarray(ids, np.int32)

    def make_reqs(tag):
        reqs = []
        for i in range(num_slots * 2):
            plen = max(2, int(prefill * (0.5 + 0.5 * (i % 2))))
            row = ids_np[i % ids_np.shape[0]]
            prompt = np.asarray(
                [row[j % row.shape[0]] for j in range(plen)], np.int32)
            reqs.append(Request(uid=f"{tag}{i}", prompt=prompt,
                                max_new_tokens=new_tokens))
        return reqs

    def drive(eng, tag):
        hist = eng.metrics.histogram("serve_decode_step_seconds")
        toks = eng.metrics.counter("serve_tokens_total")
        for r in make_reqs(tag):
            eng.submit(r)
        eng.step()                   # admission + compile + 1st step
        mark = hist.state()
        tok0 = toks.value
        t0 = time.perf_counter()
        while not eng.sched.idle():
            eng.step()
        wall = time.perf_counter() - t0
        steps = hist.count - mark[2]
        produced = toks.value - tok0
        p50 = hist.quantile(0.5, since=mark) * 1e3 if steps else 0.0
        p99 = hist.quantile(0.99, since=mark) * 1e3 if steps else 0.0
        return {"tok_s": round(produced / wall, 2) if wall else 0.0,
                "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
                "steps": int(steps),
                "tokens_per_step": round(produced / max(steps, 1), 4),
                "retraces": max(eng.trace_counts.values())}

    base = drive(ServeEngine(params, cfg, scfg, registry=Registry()),
                 "b")
    eng = SpecEngine(params, cfg, scfg, dp, dcfg,
                     SpecConfig(k=spec_k), registry=Registry())
    spec = drive(eng, "s")
    spec["acceptance_rate"] = round(float(
        eng.metrics.gauge("serve_spec_acceptance_rate").value), 4)
    ab_ok = spec["tokens_per_step"] > base["tokens_per_step"] \
        and base["retraces"] == 1 and spec["retraces"] == 1
    return {"tok_s": spec["tok_s"], "batch": num_slots,
            "prefill": prefill, "new_tokens": new_tokens,
            "spec_k": spec_k, "draft_layers": draft_layers,
            "p50_ms": spec["p50_ms"], "p99_ms": spec["p99_ms"],
            "baseline": base, "spec": spec,
            "ab_ok": bool(ab_ok)}


def bench_serve_disagg(warmup: int, iters: int, peak: float,
                       n_replicas: int = 2, slots_per_replica: int = 8,
                       prefill: int = 512, new_tokens: int = 128,
                       tiny: bool = False):
    """Disaggregated-vs-monolithic serve A/B at EQUAL resources
    (:class:`apex_tpu.serve.DisaggRouter` vs one
    :class:`~apex_tpu.serve.ServeEngine`): the same offered load —
    ``c = n_replicas x slots_per_replica`` mixed-length requests, the
    same request stream, the same platform — served (a) by one
    monolithic engine with ``c`` slots interleaving prefill chunks and
    decode steps on one set of devices, and (b) by the disaggregated
    fleet: prefill on its own mesh slice, ``n_replicas`` decode
    replicas of ``slots_per_replica`` slots each on disjoint slices,
    KV shipped between them.

    Per arm: ``tok_s`` and decode-step ``p50_ms``/``p99_ms`` read from
    the engines' OWN ``serve_decode_step_seconds`` histograms (the
    disagg fleet's percentiles union the replicas' windows through the
    same Histogram math).  ``ab_ok`` is the DistServe/Splitwise claim
    as a gate: ``disagg p99 <= mono p99`` at equal device count —
    splitting bursty compute-bound prefill from steady HBM-bound
    decode must shorten the decode tail, not just move work around.
    The committed ``SERVE_DISAGG_r*.json`` artifact
    (``tools/serve_disagg.py``, schema
    ``apex_tpu/analysis/serve_disagg.py``) records the same sweep +
    the replica-kill chaos drill as gate memory."""
    del peak, warmup, iters
    import dataclasses

    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny
    from apex_tpu.obs import fleet as fleet_obs
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import (DisaggRouter, Request, RouterConfig,
                                ServeConfig, ServeEngine)

    need_devices = 1 + n_replicas
    if len(jax.devices()) < need_devices:
        return {"skipped": f"needs >= {need_devices} devices "
                           f"(1 prefill + {n_replicas} decode), have "
                           f"{len(jax.devices())}"}
    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)

    concurrency = n_replicas * slots_per_replica
    block = 16 if not tiny else 4
    mb = -(-(prefill + new_tokens) // block)
    scfg_rep = ServeConfig(
        num_slots=slots_per_replica, block_size=block,
        num_blocks=slots_per_replica * mb + 1, max_blocks_per_slot=mb,
        prefill_chunk=min(prefill, 128 if not tiny else 8))
    scfg_mono = dataclasses.replace(
        scfg_rep, num_slots=concurrency,
        num_blocks=concurrency * mb + 1)
    rng = np.random.RandomState(11)

    def make_reqs(tag):
        # the SAME mixed-length stream hits both arms (same seed, same
        # budgets) — the A/B isolates the topology, nothing else
        reqs = []
        for i in range(concurrency):
            plen = int(prefill * (0.5 + 0.5 * (i % 2)))
            reqs.append(Request(
                uid=f"{tag}{i}",
                prompt=rng.randint(0, cfg.vocab_size, (plen,)),
                max_new_tokens=new_tokens))
        return reqs

    rng_state = rng.get_state()

    # -- monolithic arm: c slots, one engine, one device set ----------
    eng = ServeEngine(params, cfg, scfg_mono, registry=Registry())
    hist = eng.metrics.histogram("serve_decode_step_seconds")
    toks = eng.metrics.counter("serve_tokens_total")
    for r in make_reqs("m"):
        eng.submit(r)
    eng.step()                        # admission + compile + 1 step
    mark = hist.state()
    tok0 = toks.value
    t0 = time.perf_counter()
    while not eng.sched.idle():
        eng._admit_and_evict()
        eng.step()
    wall = time.perf_counter() - t0
    mono = {
        "num_slots": concurrency,
        "tok_s": round((toks.value - tok0) / wall, 2) if wall else 0.0,
        "p50_ms": round(hist.quantile(0.5, since=mark) * 1e3, 3),
        "p99_ms": round(hist.quantile(0.99, since=mark) * 1e3, 3),
        "steps": int(hist.count - mark[2]),
        "retraces": eng.trace_counts["decode"],
    }

    # -- disaggregated arm: same stream, same concurrency, the fleet --
    rng.set_state(rng_state)
    reg = Registry()
    router = DisaggRouter(
        params, cfg, scfg_rep,
        RouterConfig(n_decode_replicas=n_replicas, transfer="ship"),
        registry=reg)
    hists = [r.eng.metrics.histogram("serve_decode_step_seconds")
             for r in router.replicas]
    for r in make_reqs("d"):
        router.submit(r)
    router.step()                     # route + compile + 1 step each
    marks = [h.state() for h in hists]
    tok0 = [r.eng.metrics.counter("serve_tokens_total").value
            for r in router.replicas]
    t0 = time.perf_counter()
    router.run()
    wall = time.perf_counter() - t0
    produced = sum(
        r.eng.metrics.counter("serve_tokens_total").value - t
        for r, t in zip(router.replicas, tok0))
    per_replica = []
    for h, mark in zip(hists, marks):
        steps = int(h.count - mark[2])
        per_replica.append({
            "steps": steps,
            "p50_ms": round(h.quantile(0.5, since=mark) * 1e3, 3)
            if steps else 0.0,
            "p99_ms": round(h.quantile(0.99, since=mark) * 1e3, 3)
            if steps else 0.0,
        })
    disagg = {
        "slots_per_replica": slots_per_replica,
        "n_replicas": n_replicas,
        "tok_s": round(produced / wall, 2) if wall else 0.0,
        "p50_ms": round(fleet_obs.merged_quantile(
            list(zip(hists, marks)), 0.5) * 1e3, 3),
        "p99_ms": round(fleet_obs.merged_quantile(
            list(zip(hists, marks)), 0.99) * 1e3, 3),
        "per_replica": per_replica,
        "retraces": [r.eng.trace_counts["decode"]
                     for r in router.replicas],
        "kv_transfer_bytes": int(
            reg.counter("serve_kv_transfer_bytes").value),
        "shipments": int(reg.counter("serve_kv_shipments_total").value),
        "reroutes": int(reg.counter("serve_reroute_total").value),
    }

    ab_ok = disagg["p99_ms"] <= mono["p99_ms"] \
        and mono["retraces"] == 1 \
        and all(r == 1 for r in disagg["retraces"])
    return {"tok_s": disagg["tok_s"], "batch": concurrency,
            "prefill": prefill, "new_tokens": new_tokens,
            "p50_ms": disagg["p50_ms"], "p99_ms": disagg["p99_ms"],
            "mono": mono, "disagg": disagg,
            "topology": {"n_devices": len(jax.devices()),
                         **router.slices.describe()},
            "ab_ok": bool(ab_ok)}


def bench_serve_prefix(warmup: int, iters: int, peak: float,
                       num_slots: int = 16, prefill: int = 512,
                       new_tokens: int = 128, tiny: bool = False):
    """Cross-request prefix-sharing A/B at EQUAL work: the SAME
    shared-system-prompt c``num_slots`` mixed-length stream served
    with the prefix cache ON (``ServeConfig.prefix_cache=True``,
    content-addressed block sharing + CoW + prefill skip on hit) and
    OFF (every request prefills its full prompt).

    The gated numbers are DETERMINISTIC token/block counts, not wall
    time — CPU smoke and a chip round agree on them exactly:

    - ``prefill_tokens_dispatched`` — tokens-to-first-token in work
      terms: how many prompt tokens each arm actually pushed through
      the prefill program (the sharing arm skips the matched span);
    - ``admitted_requests_per_block`` — admitted requests / peak live
      blocks: the pool deduplication (same stream, same devices,
      smaller resident footprint with sharing on).

    ``ab_ok`` = sharing dispatched FEWER prefill tokens AND admitted
    MORE requests per resident block AND both arms stayed at ONE
    decode trace (sharing must not mint executables).  Wall-clock
    ``tok_s``/``p50_ms``/``p99_ms`` ride along per arm, read from each
    engine's own ``serve_decode_step_seconds`` histogram.  The
    committed ``PREFIXCACHE_r*.json`` artifact (``tools/
    serve_prefix.py``, schema ``apex_tpu/analysis/prefixcache.py``)
    records the same sweep plus the per-request spans and the bitwise
    drill as gate memory."""
    del peak, warmup, iters
    import dataclasses

    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)

    block = 16 if not tiny else 4
    mb = -(-(prefill + new_tokens) // block)
    scfg_on = ServeConfig(
        num_slots=num_slots, block_size=block,
        num_blocks=num_slots * mb + 1, max_blocks_per_slot=mb,
        prefill_chunk=min(prefill, 128 if not tiny else 8),
        prefix_cache=True)
    scfg_off = dataclasses.replace(scfg_on, prefix_cache=False)
    rng = np.random.RandomState(11)

    # block-aligned shared system prompt (half the prefill budget) +
    # mixed-length per-request tails: the chat-service shape the
    # sharing claim is about
    sys_len = max((prefill // 2) // block * block, block)
    system = rng.randint(0, cfg.vocab_size, (sys_len,))
    tail_budget = max(prefill - sys_len, 1)
    prompts = []
    for i in range(num_slots):
        tlen = max(int(tail_budget * (0.5 + 0.5 * (i % 2))), 1)
        prompts.append(np.concatenate(
            [system, rng.randint(0, cfg.vocab_size, (tlen,))]))

    def drive(scfg, tag):
        eng = ServeEngine(params, cfg, scfg, registry=Registry())
        hist = eng.metrics.histogram("serve_decode_step_seconds")
        toks = eng.metrics.counter("serve_tokens_total")
        chunks = eng.metrics.counter("serve_prefill_chunks_total")
        for i, p in enumerate(prompts):
            eng.submit(Request(uid=f"{tag}{i}", prompt=p,
                               max_new_tokens=new_tokens))
        eng.step()                    # admission + compile + 1 step
        mark = hist.state()
        tok0 = toks.value
        peak_live = peak_shared = 0
        t0 = time.perf_counter()
        while not eng.sched.idle():
            eng._admit_and_evict()
            eng.step()
            peak_live = max(peak_live, eng.sched.allocator.live_count)
            peak_shared = max(peak_shared,
                              eng.sched.allocator.shared_count)
        wall = time.perf_counter() - t0
        sched = eng.sched
        if scfg.prefix_cache:
            # the scheduler's own spans are the ground truth the
            # artifact re-derives everything from
            dispatched = sum(e["dispatched"]
                             for e in sched.prefix_events)
        else:
            dispatched = sum(len(p) for p in prompts)
        arm = {
            "tok_s": round((toks.value - tok0) / wall, 2)
            if wall else 0.0,
            "p50_ms": round(hist.quantile(0.5, since=mark) * 1e3, 3),
            "p99_ms": round(hist.quantile(0.99, since=mark) * 1e3, 3),
            "prefill_chunks": int(chunks.value),
            "prefill_tokens_dispatched": int(dispatched),
            "admitted_requests": len(prompts),
            "peak_live_blocks": int(peak_live),
            "admitted_requests_per_block":
                round(len(prompts) / max(peak_live, 1), 6),
            "retraces": eng.trace_counts["decode"],
        }
        if scfg.prefix_cache:
            arm["prefix"] = {
                "probes": int(sched.prefix_probes),
                "hits": int(sched.prefix_hits),
                "hit_rate": round(
                    sched.prefix_hits / max(sched.prefix_probes, 1), 6),
                "hit_tokens": int(sched.prefix_hit_tokens),
                "cow_copies": int(eng.metrics.counter(
                    "serve_prefix_cow_copies_total").value),
                "shared_blocks_peak": int(peak_shared),
                "cached_evictions": int(
                    sched.allocator.cached_evictions),
                "requests": [dict(e) for e in sched.prefix_events],
            }
        return arm

    sharing = drive(scfg_on, "p")
    baseline = drive(scfg_off, "b")
    ab_ok = (sharing["prefill_tokens_dispatched"]
             < baseline["prefill_tokens_dispatched"]
             and sharing["admitted_requests_per_block"]
             > baseline["admitted_requests_per_block"]
             and sharing["retraces"] == 1 and baseline["retraces"] == 1)
    return {"tok_s": sharing["tok_s"], "batch": num_slots,
            "prefill": prefill, "new_tokens": new_tokens,
            "p50_ms": sharing["p50_ms"], "p99_ms": sharing["p99_ms"],
            "system_prompt_tokens": int(sys_len), "block_size": block,
            "sharing": sharing, "baseline": baseline,
            "ab_ok": bool(ab_ok)}


def bench_pipeline_ab(warmup: int, iters: int, peak: float,
                      batch: int = 256, size: int = 64):
    """Host-input pipeline A/B at a COMPUTE-visible shape (b256/64px:
    ~3.1 MB uint8/batch, transfer comparable to the ~8 ms step): the
    overlapped prefetcher (``apex_tpu.data.prefetch_to_device``,
    lookahead 2) versus a naive serial ``device_put``+step loop on the
    same loader, same jitted normalize, same compiled step.  The gate is
    on the DELTA SIGN — the pipeline must not lose to naive — because
    the absolute rate tracks the tunnel wire (documented 2x swing),
    while pipeline-vs-naive isolates the framework's contribution.  The
    224px ``resnet50_o2_hoststream`` config stays as wire-bound context
    (reference capability: ``examples/imagenet/main_amp.py:256-290``)."""
    del peak
    from apex_tpu import amp
    from apex_tpu.data import (host_synthetic_loader, normalize_uint8,
                               prefetch_to_device)
    from apex_tpu.models.resnet import ResNet50
    from apex_tpu.optimizers import FusedAdam

    model = ResNet50()
    x0 = jax.random.normal(jax.random.PRNGKey(0), (batch, size, size, 3),
                           jnp.float32)
    y0 = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
    variables = model.init(jax.random.PRNGKey(2), x0[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, xb, yb):
        logits, _ = model.apply({"params": p, "batch_stats": batch_stats},
                                xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))
    compiled = step.lower(state, x0, y0).compile()
    normalize = jax.jit(normalize_uint8)

    def run_naive(st):
        metrics = None
        t0 = None
        for n, (xb, yb) in enumerate(
                host_synthetic_loader(warmup + iters, batch, size,
                                      seed=0)):
            if n == warmup:
                if metrics is not None:
                    float(metrics["loss"])
                t0 = time.perf_counter()
            xd, yd = normalize(jax.tree.map(jax.device_put, (xb, yb)))
            st, metrics = compiled(st, xd, yd)
        float(metrics["loss"])
        return st, time.perf_counter() - t0

    def run_pipeline(st):
        metrics = None
        t0 = None
        n = 0
        for xb, yb in prefetch_to_device(
                host_synthetic_loader(warmup + iters, batch, size,
                                      seed=0),
                lookahead=2, transform=normalize):
            if n == warmup:
                if metrics is not None:
                    float(metrics["loss"])
                t0 = time.perf_counter()
            st, metrics = compiled(st, xb, yb)
            n += 1
        float(metrics["loss"])
        return st, time.perf_counter() - t0

    # interleave A/B/A/B and keep each arm's best run: same-minute wire
    # conditions, minimum sensitivity to transport drift mid-measurement
    state, dt_n1 = run_naive(state)
    state, dt_p1 = run_pipeline(state)
    state, dt_n2 = run_naive(state)
    state, dt_p2 = run_pipeline(state)
    naive_rate = batch * iters / min(dt_n1, dt_n2)
    pipe_rate = batch * iters / min(dt_p1, dt_p2)
    delta = pipe_rate / naive_rate - 1.0
    return {"img_s": round(pipe_rate, 2),
            "naive_img_s": round(naive_rate, 2),
            "delta_frac": round(delta, 4),
            # sign gate with a 1% noise guard: the pipeline must at
            # least match the naive loop
            "ab_ok": bool(pipe_rate >= naive_rate * 0.99),
            "batch": batch, "px": size}


RATE_KEYS = ("img_s", "tok_s", "seq_s")

#: configs whose throughput tracks the tunnel WIRE speed (documented
#: swing ~25-50 MB/s, a 2x range) rather than chip performance — always
#: reported, never gated: the 10% threshold is calibrated to chip-day
#: variance (±2-4%), not transport variance.  The pipeline A/B config
#: is wire-coupled too; its gate is the delta SIGN (``ab_ok``), checked
#: separately.
UNGATED_CONFIGS = ("resnet50_o2_hoststream", "resnet50_pipeline_ab_64px")

#: Published per-config MFU floors.  The RN50 floors are the
#: ROOFLINE_RN50_r04 conclusions ("hold >=0.30 conv7 / >=0.32 s2d");
#: transformer floors are the round-4 measured values rounded to two
#: places.  The gate trips when measured MFU < floor * (1 - BAND): the
#: band is the re-statement VERDICT r4 weak #2 asked for — r4's
#: resnet50_o2 0.2983 sat 0.6% under the prose floor, inside the
#: documented ±2-4% chip-day variance, so a bandless floor misfires on
#: environment noise.  0.2983 passes the banded gate; a real >5%
#: efficiency loss does not.
MFU_VARIANCE_BAND = 0.05
MFU_FLOORS = {
    "resnet50_o2": 0.30,
    "resnet50_o3": 0.30,
    "resnet50_s2d_o2": 0.32,
    # r5 same-day spread on this config was 0.4032-0.4211 (-4.3% within
    # one day): the observed low cleared the former 0.42-floor gate
    # (0.399) by only 0.8%, thinner than the chip-day variance that
    # stacks ON TOP of same-day spread — floor widened one point so a
    # soft day cannot trip it; a real >7% loss still does
    "gpt_small_o2": 0.41,
    "bert_large_lamb_o2": 0.49,
    "gpt_small_tpu_heads_o2": 0.54,
    "bert_large_tpu_heads_lamb_o2": 0.59,
    "gpt_small_tpu_heads_L8192_o2": 0.55,
    "gpt_small_tpu_heads_L16384_o2": 0.51,
    "gpt_medium_tpu_o2": 0.58,
}

#: Published fraction-of-HBM-decode-ceiling floors for the decode
#: configs — the bandwidth analog of MFU_FLOORS, same band, gated by
#: :func:`check_decode_floors`.  Pinned at the r05 measured values
#: (ladder: b1 0.5433, b8 0.4346) now that DECODE_DECOMPOSE_r01.json
#: explains the b8 number (the ceiling byte model is the ideal-fusion
#: floor; the measured step carries ~1.5x that traffic, residual
#: attributed to the per-layer cache-slice materialization).  The
#: serve/preferred_element_type rewrites target exactly that residual:
#: the next on-chip round should ratchet b8 toward the >= 0.55 the
#: ROADMAP names, citing BENCH_VARIANCE like every floor raise.
DECODE_FLOORS = {
    "gpt_small_tpu_decode_b1": 0.54,
    "gpt_small_tpu_decode_b8": 0.43,
    # int8-KV b8 config: the ceiling itself is derived from the int8
    # byte model (cache term halves: ~1.6x the dense-config ceiling at
    # b8/2048+256, approaching 2x as context grows and kv_read
    # dominates), so the same hbm_frac would mean ~1.6x the tokens/s.
    # Floor seeded from the CPU-smoke measurement (hbm_frac 0.0011 vs
    # the TPU roofline — a catastrophic-regression guard only); the
    # first on-chip round ratchets it to the measured value per the
    # no-ratchet-down house rule (raising is always allowed).
    "gpt_small_tpu_decode_kv8": 0.001,
}


def check_decode_floors(configs: dict,
                        search_dir: "str | None" = None) -> dict:
    """Decode-bandwidth gate: every measured decode config with a
    published floor must hold ``hbm_frac >= floor * (1 - band)`` —
    same variance band as the MFU gate, same absolute (no-baseline)
    semantics through :func:`gate_exit_code`.  A floor above 1 is a
    calibration bug (nothing can beat the roofline) and fails
    loudly.

    With ``search_dir`` the floors consult the committed variance
    artifact (:func:`derive_floor_bands` — the MFU-gate contract on
    the ``hbm_frac`` statistic).  CPU-smoke-seeded floors
    (:data:`PROVISIONAL_FLOORS`, e.g. the kv8 0.001 guard) are marked
    ``provisional`` in the gate record: they still catch catastrophic
    regressions, but the record — and the timeline reading it — report
    them as unmeasured rather than as calibrated bars."""
    floors, bands = effective_floors(DECODE_FLOORS, search_dir,
                                     kind="config", stat="hbm_frac")
    checked, violations = {}, []
    for name, floor in floors.items():
        if floor > 1.0:
            checked[name] = {"floor": floor, "ok": False,
                             "error": "floor above the roofline "
                                      "ceiling (1.0) — impossible bar"}
            violations.append(name)
            continue
        cur = configs.get(name)
        # skip only configs with NO measurement (error/skipped records)
        # — an hbm_frac of exactly 0.0 is the catastrophic-regression
        # case the gate exists for, not a missing value (the falsy-zero
        # armed-gate class PR 4 fixed in the HFU audit)
        if not isinstance(cur, dict) or \
                not isinstance(cur.get("hbm_frac"), (int, float)):
            continue
        gate = floor * (1.0 - MFU_VARIANCE_BAND)
        ok = cur["hbm_frac"] >= gate
        checked[name] = {"hbm_frac": cur["hbm_frac"], "floor": floor,
                         "source": bands[name]["source"],
                         "gate": round(gate, 4), "ok": ok}
        if bands[name]["provisional"]:
            checked[name]["provisional"] = True
        if not ok:
            violations.append(name)
    return {"band": MFU_VARIANCE_BAND, "checked": checked,
            "provisional": sorted(n for n, b in bands.items()
                                  if b["provisional"]),
            "violations": violations, "ok": not violations}


LADDER_BASELINES = "BENCH_LADDER_BASELINES.json"

#: Recorded-variance artifact (tools/bench_variance.py) — the statistic
#: floor/band changes must cite.  Round-numbered committed artifacts
#: (``BENCH_VARIANCE_r*.json``, schema-validated by gate_hygiene) are
#: preferred; the un-numbered name stays accepted as the scratch
#: output.
VARIANCE_ARTIFACT = "BENCH_VARIANCE.json"


def _newest_round_artifact(search_dir: str,
                           prefix: str) -> "str | None":
    """Newest ``{prefix}_r{N}.json`` in ``search_dir`` by round
    number — the one lookup every round-numbered gate family shares."""
    rounds = []
    for path in glob.glob(os.path.join(search_dir,
                                       f"{prefix}_r*.json")):
        m = re.search(rf"{re.escape(prefix)}_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    return max(rounds)[1] if rounds else None


def find_variance_artifact(search_dir: str) -> "str | None":
    """Newest committed ``BENCH_VARIANCE_r{N}.json`` next to this
    script, else the legacy un-numbered ``BENCH_VARIANCE.json``."""
    path = _newest_round_artifact(search_dir, "BENCH_VARIANCE")
    if path is not None:
        return path
    legacy = os.path.join(search_dir, VARIANCE_ARTIFACT)
    return legacy if os.path.exists(legacy) else None


def load_variance(search_dir: str) -> "dict | None":
    path = find_variance_artifact(search_dir)
    if path is None:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def floor_change_allowed(name: str, old_floor: float, new_floor: float,
                         variance_doc: "dict | None",
                         kind: str = "config",
                         stat: "str | None" = None) -> bool:
    """The no-ratchet-down rule for the published floors (MFU_FLOORS
    here, KERNEL_FLOORS in tools/kernel_bench.py) — the floor analog of
    the ladder-baseline rule: RAISING a floor is always allowed
    (measured gains ratchet the bar up), LOWERING one requires a
    recorded-variance entry (``tools/bench_variance.py`` →
    BENCH_VARIANCE.json) for that config/kernel whose relative spread
    covers the drop.  Without the artifact — or with only a tiny-smoke
    one — no lowering: that is exactly the anecdote-calibrated erosion
    VERDICT r5 weak #1/#6 called out (a floor quietly lowered in the
    same commit that turns a gate green).  Enforced by
    tests/l1/test_bench_units.py against a frozen snapshot."""
    if new_floor >= old_floor:
        return True
    if not isinstance(variance_doc, dict) or variance_doc.get("tiny"):
        return False
    entry = (variance_doc.get("entries") or {}).get(f"{kind}:{name}")
    if not isinstance(entry, dict):
        return False
    spread = entry.get("rel_spread")
    if stat is not None:
        # the drop must be judged by the spread of the SAME statistic
        # the floor gates (hbm_frac for decode floors, roofline_frac
        # for kernel floors) — a wide spread on a different metric is
        # not evidence about this one
        sub = entry.get(stat)
        spread = sub.get("rel_spread") if isinstance(sub, dict) \
            else None
    elif kind == "config" and isinstance(entry.get("mfu"), dict):
        # MFU floors gate the mfu statistic when recorded; rate
        # otherwise (the legacy no-stat call path)
        spread = entry["mfu"].get("rel_spread", spread)
    if not spread:
        return False
    return (old_floor - new_floor) / old_floor <= spread


#: Floors seeded from CPU smokes rather than on-chip measurement —
#: catastrophic-regression guards, NOT calibrated bars.  The gate
#: records and the timeline report them as ``provisional`` (unmeasured)
#: instead of passing them off as floors; the first on-chip
#: bench_variance round with an entry for the config graduates them.
PROVISIONAL_FLOORS = frozenset({"gpt_small_tpu_decode_kv8"})

#: The derived-floor formula: ``floor = mean − FLOOR_BAND_K · std``
#: over at least FLOOR_MIN_SAMPLES recorded repeats of the GATED
#: statistic.  k = 2 puts the floor two sample standard deviations
#: under the recorded mean — on the documented same-day spreads
#: (±2-4%) that is a wider allowance than the hand 5% band only when
#: the recorded variance actually is wider, which is the point: band
#: width derives from measured spread, not anecdote.
FLOOR_BAND_K = 2.0
FLOOR_MIN_SAMPLES = 5

#: which variance-entry sub-statistic carries each floor table's unit
_FLOOR_STATS = {"mfu": "mfu", "hbm_frac": "hbm_frac",
                "roofline_frac": "roofline_frac"}


def derive_floor_bands(hand_floors: dict,
                       variance_doc: "dict | None",
                       kind: str = "config",
                       stat: "str | None" = None) -> dict:
    """Statistical floors from recorded variance, hand floors as the
    frozen fallback: for every published floor, when the newest
    committed variance artifact carries a qualifying entry (non-tiny
    document, ``n >= FLOOR_MIN_SAMPLES``, a ``std``-carrying stats
    block for the gated statistic), the derived candidate is
    ``mean − FLOOR_BAND_K · std``; otherwise the hand floor stands.

    The no-ratchet-down rule applies to DERIVED floors too: a
    candidate above the hand floor ratchets the bar up; a candidate
    below it is only accepted when :func:`floor_change_allowed` says
    the recorded spread covers the drop — so consulting the variance
    artifact can tighten gates but never silently loosen one
    (``tests/l1/test_bench_units.py`` pins the frozen-fallback
    behavior against the committed artifact).

    Returns ``{name: {"floor", "source": "derived"|"hand",
    "provisional": bool, ...evidence}}`` — ``provisional`` marks the
    CPU-smoke-seeded guards (:data:`PROVISIONAL_FLOORS`) that have no
    measurement behind them yet.

    Qualifying evidence must be ON-CHIP: the artifact must record
    ``platform == "tpu"`` as well as not-tiny — a full-size CPU run
    (interpret-mode timings, host noise) passes the schema but says
    nothing about the floors the TPU gates enforce, and must never
    loosen them."""
    usable = isinstance(variance_doc, dict) \
        and not variance_doc.get("tiny") \
        and variance_doc.get("platform") == "tpu"
    entries = (variance_doc or {}).get("entries") or {}
    out = {}
    for name, hand in hand_floors.items():
        rec = {"floor": hand, "source": "hand",
               "provisional": name in PROVISIONAL_FLOORS}
        out[name] = rec
        if not usable:
            continue
        e = entries.get(f"{kind}:{name}")
        if stat is not None and isinstance(e, dict):
            e = e.get(stat)
        if not isinstance(e, dict):
            continue
        n, mean, std = e.get("n"), e.get("mean"), e.get("std")
        if not (isinstance(n, int) and n >= FLOOR_MIN_SAMPLES
                and isinstance(mean, (int, float))
                and isinstance(std, (int, float))):
            rec["reason"] = (f"insufficient variance evidence "
                            f"(n={n!r} < {FLOOR_MIN_SAMPLES} or "
                            f"missing mean/std)")
            continue
        candidate = round(mean - FLOOR_BAND_K * std, 4)
        rec.update(mean=mean, std=std, n=n, k=FLOOR_BAND_K,
                   candidate=candidate)
        if candidate >= hand or floor_change_allowed(
                name, hand, candidate, variance_doc, kind=kind,
                stat=stat):
            rec.update(floor=candidate, source="derived",
                       provisional=False)
        else:
            rec["reason"] = ("derived candidate below the hand floor "
                             "beyond the recorded spread — hand floor "
                             "stands (no-ratchet-down)")
    return out


def effective_floors(hand_floors: dict, search_dir: "str | None",
                     kind: str = "config",
                     stat: "str | None" = None) -> "tuple[dict, dict]":
    """``({name: floor}, bands_record)`` — the floors a gate should
    apply: derived where the committed variance artifact qualifies,
    hand otherwise.  ``search_dir=None`` skips the artifact entirely
    (unit tests that pin the hand tables)."""
    doc = load_variance(search_dir) if search_dir else None
    bands = derive_floor_bands(hand_floors, doc, kind=kind, stat=stat)
    return {name: rec["floor"] for name, rec in bands.items()}, bands


def check_mfu_floors(configs: dict,
                     search_dir: "str | None" = None) -> dict:
    """Efficiency gate: every measured config with a published floor
    must hold ``MFU >= floor * (1 - MFU_VARIANCE_BAND)``.  Catches the
    regression class throughput deltas cannot: an OOM-laddered config
    whose batch changed (tok/s incomparable) still has comparable MFU,
    and a kernel regression on a chip-day when the baseline was fast
    shows up here before it survives two rounds of deltas.

    With ``search_dir``, the floors CONSULT the committed variance
    artifact through :func:`derive_floor_bands` (statistical floors
    where recorded evidence qualifies, the hand table as the frozen
    fallback — nothing loosens without a qualifying entry); each
    checked record names the floor's ``source``."""
    floors, bands = effective_floors(MFU_FLOORS, search_dir,
                                     kind="config", stat="mfu")
    checked, violations = {}, []
    for name, floor in floors.items():
        cur = configs.get(name)
        if not isinstance(cur, dict) or not cur.get("mfu"):
            continue
        gate = floor * (1.0 - MFU_VARIANCE_BAND)
        ok = cur["mfu"] >= gate
        checked[name] = {"mfu": cur["mfu"], "floor": floor,
                         "source": bands[name]["source"],
                         "gate": round(gate, 4), "ok": ok}
        if not ok:
            violations.append(name)
    return {"band": MFU_VARIANCE_BAND, "checked": checked,
            "violations": violations, "ok": not violations}


def load_ladder_baselines(search_dir: str) -> dict:
    try:
        with open(os.path.join(search_dir, LADDER_BASELINES)) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def update_ladder_baselines(search_dir: str, configs: dict) -> None:
    """Persist every successful result keyed ``(config, batch)`` so a
    future round that lands on a different ladder rung (the tunneled
    chip's usable HBM varies by day) still compares like-for-like
    instead of reporting "uncompared" (VERDICT r4 missing #3/next #4).
    Rungs never ratchet DOWNWARD: a slow chip-day may only add missing
    rungs, not overwrite a faster stored one — otherwise two soft days
    in a row would quietly lower the bar a real regression is gated
    against.  Best-effort: a read-only checkout must not fail the
    bench."""
    path = os.path.join(search_dir, LADDER_BASELINES)
    doc = load_ladder_baselines(search_dir)
    stamp = time.strftime("%Y-%m-%d")
    for name, cur in configs.items():
        if not isinstance(cur, dict) or cur.get("batch") is None:
            continue
        key = next((k for k in RATE_KEYS if cur.get(k)), None)
        if key is None:
            continue
        prev = doc.get(name, {}).get(str(cur["batch"]))
        if isinstance(prev, dict) and prev.get(key) and \
                prev[key] > cur[key]:
            continue
        entry = dict(cur)
        entry["recorded"] = stamp
        doc.setdefault(name, {})[str(cur["batch"])] = entry
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    except OSError:
        pass


def find_kernel_bench_artifact(search_dir: str) -> "str | None":
    """Newest committed ``KERNELBENCH_r{N}.json`` next to this script —
    the kernel-level gate's memory (tools/kernel_bench.py writes it on
    chip; tools/gate_hygiene.py keeps it committed)."""
    return _newest_round_artifact(search_dir, "KERNELBENCH")


def check_kernel_floor_artifact(search_dir: str) -> "dict | None":
    """Surface the per-kernel roofline-fraction floors
    (``tools/kernel_bench.KERNEL_FLOORS``) in this gate record, checked
    against the newest KERNELBENCH_r*.json artifact — the kernel analog
    of the MFU floors, and an ABSOLUTE gate: a committed artifact that
    violates a floor fails the model bench too, so an optimizer-kernel
    bandwidth regression cannot hide behind a green model round (the
    2%-of-step problem the kernel bench exists for).  Best-effort like
    every artifact read here: no artifact → None, unreadable → recorded
    but never failing after the chip time is spent."""
    path = find_kernel_bench_artifact(search_dir)
    if path is None:
        return None
    name = os.path.basename(path)
    # THIS repo's floor table judges the artifact wherever it lives
    # (search_dir may be a scratch dir in tests); guard the insert so
    # repeated calls never grow sys.path.  An unimportable kernel_bench
    # is OUR bug, not a bad artifact: fail the gate loudly rather than
    # run with it silently off.
    tools_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    try:
        import kernel_bench
        check_fn = kernel_bench.check_kernel_floors
    except Exception as e:  # noqa: BLE001
        return {"artifact": name, "ok": False,
                "error": f"tools/kernel_bench unimportable: {e}"[:300]}
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"expected object, got {type(doc).__name__}")
        if doc.get("platform") != "tpu":
            return {"artifact": name, "ok": True,
                    "skipped": "non-TPU artifact: roofline fractions "
                               "only meaningful on chip"}
        # the kernel gate consults the committed variance artifact the
        # same way the MFU/decode gates do — through the ONE shared
        # wiring (statistical floors where a qualifying kernel entry
        # exists, the hand table otherwise), against the SAME
        # search_dir the artifact came from
        eff, bands = kernel_bench.effective_kernel_floors(search_dir)
        out = check_fn(doc.get("kernels") or {}, floors=eff)
        out["floor_sources"] = {n: b["source"]
                                for n, b in bands.items()}
        out["artifact"] = name
        return out
    except Exception as e:  # noqa: BLE001 - artifact reads never crash
        return {"artifact": name, "ok": True,
                "error": f"artifact unreadable: {e}"[:300]}


def find_export_artifact(search_dir: str) -> "str | None":
    """Newest committed ``EXPORT_r{N}.json`` next to this script — the
    AOT-export pipeline's round evidence (tools/aot_export.py writes
    it; tools/gate_hygiene.py keeps it committed and schema-valid)."""
    return _newest_round_artifact(search_dir, "EXPORT")


def check_export_cold_start(search_dir: str) -> "dict | None":
    """Serve cold-start gate, SOURCED from the newest committed
    EXPORT_r*.json (never re-measured here, so bench and the artifact
    can never disagree on the number): loading the serve lane's
    executable from the content-addressed AOT cache must cost at most
    ``budget`` (0.5) of compiling it on the recording host — the whole
    point of lint-then-serialize is that a scale-out replica stops
    paying XLA compilation; a cache slower than half a compile is
    decoration.  An ABSOLUTE gate like the MFU floors: no baseline
    needed, fails the run via :func:`gate_exit_code`.  No artifact →
    ``None`` (nothing to gate); unreadable → recorded but never
    failing after the chip time is spent (the best-effort artifact
    contract), while the verdict itself re-derives ``ok`` from the
    numbers rather than trusting the recorded flag."""
    path = find_export_artifact(search_dir)
    if path is None:
        return None
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
        cs = doc.get("cold_start") if isinstance(doc, dict) else None
        if not isinstance(cs, dict):
            raise ValueError("no cold_start block")
        ratio = cs["load_ratio"]
        budget = cs["budget"]
        return {"artifact": name, "lane": cs.get("lane"),
                "compile_s": cs.get("compile_s"),
                "load_s": cs.get("load_s"),
                "load_ratio": ratio, "budget": budget,
                "ok": bool(ratio <= budget)}
    except (OSError, ValueError, KeyError, TypeError) as e:
        return {"artifact": name, "ok": True,
                "error": f"artifact unreadable: {e}"[:300]}


def check_floor_calibration(search_dir: str) -> dict:
    """The static half of gate calibration (apex_tpu.analysis.cost):
    the published floors (MFU_FLOORS here, KERNEL_FLOORS in
    tools/kernel_bench.py) and the measurements in the newest committed
    KERNELBENCH/BENCH artifacts must all sit UNDER the cost-model
    ceilings — a floor above the roofline (fraction > 1, MFU > 1) or a
    measured bandwidth above the HBM peak means the gate was calibrated
    against impossible physics, and every later round inherits the
    miscalibration.  An unimportable audit is OUR bug: fail loudly
    rather than run with the check silently off (same contract as
    check_kernel_floor_artifact)."""
    try:
        from apex_tpu.analysis import cost as _cost
    except Exception as e:  # noqa: BLE001
        return {"ok": False,
                "error": f"apex_tpu.analysis.cost unimportable: {e}"[:300]}
    try:
        tools_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools")
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        import kernel_bench
        kernel_floors = kernel_bench.KERNEL_FLOORS
    except Exception as e:  # noqa: BLE001
        # same fail-loud contract as the analysis.cost import above:
        # an unimportable floor table means half the calibration gate
        # is off, which must never read as "calibrated clean"
        return {"ok": False,
                "error": f"tools/kernel_bench unimportable — "
                         f"KERNEL_FLOORS not audited: {e}"[:300]}
    findings = _cost.audit_floor_artifacts(
        search_dir, kernel_floors=kernel_floors, mfu_floors=MFU_FLOORS)
    errors = [f.message for f in findings if f.severity == "error"]
    # CPU-smoke-seeded floors are named as UNMEASURED (provisional):
    # they guard against catastrophe but calibrate nothing — the
    # timeline and the gate record must not pass them off as floors
    provisional = sorted(n for n in PROVISIONAL_FLOORS
                         if n in DECODE_FLOORS or n in MFU_FLOORS
                         or n in kernel_floors)
    return {"ok": not errors, "errors": errors,
            "provisional_floors": provisional}


def find_prior_bench(search_dir: str) -> "str | None":
    """Newest ``BENCH_r{N}.json`` next to this script (by round number) —
    the default regression baseline when ``--compare`` isn't given."""
    return _newest_round_artifact(search_dir, "BENCH")


def compare_configs(prior_path: str, configs: dict,
                    threshold: float = 0.10,
                    ladder: "dict | None" = None) -> dict:
    """Per-config throughput regression check against a prior round's
    ``BENCH_r{N}.json``.  A config counts as regressed when its rate
    metric drops by more than ``threshold`` (default 10%: documented
    chip-day variance is ±2-4%, so ≥8-10% same-config is signal, not
    noise — VERDICT r3 weak #6).  Configs present on only one side, or
    errored/skipped on either, are listed but never fail the gate.

    ``ladder``: persisted ``{config: {str(batch): result}}`` baselines
    (``BENCH_LADDER_BASELINES.json``).  When the round baseline's batch
    mismatches (an OOM-ladder rung change), the same-batch ladder entry
    substitutes so the config is still gated like-for-like; the
    substitution is recorded in ``ladder_compared``."""
    try:
        with open(prior_path) as f:
            doc = json.load(f)
        # the driver's BENCH_r{N}.json wraps the bench line under
        # "parsed" (raw stdout under "tail"); a tee'd run is the line
        # itself — accept both shapes.  Any OTHER shape (valid JSON
        # that isn't the expected dict-of-dicts) counts as unreadable:
        # a malformed artifact next to bench.py must never crash the
        # run after the chip time is already spent.
        if not isinstance(doc, dict):
            raise ValueError(f"expected object, got {type(doc).__name__}")
        if "configs" not in doc and isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        prior = doc.get("configs")
        if not isinstance(prior, dict):
            raise ValueError("no configs map")
    except (OSError, ValueError, TypeError) as e:
        return {"baseline": prior_path, "ok": True,
                "error": f"baseline unreadable: {e}"}
    deltas, regressions, uncompared = {}, [], []
    ladder_compared = {}
    for name, cur in configs.items():
        if name in UNGATED_CONFIGS or not isinstance(cur, dict):
            uncompared.append(name)
            continue
        key = next((k for k in RATE_KEYS if cur.get(k)), None)
        if key is None:
            uncompared.append(name)
            continue
        old = prior.get(name)
        base = None
        if (isinstance(old, dict) and old.get(key)
                and (cur.get("batch") is None or old.get("batch") is None
                     or cur["batch"] == old["batch"])):
            base = old
        elif cur.get("batch") is not None:
            # the round baseline is batch-mismatched (an OOM-ladder rung
            # change reshapes the tok/s denominator), errored, or
            # missing — a persisted same-batch ladder rung still gates
            # like-for-like
            sub = (ladder or {}).get(name, {}).get(str(cur["batch"]))
            if isinstance(sub, dict) and sub.get(key):
                base = sub
                ladder_compared[name] = {"batch": cur["batch"],
                                         "recorded": sub.get("recorded")}
        if base is None:
            uncompared.append(name)
            continue
        delta = cur[key] / base[key] - 1.0
        deltas[name] = round(delta, 4)
        if delta < -threshold:
            regressions.append(name)
    # a config the BASELINE had but this run lost entirely must be
    # visible too — a silent disappearance is a 100% regression
    uncompared += [n for n in prior if n not in configs]
    return {"baseline": os.path.basename(prior_path),
            "threshold": threshold, "deltas": deltas,
            "regressions": regressions, "uncompared": uncompared,
            "ladder_compared": ladder_compared,
            "ok": not regressions}


def gate_exit_code(regression_check: dict, compare_given: bool) -> int:
    """2 when the run must fail, else 0.

    The MFU floors, the decode-bandwidth floors (DECODE_FLOORS on
    hbm_frac), the per-kernel roofline floors (from the newest
    KERNELBENCH artifact), and the A/B sign checks are ABSOLUTE gates —
    they need no baseline, so they fail the run with or without
    ``--compare`` (CI without a BENCH_r*.json must not silently pass an
    efficiency regression).  The throughput-delta gate stays opt-in via
    ``--compare``: without a chosen baseline the comparison is recorded
    in the output but informational."""
    mfu = regression_check.get("mfu_floors") or {}
    dec = regression_check.get("decode_floors") or {}
    kfl = regression_check.get("kernel_floors") or {}
    cal = regression_check.get("floor_calibration") or {}
    exp = regression_check.get("export_cold_start") or {}
    absolute_failed = bool(regression_check.get("ab_failures")) or \
        not mfu.get("ok", True) or not dec.get("ok", True) or \
        not kfl.get("ok", True) or not cal.get("ok", True) or \
        not exp.get("ok", True)
    if absolute_failed or (compare_given
                           and not regression_check.get("ok", True)):
        return 2
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--compare", metavar="BENCH_rN.json", default=None,
                    help="regression-gate against this prior bench "
                         "artifact: exit 2 (after printing the JSON "
                         "line) if any config's throughput dropped more "
                         "than --threshold.  Without this flag the "
                         "newest BENCH_r*.json next to the script is "
                         "still compared and the verdict recorded in "
                         "the output but the delta gate never fails the "
                         "run; the ABSOLUTE gates (MFU floors, A/B "
                         "sign) need no baseline and fail it either "
                         "way.")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional per-config drop that counts as a "
                         "regression (default 0.10)")
    opts = ap.parse_args(argv)

    platform = _backend_or_die()[0].platform
    on_tpu = platform == "tpu"
    peak = chip_peak_flops() if on_tpu else None  # MFU only meaningful on chip
    # Real configs on TPU; tiny stand-ins on CPU so the script stays
    # runnable anywhere (the driver runs it on the real chip).
    if on_tpu:
        # iters sized so the full 10-config suite fits the time budget:
        # measurement noise at these counts is ~1%, well under chip-day
        # variance (+-2-4%), and the budget headroom keeps the optional
        # long-context configs from being skipped
        rn_args = dict(batch=256, size=224, warmup=4, iters=20)
        gpt_args = dict(batch=8, seq=2048, warmup=3, iters=12, tiny=False)
        bert_args = dict(batch=16, seq=512, warmup=3, iters=10, tiny=False)
    else:
        rn_args = dict(batch=8, size=64, warmup=1, iters=3)
        gpt_args = dict(batch=2, seq=64, warmup=1, iters=3, tiny=True)
        bert_args = dict(batch=2, seq=64, warmup=1, iters=3, tiny=True)

    # Each config is fault-isolated: an OOM or compile failure in one
    # (e.g. bert-large at this batch on a smaller-HBM part) records an
    # error entry instead of costing the whole round's benchmark artifact.
    configs = {}
    t_start = time.perf_counter()
    #: the one JSON line must print before any driver timeout: optional
    #: configs are skipped (recorded as such) once the suite has been
    #: running this long.  The required configs (RN50 O2/O3, gpt-small,
    #: bert-large = the BASELINE set) always run.
    try:
        optional_budget_s = float(
            os.environ.get("APEX_TPU_BENCH_BUDGET_S", 2100))
    except ValueError:  # malformed env must not cost the round's artifact
        optional_budget_s = 2100.0

    def record(name, fn, optional=False, fresh=False, **kw):
        if optional and time.perf_counter() - t_start > optional_budget_s:
            configs[name] = {"skipped": "bench time budget"}
            return
        if fresh:
            # drop cached executables + their donated buffers first: HBM
            # fragmentation from earlier configs tanks very-long-context
            # allocations (round-2: L16384 measured 3x slower after an
            # L8192 model in the same process)
            import gc
            jax.clear_caches()
            gc.collect()
        # one in-place retry first: the tunneled device occasionally drops
        # an attempt that succeeds immediately on rerun; only a SECOND
        # failure (e.g. a genuine OOM) is recorded as this config's error,
        # keeping both attempts' messages so the real cause isn't masked
        # by a different transient on the retry
        errs = []
        for attempt in (0, 1):
            try:
                configs[name] = fn(peak=peak, **kw)
                return
            except Exception as e:  # noqa: BLE001 - diagnostic record
                errs.append(f"{type(e).__name__}: {e}"[:300])
                if attempt == 0:
                    time.sleep(10)
        configs[name] = {"error": errs[0], "retry_error": errs[1]}

    record("resnet50_o2", bench_resnet, opt_level="O2", **rn_args)
    record("resnet50_o3", bench_resnet, opt_level="O3", **rn_args)
    record("gpt_small_o2", bench_gpt, **gpt_args)
    record("bert_large_lamb_o2", bench_bert, **bert_args)
    if on_tpu:
        # meaningless off-TPU: the tiny CPU stand-in ignores tpu_heads,
        # so it would just duplicate gpt_small_o2 under another name
        record("gpt_small_tpu_heads_o2", bench_gpt, optional=True,
               tpu_heads=True, **gpt_args)
        record("bert_large_tpu_heads_lamb_o2", bench_bert, optional=True,
               tpu_heads=True, **bert_args)
        # long-context single-chip: flash + remat keep the (L, L) scores
        # and activations out of HBM at 8K tokens of context
        record("gpt_small_tpu_heads_L8192_o2", bench_gpt, optional=True,
               tpu_heads=True, remat=True, batch=2, seq=8192, warmup=3,
               iters=15, tiny=False)
        # TPU-native input stem (space-to-depth, +8% over conv7+maxpool)
        record("resnet50_s2d_o2", bench_resnet, optional=True,
               opt_level="O2", s2d=True, **rn_args)
        # KV-cached decode throughput (bandwidth-bound; see
        # docs/source/models.rst) — serving latency (b1) and a small
        # serving batch (b8).  Ordered before the wire-coupled and
        # very-long-context configs: fresh round evidence must not be
        # the first thing the time budget sheds.
        record("gpt_small_tpu_decode_b1", bench_generate, optional=True,
               batch=1, prefill=2048, new_tokens=256, warmup=1, iters=4,
               tiny=False)
        record("gpt_small_tpu_decode_b8", bench_generate, optional=True,
               batch=8, prefill=2048, new_tokens=256, warmup=1, iters=4,
               tiny=False)
        # int8 KV cache variant of the b8 decode config: half the
        # cache bytes -> the ceiling (derived from the int8 byte model
        # via roofline_expectation inside bench_generate) nearly
        # doubles at this context length; hbm_frac is gated by its own
        # DECODE_FLOORS entry (CPU-smoke-seeded; on-chip ratchet next
        # driver round)
        record("gpt_small_tpu_decode_kv8", bench_generate, optional=True,
               batch=8, prefill=2048, new_tokens=256, warmup=1, iters=4,
               tiny=False, kv_dtype="int8")
        # continuous-batching serve engine (apex_tpu.serve): offered-
        # load sweep c1 -> c8 over the paged KV cache, decode-step
        # p50/p99 latency + tokens/s; the latency-tail ab gate catches
        # a mid-serve retrace/host-sync (static-shape contract at
        # runtime)
        record("gpt_small_tpu_serve_c8", bench_serve, optional=True,
               warmup=1, iters=1, num_slots=8, prefill=512,
               new_tokens=128, tiny=False)
        # speculative decoding vs the plain engine on the SAME c8
        # stream (truncated layer-skip draft, k=4): gated on tokens
        # per decode dispatch strictly greater with spec on +
        # retraces==1 both arms — the latency-win claim of
        # apex_tpu.serve.spec as a bench gate (the full scenario grid
        # is SCENARIO_r*.json via tools/serve_scenarios.py)
        record("gpt_small_tpu_serve_spec_c8", bench_serve_spec,
               optional=True, warmup=1, iters=1, num_slots=8,
               prefill=512, new_tokens=128, spec_k=4, draft_layers=3,
               tiny=False)
        # disaggregated prefill/decode fleet vs the monolithic engine
        # at EQUAL resources and the same c16 request stream: prefill
        # on its own mesh slice, 2 decode replicas on disjoint slices,
        # KV shipped between them; gated on the DistServe claim
        # (disagg decode p99 <= mono p99) via ab_ok.  Skips (recorded)
        # on hosts with fewer than 3 addressable devices.
        record("gpt_small_tpu_serve_disagg_c16", bench_serve_disagg,
               optional=True, warmup=1, iters=1, n_replicas=2,
               slots_per_replica=8, prefill=512, new_tokens=128,
               tiny=False)
        # cross-request prefix sharing vs no sharing on the SAME c16
        # shared-system-prompt stream at equal devices: gated on the
        # deterministic counts (sharing arm dispatches fewer prefill
        # tokens + admits more requests per resident block, retraces==1
        # both arms) via ab_ok; the committed PREFIXCACHE_r*.json
        # (tools/serve_prefix.py) carries the spans + bitwise drill
        record("gpt_small_tpu_serve_prefix_c16", bench_serve_prefix,
               optional=True, warmup=1, iters=1, num_slots=16,
               prefill=512, new_tokens=128, tiny=False)
        # pipeline-vs-naive at the compute-visible shape; gated on the
        # delta sign (ab_ok), not the wire-coupled absolute rate
        record("resnet50_pipeline_ab_64px", bench_pipeline_ab,
               optional=True, warmup=3, iters=12)
        # host-streamed input pipeline A/B vs resnet50_o2 (uint8 over
        # the wire, normalize on device, double-buffered H2D)
        record("resnet50_o2_hoststream", bench_resnet, optional=True,
               opt_level="O2", host_stream=True, **rn_args)
        # 16K context (fresh: clearing caches avoids the HBM-
        # fragmentation slowdown of back-to-back long-context models in
        # one process); the fused one-pass attention backward still
        # runs (805 MB dq partials, under the 1 GiB budget)
        record("gpt_small_tpu_heads_L16384_o2", bench_gpt, optional=True,
               fresh=True, tpu_heads=True, remat=True, batch=1,
               seq=16384, warmup=2, iters=8, tiny=False)
        # bigger matmuls lift MFU: ~368M params, 8x128 heads; OOM
        # ladder b8->6->4 for low-HBM chip days (round 4) — ordered
        # LAST: its worst-case subprocess retries (three fresh
        # compiles on OOM chip-days) must not starve any other config
        # of the time budget
        record("gpt_medium_tpu_o2", bench_gpt, optional=True, fresh=True,
               tpu_heads="medium", batch=8, seq=2048, warmup=3, iters=12,
               tiny=False, batch_fallbacks=(6, 4))

    # Headline = the parity configs only (the conv7-stem model the
    # BASELINE derivation refers to); the s2d variant stays a
    # configs-map entry like the TPU-heads transformers.
    ok_rn = [(k, v) for k, v in configs.items()
             if k in ("resnet50_o2", "resnet50_o3") and "img_s" in v]
    if not ok_rn:
        raise RuntimeError(f"no ResNet-50 config succeeded: {configs}")
    best_lvl, best = max(ok_rn, key=lambda kv: kv[1]["img_s"])

    here = os.path.dirname(os.path.abspath(__file__))
    prior = opts.compare or find_prior_bench(here)
    ladder = load_ladder_baselines(here)
    # The gate record ALWAYS exists: the MFU floors and A/B sign checks
    # are absolute (no baseline needed), so a missing BENCH_r*.json must
    # not silently discard them.
    regression_check = (compare_configs(prior, configs, opts.threshold,
                                        ladder=ladder)
                       if prior else {"baseline": None, "ok": True})
    # both floor gates consult the committed BENCH_VARIANCE_r*.json
    # through derive_floor_bands (hand tables as the frozen fallback)
    mfu_check = check_mfu_floors(configs, search_dir=here) \
        if on_tpu else None
    # decode-bandwidth floors: absolute like the MFU floors (hbm_frac
    # against the roofline ceiling — only meaningful on chip)
    decode_check = check_decode_floors(configs, search_dir=here) \
        if on_tpu else None
    # the kernel-level floors ride the committed KERNELBENCH artifact
    # (checked regardless of this run's platform: the artifact carries
    # its own; a non-TPU artifact records skipped)
    kernel_floor_check = check_kernel_floor_artifact(here)
    # delta-sign gates (pipeline-vs-naive A/B): wire-coupled rates,
    # framework-attributable sign
    ab_failures = [n for n, v in configs.items()
                   if isinstance(v, dict) and v.get("ab_ok") is False]
    # floors must sit under the cost-model ceiling (the lint analog:
    # apex_tpu.analysis.cost — a roofline fraction or MFU floor above 1,
    # or a committed measurement above physics, is a calibration bug)
    calibration_check = check_floor_calibration(here)
    # the serve cold-start gate rides the committed EXPORT artifact
    # (load <= 0.5x compile; platform-independent — the artifact
    # carries its own recording host), and the configs map records the
    # same numbers so the cold-start story shows up next to the
    # throughput it buys
    export_check = check_export_cold_start(here)
    if export_check is not None and "error" not in export_check:
        configs["serve_cold_start"] = {
            "source": export_check["artifact"],
            "lane": export_check["lane"],
            "compile_s": export_check["compile_s"],
            "load_s": export_check["load_s"],
            "load_ratio": export_check["load_ratio"],
            "budget": export_check["budget"]}
    regression_check["mfu_floors"] = mfu_check
    regression_check["decode_floors"] = decode_check
    regression_check["kernel_floors"] = kernel_floor_check
    regression_check["floor_calibration"] = calibration_check
    regression_check["export_cold_start"] = export_check
    regression_check["ab_failures"] = ab_failures
    regression_check["ok"] = bool(
        regression_check["ok"] and not ab_failures
        and (mfu_check is None or mfu_check["ok"])
        and (decode_check is None or decode_check["ok"])
        and (kernel_floor_check is None or kernel_floor_check["ok"])
        and calibration_check["ok"]
        and (export_check is None or export_check["ok"]))
    if on_tpu and regression_check["ok"]:
        # a gate-failing run must not become the future like-for-like
        # baseline (a regressed rung would mask the loss once batches
        # churn) — persist rungs only from green runs
        update_ladder_baselines(here, configs)

    print(json.dumps({
        "metric": f"resnet50_amp_{best_lvl.split('_')[1]}_fused_adam_"
                  f"throughput_{platform}_b{best['batch']}_{best['px']}px",
        "value": best["img_s"],
        "unit": "img/s",
        "vs_baseline": round(best["img_s"] / BASELINE_IMG_PER_SEC_PER_CHIP,
                             4),
        "mfu": best["mfu"],
        "configs": configs,
        "regression_check": regression_check,
    }))
    rc = gate_exit_code(regression_check, bool(opts.compare))
    if rc:
        # an unreadable/missing baseline early-returns a dict WITHOUT
        # regressions/deltas — the absolute gates must still report
        # instead of dying on a KeyError after the chip time is spent;
        # with no baseline at all, name the absolute gates rather than
        # pointing the triage at a nonexistent comparison
        base = regression_check.get("baseline")
        vs = f"vs {base}" if base else "(absolute gates, no baseline)"
        print(f"bench: gate failed {vs}: throughput "
              f"regressions {regression_check.get('regressions', [])}, "
              f"MFU-floor violations "
              f"{(mfu_check or {}).get('violations', [])}, decode-floor "
              f"violations {(decode_check or {}).get('violations', [])}, "
              f"kernel-floor violations "
              f"{(kernel_floor_check or {}).get('violations', [])}, "
              f"A/B sign failures {ab_failures}, cold-start gate "
              f"{'FAILED' if export_check and not export_check['ok'] else 'ok'} "
              f"(deltas {regression_check.get('deltas', {})})",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    # transient-drop retries live per config inside record(); the only
    # exception reaching here is "no ResNet-50 config succeeded", which a
    # full rerun would not fix — let it propagate with its traceback
    raise SystemExit(main())
