"""Headline benchmark: ResNet-50 amp O2 + FusedAdam throughput, one chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

Baseline derivation (BASELINE.json north star: "v5e-16 within 90% of
8xA100 images/sec"): 8xA100 ResNet-50 amp synthetic-data throughput
~2500 img/s/GPU => 20000 img/s; 90% over 16 v5e chips =>
1125 img/s/chip.  ``vs_baseline`` is measured img/s on this one chip
divided by that per-chip target (>1.0 beats the north star pro-rata).
"""

import json
import time

import jax
import jax.numpy as jnp
import optax

BASELINE_IMG_PER_SEC_PER_CHIP = 1125.0


def main():
    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet50
    from apex_tpu.optimizers import FusedAdam

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # Real config on TPU; a tiny stand-in on CPU so the script stays
    # runnable anywhere (the driver runs it on the real chip).
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64
    warmup, iters = (5, 30) if on_tpu else (1, 3)

    model = ResNet50()
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, size, size, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
    variables = model.init(jax.random.PRNGKey(2), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, xb, yb):
        logits, _ = model.apply({"params": p, "batch_stats": batch_stats},
                                xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))

    # NB: a scalar fetch, not block_until_ready — the latter does not
    # drain the pipeline over tunneled device transports.
    for _ in range(warmup):
        state, metrics = step(state, x, y)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, x, y)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    img_per_sec = batch * iters / dt
    print(json.dumps({
        "metric": f"resnet50_amp_o2_fused_adam_throughput_{platform}"
                  f"_b{batch}_{size}px",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # One retry: the tunneled device occasionally drops a first
        # attempt (observed transient trace/execute failure that succeeds
        # immediately on rerun); the driver records this script's single
        # JSON line, so don't let a hiccup cost the round's benchmark.
        import traceback
        traceback.print_exc()
        time.sleep(15)
        main()
