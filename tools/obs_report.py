"""Measure the telemetry layer's cost and emit the OBS_r*.json artifact.

The observability layer's acceptance criteria are themselves
observability claims, so they get the same treatment as every other
gate in this repo: measured, machine-checked, committed.  This tool
produces ``OBS_r*.json`` (schema: ``apex_tpu/analysis/obs.py``,
enforced on committed copies by ``tools/gate_hygiene.py``) with three
sections:

- **overhead** — wall time of a bare jitted train loop vs the same
  loop wrapped with :func:`apex_tpu.obs.metrics.instrument_step`
  (per-step dispatch histogram + counters + lag-deferred loss/overflow
  resolution), at the bench-smoke scale with the
  ``tools/chaos_run.py --overhead`` methodology (interleaved reps,
  min-to-min — the standard noise-robust wall-clock estimator).  The
  schema enforces the < 1% budget;
- **syncs** — the graph-lint ``syncs`` pass over the instrumented
  lanes (the serve engine's compiled decode step, which carries the
  ``serve/decode_step`` span, and the mlp O1/O2 train steps): zero
  host callbacks, zero static-scalar retrace hazards, zero errors.
  Instrumentation that costs a sync would fail here before it could
  be committed;
- **export** — a registry snapshot after an instrumented train + serve
  sample: pins the metric catalog and the JSON export shape reviewers
  and scrapers rely on;
- **tracing** (r02+) — the request-tracing lane (ISSUE 13): the
  per-event cost of :meth:`apex_tpu.obs.reqtrace.RequestTracer.record`
  and :meth:`apex_tpu.obs.flight.FlightRecorder.note` microbenched
  like the instrument cost, times the events a decode step records,
  gated at <= 1% of the measured bench-smoke decode step
  (schema-enforced like the instrument budget);
- **contprof** (r03+) — the continuous-profiler lane (ISSUE 15): the
  per-window capture+parse+sentinel cost of a REAL profiled serve
  session (:mod:`apex_tpu.obs.contprof`), amortized over the
  recorded ``capture_every`` at the windows' own measured step wall,
  gated <= 1% (schema-enforced, with the overhead re-derived from
  the recorded numbers); the syncs table gains the
  ``serve_step_contprof`` lane — the profiler-attached engine's
  compiled step must stay exactly as clean as the bare one.

Usage::

    python tools/obs_report.py --emit OBS_r01.json
    python tools/obs_report.py --quick          # fast smoke (tests)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import graph_lint  # noqa: E402  (sibling tool: sets platform/flags, lanes)

import jax  # noqa: E402

from apex_tpu.obs import metrics as obs_metrics  # noqa: E402

import chaos_run  # noqa: E402  (sibling tool: shared workload builder)


def measure_overhead(steps: int = 40, reps: int = 5, seed: int = 0,
                     calls: int = 2000) -> dict:
    """Instrumentation overhead at the CPU bench-smoke scale.

    The **gated number** (``overhead_pct``) is a deterministic
    decomposition: the per-call host cost of the full
    :func:`~apex_tpu.obs.metrics.instrument_step` path — dispatch
    histogram, counters, the deferred loss/overflow records, and the
    batched lag resolution fetching real device scalars — microbenched
    over ``calls`` invocations against a precomputed step output,
    divided by the measured bare step time.  On this class of shared
    2-vCPU host, end-to-end wall clock swings ±5-10% rep to rep
    (recorded below as ``bare_spread_s``), so a <1% budget can only
    be checked against a measurement whose own noise is well under
    1%; the microbench is exact to microseconds.

    The end-to-end comparison (order-balanced interleaved reps,
    min-to-min — the ``tools/chaos_run.py --overhead`` methodology) is
    still run and recorded as ``wall_check``: it bounds the true cost
    from above within the host's noise and would catch a pathological
    regression (an accidental per-step sync shows up as +50-500%, far
    over any noise)."""
    amp_obj, step_fn, state0, batch_fn = chaos_run.build_workload(
        seed, features=(256, 256), batch=256, d_in=256)
    del amp_obj
    batch = batch_fn(0)

    def bare():
        st = state0
        t0 = time.perf_counter()
        for _ in range(steps):
            st, m = step_fn(st, *batch)
        jax.block_until_ready(m["loss"])
        return time.perf_counter() - t0

    def instrumented():
        reg = obs_metrics.Registry()
        wrapped = obs_metrics.instrument_step(step_fn, registry=reg,
                                              name="train")
        st = state0
        t0 = time.perf_counter()
        for _ in range(steps):
            st, m = wrapped(st, *batch)
        jax.block_until_ready(m["loss"])
        reg.flush()
        return time.perf_counter() - t0

    bare(); instrumented()        # compile outside the timed region
    import gc
    bare_ts, inst_ts = [], []
    for rep in range(reps):
        # balanced order + a collected heap per rep: a fixed
        # bare-first order would bill GC pressure and noise epochs to
        # whichever loop runs second
        gc.collect()
        if rep % 2 == 0:
            bare_ts.append(bare())
            inst_ts.append(instrumented())
        else:
            inst_ts.append(instrumented())
            bare_ts.append(bare())
    bare_t, inst_t = min(bare_ts), min(inst_ts)

    # -- the deterministic per-step instrumentation cost --------------
    out = step_fn(state0, *batch)
    jax.block_until_ready(out[1]["loss"])

    def precomputed_step(st, *a):
        return out

    reg = obs_metrics.Registry()
    wrapped = obs_metrics.instrument_step(precomputed_step,
                                          registry=reg, name="train")
    wrapped(state0, *batch)       # instrument creation outside timing
    t0 = time.perf_counter()
    for _ in range(calls):
        wrapped(state0, *batch)
    reg.flush()
    inst_us = (time.perf_counter() - t0) / calls * 1e6

    bare_ms = bare_t / steps * 1e3
    return {
        "scale": "bench-smoke (MLP 256x256, batch 256, amp O2)",
        "method": "per-step instrument path microbenched over "
                  f"{calls} calls (incl. batched lag resolution of "
                  "device scalars) / measured bare step time; wall "
                  "check: order-balanced interleaved reps, min-to-min",
        "steps": steps, "reps": reps,
        "bare_s": round(bare_t, 4),
        "instrumented_s": round(inst_t, 4),
        "bare_spread_s": [round(t, 4) for t in sorted(bare_ts)],
        "bare_ms_per_step": round(bare_ms, 3),
        "instrument_us_per_step": round(inst_us, 3),
        "overhead_pct": round(100.0 * inst_us / (bare_ms * 1e3), 3),
        "wall_check": {
            "instrumented_ms_per_step": round(inst_t / steps * 1e3, 3),
            "wall_overhead_pct": round(
                100.0 * (inst_t - bare_t) / bare_t, 3),
            "note": "noise-bounded upper check, not the gated number "
                    "(host wall spread exceeds the 1% budget)"},
    }


def measure_trace_overhead(calls: int = 20000,
                           quick: bool = False) -> dict:
    """The request-tracing lane: per-event record cost (microbenched —
    exact to fractions of a microsecond, the same reasoning as the
    instrument-cost gate: the budget is 1% and this host's wall noise
    is 5-10%) against the measured bench-smoke decode step.

    TWO density lanes, gated on the WORSE one: the plain decode step
    records one ``decode_step`` event per active slot (+ one flight
    note), and the speculative engine's round — the densest in-tree
    pattern — records ``spec_draft`` + ``spec_verify`` per active
    slot (+ retire + a flight note) against its own measured
    draft+verify round wall.  ``overhead_pct`` is
    ``max(decode lane, spec lane)``."""
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_tiny
    from apex_tpu.obs.flight import FlightRecorder
    from apex_tpu.obs.reqtrace import RequestTracer
    from apex_tpu.serve import (Request, ServeConfig, ServeEngine,
                                SpecConfig, SpecEngine,
                                truncated_draft)

    # -- per-event record cost (tracer + flight ring) ------------------
    tracer = RequestTracer()
    tracer.record("enqueue", "bench", "router")
    t0 = time.perf_counter()
    for i in range(calls):
        tracer.record("decode_step", "bench", "replica0", step=i,
                      token=7, batch=4, tokens=1)
    per_event_us = (time.perf_counter() - t0) / calls * 1e6
    flight = FlightRecorder(capacity=256)
    t0 = time.perf_counter()
    for i in range(calls):
        flight.note("step", step=i, loss=0.5)
    flight_note_us = (time.perf_counter() - t0) / calls * 1e6

    # -- the bench-smoke decode step the budget is a fraction of ------
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    num_slots = 4
    scfg = ServeConfig(num_slots=num_slots, block_size=4,
                       num_blocks=num_slots * 8 + 1,
                       max_blocks_per_slot=8, prefill_chunk=8)
    import numpy as np
    rng = np.random.RandomState(0)
    budget = 8 if quick else 16

    def drive(e):
        for i in range(num_slots):
            e.submit(Request(
                uid=f"s{i}",
                prompt=rng.randint(0, cfg.vocab_size, (8,)),
                max_new_tokens=budget))
        e.step()                      # admission + compile + 1st step
        hist = e.metrics.histogram("serve_decode_step_seconds")
        mark = hist.state()
        while not e.sched.idle():
            e.step()
        return hist.quantile(0.5, since=mark) * 1e3

    decode_step_ms = drive(
        ServeEngine(params, cfg, scfg,
                    registry=obs_metrics.Registry()))
    # the spec engine's round (one draft + one verify dispatch) is the
    # densest record pattern in tree: 2 events per active slot; its
    # denominator is its OWN measured round wall, not the plain step's
    dp, dcfg = truncated_draft(params, cfg, 1)
    spec_round_ms = drive(
        SpecEngine(params, cfg, scfg, dp, dcfg, SpecConfig(k=2),
                   registry=obs_metrics.Registry()))

    worst_event_us = max(per_event_us, flight_note_us)
    events_per_step = num_slots + 1   # per-slot attribution + 1 note
    decode_pct = 100.0 * events_per_step * worst_event_us \
        / (decode_step_ms * 1e3)
    spec_events_per_step = 2 * num_slots + 2   # draft+verify per slot
    spec_pct = 100.0 * spec_events_per_step * worst_event_us \
        / (spec_round_ms * 1e3)
    return {
        "method": "RequestTracer.record / FlightRecorder.note "
                  f"microbenched over {calls} calls; denominators = "
                  "steady-state p50 of the smoke engines' plain "
                  "decode step and spec draft+verify round (compiles "
                  "windowed out); overhead_pct = worse lane",
        "calls": calls,
        "per_event_us": round(per_event_us, 3),
        "flight_note_us": round(flight_note_us, 3),
        "events_per_step": events_per_step,
        "decode_step_ms": round(decode_step_ms, 3),
        "decode_overhead_pct": round(decode_pct, 3),
        "spec_events_per_step": spec_events_per_step,
        "spec_round_ms": round(spec_round_ms, 3),
        "spec_overhead_pct": round(spec_pct, 3),
        "overhead_pct": round(max(decode_pct, spec_pct), 3),
    }


def measure_contprof_overhead(quick: bool = False) -> dict:
    """The continuous-profiler lane (ISSUE 15): per-window cost —
    capture (trace start/stop + flush) + parse (xplane → buckets) +
    sentinel (band rule + K-machine) — measured on a REAL profiled
    serve session, amortized over the inter-capture interval.  The
    recorded ``capture_every`` is the smallest cadence that keeps the
    amortized cost under the 1% budget at the measured step wall
    (exactly the fixed point ``ContProfConfig.max_overhead_pct``'s
    auto-throttle converges to in production), and ``overhead_pct``
    re-derives from the recorded numbers (schema-enforced)."""
    import math

    import numpy as np

    from apex_tpu.analysis.obs import CONTPROF_BUDGET_PCT
    from apex_tpu.obs import contprof
    from apex_tpu.serve import Request

    num_slots = 4
    reg = obs_metrics.Registry()
    # the ONE shared serve-engine construction (graph_lint's) at the
    # profile geometry tools/continuous_profile.py measures with
    eng, _ = graph_lint.build_serve_engine(
        num_slots=num_slots, block_size=16,
        num_blocks=num_slots * 8 + 1, max_blocks_per_slot=8,
        prefill_chunk=16, registry=reg)
    cfg = eng.cfg
    sent = contprof.DriftSentinel(band=0.12, k=2, registry=reg)
    n_windows = 2 if quick else 4
    every = 8
    pcfg = contprof.ContProfConfig(
        capture_every=every, capture_steps=4, warmup_steps=2,
        max_overhead_pct=None, max_windows=n_windows)
    prof = contprof.serve_profiler(eng, config=pcfg, sentinel=sent)
    rng = np.random.RandomState(0)
    budget = pcfg.warmup_steps + n_windows * every \
        + pcfg.capture_steps + 4
    for i in range(num_slots):
        eng.submit(Request(uid=f"s{i}",
                           prompt=rng.randint(0, cfg.vocab_size, (8,)),
                           max_new_tokens=budget + 8))
    for _ in range(budget):
        eng.step()
        if len(prof.windows) >= n_windows and not prof.in_window:
            break
    prof.abort_window()

    if not prof.windows:
        raise RuntimeError(
            f"contprof overhead lane captured no clean windows "
            f"({len(prof.discarded)} discarded, "
            f"{prof.skipped_windows} skipped — a leftover profiler "
            f"holding the process-global capture lock?); cannot "
            f"measure a window cost")
    # steady-state per-window cost: window 0 pays the one-time
    # classifier build (lower+compile, recorded separately); the
    # amortized production cost is the later windows'
    steady = prof.windows[1:] or prof.windows
    mean = lambda key: sum(w.get(key, 0.0) for w in steady) \
        / max(len(steady), 1)
    capture_s = round(mean("capture_s"), 4)
    parse_s = round(mean("parse_s"), 4)
    sentinel_s = round(mean("sentinel_s"), 4)
    cost_s = round(capture_s + parse_s + sentinel_s, 4)
    step_wall_ms = round(mean("step_wall_s") * 1e3, 3)
    # the budget-holding cadence at this window cost and step wall —
    # the auto-throttle's fixed point
    ce = max(1, int(math.ceil(
        100.0 * cost_s / (CONTPROF_BUDGET_PCT * step_wall_ms / 1e3))))
    overhead_pct = round(100.0 * cost_s / (ce * step_wall_ms / 1e3), 3)
    return {
        "method": "real profiled serve session (jax.profiler capture "
                  "windows on the live engine's decode dispatches); "
                  "steady per-window capture/parse/sentinel cost, "
                  "amortized over the recorded capture_every at the "
                  "windows' own measured step wall; capture_every = "
                  "the smallest cadence holding the budget (the "
                  "ContProfConfig.max_overhead_pct auto-throttle's "
                  "fixed point)",
        "windows": len(prof.windows),
        "capture_steps": pcfg.capture_steps,
        "capture_s": capture_s,
        "parse_s": parse_s,
        "sentinel_s": sentinel_s,
        "window_cost_s": cost_s,
        "classifier_build_s": round(prof.classifier_build_s, 4),
        "step_wall_ms": step_wall_ms,
        "capture_every": ce,
        "overhead_pct": overhead_pct,
        "drifts": len(sent.drifts),
        "excluded_steps": int(reg.histogram(
            "serve_profiled_step_seconds").count),
    }


def syncs_evidence(include_trains: bool = True) -> dict:
    """The graph-lint ``syncs`` pass over the INSTRUMENTED lanes: the
    serve engine's compiled decode step (span-carrying body) and the
    mlp O1/O2 train lanes.  Returns the OBS ``syncs`` section."""
    lanes = {}

    def record(name, report):
        syncs = report.by_pass("syncs")
        lanes[name] = {
            "host_callbacks": sum(1 for f in syncs
                                  if f.op == "host-callback"),
            "static_scalars": sum(1 for f in syncs
                                  if f.op == "static-scalar"),
            "errors": len(report.errors),
            "findings": len(syncs),
        }

    record("serve_step",
           graph_lint.lint_serve("serve_step", passes=("syncs",)))
    record("serve_step_contprof", _lint_contprof_serve())
    if include_trains:
        for opt_level in ("O1", "O2"):
            record(f"mlp_{opt_level.lower()}_train",
                   graph_lint.lint_family("mlp", passes=("syncs",),
                                          opt_level=opt_level))
    clean = all(v["host_callbacks"] == 0 and v["static_scalars"] == 0
                and v["errors"] == 0 for v in lanes.values())
    return {"clean": bool(clean), "lanes": lanes,
            "pass": "analysis/syncs.py (host callbacks, infeed/"
                    "outfeed, static-scalar retrace hazards)"}


def _lint_contprof_serve():
    """The syncs pass over the CONTPROF-INSTRUMENTED serve lane: an
    engine with a live profiler + sentinel attached, its compiled
    decode step linted exactly like graph_lint's serve lane.  The
    profiler is strictly host-side (capture windows around the
    dispatch, never inside it), so the lane must stay clean — this
    lane is the machine check."""
    from apex_tpu.obs import contprof

    reg = obs_metrics.Registry()
    # graph_lint's serve lane engine, with the profiler attached: the
    # same construction AND the same args tuple the gated lane lints
    eng, props = graph_lint.build_serve_engine(registry=reg)
    contprof.serve_profiler(
        eng, config=contprof.ContProfConfig(capture_every=8,
                                            capture_steps=2),
        sentinel=contprof.DriftSentinel(k=2, registry=reg))
    return graph_lint._lint_serve_program(
        "serve_step_contprof", eng._decode_step,
        eng.decode_step_args(), props, ("syncs",), True, None, None)


def export_sample(quick: bool = False) -> dict:
    """Populate a fresh registry with an instrumented train + serve
    sample and export it — the committed metric-catalog snapshot."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTModel, gpt_tiny
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    reg = obs_metrics.Registry()
    # train sample: a few instrumented steps (tiny workload)
    _, step_fn, state, batch_fn = chaos_run.build_workload(0)
    wrapped = obs_metrics.instrument_step(step_fn, registry=reg)
    for i in range(4):
        state, _m = wrapped(state, *batch_fn(i))
    reg.flush()

    # serve sample: a short mixed stream through a tiny engine
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=9,
                       max_blocks_per_slot=4, prefill_chunk=4)
    eng = ServeEngine(params, cfg, scfg, registry=reg)
    rng = np.random.RandomState(0)
    n_req = 2 if quick else 3
    for i in range(n_req):
        eng.submit(Request(uid=f"s{i}",
                           prompt=rng.randint(0, cfg.vocab_size, (5,)),
                           max_new_tokens=4))
    eng.run()
    reg.flush()
    return reg.snapshot()


def build_doc(steps: int, reps: int, quick: bool) -> dict:
    return {
        "round": 1,
        "platform": jax.devices()[0].platform,
        "overhead": measure_overhead(steps=steps, reps=reps),
        "syncs": syncs_evidence(include_trains=not quick),
        "tracing": measure_trace_overhead(
            calls=2000 if quick else 20000, quick=quick),
        "contprof": measure_contprof_overhead(quick=quick),
        "export": export_sample(quick=quick),
        "note": (
            "Telemetry-layer acceptance evidence: instrumentation "
            "overhead under the 1% budget (schema-enforced), the "
            "syncs pass clean over the instrumented serve + train "
            "lanes INCLUDING the contprof-attached serve lane "
            "(schema-enforced), the request-tracing per-event "
            "cost under the 1% decode-step budget (schema-enforced, "
            "r02+), the continuous profiler's amortized window cost "
            "under the 1% budget at its recorded cadence "
            "(schema-enforced, r03+), and the registry export "
            "snapshot pinning the metric catalog.  Regenerate with "
            "tools/obs_report.py --emit OBS_rN.json on a quiet "
            "host."),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--quick", action="store_true",
                    help="smaller everything (smoke/tests); not for "
                         "committed artifacts")
    ap.add_argument("--emit", default=None, metavar="OBS_rN.json",
                    help="write the committed artifact (validated "
                         "against apex_tpu/analysis/obs.py; refuses "
                         "an invalid document)")
    opts = ap.parse_args(argv)
    if opts.quick:
        opts.steps, opts.reps = 20, 2

    doc = build_doc(opts.steps, opts.reps, opts.quick)
    if opts.emit:
        m = re.search(r"_r(\d+)\.json$", os.path.basename(opts.emit))
        if m:
            doc["round"] = int(m.group(1))
        from apex_tpu.analysis import obs as schema
        problems = schema.validate_obs(doc)
        if problems:
            print(f"refusing to write {opts.emit}: {problems}",
                  file=sys.stderr)
            return 1
        with open(opts.emit, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"obs artifact written: {opts.emit}", file=sys.stderr)
    else:
        print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
