"""Elastic-fleet chaos drill: kill a real rank mid-training, require
bitwise recovery, emit the ``TRAINFLEET_r*.json`` gate artifact.

The drill (all real processes, CPU + gloo collectives):

1. launch a 2-rank fleet of per-rank supervisors
   (``python -m apex_tpu.resilience.fleet --role supervisor``), each of
   which spawns a generation child running DDP + amp-O2 training under
   :func:`apex_tpu.resilience.run_resilient`;
2. a scheduled :class:`~apex_tpu.resilience.faults.RankKill` SIGKILLs
   one rank (child AND supervisor — the heartbeat lease must actually
   go stale) mid-training;
3. the survivor detects the stale lease within the bounded window,
   ends its generation, re-plans onto the smaller mesh, restores the
   last durable step and continues;
4. once the shrunken generation has committed a snapshot of its own,
   the harness relaunches the killed rank's supervisor; its fresh
   lease is the regrow signal — the fleet re-plans back to full size
   and runs to completion.

The artifact's verdicts are **re-derivable**: bitwise claims are made
by *replaying* the post-restore schedules through the SAME child code
path (fresh ledger, synthetic plan, the drill's own seed snapshot) and
comparing sha256 state digests —

- **shrink bitwise**: an uninterrupted 1-rank run of the post-kill
  schedule (restore step → the shrunken generation's last durable
  step) must digest-match the drill's own snapshot at that step;
- **regrow bitwise**: an uninterrupted 2-rank run of the post-regrow
  schedule must digest-match the drill's finals on every rank;
- **cross-rank bitwise**: the drill's two final digests must agree.

``apex_tpu/analysis/trainfleet.py`` validates the committed artifact
and REJECTS contradictions: every stored verdict (steps-lost bound,
bitwise flags, gate.ok) is recomputed from the recorded event log and
digests, and a mismatch fails tier-1 via ``tools/gate_hygiene.py``.

Usage::

    python tools/train_fleet.py --out TRAINFLEET_r01.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from apex_tpu.resilience.fleet import (  # noqa: E402
    EXIT_MEMBERSHIP, FleetConfig, FleetLedger, latest_verified_step,
    snapshot_digest)

#: signal-death codes the harness expects from the killed rank's
#: supervisor (negative = POSIX signal via subprocess)
_KILLED = (-9,)


class DrillError(RuntimeError):
    pass


def _env() -> dict:
    env = dict(os.environ)
    # the drill forms its own process mesh: the single-process test
    # launcher's virtual-device flags and any ambient cluster config
    # must not leak into supervisors or their children
    for var in ("XLA_FLAGS", "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK"):
        env.pop(var, None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _launch_supervisor(root: str, rank: int) -> subprocess.Popen:
    log = open(os.path.join(root, "logs", f"supervisor_r{rank}.log"), "w")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "apex_tpu.resilience.fleet",
             "--role", "supervisor", "--ledger", root,
             "--rank", str(rank)],
            stdout=log, stderr=subprocess.STDOUT, env=_env())
    finally:
        log.close()     # the child holds its own fd


def _wait_for(pred, timeout_s: float, what: str, poll_s: float = 0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        val = pred()
        if val:
            return val
        time.sleep(poll_s)  # preds rescan events/: don't peg a core
    raise DrillError(f"timed out after {timeout_s:g}s waiting for {what}")


def _drain(procs: Dict[int, subprocess.Popen], timeout_s: float,
           what: str) -> Dict[int, int]:
    deadline = time.monotonic() + timeout_s
    codes: Dict[int, int] = {}
    while len(codes) < len(procs):
        for r, p in procs.items():
            if r not in codes and p.poll() is not None:
                codes[r] = p.returncode
        if time.monotonic() > deadline:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
            raise DrillError(
                f"timed out after {timeout_s:g}s waiting for {what} "
                f"(codes so far: {codes})")
        time.sleep(0.1)
    return codes


def _seed_replay_root(tag: str, base: str, drill_ckpt: str,
                      seed_step: int) -> str:
    """A fresh ledger root whose ckpt/ holds EXACTLY the drill's
    snapshot at ``seed_step`` — so the replay supervisors' initial
    plan restores that step and nothing else."""
    from apex_tpu.resilience.durable import _step_dirname
    root = os.path.join(base, f"replay_{tag}")
    ledger = FleetLedger(root)     # creates the layout
    src = os.path.join(drill_ckpt, _step_dirname(seed_step))
    if not os.path.isdir(src):
        raise DrillError(f"replay {tag}: drill has no snapshot at step "
                         f"{seed_step} to seed from")
    shutil.copytree(src, os.path.join(ledger.ckpt_dir,
                                      _step_dirname(seed_step)))
    return root


def _run_replay(tag: str, base: str, drill_cfg: FleetConfig,
                drill_ckpt: str, seed_step: int, world: int,
                num_steps: int, timeout_s: float) -> dict:
    """Run an UNINTERRUPTED fleet of ``world`` ranks from the drill's
    own snapshot at ``seed_step`` through ``num_steps`` total steps —
    the same supervisor→child→``run_resilient`` path as the drill, with
    no faults — and return its finals + event skeleton."""
    root = _seed_replay_root(tag, base, drill_ckpt, seed_step)
    ledger = FleetLedger(root)
    # no faults, no pacing: the throttle is pure wall time (a host
    # sleep in batch_fn), so dropping it cannot change the math the
    # replay exists to reproduce bit-for-bit
    cfg = dataclasses.replace(drill_cfg, world_size=world,
                              num_steps=num_steps, faults=(),
                              step_delay_s=0.0)
    ledger.write_config(cfg)
    procs = {r: _launch_supervisor(root, r) for r in range(world)}
    codes = _drain(procs, timeout_s, f"replay {tag} supervisors")
    if any(c != 0 for c in codes.values()):
        tails = {r: _log_tail(root, r) for r in codes}
        raise DrillError(f"replay {tag}: supervisor exit codes {codes}; "
                         f"log tails: {tails}")
    finals = ledger.finals()
    if sorted(finals) != list(range(world)):
        raise DrillError(f"replay {tag}: finals missing ranks "
                         f"(got {sorted(finals)})")
    plan0 = ledger.read_plan(0)
    return {
        "tag": tag, "world": world, "restore_step": seed_step,
        "final_step": num_steps - 1,
        "finals": {str(r): {"step": f["step"], "digest": f["digest"]}
                   for r, f in finals.items()},
        "plan_restore_step": plan0.get("restore_step") if plan0 else None,
        "root": root,
    }


def _log_tail(root: str, rank: int, limit: int = 800) -> str:
    try:
        with open(os.path.join(root, "logs",
                               f"supervisor_r{rank}.log"),
                  errors="replace") as f:
            return f.read()[-limit:]
    except OSError:
        return "<no log>"


def run_drill(args) -> dict:
    base = args.root or tempfile.mkdtemp(prefix="apex_tpu_fleet_")
    root = os.path.join(base, "drill")
    ledger = FleetLedger(root)
    cfg = FleetConfig(
        num_steps=args.steps, checkpoint_every=args.checkpoint_every,
        world_size=2, seed=args.seed,
        lease_ttl_s=args.lease_ttl, heartbeat_s=args.heartbeat,
        step_delay_s=args.step_delay,
        faults=(f"rank_kill@{args.kill_step}:{args.kill_rank}",))
    ledger.write_config(cfg)
    t_start = time.time()

    procs = {r: _launch_supervisor(root, r) for r in range(2)}
    try:
        # 1. the kill: the doomed rank writes its forensic event and
        #    SIGKILLs child + supervisor
        _wait_for(lambda: [e for e in ledger.events()
                           if e["kind"] == "kill"],
                  args.timeout, "the scheduled rank kill")
        _wait_for(lambda: procs[args.kill_rank].poll() is not None,
                  30.0, "the killed supervisor to die")
        kill_code = procs[args.kill_rank].returncode
        if kill_code not in _KILLED:
            raise DrillError(f"killed rank's supervisor exited {kill_code},"
                             " expected SIGKILL death")

        # 2. shrink: the survivor replans (gen >= 1) and the shrunken
        #    generation commits durable progress of its own — only then
        #    is the regrow bitwise gate non-trivial
        def _shrunk():
            if ledger.finals():
                raise DrillError(
                    "the shrunken generation finished before the killed "
                    "rank could be relaunched — raise --step-delay so a "
                    "generation outlives the rejoin latency")
            plan = ledger.latest_plan()
            if plan is None or int(plan["gen"]) < 1:
                return None
            restore = plan.get("restore_step")
            latest = latest_verified_step(ledger.ckpt_dir)
            if latest is None or restore is None:
                return None
            return plan if latest > int(restore) else None

        plan1 = _wait_for(_shrunk, args.timeout,
                          "the shrunken generation to commit a snapshot")

        # 3. regrow: relaunch the killed rank's supervisor; its fresh
        #    heartbeat is the regrow signal
        procs[args.kill_rank] = _launch_supervisor(root, args.kill_rank)
        codes = _drain(procs, args.timeout, "the regrown fleet to finish")
        if any(c != 0 for c in codes.values()):
            tails = {r: _log_tail(root, r) for r in codes}
            raise DrillError(f"supervisor exit codes {codes}; "
                             f"log tails: {tails}")
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()

    wall_s = time.time() - t_start
    events = ledger.events()
    finals = ledger.finals()
    plans = []
    g = 0
    while True:
        plan = ledger.read_plan(g)
        if plan is None:
            break
        plans.append(plan)
        g += 1
    if len(plans) < 3:
        raise DrillError(f"expected >= 3 generations (initial/shrink/"
                         f"regrow), got {len(plans)}")
    if sorted(finals) != [0, 1]:
        raise DrillError(f"finals missing ranks (got {sorted(finals)})")

    kill_events = [e for e in events if e["kind"] == "kill"]
    snapshots = {}
    from apex_tpu.resilience.durable import _STEP_PREFIX
    for name in sorted(os.listdir(ledger.ckpt_dir)):
        if name.startswith(_STEP_PREFIX):
            step = int(name[len(_STEP_PREFIX):])
            snapshots[str(step)] = snapshot_digest(ledger.ckpt_dir, step)

    incidents = []
    inc_dir = ledger.path("incidents")
    for name in sorted(os.listdir(inc_dir)):
        if name.endswith(".json"):
            with open(os.path.join(inc_dir, name)) as f:
                incidents.append(json.load(f))

    return {
        "base": base, "root": root, "cfg": cfg, "wall_s": wall_s,
        "events": events, "finals": finals, "plans": plans,
        "kill_events": kill_events, "snapshots": snapshots,
        "incidents": incidents, "plan1": plan1,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--checkpoint-every", type=int, default=4)
    ap.add_argument("--kill-step", type=int, default=10)
    ap.add_argument("--kill-rank", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lease-ttl", type=float, default=2.0)
    ap.add_argument("--heartbeat", type=float, default=0.25)
    ap.add_argument("--step-delay", type=float, default=0.75,
                    help="host sleep per drill step: paces the toy CPU "
                    "workload so a relaunched rank can rejoin a LIVE "
                    "generation (replays run unthrottled)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="per-phase wall budget (kill/shrink/finish)")
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument("--root", default=None,
                    help="working dir (default: fresh tempdir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the working dir for forensics")
    ap.add_argument("--out", default="TRAINFLEET_r01.json")
    args = ap.parse_args(argv)

    from apex_tpu.analysis.trainfleet import validate_trainfleet
    from apex_tpu.resilience.incidents import utc_now

    drill = run_drill(args)
    cfg: FleetConfig = drill["cfg"]
    plans = drill["plans"]
    plan1, plan2 = plans[1], plans[2]
    s1 = int(plan1["restore_step"])      # shrink restore (pre-kill)
    s2 = int(plan2["restore_step"])      # regrow restore (gen-1 progress)
    kill_step = int(drill["kill_events"][0]["step"])

    # -- the replay-based bitwise gates ---------------------------------
    replay_shrink = _run_replay(
        "shrink", drill["base"], cfg, FleetLedger(drill["root"]).ckpt_dir,
        seed_step=s1, world=len(plan1["members"]), num_steps=s2 + 1,
        timeout_s=args.timeout)
    replay_regrow = _run_replay(
        "regrow", drill["base"], cfg, FleetLedger(drill["root"]).ckpt_dir,
        seed_step=s2, world=len(plan2["members"]), num_steps=cfg.num_steps,
        timeout_s=args.timeout)

    finals = {str(r): {"step": f["step"], "digest": f["digest"]}
              for r, f in drill["finals"].items()}
    shrink_digest = replay_shrink["finals"]["0"]["digest"]
    bitwise = {
        # uninterrupted 1-rank replay of the post-kill schedule lands
        # bit-identical to the drill's own durable snapshot at s2
        "shrink_matches_uninterrupted":
            shrink_digest == drill["snapshots"].get(str(s2)),
        # uninterrupted 2-rank replay of the post-regrow schedule lands
        # bit-identical to the drill's finals, rank by rank
        "regrow_matches_uninterrupted": all(
            replay_regrow["finals"][r]["digest"] == finals[r]["digest"]
            for r in finals),
        "final_cross_rank_identical":
            len({f["digest"] for f in finals.values()}) == 1,
    }

    doc = {
        "artifact": "TRAINFLEET",
        "round": args.round,
        "generated_utc": utc_now(),
        "platform": "cpu",
        "harness": "tools/train_fleet.py -> apex_tpu.resilience.fleet",
        "config": {
            "num_steps": cfg.num_steps,
            "checkpoint_every": cfg.checkpoint_every,
            "world_size": cfg.world_size,
            "seed": cfg.seed,
            "lease_ttl_s": cfg.lease_ttl_s,
            "heartbeat_s": cfg.heartbeat_s,
            "faults": list(cfg.faults),
        },
        "wall_s": round(drill["wall_s"], 3),
        "events": drill["events"],
        "generations": [
            {"gen": int(p["gen"]),
             "members": [int(r) for r in p["members"]],
             "restore_step": p.get("restore_step"),
             "reason": p["reason"], "created_by": int(p["created_by"])}
            for p in plans],
        "recoveries": [
            {"generation": int(plan1["gen"]),
             "reason": "shrink",
             "interrupted_step": kill_step,
             "restore_step": s1,
             "steps_lost": kill_step - s1,
             "ranks": sorted(set([int(r) for r in plans[0]["members"]])
                             - set([int(r) for r in plan1["members"]]))},
            {"generation": int(plan2["gen"]),
             "reason": "regrow",
             "interrupted_step": None,
             "restore_step": s2,
             "steps_lost": 0,
             "ranks": sorted(set([int(r) for r in plan2["members"]])
                             - set([int(r) for r in plan1["members"]]))},
        ],
        "snapshots": drill["snapshots"],
        "finals": finals,
        "replays": {
            "shrink": {k: replay_shrink[k] for k in
                       ("world", "restore_step", "final_step", "finals")},
            "regrow": {k: replay_regrow[k] for k in
                       ("world", "restore_step", "final_step", "finals")},
        },
        "bitwise": bitwise,
        "incidents": drill["incidents"],
        "gate": {
            "ok": all(bitwise.values()),
            "criteria": [
                "rank killed mid-training (real SIGKILL, supervisor too)",
                "survivor shrank within the lease window and restored "
                "the last durable step",
                "steps lost <= checkpoint interval",
                "shrunken run bitwise-equal to uninterrupted same-"
                "schedule run",
                "fleet regrew on rank return and finished bitwise-"
                "identical on every rank",
            ],
        },
    }

    problems = validate_trainfleet(doc)
    if problems:
        print(json.dumps({"ok": False, "problems": problems}, indent=1))
        return 1

    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.out)

    if not args.keep and args.root is None:
        shutil.rmtree(drill["base"], ignore_errors=True)

    print(json.dumps({
        "ok": doc["gate"]["ok"], "out": args.out,
        "wall_s": doc["wall_s"],
        "kill_step": kill_step, "shrink_restore": s1,
        "regrow_restore": s2,
        "steps_lost": kill_step - s1,
        "generations": len(plans), "bitwise": bitwise,
    }))
    return 0 if doc["gate"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
