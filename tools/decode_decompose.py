"""Decompose the b8 decode step — explain the 0.43-of-ceiling number
(VERDICT r5 #6) with device-time buckets the way D64_DECOMPOSE did for
the train step.

Decode is HBM-bandwidth-bound, so under the roofline model a byte
accounting IS a device-time accounting: bucket every byte of the decode
step's HBM traffic and you have bucketed the step.  This tool walks the
lowered StableHLO of the EXACT bench program
(``apex_tpu.models.generate._generate_impl`` at gpt_small_tpu b8,
prefill 2048, 256 new tokens — lowered from ShapeDtypeStructs, nothing
is initialized or run) and classifies every op of the per-token step
function (layer-loop trip counts applied, private calls expanded) into:

- ``param_read``   — weight reads: per-layer projection/FFN slices,
  lm_head, final LN, the embedding-row gather
- ``kv_read``      — the cache-slice operands of the attention dots
  (the K and V reads of every layer)
- ``kv_write``     — the two per-layer ``dynamic_update_slice`` token
  writes (in-place on the loop carry: update bytes ×2)
- ``attention``    — the score/output dots' non-cache traffic and the
  fp32 softmax chain
- ``sampling``     — the argmax/top-k epilogue over ``(B, V)`` logits
- ``host_sync``    — host callbacks on the token loop (count; must be
  0 bytes — the loop is a device-side ``lax.scan``)
- ``other``        — rope tables, layernorm stats, residual adds

Conventions (stated in the artifact): element-wise/reshape/convert ops
are counted FUSED (result bytes only, or zero for pure layout ops) —
the walk models the roofline-ideal step.  The ops XLA *could* fail to
fuse (the per-layer cache-slice copies, the bf16→f32 cache converts)
are recorded separately as **materialization candidates** with their
would-be volumes.  Headline (r01): the measured step (committed r05
ladder: 3004 tok/s b8 = 2.66 ms/step = 2.18 GB at 819 GB/s) carries
~1.5× the walk-modeled ideal (1.47 GB) — so the bench's 0.43
``hbm_frac`` (bench byte model 0.95 GB / measured 2.18 GB) is mostly
the bench CEILING MODEL undercounting required traffic, plus a real
~0.7 GB residual that matches the per-layer KV slice-copy candidate
within 5%.  The serve engine's KV choices act on that residual —
``preferred_element_type`` attention (kills the materialized f32
K-cache cast; also applied to ``generate._attn_cached``) and the
paged pool's layer-leading layout.

The committed ``DECODE_DECOMPOSE_r01.json`` is schema-validated by
``tools/gate_hygiene.py`` against
``apex_tpu/analysis/decode_decompose.py`` (stdlib-only), which
enforces the >= 90% named-bucket coverage bar.

Usage:
    python tools/decode_decompose.py [--batch 8] [--prefill 2048]
        [--new-tokens 256] [--tiny] [--no-compile]
        [--emit DECODE_DECOMPOSE_r01.json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

from apex_tpu.analysis import dflow  # noqa: E402

_ELEM_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "i1": 1,
               "i8": 1, "ui8": 1, "i16": 2, "ui16": 2, "i32": 4,
               "ui32": 4, "i64": 8, "ui64": 8}

_CALLEE = re.compile(r"@([\w$.-]+)")

#: host-round-trip custom-call targets (the syncs-pass list)
_CALLBACK = ("python_cpu_callback", "python_gpu_callback",
             "python_tpu_callback", "tpu_host_callback")


def _nbytes(payload: str) -> int:
    dims = dflow.dims_of(payload)
    et = dflow.element_type(payload)
    return int(math.prod(dims)) * _ELEM_BYTES.get(et, 4) if dims \
        else _ELEM_BYTES.get(et, 4)


def lower_decode(batch: int, prefill: int, new_tokens: int,
                 tiny: bool = False):
    """AOT-lower the exact bench decode program from ShapeDtypeStructs
    (bf16 serving layout) — no params materialize, nothing runs.
    Returns ``(lowered, cfg)``."""
    from importlib import import_module

    gen = import_module("apex_tpu.models.generate")
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    prompt = jax.ShapeDtypeStruct((batch, prefill), jnp.int32)
    params = jax.eval_shape(lambda k, p: model.init(k, p)["params"],
                            jax.random.PRNGKey(0), prompt)
    params = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, jnp.bfloat16 if jnp.issubdtype(s.dtype, jnp.floating)
            else s.dtype), params)
    blocks = [params[f"block_{i}"] for i in range(cfg.num_layers)]
    stacked = jax.tree.map(
        lambda *xs: jax.ShapeDtypeStruct((len(xs),) + xs[0].shape,
                                         xs[0].dtype), *blocks)
    top = {k: v for k, v in params.items() if not k.startswith("block_")}
    lowered = gen._generate_impl.lower(
        top, stacked, prompt, jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((2,), jnp.uint32), cfg=cfg,
        max_new_tokens=new_tokens, sample=False)
    return lowered, cfg


def find_step_funcs(funcs, cache_dims):
    """``(step_fn_name, layer_fn_name)``: among the private functions
    carrying both full caches as args, the decode STEP is the one whose
    (layer-loop) body calls another cache-carrying function — that
    callee is the per-layer block.  Fails loudly rather than bucketing
    the wrong program."""
    carriers = [name for name, f in funcs.items()
                if sum(1 for _t, p in f.args
                       if dflow.dims_of(p) == cache_dims) >= 2]
    for name in carriers:
        for op in funcs[name].ops:
            if op.name != "call":
                continue
            m = _CALLEE.search(op.line)
            if m and m.group(1) in carriers and m.group(1) != name:
                return name, m.group(1)
    raise RuntimeError(
        f"could not identify the decode step function among cache "
        f"carriers {carriers} — the lowering layout changed; update "
        f"find_step_funcs")


class Walk:
    """Bucketed byte accounting of the per-token decode step (see the
    module docstring for the conventions)."""

    def __init__(self, funcs, cfg, batch, m_ctx, vocab):
        self.funcs = funcs
        self.L = cfg.num_layers
        self.cache_dims = (cfg.num_layers, batch, m_ctx, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads)
        self.m_ctx = m_ctx
        self.vocab = vocab
        self.slice_elems = int(math.prod(self.cache_dims[1:]))
        self.buckets = {k: 0.0 for k in
                        ("param_read", "kv_read", "kv_write",
                         "attention", "sampling", "host_sync", "other")}
        self.host_sync_count = 0
        self.candidates = []      # (label, would_be_bytes, count)

    def _is_cache(self, payload):
        return dflow.dims_of(payload) == self.cache_dims

    def _is_cache_slice(self, payload):
        dims = dflow.dims_of(payload)
        return (self.m_ctx in dims
                and int(math.prod(dims)) >= self.slice_elems)

    def _has_vocab(self, op):
        return any(self.vocab in dflow.dims_of(t) for t in op.types)

    def _add(self, bucket, nbytes, mult):
        self.buckets[bucket] += nbytes * mult

    def _candidate(self, label, nbytes, mult):
        self.candidates.append((label, int(nbytes * mult)))

    def run(self, step_fn, layer_fn):
        self._walk(step_fn, mult=1, layer_mult=self.L,
                   layer_fn=layer_fn)

    def _walk(self, fname, mult, layer_mult=1, layer_fn=None,
              depth_guard=0):
        if depth_guard > 6 or fname not in self.funcs:
            return
        for op in self.funcs[fname].ops:
            m = mult * (layer_mult if op.depth >= 1 else 1)
            if op.name == "while":
                continue                      # body ops counted below
            if op.name == "call":
                cm = _CALLEE.search(op.line)
                if cm:
                    self._walk(cm.group(1), m, 1, None, depth_guard + 1)
                continue
            self._classify(op, m)

    def _classify(self, op, m):
        name, types = op.name, op.types
        res = types[-1] if types else None
        res_b = _nbytes(res) if res else 0
        if name == "custom_call" and any(t in op.line
                                         for t in _CALLBACK):
            self.host_sync_count += int(m)
            self._add("host_sync", 0, m)
            return
        if name == "dynamic_update_slice" and res and \
                self._is_cache(res):
            upd = _nbytes(types[1]) if len(types) >= 2 else 0
            self._add("kv_write", 2 * upd, m)
            return
        if name == "dynamic_slice" and types and \
                self._is_cache(types[0]):
            # the slice READ itself is charged to the consuming dot
            # (kv_read); a copy that fails to fuse would add this much:
            self._candidate("kv-slice-copy-write", res_b, m)
            return
        if name == "convert" and types and \
                self._is_cache_slice(types[0]):
            op_b = _nbytes(types[0])
            self._candidate("kv-f32-convert-roundtrip", op_b + res_b, m)
            return
        if name in ("reshape", "broadcast_in_dim"):
            return          # layout/expansion: fused, no HBM traffic
        if name == "dot_general":
            cache_ops = [t for t in types[:-1]
                         if self._is_cache_slice(t)]
            if cache_ops:
                for t in cache_ops:
                    self._add("kv_read", _nbytes(t), m)
                rest = sum(_nbytes(t) for t in types[:-1]
                           if not self._is_cache_slice(t))
                self._add("attention", rest + res_b, m)
                return
            # projection/FFN/logits matmul: dominated by the weight
            # operand — the whole op is a parameter read
            self._add("param_read",
                      sum(_nbytes(t) for t in types), m)
            return
        if name == "dynamic_slice" and types and \
                dflow.dims_of(types[0])[:1] == (self.L,):
            # per-layer slice of the stacked params: one read
            self._add("param_read", res_b, m)
            return
        if name == "gather" and types and \
                self.vocab in dflow.dims_of(types[0])[:1]:
            # embedding rows: read + result write + indices
            self._add("param_read", 2 * res_b, m)
            return
        if self._has_vocab(op):
            self._add("sampling", res_b, m)
            return
        if res and self.m_ctx in dflow.dims_of(res):
            # score-chain tensors (B, H, 1, M): softmax/where/compare
            self._add("attention", res_b, m)
            return
        self._add("other", res_b, m)


def measured_reconciliation(batch: int):
    """The committed r05 decode measurement for this batch (ladder
    baselines), restated as bytes/step at the chip's HBM peak — the
    number the modeled step is reconciled against.  ``None`` off-repo
    or for un-measured configs."""
    try:
        with open(REPO / "BENCH_LADDER_BASELINES.json") as f:
            doc = json.load(f)
        entry = doc[f"gpt_small_tpu_decode_b{batch}"][str(batch)]
    except (OSError, ValueError, KeyError):
        return None
    import bench
    bw = bench.HBM_BYTES_PER_S["v5e"]     # the r05 rig
    step_s = batch / entry["tok_s"]
    return {
        "source": "BENCH_LADDER_BASELINES.json",
        "tok_s": entry["tok_s"],
        "hbm_frac": entry["hbm_frac"],
        "hbm_tok_s_ceiling": entry["hbm_tok_s_ceiling"],
        "step_ms": round(step_s * 1e3, 3),
        "hbm_bytes_per_s": bw,
        "implied_bytes_per_step": int(step_s * bw),
    }


def decompose(batch: int, prefill: int, new_tokens: int,
              tiny: bool = False, compile: bool = True) -> dict:
    lowered, cfg = lower_decode(batch, prefill, new_tokens, tiny=tiny)
    funcs = dflow.parse_module(lowered.as_text())
    m_ctx = prefill + new_tokens
    cache_dims = (cfg.num_layers, batch, m_ctx, cfg.num_heads,
                  cfg.hidden_size // cfg.num_heads)
    step_fn, layer_fn = find_step_funcs(funcs, cache_dims)
    walk = Walk(funcs, cfg, batch, m_ctx, cfg.vocab_size)
    walk.run(step_fn, layer_fn)

    total = sum(walk.buckets.values())
    fractions = {k: round(v / total, 4) for k, v in walk.buckets.items()}
    coverage = round(1.0 - fractions["other"], 4)

    # rank the materialization candidates (merged by label)
    cand: dict = {}
    for label, b in walk.candidates:
        cand[label] = cand.get(label, 0) + b
    cand = dict(sorted(cand.items(), key=lambda kv: -kv[1]))

    meas = measured_reconciliation(batch)
    gap = None
    if meas:
        residual = meas["implied_bytes_per_step"] - total
        # name the static candidate whose volume matches the residual
        best = min(cand.items(), key=lambda kv: abs(kv[1] - residual),
                   default=(None, 0))
        match = best[0] if best[0] and residual > 0 and \
            abs(best[1] - residual) / max(residual, 1) < 0.15 else None
        verdict = (
            f"the modeled roofline-ideal step "
            f"({total / 1e6:.0f} MB) is "
            f"{total / meas['implied_bytes_per_step']:.2f} of the "
            f"measured per-step traffic "
            f"({meas['implied_bytes_per_step'] / 1e6:.0f} MB at the "
            f"HBM peak) — the 0.43 'gap' is mostly the bench ceiling "
            f"model undercounting required traffic, plus a real "
            f"{residual / 1e6:.0f} MB residual")
        if match:
            verdict += (
                f"; the residual matches the {match!r} candidate "
                f"({cand[match] / 1e6:.0f} MB) within 15% — the "
                f"per-layer materialization the serve paged layout "
                f"and the preferred_element_type attention rewrite "
                f"target; on-chip confirmation is the next driver "
                f"round's profile")
        else:
            verdict += ("; no single static candidate matches it — "
                        "attribute on-chip next driver round")
        gap = {
            "modeled_ideal_bytes": int(total),
            "implied_measured_bytes": meas["implied_bytes_per_step"],
            "residual_bytes": int(residual),
            "residual_frac_of_step": round(
                residual / meas["implied_bytes_per_step"], 4),
            "static_candidates_ranked": cand,
            "residual_matches_candidate": match,
            "verdict": verdict,
        }

    doc = {
        "round": 1,
        "platform": jax.devices()[0].platform,
        "config": {"batch": batch, "prefill": prefill,
                   "new_tokens": new_tokens,
                   "model": "gpt_tiny" if tiny else "gpt_small_tpu"},
        "method": "stablehlo-walk",
        "step_fn": {"step": step_fn, "layer_body": layer_fn,
                    "layer_trips": cfg.num_layers},
        "step_bytes": {"total": int(total),
                       "buckets": {k: int(v)
                                   for k, v in walk.buckets.items()}},
        "device_time_fractions": fractions,
        "coverage": coverage,
        "host_sync_count": walk.host_sync_count,
        "measured": meas,
        "gap_attribution": gap,
        "note": (
            "Bytes conventions: elementwise/layout ops fused (result "
            "bytes only / zero); cache DUS in-place (2x update); cache "
            "reads charged at the consuming dot; per-layer ops x "
            "num_layers via the layer-loop walk.  Fractions model the "
            "roofline-IDEAL step: on a bandwidth-bound program they "
            "are device-time fractions.  gap_attribution reconciles "
            "against the committed measured rate; the candidates are "
            "the statically-visible buffers XLA may materialize on "
            "top of the ideal."),
    }
    if compile:
        try:
            from apex_tpu.analysis import cost as cost_mod
            ct = cost_mod.cost_table(lowered.compile())
            if ct:
                ct["caveat"] = ("XLA:CPU cost model counts loop bodies "
                                "once, not per trip — reference only")
                doc["xla_cost_model"] = ct
        except Exception as e:  # noqa: BLE001 - reference info only
            doc["xla_cost_model"] = {"error": str(e)[:200]}
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=2048)
    ap.add_argument("--new-tokens", type=int, default=256)
    ap.add_argument("--tiny", action="store_true",
                    help="gpt_tiny config (tests)")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the XLA cost-model reference read")
    ap.add_argument("--emit", default=None,
                    metavar="DECODE_DECOMPOSE_rN.json",
                    help="write the committed artifact (validated "
                         "against apex_tpu/analysis/decode_decompose.py "
                         "before writing; refuses an invalid document)")
    opts = ap.parse_args(argv)

    doc = decompose(opts.batch, opts.prefill, opts.new_tokens,
                    tiny=opts.tiny, compile=not opts.no_compile)
    if opts.emit:
        m = re.search(r"_r(\d+)\.json$", os.path.basename(opts.emit))
        if m:
            doc["round"] = int(m.group(1))
        from apex_tpu.analysis import decode_decompose as schema
        problems = schema.validate_decompose(doc)
        if problems:
            print(f"refusing to write {opts.emit}: {problems}",
                  file=sys.stderr)
            return 1
        with open(opts.emit, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"decode decomposition written: {opts.emit}",
              file=sys.stderr)
    else:
        print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
