"""Serve the metrics registry over HTTP — a real scrape target.

Stands up :class:`apex_tpu.obs.exposition.MetricsServer` (stdlib
``http.server``; zero dependencies) in front of a live registry:

- ``/metrics`` — the registry's Prometheus text exposition (the SAME
  ``Registry.to_prometheus`` export the committed OBS artifacts pin);
- ``/fleet`` — the :mod:`apex_tpu.obs.fleet` merged view when fleet
  registries are attached (counters summed, histogram buckets
  unioned, gauges tabulated per replica as ``# gauge-table`` lines);
- ``/healthz`` — liveness.

With ``--demo`` the tool first drives a short instrumented train +
serve sample (the ``tools/obs_report.py`` export workload) so the
scrape returns a populated catalog instead of an empty registry —
that is also what the smoke test GETs.  ``--once`` performs one local
GET of ``/metrics`` and exits (scripted smoke; exit 1 when the scrape
fails).

Usage:
    python tools/obs_serve.py [--port 9464] [--host 127.0.0.1]
        [--demo] [--once] [--duration SECONDS]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

from apex_tpu.obs import metrics as obs_metrics  # noqa: E402
from apex_tpu.obs.exposition import MetricsServer  # noqa: E402


def demo_registry() -> obs_metrics.Registry:
    """A populated registry: a few instrumented train steps + a short
    serve stream (the obs_report export-sample workload)."""
    import obs_report
    reg = obs_metrics.Registry()
    snapshot = obs_report.export_sample(quick=True)
    # export_sample builds its own registry; replay its resolved
    # state into ours so the scrape carries the full catalog
    for row in snapshot["metrics"]:
        if row["type"] == "counter":
            reg.counter(row["name"], row["help"])._apply_scalar(
                row["value"])
        elif row["type"] == "gauge":
            reg.gauge(row["name"], row["help"])._apply_scalar(
                row["value"])
        else:
            h = reg.histogram(row["name"], row["help"])
            n = int(row["count"])
            if n > 0:
                # replay every observation at the recorded mean so
                # bucket counts, _sum and _count stay mutually
                # consistent — a scrape with _count > 0 over all-zero
                # buckets would feed histogram_quantile() nonsense
                import bisect
                mean = row["sum"] / n
                h.counts[bisect.bisect_left(h.bounds, mean)] += n
                h.sum, h.count = row["sum"], n
    return reg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9464)
    ap.add_argument("--demo", action="store_true",
                    help="populate the registry with a short "
                         "instrumented train+serve sample first")
    ap.add_argument("--once", action="store_true",
                    help="serve, GET /metrics once from localhost, "
                         "print it, exit (smoke mode)")
    ap.add_argument("--duration", type=float, default=None,
                    help="serve for N seconds then exit (default: "
                         "until interrupted)")
    opts = ap.parse_args(argv)

    registry = demo_registry() if opts.demo else obs_metrics.DEFAULT
    srv = MetricsServer(registry=registry, host=opts.host,
                        port=0 if opts.once else opts.port)
    host, port = srv.start()
    print(f"serving /metrics /fleet /healthz on http://{host}:{port}",
          file=sys.stderr)
    try:
        if opts.once:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=5) as r:
                body = r.read().decode()
            print(body)
            return 0 if "# TYPE" in body else 1
        end = None if opts.duration is None \
            else time.monotonic() + opts.duration
        while end is None or time.monotonic() < end:
            time.sleep(0.5)
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        srv.stop()


if __name__ == "__main__":
    raise SystemExit(main())
