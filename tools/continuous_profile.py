"""Scripted continuous-profiling session → PROFILE_DRIFT_r*.json.

Runs the always-on profiler (:mod:`apex_tpu.obs.contprof`) against a
real serve engine in TWO lanes and commits the evidence:

- **clean** — a steady decode stream, capture windows every
  ``capture_every`` steps, sentinel self-baselined on the first
  window.  The sentinel must stay QUIET: zero confirmed drifts across
  the whole session (single noisy windows are allowed — the
  K-consecutive rule exists exactly for them);
- **seeded** — the same stream, with a DOCUMENTED synthetic
  regression seeded into the measured op-time table from window
  ``seed_from`` onward: every op the compiled-HLO classifier assigns
  to the seeded bucket has its measured time multiplied by
  ``seed_factor`` — as if the kv reads grew a materialized copy.
  The seeding happens at the op-times level, BEFORE bucketing, so the
  entire pipeline under test (bucket fold → band rule → K-consecutive
  confirmation → incident/gauge) runs on the seeded data exactly as
  it would on a real regression.  The sentinel must CATCH it — first
  confirmed drift at window ``seed_from + k − 1``, naming the seeded
  bucket.

Baseline note: the committed ``DECODE_PROFILE_r*.json`` fractions are
thread-summed XLA:CPU host-executor times and spread ~10 percentage
points ACROSS hosts (measured), so a foreign-host committed baseline
would alarm on every window here; each session self-baselines on its
own first window and the newest committed DECODE_PROFILE is recorded
as ``baseline_ref`` (cross-reference, not the gate).  On a TPU the
same tool runs with ``--baseline committed``
(:func:`apex_tpu.obs.contprof.baseline_from_profile`) — a stable
device makes committed fractions directly comparable.

The emitted document is validated against
``apex_tpu/analysis/profile_drift.py`` (stdlib-only; gate_hygiene
enforces it on committed copies, replaying the sentinel rule over the
recorded windows) and the tool refuses to write an invalid one.

Usage:
    python tools/continuous_profile.py [--windows 5] [--k 2]
        [--band 0.12] [--capture-every 12] [--capture-steps 8]
        [--seed-bucket kv_read] [--seed-factor 2.0] [--quick]
        [--baseline first-window|committed]
        [--emit PROFILE_DRIFT_rN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

import numpy as np  # noqa: E402

from apex_tpu.analysis import profile_drift as schema  # noqa: E402
from apex_tpu.obs import contprof  # noqa: E402
from apex_tpu.obs import metrics as obs_metrics  # noqa: E402
from apex_tpu.serve import Request  # noqa: E402


class SeededProfiler(contprof.ContinuousProfiler):
    """The seeded-regression lane: inflate the measured op times of
    one classified bucket from window ``seed_from`` onward, BEFORE
    bucketing — the only difference from production is the synthetic
    regression itself."""

    def __init__(self, *args, seed_bucket=None, seed_factor=2.0,
                 seed_from=1, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed_bucket = seed_bucket
        self.seed_factor = float(seed_factor)
        self.seed_from = int(seed_from)

    def _seed(self, step_times, clf):
        if self.seed_bucket is None:
            return step_times
        idx = len(self.windows) + len(self.discarded)
        if idx < self.seed_from:
            return step_times
        return {n: (int(ps * self.seed_factor)
                    if clf(n) == self.seed_bucket else ps)
                for n, ps in step_times.items()}


def build_engine(num_slots: int, registry):
    """The ONE shared serve-engine construction
    (``graph_lint.build_serve_engine``) at the profile geometry —
    obs_report's contprof overhead lane measures the same engine."""
    import graph_lint

    eng, _ = graph_lint.build_serve_engine(
        num_slots=num_slots, block_size=16,
        num_blocks=num_slots * 8 + 1, max_blocks_per_slot=8,
        prefill_chunk=16, registry=registry)
    return eng, eng.cfg, eng.scfg


def run_session(opts, seed_bucket=None, baseline=None) -> dict:
    """One scripted lane: admit a full batch, decode for exactly the
    steps ``--windows`` windows need, return the session record."""
    reg = obs_metrics.Registry()
    eng, cfg, scfg = build_engine(opts.slots, reg)
    sent = contprof.DriftSentinel(
        baseline=baseline, band=opts.band,
        band_source=opts.band_source, k=opts.k, registry=reg)
    pcfg = contprof.ContProfConfig(
        capture_every=opts.capture_every,
        capture_steps=opts.capture_steps,
        warmup_steps=opts.warmup, max_overhead_pct=None,
        max_windows=opts.windows)
    prof = SeededProfiler(
        buckets=contprof.DECODE_BUCKETS,
        classifier_builder=contprof.serve_classifier_builder(eng),
        config=pcfg, sentinel=sent, registry=reg,
        seed_bucket=seed_bucket, seed_factor=opts.seed_factor,
        seed_from=opts.seed_from)
    eng.profiler = prof

    total_steps = opts.warmup + opts.windows * opts.capture_every \
        + opts.capture_steps + 2
    rng = np.random.RandomState(0)
    for i in range(opts.slots):
        eng.submit(Request(
            uid=f"s{i}", prompt=rng.randint(0, cfg.vocab_size, (8,)),
            max_new_tokens=total_steps + 8))
    for _ in range(total_steps):
        eng.step()
        if len(prof.windows) + len(prof.discarded) >= opts.windows \
                and not prof.in_window:
            break
    prof.abort_window()

    session = {
        "baseline": sent.baseline,
        "windows": prof.windows,
        "drifts": sent.drifts,
        "quiet": len(sent.drifts) == 0,
        "discarded_windows": len(prof.discarded),
        "skipped_windows": prof.skipped_windows,
        "classifier_build_s": prof.classifier_build_s,
    }
    if seed_bucket is not None:
        session["seed"] = {"bucket": seed_bucket,
                           "factor": opts.seed_factor,
                           "from_window": opts.seed_from}
    return session


def committed_profile_ref():
    """The newest committed DECODE_PROFILE document (cross-reference
    for the self-baselined CPU sessions; the gating baseline under
    ``--baseline committed`` on a stable device)."""
    path = max(REPO.glob("DECODE_PROFILE_r*.json"), default=None)
    if path is None:
        return None, None
    try:
        with open(path) as f:
            return path.name, json.load(f)
    except (OSError, ValueError):
        return None, None


def build_doc(opts) -> dict:
    ref_name, ref_doc = committed_profile_ref()
    committed_baseline = None
    if opts.baseline == "committed":
        if ref_doc is None:
            raise SystemExit("--baseline committed: no committed "
                             "DECODE_PROFILE_r*.json found")
        committed_baseline = contprof.baseline_from_profile(ref_doc)

    clean = run_session(opts, seed_bucket=None,
                        baseline=dict(committed_baseline)
                        if committed_baseline else None)
    seeded = run_session(opts, seed_bucket=opts.seed_bucket,
                         baseline=dict(committed_baseline)
                         if committed_baseline else None)

    caught = [d for d in seeded["drifts"]]
    doc = {
        "round": 1,
        "platform": jax.devices()[0].platform,
        "kind": "serve-decode",
        "config": {
            "model": "gpt_tiny", "num_slots": opts.slots,
            "capture_every": opts.capture_every,
            "capture_steps": opts.capture_steps,
            "warmup_steps": opts.warmup, "windows": opts.windows,
            "baseline_mode": opts.baseline,
        },
        "band": {"value": opts.band, "source": opts.band_source},
        "k": opts.k,
        "sessions": {"clean": clean, "seeded": seeded},
        "gate": {
            "clean_quiet": clean["quiet"],
            "seeded_caught": bool(caught),
            "ok": clean["quiet"] and bool(caught),
        },
        "note": (
            "Continuous-profiler drift evidence: a clean serve-decode "
            "session the sentinel stays quiet on, and a seeded "
            "synthetic regression (documented op-time inflation of "
            "one classified bucket, applied before bucketing) it must "
            "catch in exactly k consecutive windows, naming the "
            "bucket.  Windows are jax.profiler captures of the LIVE "
            "engine's decode dispatches parsed through obs.xplane "
            "(XLA:CPU host-executor fallback on this platform — "
            "thread-summed times, no HBM claim) and bucketed by the "
            "shared compiled-HLO classifier "
            "(apex_tpu.obs.stepclass.ServeStepClassifier).  Sessions "
            "self-baseline on their first window; the committed "
            "DECODE_PROFILE fractions are recorded as baseline_ref "
            "(cross-host CPU thread-sum spread ~10pp makes them a "
            "cross-reference here; on a TPU run --baseline "
            "committed).  Profiled steps are excluded from "
            "serve_decode_step_seconds (gate-exclusion contract, "
            "tested in tests/l0/test_contprof.py)."),
    }
    if caught:
        first = caught[0]
        doc["gate"]["caught_in_windows"] = \
            first["window"] - opts.seed_from + 1
    if ref_name is not None:
        doc["baseline_ref"] = {
            "file": ref_name,
            "device_time_fractions":
                (ref_doc or {}).get("device_time_fractions"),
        }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--windows", type=int, default=5,
                    help="capture windows per session")
    ap.add_argument("--k", type=int, default=2,
                    help="consecutive out-of-band windows to confirm")
    ap.add_argument("--band", type=float, default=0.12)
    ap.add_argument("--band-source", default=None,
                    help="recorded provenance of the band width "
                         "(default: a text derived from --band)")
    ap.add_argument("--capture-every", type=int, default=12)
    ap.add_argument("--capture-steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=4)
    ap.add_argument("--seed-bucket", default="kv_read",
                    choices=[b for b in schema.DECODE_BUCKETS
                             if b != "other"])
    ap.add_argument("--seed-factor", type=float, default=2.0)
    ap.add_argument("--seed-from", type=int, default=1,
                    help="first seeded window index")
    ap.add_argument("--baseline", default="first-window",
                    choices=("first-window", "committed"))
    ap.add_argument("--quick", action="store_true",
                    help="smaller everything (tests); not for "
                         "committed artifacts")
    ap.add_argument("--emit", default=None,
                    metavar="PROFILE_DRIFT_rN.json")
    opts = ap.parse_args(argv)
    if opts.quick:
        opts.windows = min(opts.windows, 3)
        opts.capture_every = 6
        opts.capture_steps = 4
        opts.warmup = 2
    if opts.band_source is None:
        opts.band_source = (
            "measured same-host window spread of thread-summed "
            "XLA:CPU captures (BENCH_VARIANCE carries no decode-"
            "profile entry; the 0.03 chip-day default is a TPU "
            "number)" if opts.band != schema.DEFAULT_BAND
            else "default")

    doc = build_doc(opts)
    if opts.emit:
        m = re.search(r"_r(\d+)\.json$", os.path.basename(opts.emit))
        if m:
            doc["round"] = int(m.group(1))
        problems = schema.validate_profile_drift(doc)
        if problems:
            print(f"refusing to write {opts.emit}: {problems}",
                  file=sys.stderr)
            return 1
        with open(opts.emit, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"profile-drift artifact written: {opts.emit}",
              file=sys.stderr)
    else:
        print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
