"""Disaggregated-serving gate artifact: the c16 offered-load A/B plus
the replica-kill chaos drill, committed as ``SERVE_DISAGG_r*.json``.

Runs ``bench.bench_serve_disagg`` — the SAME sweep the
``gpt_small_tpu_serve_disagg_c16`` bench config runs on chip — on a
virtual 16-device platform (the tool forces
``--xla_force_host_platform_device_count=16`` before jax initializes,
exactly like ``tools/graph_lint.py`` arranges its 8-device mesh), then
drills the failure path: kill a decode replica mid-stream, let the
router rebuild its in-flight requests from the streamed-token log and
re-prefill them elsewhere, and check every final output BITWISE
against solo ``generate()``.

The emitted document (schema ``apex_tpu/analysis/serve_disagg.py``,
validated by ``tools/gate_hygiene.py`` in tier-1) carries both gates:

- ``gate.p99_ok`` — disaggregated decode p99 <= monolithic p99 at
  equal resources (the DistServe/Splitwise claim);
- ``chaos.bitwise_ok`` — the kill drill's outputs greedy-match solo.

A verdict contradicting its own numbers is schema-invalid, so the
artifact cannot rot into an "ok" nobody re-derived.

Usage:
    python tools/serve_disagg.py --emit-json SERVE_DISAGG_r01.json \
        [--cpu-smoke] [--n-replicas 2] [--slots 8] [--prefill 512]
        [--new-tokens 128]

``--cpu-smoke`` is the committed-r01 shape: gpt_tiny at FULL c16
concurrency (2 replicas x 8 slots) on the 16-device CPU platform —
the topology is the real thing, the model is test-scale.  Without it
the sweep runs gpt_small_tpu (a chip-round config).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# 16 virtual host devices BEFORE any jax backend initialization: 1
# prefill slice + decode replica slices, CPU-testable end to end.
os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=16").strip()
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))
jax.config.update("jax_threefry_partitionable", True)


def chaos_drill(tiny: bool, n_replicas: int, prefill: int,
                new_tokens: int) -> dict:
    """Kill a decode replica mid-stream; every request — rerouted ones
    included — must end bitwise equal to its solo ``generate()`` run.
    Returns the drill record for the artifact's ``chaos`` block."""
    from apex_tpu import amp
    from apex_tpu.models.generate import generate
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny
    from apex_tpu.obs import fleet as fleet_obs
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import (DisaggRouter, Request, RouterConfig,
                                ServeConfig)

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    block = 4 if tiny else 16
    mb = -(-(prefill + new_tokens) // block)
    scfg = ServeConfig(num_slots=2, block_size=block,
                       num_blocks=2 * mb + 1, max_blocks_per_slot=mb,
                       prefill_chunk=min(prefill, 8 if tiny else 128))
    router = DisaggRouter(
        params, cfg, scfg,
        RouterConfig(n_decode_replicas=n_replicas, transfer="ship"),
        registry=Registry())
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, cfg.vocab_size, (prefill // (i + 1),)),
             new_tokens) for i in range(4)]
    for i, (p, n) in enumerate(reqs):
        router.submit(Request(uid=f"c{i}", prompt=p, max_new_tokens=n))
    for _ in range(3):
        router.step()
    victim = max(router.replicas,
                 key=lambda r: r.eng.sched.n_active()).index
    rerouted = router.kill_replica(victim)
    out = router.run()
    bitwise = True
    for i, (p, n) in enumerate(reqs):
        want = np.asarray(generate(params, cfg, jnp.asarray(p[None]),
                                   n))[0, len(p):]
        if not np.array_equal(out[f"c{i}"], want):
            bitwise = False
    # fleet token accounting through the ONE merge implementation
    # (apex_tpu.obs.fleet — the same counter-sum a production scrape
    # runs; never hand-summed here so the two can't drift)
    merged = fleet_obs.merge_registries(
        [router.prefill.eng.metrics]
        + [r.eng.metrics for r in router.replicas])
    return {"killed_replica": int(victim),
            "rerouted": len(rerouted),
            "bitwise_ok": bool(bitwise),
            "fleet_tokens_total": int(
                merged.counter("serve_tokens_total").value)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--emit-json", default=None,
                    metavar="SERVE_DISAGG_rN.json",
                    help="write the committed gate artifact")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="gpt_tiny model at full c16 topology (the "
                         "committed-r01 shape); default gpt_small_tpu")
    ap.add_argument("--n-replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=8,
                    help="decode slots per replica (concurrency = "
                         "n_replicas x slots)")
    ap.add_argument("--prefill", type=int, default=None,
                    help="prompt length (default 512; 64 under "
                         "--cpu-smoke)")
    ap.add_argument("--new-tokens", type=int, default=None,
                    help="generation budget (default 128; 16 under "
                         "--cpu-smoke)")
    opts = ap.parse_args(argv)
    prefill = opts.prefill if opts.prefill is not None \
        else (64 if opts.cpu_smoke else 512)
    new_tokens = opts.new_tokens if opts.new_tokens is not None \
        else (16 if opts.cpu_smoke else 128)

    import bench

    rec = bench.bench_serve_disagg(
        warmup=1, iters=1, peak=0.0, n_replicas=opts.n_replicas,
        slots_per_replica=opts.slots, prefill=prefill,
        new_tokens=new_tokens, tiny=opts.cpu_smoke)
    if "skipped" in rec:
        print(f"serve_disagg: {rec['skipped']}", file=sys.stderr)
        return 1
    chaos = chaos_drill(opts.cpu_smoke, opts.n_replicas, prefill,
                        new_tokens)
    p99_ok = rec["disagg"]["p99_ms"] <= rec["mono"]["p99_ms"]
    doc = {
        "round": 0,
        "platform": jax.devices()[0].platform,
        "config": {
            "model": "gpt_tiny" if opts.cpu_smoke else "gpt_small_tpu",
            "concurrency": int(rec["batch"]),
            "prefill": int(prefill),
            "new_tokens": int(new_tokens),
            "block_size": 4 if opts.cpu_smoke else 16,
        },
        "topology": {
            "n_devices": rec["topology"]["n_devices"],
            "transfer": "ship",
            "prefill_devices": rec["topology"]["prefill"],
            "replica_devices": rec["topology"]["decode"],
        },
        "mono": rec["mono"],
        "disagg": rec["disagg"],
        "chaos": chaos,
        "gate": {"p99_ok": bool(p99_ok),
                 "ok": bool(p99_ok and chaos["bitwise_ok"])},
        "note": (
            "CPU smoke: virtual devices share host cores, so the A/B "
            "isolates what disaggregation changes structurally — "
            "per-step decode batch width and prefill/decode "
            "interference — while the chip round measures the "
            "hardware side at real equal chip count."
            if jax.devices()[0].platform == "cpu" else
            "on-chip offered-load A/B at equal device count"),
    }
    if opts.emit_json:
        m = re.search(r"_r(\d+)\.json$",
                      os.path.basename(opts.emit_json))
        doc["round"] = int(m.group(1)) if m else 0
        from apex_tpu.analysis.serve_disagg import validate_serve_disagg
        problems = validate_serve_disagg(doc)
        if problems:
            print(f"serve_disagg: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
        with open(opts.emit_json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"serve-disagg artifact written: {opts.emit_json}",
              file=sys.stderr)
    print(json.dumps(doc))
    return 0 if doc["gate"]["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
