"""Chaos harness: a small amp-O2 train loop driven under a fault schedule.

The resilience layer's claims (``apex_tpu/resilience/``) are only worth
what survives injection, so this tool runs a tiny MLP + FusedAdam amp-O2
loop through :func:`apex_tpu.resilience.run_resilient` with a
command-line fault schedule and emits an ``INCIDENT_r*.json``-schema
artifact (validated by the same :mod:`apex_tpu.resilience.incidents`
schema ``tools/gate_hygiene.py`` enforces on committed incidents).

Fault specs (``--faults``, repeatable):

- ``nan_storm@S[:D]``    — poison the batch for D firings from step S
  (default D=6: long enough to pin the scale at its floor and trip the
  divergence sentinel, i.e. a *storm*, not a normal transient overflow);
- ``ckpt_truncate@S`` / ``ckpt_corrupt@S`` — damage the first checkpoint
  committed at/after step S (restore must fall back to the last good one);
- ``preempt@S``          — SIGTERM mid-step: the harness then simulates a
  scheduler restart (fresh process state, restore from disk, resume);
- ``hang@S[:SEC]``       — host hang at step S (watchdog prey);
- ``flaky_io[:N]``       — first N checkpoint saves raise OSError;
- ``slow_io[:SEC]``      — every save sleeps SEC first;
- ``rank_kill@S[:RANK]`` — SIGKILL a real training process at step S
  (the ``--fleet`` lane only: the single-process lane has no peer to
  survive the kill).

``--fleet`` switches the harness from the in-process loop to the REAL
multi-process elastic-fleet drill (``tools/train_fleet.py``): the one
scheduled ``rank_kill`` fault is executed as an actual ``SIGKILL`` on a
live ``jax.distributed`` rank, the survivor shrinks, the returned rank
regrows, and the emitted ``TRAINFLEET_r*.json`` artifact is validated
by ``apex_tpu/analysis/trainfleet.py``.  Both lanes share one fault
vocabulary (:func:`apex_tpu.resilience.faults.parse_fault`).

``--overhead`` additionally measures the resilience wrapper's normal-path
cost (bare jitted loop vs ``run_resilient`` with no faults and no
checkpointing) and records it in the artifact — the "< 2% step time"
budget documented in ``docs/source/checkpoint.rst``.

The emitted incident embeds the loop's **flight-recorder tail**
(:class:`apex_tpu.obs.flight.FlightRecorder` — the bounded ring of
step/overflow/fault/rewind events), and the harness ASSERTS that tail
is schema-valid and actually contains the injected faults' events (a
scheduled nan storm must appear as ``fault`` firings, an executed
rewind as a ``rewind`` event): a black box that missed the crash it
flew through fails the run, not just the review.

Usage::

    python tools/chaos_run.py --steps 24 \
        --faults nan_storm@6 ckpt_truncate@11 --checkpoint-every 4 \
        --out INCIDENT_chaos_run.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def parse_fault(spec: str):
    """``name@step[:arg]`` / ``name[:arg]`` → fault dataclass.  The
    vocabulary lives in :func:`apex_tpu.resilience.faults.parse_fault`
    (one grammar for this harness AND the fleet drill); this shim just
    turns its ``ValueError`` into a CLI usage error."""
    from apex_tpu.resilience.faults import parse_fault as _parse
    try:
        return _parse(spec)
    except ValueError as e:
        raise SystemExit(str(e))


def _run_fleet_lane(args) -> int:
    """The ``--fleet`` chaos lane: delegate to the elastic-fleet drill
    harness with the ``rank_kill`` fault translated from the shared
    injector vocabulary.  Exactly one ``rank_kill@S[:RANK]`` must be
    scheduled; the other fault kinds belong to the in-process lane."""
    from apex_tpu.resilience.faults import RankKill

    faults = [parse_fault(s) for s in args.faults]
    kills = [f for f in faults if isinstance(f, RankKill)]
    if len(kills) != 1 or len(faults) != len(kills):
        raise SystemExit(
            "--fleet takes exactly one rank_kill@STEP[:RANK] fault and "
            f"no others (got --faults {args.faults or 'none'}); the "
            "in-process fault kinds run without --fleet")
    kill = kills[0]
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_apex_train_fleet", str(REPO / "tools" / "train_fleet.py"))
    train_fleet = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train_fleet)
    return train_fleet.main([
        "--steps", str(args.steps),
        "--checkpoint-every", str(args.checkpoint_every),
        "--kill-step", str(kill.step),
        "--kill-rank", str(kill.rank if kill.rank is not None else 1),
        "--seed", str(args.seed),
        "--out", args.out])


def build_workload(seed: int = 0, min_loss_scale: float = 2.0 ** 14,
                   features=(32,), batch: int = 32, d_in: int = 16):
    """MLP + FusedAdam amp-O2 training step with fixed batches.

    ``min_loss_scale`` sits high so an injected storm pins the scale in a
    couple of overflows — the sentinel's storm signal fires within a
    handful of steps instead of after 16 halvings.  The default shape is
    tiny (fast chaos loops); :func:`measure_overhead` uses a bench-smoke
    sized one.
    """
    from apex_tpu import amp
    from apex_tpu.models.mlp import MLP, cross_entropy_loss
    from apex_tpu.optimizers import FusedAdam

    model = MLP(features=features)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, d_in)))["params"]
    amp_obj = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                             min_loss_scale=min_loss_scale, verbosity=0)
    step_fn = jax.jit(amp.make_train_step(
        amp_obj, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y)))
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (batch, d_in))
    y = jax.random.randint(jax.random.PRNGKey(seed + 2), (batch,), 0, 10)
    state = amp_obj.init(params)
    return amp_obj, step_fn, state, lambda i: (x, y)


def measure_overhead(steps: int = 40, reps: int = 5, seed: int = 0) -> dict:
    """Wall time of a bare jitted loop vs run_resilient with no faults /
    no checkpointing — the normal-path cost of the wrapper, at the CPU
    bench-smoke scale (a ~dozens-of-ms step, like the bench.py smoke
    configs; on a microscopic sub-ms step the fixed ~0.1 ms/step Python
    bookkeeping dominates and the percentage is meaningless).  Reps are
    interleaved bare/wrapped and compared min-to-min: on a shared/noisy
    host the run-to-run spread (±30% observed) dwarfs the effect, and
    the minimum is the standard noise-robust wall-clock estimator."""
    from apex_tpu.resilience import ResilienceConfig, run_resilient

    amp_obj, step_fn, state0, batch_fn = build_workload(
        seed, features=(256, 256), batch=256, d_in=256)
    batch = batch_fn(0)

    def bare():
        st = state0
        t0 = time.perf_counter()
        for _ in range(steps):
            st, m = step_fn(st, *batch)
        jax.block_until_ready(m["loss"])
        return time.perf_counter() - t0

    def wrapped():
        cfg = ResilienceConfig(watchdog_timeout_s=300.0, checkpoint_every=0)
        t0 = time.perf_counter()
        run_resilient(step_fn, state0, batch_fn, steps, amp_obj=amp_obj,
                      config=cfg)
        return time.perf_counter() - t0

    bare(); wrapped()      # compile outside the timed region
    bare_ts, wrap_ts = [], []
    for _ in range(reps):
        bare_ts.append(bare())
        wrap_ts.append(wrapped())
    bare_t, wrap_t = min(bare_ts), min(wrap_ts)
    return {"steps": steps, "reps": reps,
            "bare_s": round(bare_t, 4), "resilient_s": round(wrap_t, 4),
            "bare_ms_per_step": round(bare_t / steps * 1e3, 3),
            "resilient_ms_per_step": round(wrap_t / steps * 1e3, 3),
            "normal_path_overhead_pct":
                round(100.0 * (wrap_t - bare_t) / bare_t, 2)}


def check_flight(rec: dict, fault_specs, rewinds) -> list:
    """Problems with the incident's flight tail as a black box of this
    run (``[]`` = covered): the ``flight`` field must be present and
    schema-valid (``validate_incident`` already enforces the shape —
    this re-checks so the verdict is usable standalone), every
    scheduled nan-storm must appear among its ``fault`` events, and an
    executed rewind must appear as a ``rewind`` event."""
    from apex_tpu.resilience.incidents import _validate_flight

    flight = rec.get("flight")
    if flight is None:
        return ["incident carries no 'flight' field — the loop's ring "
                "was not dumped"]
    problems = [f"flight: {p}" for p in _validate_flight(flight)]
    events = flight.get("events") if isinstance(flight, dict) else []
    if not isinstance(events, list):
        events = []
    kinds = [e.get("kind") for e in events if isinstance(e, dict)]
    fired_faults = {e.get("fault") for e in events
                    if isinstance(e, dict) and e.get("kind") == "fault"}
    for spec in fault_specs:
        name = spec.partition("@")[0].partition(":")[0]
        if name == "nan_storm" and "nan_storm" not in fired_faults:
            problems.append(
                f"flight tail never recorded the scheduled {spec!r} "
                f"firing (fault kinds seen: {sorted(fired_faults)})")
    if rewinds and "rewind" not in kinds:
        problems.append(
            f"loop rewound {rewinds}x but the flight tail has no "
            f"'rewind' event (kinds seen: {sorted(set(kinds))})")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--faults", nargs="*", default=[])
    ap.add_argument("--checkpoint-every", type=int, default=4)
    ap.add_argument("--watchdog", type=float, default=60.0)
    ap.add_argument("--patience", type=int, default=3,
                    help="K consecutive pinned-at-floor overflows → rewind")
    ap.add_argument("--max-rewinds", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--out", default=None,
                    help="artifact path (default INCIDENT_chaos_run.json,"
                         " or TRAINFLEET_r01.json under --fleet)")
    ap.add_argument("--overhead", action="store_true",
                    help="also measure the wrapper's normal-path overhead")
    ap.add_argument("--fleet", action="store_true",
                    help="run the multi-process elastic-fleet drill "
                         "(tools/train_fleet.py) instead of the "
                         "in-process loop; requires one rank_kill fault")
    args = ap.parse_args(argv)
    if args.out is None:
        args.out = "TRAINFLEET_r01.json" if args.fleet \
            else "INCIDENT_chaos_run.json"

    if args.fleet:
        return _run_fleet_lane(args)

    from apex_tpu.resilience import (DivergenceError, DurableCheckpointManager,
                                     FaultInjector, ResilienceConfig,
                                     SimulatedPreemption, WatchdogTimeout,
                                     run_resilient)

    faults = [parse_fault(s) for s in args.faults]
    injector = FaultInjector(faults, seed=args.seed)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="apex_tpu_chaos_")
    cfg = ResilienceConfig(
        watchdog_timeout_s=args.watchdog,
        checkpoint_every=args.checkpoint_every,
        overflow_patience=args.patience,
        max_rewinds=args.max_rewinds,
        incident_path=args.out)

    def make_manager():
        return DurableCheckpointManager(ckpt_dir, max_to_keep=3,
                                        io_hook=injector.io_hook,
                                        on_commit=injector.on_commit)

    from apex_tpu.obs.flight import FlightRecorder
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.obs.slo import SLObjective, SLOEvaluator

    amp_obj, step_fn, state, batch_fn = build_workload(args.seed)
    # SLO verdicts over the loop's own registry (apex_tpu.obs.slo):
    # the overflow-rate objective judges the storm's damage (a clean
    # run overflows ~never; a nan storm burns the budget), the
    # watchdog-margin gauge the proximity to a wedge.  The evaluator
    # reads resolved host state only; the first evaluate() seeds the
    # window base at zero.
    registry = Registry()
    registry.counter("train_steps_total")
    registry.counter("train_overflows_total")
    registry.gauge("train_watchdog_margin_s").set(args.watchdog)
    slo_ev = SLOEvaluator(registry, (
        SLObjective(name="overflow_rate", kind="ratio",
                    ratio_num="train_overflows_total",
                    ratio_den="train_steps_total", op="le",
                    threshold=0.25, window=1,
                    min_count=min(8, args.steps)),
        SLObjective(name="watchdog_margin", kind="gauge",
                    metric="train_watchdog_margin_s", op="ge",
                    threshold=0.0, window=1, min_count=1),
    ))
    slo_ev.evaluate()
    restarts = 0
    status, summary = "completed", "chaos run completed"
    result = None
    evidence = []
    # ONE flight recorder across restarts: the final incident's tail
    # must span the whole chaos run, preemption restarts included.
    # Capacity is sized to the run (the loop notes up to ~4 events per
    # step): check_flight below DEMANDS the injected faults' events in
    # the tail, so a long run must not evict an early fault's firing
    # out of the black box it is later judged by.
    flight = FlightRecorder(capacity=max(256, args.steps * 4 + 64))
    with injector:
        remaining = True
        while remaining:
            remaining = False
            manager = make_manager()
            try:
                result = run_resilient(
                    step_fn, state, batch_fn, args.steps, amp_obj=amp_obj,
                    manager=manager, config=cfg, injector=injector,
                    registry=registry, flight=flight)
            except SimulatedPreemption as e:
                # scheduler restart: fresh process state, restore from the
                # last GOOD (checksum-verified) snapshot, resume
                restarts += 1
                amp_obj, step_fn, state, batch_fn = build_workload(args.seed)
                manager = make_manager()
                try:
                    state, _ = manager.restore(state)
                    evidence.append(
                        f"preempted at step {e.step}; restart restored "
                        f"checkpoint step {manager.last_restore['step']} "
                        f"(skipped: {manager.last_restore['skipped']})")
                except FileNotFoundError:
                    # preempted before the first commit: a real restart
                    # starts over from initialization
                    evidence.append(
                        f"preempted at step {e.step} before any checkpoint "
                        "committed; restarted from scratch")
                remaining = True
            except (WatchdogTimeout, DivergenceError) as e:
                status, summary = "aborted", f"{type(e).__name__}: {e}"
                evidence.append(str(e))

    final_loss = None
    if result is not None and result.losses:
        final_loss = result.losses[-1][1]
        if result.rewinds or restarts:
            status, summary = "recovered", (
                f"run completed after {result.rewinds} rewind(s) and "
                f"{restarts} restart(s); final loss {final_loss:.4f}")
    evidence += [f"faults scheduled: {args.faults or 'none'}",
                 {"injector_events": injector.events}]
    if result is not None:
        evidence.append({"loop_events": result.events,
                         "loop_incidents": [r.get("summary")
                                            for r in result.incidents],
                         "final_loss": final_loss,
                         "steps_completed": result.steps_completed,
                         "rewinds": result.rewinds})

    # the run's SLO verdict: one end-of-run evaluation over the whole
    # window (base = the pre-run snapshot) — recorded into the
    # incident so the chaos artifact carries an objective-level story
    # next to the event-level flight tail
    registry.flush()
    slo_verdict = None
    try:
        slo_ev.evaluate()
        slo_verdict = slo_ev.summary()
    except Exception as e:  # noqa: BLE001 - forensics must not die
        slo_verdict = {"error": f"{type(e).__name__}: {e}"[:200]}

    extra = {"artifact": "chaos-run fault-injection record",
             "harness": "tools/chaos_run.py -> apex_tpu.resilience",
             "faults": list(args.faults), "restarts": restarts,
             "checkpoint_dir": ckpt_dir,
             "slo": slo_verdict,
             "flight": flight.dump()}
    if args.overhead:
        extra["overhead"] = measure_overhead(seed=args.seed)

    from apex_tpu.resilience import write_incident
    rec = write_incident(args.out, status, summary, evidence, **extra)
    # the black-box bar: the dumped tail must be schema-valid AND
    # contain the injected faults' events — a completed chaos run whose
    # flight recorder missed the injected crash fails here
    flight_problems = check_flight(rec, args.faults,
                                   getattr(result, "rewinds", 0))
    if flight_problems:
        print(f"chaos_run: flight-recorder tail incomplete: "
              f"{flight_problems}", file=sys.stderr)
    print(json.dumps({"status": rec["status"], "out": args.out,
                      "slo_ok": (slo_verdict or {}).get("ok"),
                      "restarts": restarts,
                      "rewinds": getattr(result, "rewinds", None),
                      "final_loss": final_loss,
                      "flight_events": len(rec["flight"]["events"]),
                      **({"overhead": extra["overhead"]}
                         if args.overhead else {})}))
    ok = status in ("completed", "recovered") and final_loss is not None \
        and np.isfinite(final_loss) and not flight_problems
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
