"""Convergence milestones for the transformer + GAN workloads (round 3).

The reference's north star is a *convergence* number, not a throughput
one; round 2 proved the imagenet path (digits 98.6% top-1 on chip) but
left GPT/BERT/DCGAN as throughput-only configs.  This tool runs three
zero-egress proofs on the real chip and writes ``CONVERGENCE_r03.json``
with machine-readable targets:

1. ``gpt_pysrc``   — byte-level causal LM over the Python stdlib sources
   (the ``examples/gpt_lm.py --data pysrc`` corpus) with a held-out
   tail; target: validation loss (nats/byte) under the bar.
2. ``bert_mlm``    — byte-level masked-LM over the same corpus (15%
   masking); target: masked-position CE under the bar (vs ln(vocab) =
   5.6 at chance).
3. ``dcgan_two_scaler`` — the two-optimizer/two-scaler GAN loop run in
   fp16 compute, where dynamic-range overflows actually happen: the
   proof is overflow events OBSERVED and RECOVERED (scales halved, the
   run continues, final losses finite) — the reference's ``num_losses``
   machinery under real dynamics (``apex/amp/handle.py:53-58``).

Scales are parameterized so the l1 slow tier can run miniatures on CPU
(``tests/l1/test_convergence_targets.py``); the defaults are the
on-chip proof.  Usage: ``python tools/convergence_run.py [out.json]``.
"""

import dataclasses
import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "examples"))


def _corpus():
    from gpt_lm import _load_pysrc_corpus
    return _load_pysrc_corpus()


def _windows(corpus, rng, batch, seq, lo, hi):
    starts = rng.randint(lo, hi - seq - 1, size=batch)
    return jnp.asarray(
        np.stack([corpus[s:s + seq] for s in starts]).astype(np.int32))


def corpus_anchors(corpus, split_frac=0.9):
    """Externally-anchored baselines on the SAME corpus and train/val
    split the models use, so the convergence targets stop being
    self-referential (VERDICT r3 weak #4):

    - ``ngram{1,2,3}_nats_per_byte`` — held-out cross-entropy of
      add-1-smoothed byte n-gram models FIT ON THE TRAIN SPLIT and
      evaluated on the val split: the classical statistical floors a
      trained LM must beat to demonstrate it learned more than local
      byte statistics.
    - ``gzip/bz2/lzma_nats_per_byte`` — standalone compression of the
      val split (``len(compressed)·8·ln2 / len(val)``): practical
      long-range-redundancy references.  Dictionary compressors exploit
      verbatim long-range matches a short-context LM cannot see, so
      they bound from a different direction and are reported as
      context, not as a pass/fail bar.

    All integer counting in numpy; deterministic.
    """
    import bz2
    import gzip
    import lzma

    split = int(len(corpus) * split_frac)
    train = np.asarray(corpus[:split], dtype=np.int64)
    val = np.asarray(corpus[split:], dtype=np.int64)
    out = {"split_frac": split_frac, "train_bytes": int(train.size),
           "val_bytes": int(val.size)}

    for k in (1, 2, 3):
        # counts over train: table of 256^(k-1) contexts x 256
        # next-bytes, flattened; add-1 smoothing; held-out nats/byte

        def ctx_ids(arr, k=k):
            """ids of every (k-1)-byte window; m = size - k + 2."""
            m = arr.size - (k - 1) + 1
            ids = np.zeros(m, dtype=np.int64)
            for j in range(k - 1):
                ids = ids * 256 + arr[j:j + m]
            return ids

        counts = np.zeros(256 ** k, dtype=np.int64)
        if k == 1:
            np.add.at(counts, train, 1)
            logp = np.log((counts + 1.0) / (counts.sum() + 256.0))
            nats = float(-logp[val].mean())
        else:
            joint = ctx_ids(train)[:-1] * 256 + train[k - 1:]
            np.add.at(counts, joint, 1)
            ctx_tot = counts.reshape(-1, 256).sum(axis=1)
            vctx = ctx_ids(val)[:-1]
            vj = vctx * 256 + val[k - 1:]
            c = counts[vj].astype(np.float64)
            t = ctx_tot[vctx].astype(np.float64)
            nats = float(-np.log((c + 1.0) / (t + 256.0)).mean())
        out[f"ngram{k}_nats_per_byte"] = round(nats, 4)

    raw = bytes(bytearray(int(b) & 0xFF for b in val.tolist()))
    for name, comp in (("gzip", gzip.compress), ("bz2", bz2.compress),
                       ("lzma", lzma.compress)):
        nats = len(comp(raw)) * 8.0 * float(np.log(2.0)) / max(len(raw), 1)
        out[f"{name}_nats_per_byte"] = round(nats, 4)
    return out


def run_gpt_pysrc(steps=600, batch=16, seq=512, hidden=256, layers=4,
                  heads=4, lr=3e-4, target_val_nats=1.75, seed=0,
                  corpus=None):
    """Byte-level GPT on pysrc; returns the record with val nats/byte."""
    from apex_tpu import amp
    from apex_tpu.models.gpt import GPTConfig, GPTModel, lm_loss
    from apex_tpu.optimizers import FusedAdam

    corpus = _corpus() if corpus is None else corpus
    split = int(len(corpus) * 0.9)
    rng = np.random.RandomState(seed)
    cfg = GPTConfig(vocab_size=256, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, intermediate_size=4 * hidden)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=lr), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, ids):
        logits = model.apply({"params": p}, ids)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    step = jax.jit(amp.make_train_step(a, loss_fn))
    eval_loss = jax.jit(lambda p, ids: loss_fn(p, ids))

    t0 = time.perf_counter()
    train_loss = None
    for i in range(steps):
        ids = _windows(corpus, rng, batch, seq, 0, split)
        state, m = step(state, ids)
        train_loss = float(m["loss"])
    # fixed held-out windows from the tail 10% the model never saw
    vrng = np.random.RandomState(10_000 + seed)
    val = float(np.mean([
        float(eval_loss(a.model_params(state),
                        _windows(corpus, vrng, batch, seq, split,
                                 len(corpus))))
        for _ in range(8)]))
    return {"name": "gpt_pysrc", "steps": steps, "batch": batch,
            "seq": seq, "hidden": hidden, "layers": layers,
            "train_nats": round(train_loss, 4),
            "val_nats_per_byte": round(val, 4),
            "val_bits_per_byte": round(val / float(np.log(2)), 4),
            "target_val_nats": target_val_nats,
            "wall_s": round(time.perf_counter() - t0, 1),
            "ok": bool(val <= target_val_nats)}


def run_bert_mlm(steps=600, batch=16, seq=256, hidden=256, layers=4,
                 heads=4, lr=3e-4, target_mlm_nats=3.25, seed=0,
                 corpus=None):
    """Byte-level BERT MLM on pysrc (mask id 256, 15% positions).

    Target derivation: chance is ln(257) = 5.55 nats; the 4-layer
    miniature converges to ~3.07 val nats on chip (train 2.87 — the
    ~0.2 gap is this model's capacity/overfit limit on the 7 MB
    corpus), so the bar sits at 3.25 = ~41% below chance with ~6%
    regression headroom — a drift alarm, not a leaderboard."""
    from apex_tpu import amp
    from apex_tpu.models.bert import BertConfig, BertForPreTraining
    from apex_tpu.optimizers import FusedLAMB

    corpus = _corpus() if corpus is None else corpus
    split = int(len(corpus) * 0.9)
    rng = np.random.RandomState(seed)
    cfg = BertConfig(vocab_size=257, hidden_size=hidden, num_layers=layers,
                     num_heads=heads, intermediate_size=4 * hidden,
                     max_position_embeddings=seq)
    model = BertForPreTraining(cfg)
    MASK = 256

    def make_batch(lo, hi, r):
        ids = _windows(corpus, r, batch, seq, lo, hi)
        pos = jnp.asarray(r.rand(batch, seq) < 0.15)
        return jnp.where(pos, MASK, ids), ids, pos.astype(jnp.float32)

    x0, _, _ = make_batch(0, split, rng)
    params = model.init(jax.random.PRNGKey(seed), x0)["params"]
    a = amp.initialize(optimizer=FusedLAMB(lr=lr), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, masked, labels, mpos):
        mlm, _nsp = model.apply({"params": p}, masked)
        logp = jax.nn.log_softmax(mlm.astype(jnp.float32))
        ce = -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
        return jnp.sum(ce * mpos) / jnp.maximum(jnp.sum(mpos), 1.0)

    step = jax.jit(amp.make_train_step(a, loss_fn))
    eval_loss = jax.jit(lambda p, *b: loss_fn(p, *b))

    t0 = time.perf_counter()
    for i in range(steps):
        state, m = step(state, *make_batch(0, split, rng))
    vrng = np.random.RandomState(20_000 + seed)
    val = float(np.mean([
        float(eval_loss(a.model_params(state),
                        *make_batch(split, len(corpus), vrng)))
        for _ in range(8)]))
    return {"name": "bert_mlm", "steps": steps, "batch": batch,
            "seq": seq, "hidden": hidden, "layers": layers,
            "train_nats": round(float(m["loss"]), 4),
            "val_mlm_nats": round(val, 4),
            "chance_nats": round(float(np.log(257)), 3),
            "target_mlm_nats": target_mlm_nats,
            "wall_s": round(time.perf_counter() - t0, 1),
            "ok": bool(val <= target_mlm_nats)}


def run_dcgan_two_scaler(steps=300, batch=32, image_size=32, zdim=64,
                         lr=2e-4, seed=0, half_dtype="float16",
                         inject=(), probe_params_every=0):
    """Two-scaler DCGAN: overflows must be observed AND recovered.

    Two modes:
    - ``half_dtype="float16"`` (CPU slow tier): fp16's 65504 max makes
      the 2^16 initial scale genuinely overflow on early GAN gradients
      (observed ~step 19 on CPU) — the organic demonstration.  On the
      TPU backend fp16 numerics corrupt the run itself (non-native
      dtype; params NaN within ~50 steps even with every bad step
      skipped), so the chip record instead uses
    - ``half_dtype="bfloat16"`` + ``inject=(a, b)``: real GAN dynamics
      with an inf planted in the REAL batch at step ``a`` (must trip
      ONLY D's scaler — G's loss never sees the real batch, proving
      per-loss scaler independence, the ``num_losses`` point) and in
      ``z`` at step ``b`` (feeds both nets; both scalers trip).

    Recovery = after the last overflow the run keeps training with
    finite losses and halved-but-stable scales.
    """
    import optax

    from apex_tpu import amp
    from apex_tpu.models.dcgan import Discriminator, Generator, gan_losses

    half = jnp.bfloat16 if half_dtype == "bfloat16" else jnp.float16

    n_up = {32: 2, 64: 3}[image_size]
    G = Generator(feature_maps=32, n_upsample=n_up)
    D = Discriminator(feature_maps=32, n_down=n_up + 1)
    z0 = jax.random.normal(jax.random.PRNGKey(seed), (2, zdim))
    img0 = jnp.zeros((2, image_size, image_size, 3))
    gv = G.init(jax.random.PRNGKey(seed + 1), z0, train=True)
    dv = D.init(jax.random.PRNGKey(seed + 2), img0, train=True)

    adam = lambda: optax.adam(lr, b1=0.5, b2=0.999)
    a_g = amp.initialize(optimizer=adam(), opt_level="O2",
                         half_dtype=half, verbosity=0)
    a_d = amp.initialize(optimizer=adam(), opt_level="O2",
                         half_dtype=half, verbosity=0)
    gs, ds = a_g.init(gv["params"]), a_d.init(dv["params"])
    g_stats, d_stats = gv["batch_stats"], dv["batch_stats"]

    def make_d_loss(g_stats, d_stats):
        def d_loss(dp, gp, z, real):
            fake = G.apply({"params": gp, "batch_stats": g_stats}, z,
                           train=True, mutable=["batch_stats"])[0]
            d_real, d_mut = D.apply(
                {"params": dp, "batch_stats": d_stats}, real,
                train=True, mutable=["batch_stats"])
            d_fake, d_mut = D.apply(
                {"params": dp, "batch_stats": d_mut["batch_stats"]},
                jax.lax.stop_gradient(fake), train=True,
                mutable=["batch_stats"])
            loss, _ = gan_losses(d_real, d_fake, d_fake)
            return loss, d_mut["batch_stats"]
        return d_loss

    def make_g_loss(g_stats, d_stats):
        def g_loss(gp, dp, z):
            fake, g_mut = G.apply({"params": gp, "batch_stats": g_stats},
                                  z, train=True, mutable=["batch_stats"])
            logits, d_mut = D.apply({"params": dp, "batch_stats": d_stats},
                                    fake, train=True,
                                    mutable=["batch_stats"])
            _, loss = gan_losses(logits, logits, logits)
            return loss, (g_mut["batch_stats"], d_mut["batch_stats"])
        return g_loss

    @jax.jit
    def train_step(gs, ds, g_stats, d_stats, z, real):
        def scaled_d(dp):
            l, stats = a_d.run(make_d_loss(g_stats, d_stats), dp,
                               a_g.model_params(gs), z, real)
            return a_d.scale_loss(l, ds), (l, stats)
        d_grads, (dl, d_stats_) = \
            jax.grad(scaled_d, has_aux=True)(a_d.model_params(ds))
        ds, d_info = a_d.apply_gradients(ds, d_grads)

        def scaled_g(gp):
            l, stats = a_g.run(make_g_loss(g_stats, d_stats_), gp,
                               a_d.model_params(ds), z)
            return a_g.scale_loss(l, gs), (l, stats)
        g_grads, (gl, (g_stats_, d_stats_2)) = \
            jax.grad(scaled_g, has_aux=True)(a_g.model_params(gs))
        gs, g_info = a_g.apply_gradients(gs, g_grads)
        return (gs, ds, g_stats_, d_stats_2, dl, gl,
                d_info["overflow"], g_info["overflow"],
                d_info["loss_scale"], g_info["loss_scale"])

    t0 = time.perf_counter()
    d_over = g_over = 0
    last_over_step = -1
    first_bad_param_step = -1
    independence_ok = not inject     # only assessable with injections

    from apex_tpu.amp.scaler import all_finite

    @jax.jit
    def params_finite(gs, ds):
        return all_finite((gs.master_params, ds.master_params))

    for i in range(steps):
        kz, kr = jax.random.split(jax.random.PRNGKey(100 + i))
        z = jax.random.normal(kz, (batch, zdim))
        real = jnp.tanh(jax.random.normal(
            kr, (batch, image_size, image_size, 3)))
        if inject and i == inject[0]:
            real = real.at[0, 0, 0, 0].set(jnp.inf)   # D-only fault
        if len(inject) > 1 and i == inject[1]:
            z = z.at[0, 0].set(jnp.inf)               # hits both nets
        (gs, ds, g_stats, d_stats, dl, gl, d_o, g_o,
         d_scale, g_scale) = train_step(gs, ds, g_stats, d_stats, z, real)
        if inject and i == inject[0]:
            # per-loss independence: the real-batch fault must trip D's
            # scaler and leave G's untouched
            independence_ok = bool(d_o) and not bool(g_o)
        if bool(d_o):
            d_over += 1
            last_over_step = i
        if bool(g_o):
            g_over += 1
            last_over_step = i
        # param-corruption probe (the fp16-on-TPU question): a NaN that
        # reaches the MASTER params despite every overflowed step being
        # skipped is compute-dtype corruption, not a scaler failure
        if (probe_params_every and first_bad_param_step < 0
                and (i % probe_params_every == 0 or i == steps - 1)):
            if not bool(params_finite(gs, ds)):
                first_bad_param_step = i
    finite = bool(np.isfinite(float(dl)) and np.isfinite(float(gl)))
    recovered = finite and last_over_step < steps - 1
    return {"name": "dcgan_two_scaler", "steps": steps, "batch": batch,
            "half_dtype": half_dtype, "inject_steps": list(inject),
            "d_overflows": d_over, "g_overflows": g_over,
            "last_overflow_step": last_over_step,
            "scaler_independence_ok": independence_ok,
            "final_d_loss": round(float(dl), 4),
            "final_g_loss": round(float(gl), 4),
            "final_d_scale": float(d_scale),
            "final_g_scale": float(g_scale),
            "first_bad_param_step": first_bad_param_step,
            "wall_s": round(time.perf_counter() - t0, 1),
            "ok": bool((d_over + g_over) > 0 and recovered
                       and independence_ok)}


def run_dcgan_fp16_natural(steps=300):
    """fp16-compute DCGAN with NO injections — the natural-overflow
    exercise, run on whatever backend is live (VERDICT r4 next #7 asks
    for this ON CHIP).  The record classifies one of three outcomes:

    - ``natural_fp16_proof``: organic overflows occurred, every bad
      step was skipped, master params stayed finite, training recovered
      — the airtight on-hardware scaler story.
    - ``fp16_unviable_on_this_backend``: the scaler did its job (bad
      steps skipped) yet master params still went non-finite at
      ``first_bad_param_step`` — measured evidence that the backend's
      fp16 COMPUTE corrupts the run (r4 carried this only as a
      docstring claim), so the bf16+injection record remains the chip's
      scaler exercise.
    - inconclusive (``ok: false``): fp16 ran clean with zero overflows
      — neither proof nor finding.
    """
    base = run_dcgan_two_scaler(steps=steps, half_dtype="float16",
                                inject=(), probe_params_every=10)
    rec = dict(base, name="dcgan_fp16_onchip")
    over = base["d_overflows"] + base["g_overflows"]
    finite_end = bool(np.isfinite(base["final_d_loss"])
                      and np.isfinite(base["final_g_loss"]))
    # corruption means the MASTER PARAMS went non-finite (the probe
    # covers the final step too) — a non-finite final-step LOSS alone
    # is an ordinary organic overflow the scaler just skipped, not
    # evidence against fp16
    corrupted = base["first_bad_param_step"] >= 0
    if corrupted:
        rec["mode"] = "fp16_unviable_on_this_backend"
        rec["finding"] = (
            "master params went non-finite at step "
            f"{base['first_bad_param_step']} with "
            f"{over} overflow(s) detected and skipped — fp16 forward/"
            "backward compute corrupts values before the scaler can "
            "protect them (non-native dtype on this backend); the "
            "scaler exercise on chip therefore uses bf16 + targeted "
            "injection (dcgan_two_scaler)")
        rec["ok"] = True   # a conclusive, evidenced finding
    elif over > 0 and finite_end \
            and base["last_overflow_step"] < steps - 1:
        rec["mode"] = "natural_fp16_proof"
        rec["ok"] = True
    elif over > 0:
        # overflows happened but the run ended ON one — nothing after
        # it demonstrates recovery, so neither proof nor finding
        rec["mode"] = "inconclusive_no_recovery_window"
        rec["ok"] = False
    else:
        rec["mode"] = "inconclusive_no_overflow"
        rec["ok"] = False
    return rec


def run_o4_mnist(steps=200, batch=64, features=(128, 128), lr=1e-3,
                 band=0.15, seed=0):
    """O4 (fp8 + delayed scaling) vs O1 on an MNIST-scale MLP — the
    convergence evidence for the fp8 regime (ISSUE 9).

    Both runs see IDENTICAL synthetic digit batches (class-dependent
    Gaussian blobs, fixed seed), identical init, identical optimizer;
    the only difference is the opt level, so the comparison isolates
    the fp8 quantization error.  ``ok`` = both curves finite AND the
    O4 final loss within ``band`` (relative, + 0.05 nats absolute
    headroom near zero) of the O1 final loss — the same
    drift-alarm-not-leaderboard framing as the harness's other bars.
    The record carries both loss curves (every 10th step) plus the O4
    regime's own evidence: final delayed scales per tensor class,
    rescale events, and the saturation gauge's last value.
    """
    import optax

    from apex_tpu import amp
    from apex_tpu.models.mlp import MLP, cross_entropy_loss

    rng = np.random.RandomState(seed)
    # class-dependent blobs + 15% label noise: high-dim blobs alone are
    # linearly separable and both arms collapse to 0.0 (comparing
    # nothing) — the label noise pins an irreducible CE plateau
    # (~0.15*ln(10) ≈ 0.35 nats) where the two regimes' optimization
    # dynamics are actually comparable
    protos = rng.randn(10, 28, 28, 1).astype(np.float32)

    def make_batch(i):
        r = np.random.RandomState(1000 + i)
        y = r.randint(0, 10, size=batch)
        x = protos[y] + 2.5 * r.randn(batch, 28, 28, 1).astype(np.float32)
        flip = r.rand(batch) < 0.15
        y = np.where(flip, r.randint(0, 10, size=batch), y)
        return jnp.asarray(x), jnp.asarray(y)

    model = MLP(features=features)
    x0, _ = make_batch(0)
    params0 = model.init(jax.random.PRNGKey(seed), x0)["params"]

    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)

    t0 = time.perf_counter()
    curves = {}
    fp8_evidence = {}
    for lvl in ("O1", "O4"):
        a = amp.initialize(optimizer=optax.adam(lr), opt_level=lvl,
                           verbosity=0)
        state = a.init(params0)
        step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=0)
        curve = []
        rescales = 0
        sat = None
        for i in range(steps):
            state, m = step(state, *make_batch(i))
            if i % 10 == 0 or i == steps - 1:
                curve.append(round(float(m["loss"]), 4))
            if lvl == "O4":
                rescales += int(m["fp8_rescales"])
                sat = float(m["fp8_amax_saturation"])
        curves[lvl] = curve
        if lvl == "O4":
            fp8_evidence = {
                "fp8_rescale_events": rescales,
                "fp8_final_saturation": round(sat, 4),
                "fp8_final_scales": {
                    "input": float(state.fp8_state.input.scale),
                    "weight": float(state.fp8_state.weight.scale),
                    "grad": float(state.fp8_state.grad.scale)},
            }
    # tail MEAN, not the last point: per-batch loss noise at the
    # plateau is larger than the regime difference being measured
    o1 = round(float(np.mean(curves["O1"][-5:])), 4)
    o4 = round(float(np.mean(curves["O4"][-5:])), 4)
    finite = bool(np.isfinite(o1) and np.isfinite(o4))
    within = bool(o4 <= o1 * (1.0 + band) + 0.05)
    return {"name": "o4_mnist", "steps": steps, "batch": batch,
            "features": list(features), "band": band,
            "o1_curve": curves["O1"], "o4_curve": curves["O4"],
            "o1_final": o1, "o4_final": o4,
            **fp8_evidence,
            "wall_s": round(time.perf_counter() - t0, 1),
            "ok": bool(finite and within)}


def run_int8_kv_decode(train_steps=80, prompts=4, prompt_len=64,
                       decode_tokens=64, min_match_rate=0.9, seed=0,
                       corpus=None):
    """int8-KV decode lane: greedy decode with the int8 KV cache
    (``kv_dtype="int8"``: per-position scales, dequant fused into the
    attention read) vs the dense cache on the SAME briefly-trained
    byte-LM — the token-match rate is the artifact's record of the
    quantization's end-to-end effect, gated at the documented
    tolerance (``docs/source/quantization.rst``: >= 0.9 greedy match
    over fresh held-out prompts).  The int8 path must also be bitwise
    deterministic across runs (same program, same inputs)."""
    from apex_tpu import amp
    from apex_tpu.models.generate import generate
    from apex_tpu.models.gpt import GPTConfig, GPTModel, lm_loss
    from apex_tpu.optimizers import FusedAdam

    corpus = _corpus() if corpus is None else corpus
    split = int(len(corpus) * 0.9)
    rng = np.random.RandomState(seed)
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=4, intermediate_size=512)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    a = amp.initialize(optimizer=FusedAdam(lr=3e-4), opt_level="O2",
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, ids):
        logits = model.apply({"params": p}, ids)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    step = jax.jit(amp.make_train_step(a, loss_fn))
    t0 = time.perf_counter()
    for _ in range(train_steps):
        state, m = step(state, _windows(corpus, rng, 16, 128, 0, split))
    serving = a.model_params(state)          # bf16 serving cast

    vrng = np.random.RandomState(7_000 + seed)
    prompt = np.asarray(_windows(corpus, vrng, prompts, prompt_len,
                                 split, len(corpus)))
    dense = np.asarray(generate(serving, cfg, jnp.asarray(prompt),
                                decode_tokens))[:, prompt_len:]
    q1 = np.asarray(generate(serving, cfg, jnp.asarray(prompt),
                             decode_tokens, kv_dtype="int8"))[:, prompt_len:]
    q2 = np.asarray(generate(serving, cfg, jnp.asarray(prompt),
                             decode_tokens, kv_dtype="int8"))[:, prompt_len:]
    match = float(np.mean(dense == q1))
    bitwise = bool(np.array_equal(q1, q2))
    return {"name": "int8_kv_decode", "train_steps": train_steps,
            "prompts": prompts, "prompt_len": prompt_len,
            "decode_tokens": decode_tokens,
            "train_nats": round(float(m["loss"]), 4),
            "token_match_rate": round(match, 4),
            "min_match_rate": min_match_rate,
            "bitwise_deterministic": bitwise,
            "wall_s": round(time.perf_counter() - t0, 1),
            "ok": bool(match >= min_match_rate and bitwise)}


#: lane name -> needs_corpus flag; the r06 quant lanes are selectable
#: via --lanes so the CPU round can commit just the new evidence
#: without re-running the on-chip-scale LM lanes
QUANT_LANES = ("o4_mnist", "int8_kv")


def main():
    argv = list(sys.argv[1:])
    args, lanes = [], None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--lanes="):
            lanes = [x.strip() for x in a.split("=", 1)[1].split(",")
                     if x.strip()]
        elif a == "--lanes":
            if i + 1 >= len(argv):
                raise SystemExit("--lanes needs a comma list "
                                 f"(from {QUANT_LANES})")
            i += 1
            lanes = [x.strip() for x in argv[i].split(",") if x.strip()]
        elif a.startswith("--"):
            # an unknown flag silently falling through would run the
            # corpus-scale full harness with the flag as its out path
            raise SystemExit(f"unknown option {a!r} (only --lanes=...)")
        else:
            args.append(a)
        i += 1
    if lanes is not None:
        bad = [x for x in lanes if x not in QUANT_LANES]
        if bad:
            raise SystemExit(
                f"--lanes supports {QUANT_LANES} (the quant lanes); "
                f"unknown {bad} — the full harness runs with no --lanes")
        out_path = Path(args[0] if args else REPO / "CONVERGENCE_r06.json")
        records = {}
        if "o4_mnist" in lanes:
            rec = run_o4_mnist()
            records[rec["name"]] = rec
            print(json.dumps(rec))
        if "int8_kv" in lanes:
            rec = run_int8_kv_decode()
            records[rec["name"]] = rec
            print(json.dumps(rec))
        records["platform"] = str(jax.devices()[0])
        records["all_ok"] = all(r.get("ok", False)
                                for name, r in records.items()
                                if isinstance(r, dict))
        out_path.write_text(json.dumps(records, indent=1))
        print(f"wrote {out_path}  all_ok={records['all_ok']}")
        return

    # default to the CURRENT round's name: the full harness now carries
    # the quant lanes, and a no-arg run must not overwrite committed
    # round-5 gate memory with round-6 content
    out_path = Path(args[0] if args else REPO / "CONVERGENCE_r06.json")
    corpus = _corpus()
    records = {}
    # Externally-anchored floors on the same corpus/split (VERDICT r3
    # weak #4): the LM targets must not be self-referential.
    anchors = corpus_anchors(corpus)
    records["anchors"] = anchors
    print(json.dumps({"anchors": anchors}))
    for fn in (lambda: run_gpt_pysrc(corpus=corpus),
               # the compressor-beating milestone: a 6-layer/384-hidden
               # TPU-geometry (3x128 heads) miniature at 2400 steps
               # (~6 min on chip) must compress held-out pysrc BETTER
               # than lzma — the strongest external anchor available
               # (round-4 chip run: 1.025 nats/byte vs lzma 1.187,
               # gzip 1.365)
               lambda: dict(run_gpt_pysrc(
                   steps=2400, hidden=384, layers=6, heads=3,
                   target_val_nats=anchors["lzma_nats_per_byte"],
                   corpus=corpus), name="gpt_pysrc_large"),
               # byte-level MLM learns slower than causal LM: 2400
               # steps (~30 s on chip) to its plateau
               lambda: run_bert_mlm(steps=2400, corpus=corpus),
               # chip record: bf16 dynamics + targeted faults (see the
               # runner's docstring for why fp16 is CPU-only)
               lambda: run_dcgan_two_scaler(half_dtype="bfloat16",
                                            inject=(60, 150)),
               # fp16-compute natural-overflow attempt ON THIS BACKEND:
               # either the organic proof or the measured
               # fp16-unviability finding (VERDICT r4 next #7)
               run_dcgan_fp16_natural,
               # round-6 quant lanes: fp8 O4-vs-O1 loss curve and the
               # int8-KV greedy decode token-match rate
               run_o4_mnist,
               lambda: run_int8_kv_decode(corpus=corpus)):
        rec = fn()
        records[rec["name"]] = rec
        print(json.dumps(rec))
    # External pass bars: the causal LM must beat the strongest
    # same-direction statistical floor (add-1 trigram fit on the train
    # split); the MLM — whose bidirectional conditioning has no causal
    # n-gram analog — must beat the unigram floor.  Compressors are
    # context only (verbatim long-range matches, different direction).
    g = records.get("gpt_pysrc")
    if g:
        g["anchor_ngram3_nats"] = anchors["ngram3_nats_per_byte"]
        g["beats_ngram3"] = bool(
            g["val_nats_per_byte"] <= anchors["ngram3_nats_per_byte"])
        g["ok"] = bool(g["ok"] and g["beats_ngram3"])
    gl = records.get("gpt_pysrc_large")
    if gl:
        for comp in ("gzip", "bz2", "lzma"):
            gl[f"beats_{comp}"] = bool(
                gl["val_nats_per_byte"]
                <= anchors[f"{comp}_nats_per_byte"])
        gl["ok"] = bool(gl["ok"] and gl["beats_lzma"])
    m = records.get("bert_mlm")
    if m:
        m["anchor_ngram1_nats"] = anchors["ngram1_nats_per_byte"]
        key = ("val_mlm_nats" if "val_mlm_nats" in m
               else "val_nats_per_byte" if "val_nats_per_byte" in m
               else None)
        if key:
            m["beats_ngram1"] = bool(
                m[key] <= anchors["ngram1_nats_per_byte"])
            m["ok"] = bool(m["ok"] and m["beats_ngram1"])
    records["platform"] = str(jax.devices()[0])
    # "anchors" is the only record without a pass/fail of its own; any
    # OTHER dict missing "ok" is a bug and must fail the aggregate —
    # not silently count as passing, and not KeyError away the whole
    # run's results before they're written.
    records["all_ok"] = all(r.get("ok", False)
                            for name, r in records.items()
                            if isinstance(r, dict) and name != "anchors")
    out_path.write_text(json.dumps(records, indent=1))
    print(f"wrote {out_path}  all_ok={records['all_ok']}")


if __name__ == "__main__":
    main()
