"""Serve scenario-matrix harness: every scenario cell gated, the
speculative-decoding latency win A/B'd, committed as
``SCENARIO_r*.json``.

Drives the serve engine (and its speculative variant,
:class:`apex_tpu.serve.SpecEngine`) through a MATRIX of scenarios —

- mixed context lengths (128 / 512 / 2048 committed; a 32k cell rides
  ``--full``, the slow lane),
- **burst vs steady** arrivals (all requests up front vs one per step
  boundary),
- per-slot **sampling knobs** (all-greedy vs greedy+temperature/top-k
  mixed in one batch),
- **slot churn**: a deliberately tight block pool so admission
  preempts mid-stream (the cell gate additionally requires
  ``preemptions >= 1`` — a churn cell that churned nothing measured
  nothing),
- the **int8 KV cache** on/off,
- **speculative decoding** on/off (truncated layer-skip draft,
  ``k`` proposals/round),
- **cross-request prefix sharing** exercised two ways: a multi-turn
  chat column (turn 2 resubmits turn 1's prompt + streamed reply —
  the content index must match the whole history) and a
  common-system-prompt burst column (every request shares a
  block-aligned system prefix).  Every cell whose engine runs the
  prefix cache records a ``prefix`` block (probes/hits/hit_rate,
  schema-validated: the rate must re-derive from the counts)

— and emits one schema-valid document (``apex_tpu/analysis/
scenario.py``, validated by ``tools/gate_hygiene.py`` in tier-1) in
which every cell carries the latency-tail gate the serve bench config
uses (``p99 <= K x p50`` from the engine's OWN
``serve_decode_step_seconds`` histogram, ``retraces == 1``), and each
spec cell is paired with its identical-workload baseline in a
tokens-per-decode-step A/B.  The ``gated`` rows — the steady greedy
cells — are the committed claim: speculative decoding converts
bandwidth into tokens/step on this host, strictly.

The model is BRIEFLY TRAINED (the PR 8 fixture pattern): a random-init
model's near-uniform logits make acceptance rates meaningless and put
ulp noise above the argmax margins; the trained tiny model gives the
draft something real to predict.

Usage:
    python tools/serve_scenarios.py --emit-json SCENARIO_r01.json \
        [--cpu-smoke] [--full] [--spec-k 3]

``--cpu-smoke`` is the committed-r01 shape (gpt_tiny, trained
in-process, the full 128-2048 matrix); without it the sweep runs
gpt_small_tpu (a chip-round config).  ``--full`` adds the 32k-context
cell (slow — minutes on CPU).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=16").strip()
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))
jax.config.update("jax_threefry_partitionable", True)

#: the latency-tail multiplier — the same bar bench.py's serve config
#: gates (a mid-serve retrace or host sync shows up as 100-1000x)
GATE_K = 20.0

#: absolute decode-step p99 SLO budget (seconds) recorded per cell via
#: apex_tpu.obs.slo: the tail gate above is the RELATIVE witness
#: (p99 vs p50); this is the absolute one — a retrace/host-sync
#: blowout (100-1000x a normal step) violates it on any host, normal
#: CPU-smoke noise does not.  A chip round tightens it to serving
#: budgets.
SLO_DECODE_P99_S = 0.25

#: spec cells additionally carry an acceptance-rate floor objective
#: (accepted/proposed over the cell window; the measured briefly-
#: trained rates run 0.8-1.0 — 0.2 is the drafts-are-working bar)
SLO_MIN_ACCEPTANCE = 0.2


def trained_model(tiny: bool):
    """``(cfg, params, ids)`` — briefly trained on a periodic stream
    (the ONE shared recipe,
    :func:`apex_tpu.models.gpt.train_toy_lm`) so argmax margins are
    real and the truncated draft has structure to predict."""
    from apex_tpu.models.gpt import gpt_small_tpu, gpt_tiny, \
        train_toy_lm

    return train_toy_lm(gpt_tiny() if tiny else gpt_small_tpu())


def _requests(ids, context, new_tokens, n, sampling,
              shared_system=False, block_size=4):
    """``n`` requests whose prompts come from the training stream
    (predictable for the draft), lengths alternating full/0.75 of the
    cell's prompt budget, knobs per the cell's sampling mode.  With
    ``shared_system`` every prompt opens with the SAME block-aligned
    system prefix (half the budget) — the chat-service shape the
    prefix-sharing columns exercise."""
    from apex_tpu.serve import Request

    plen_full = context - new_tokens
    sys_len = max((plen_full // 2) // block_size * block_size,
                  block_size) if shared_system else 0
    system = np.asarray(
        [ids[0][j % ids[0].shape[0]] for j in range(sys_len)],
        np.int32)
    reqs = []
    rng = np.random.RandomState(17)
    for i in range(n):
        plen = max(2, int(plen_full * (0.75 + 0.25 * ((i + 1) % 2))))
        row = ids[i % ids.shape[0]]
        tail = np.asarray(
            [row[j % row.shape[0]] for j in range(plen - sys_len)],
            np.int32)
        prompt = np.concatenate([system, tail]) if sys_len else tail
        kw = {}
        if sampling == "mixed" and i % 2 == 1:
            kw = dict(temperature=0.8, top_k=20,
                      seed=int(rng.randint(1 << 16)))
        reqs.append(Request(uid=f"q{i}", prompt=prompt,
                            max_new_tokens=new_tokens, **kw))
    return reqs


def run_cell(cfg, params, draft, reqs, *, context, new_tokens,
             num_slots, arrival, sampling, kv8, spec, churn, spec_k,
             block_size=4, chat=False):
    """One scenario cell: build a fresh engine of the cell's shape,
    drive the request stream ``reqs`` with the cell's arrival process,
    and return the schema's cell record (numbers + the derived
    gate).  Under ``chat`` a second turn follows the first: each
    request resubmits its own prompt + streamed reply + a recycled
    user turn, so the content index must match the whole history
    (prompt blocks registered at arm, reply blocks at decode block
    boundaries)."""
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import (ServeConfig, ServeEngine, SpecConfig,
                                SpecEngine)

    mb = -(-context // block_size)
    if churn:
        # churn: half-context requests into a pool that covers exactly
        # TWO of their worst-case footprints with THREE slots — the
        # third admission meets a free slot but a block shortage, so
        # the scheduler preempts the youngest (recompute-on-resume)
        # and the continuation re-queues; the cell gate requires the
        # preemption to actually have fired
        foot = -(-(context // 2) // block_size)
        num_blocks = 2 * foot + 1
    else:
        num_blocks = num_slots * mb + 1
    scfg = ServeConfig(
        num_slots=num_slots, block_size=block_size,
        num_blocks=num_blocks, max_blocks_per_slot=mb,
        prefill_chunk=min(64, max(block_size, context - new_tokens)),
        kv_dtype="int8" if kv8 else None,
        # churn pins sharing OFF: the training-stream prompts repeat
        # rows, so the content index would dedupe them and absorb the
        # engineered block shortage — and this column exists to
        # measure the preempt/recompute path, not prefix reuse
        prefix_cache=not churn)
    reg = Registry()
    if spec:
        dp, dcfg = draft
        eng = SpecEngine(params, cfg, scfg, dp, dcfg,
                         SpecConfig(k=spec_k), registry=reg)
    else:
        eng = ServeEngine(params, cfg, scfg, registry=reg)
    hist = reg.histogram("serve_decode_step_seconds")
    toks = reg.counter("serve_tokens_total")

    pending = list(reqs)
    if arrival == "burst":
        for r in pending:
            eng.submit(r)
        pending = []
    else:
        eng.submit(pending.pop(0))
    eng.step()                       # admission + compile + 1st step
    mark = hist.state()
    tok0 = toks.value
    # SLO verdicts ride the cell (apex_tpu.obs.slo): evaluated at the
    # same boundaries the registry already ticks, over resolved host
    # state only — the first evaluate() below just seeds the window
    # base at the post-compile mark
    from apex_tpu.obs.slo import SLObjective, SLOEvaluator
    objectives = [SLObjective(
        name="decode_p99", kind="quantile",
        metric="serve_decode_step_seconds", q=0.99,
        threshold=SLO_DECODE_P99_S, window=0, min_count=4)]
    if spec:
        objectives.append(SLObjective(
            name="spec_acceptance", kind="ratio",
            ratio_num="serve_spec_accepted_total",
            ratio_den="serve_spec_proposed_total", op="ge",
            threshold=SLO_MIN_ACCEPTANCE, window=0, min_count=4))
    slo_ev = SLOEvaluator(reg, objectives)
    slo_ev.evaluate()
    t0 = time.perf_counter()
    guard = 0
    done = {}
    while pending or not eng.sched.idle():
        if pending:
            eng.submit(pending.pop(0))
        done.update(eng.step())
        slo_ev.evaluate()
        guard += 1
        if guard > 100_000:
            raise RuntimeError("scenario cell stalled")
    if chat:
        # turn 2 of the chat: history (prompt + reply) + a recycled
        # user turn, through the SAME engine — the turn-1 blocks are
        # cached (refcount 0, still matchable) after retirement
        from apex_tpu.serve import Request
        for r in reqs:
            out = np.asarray(done[r.uid], np.int32)
            prompt2 = np.concatenate(
                [np.asarray(r.prompt, np.int32), out,
                 np.asarray(r.prompt[:block_size], np.int32)])
            eng.submit(Request(uid=f"{r.uid}t2", prompt=prompt2,
                               max_new_tokens=new_tokens))
        while not eng.sched.idle():
            done.update(eng.step())
            slo_ev.evaluate()
            guard += 1
            if guard > 100_000:
                raise RuntimeError("scenario chat turn stalled")
    wall = time.perf_counter() - t0
    decode_steps = int(hist.count - mark[2])
    decode_tokens = int(toks.value - tok0)
    p50 = hist.quantile(0.5, since=mark) * 1e3 if decode_steps else 0.0
    p99 = hist.quantile(0.99, since=mark) * 1e3 if decode_steps else 0.0
    retraces = max(eng.trace_counts.values())
    preempts = int(reg.counter("serve_preemptions_total").value)
    # gate on the ROUNDED values the record stores: the schema
    # re-derives tail_ok from the recorded numbers, so gating on the
    # raw floats could make this tool refuse its own honest artifact
    # on a borderline cell ("CONTRADICTORY" over a rounding epsilon)
    p50_r = round(p50, 3)
    p99_r = round(max(p99, p50), 3)
    tail_ok = p99_r <= GATE_K * p50_r
    retrace_ok = retraces == 1
    rec = {
        "config": {"context": int(context),
                   "new_tokens": int(new_tokens),
                   "num_slots": int(num_slots),
                   "arrival": arrival, "sampling": sampling,
                   "kv8": bool(kv8), "spec": bool(spec),
                   "churn": bool(churn)},
        "tok_s": round(decode_tokens / wall, 2) if wall else 0.0,
        "p50_ms": p50_r, "p99_ms": p99_r,
        # the REAL counts, zeros included: a cell that measured no
        # decode steps must fail the schema's >= 1 rule, not be
        # dressed up as a 1-step measurement that never happened
        "decode_steps": decode_steps,
        "decode_tokens": decode_tokens,
        "tokens_per_step": round(decode_tokens / decode_steps, 4)
        if decode_steps else 0.0,
        "retraces": int(retraces),
        # a churn cell that never preempted is schema-INVALID (the
        # scenario schema requires preemptions >= 1 under churn), so
        # the gate needs no extra term here — mutating gate.ok would
        # only make an honest churnless record read as contradictory
        "preemptions": preempts,
        "gate": {"tail_ok": bool(tail_ok),
                 "retrace_ok": bool(retrace_ok),
                 "ok": bool(tail_ok and retrace_ok)},
        # the SLO verdict block (schema-validated when present): the
        # absolute latency budget + (spec) acceptance floor, judged by
        # apex_tpu.obs.slo over the cell's own window
        "slo": slo_ev.summary(),
    }
    if spec:
        rec["acceptance_rate"] = round(
            float(reg.gauge("serve_spec_acceptance_rate").value), 4)
    # every engine running the prefix cache reports its cell-level
    # hit accounting (schema-validated: the rate must re-derive)
    if getattr(eng.sched, "prefix_cache", False):
        probes = int(eng.sched.prefix_probes)
        rec["prefix"] = {
            "probes": probes,
            "hits": int(eng.sched.prefix_hits),
            "hit_rate": round(
                eng.sched.prefix_hits / max(probes, 1), 6),
        }
    return rec


#: the committed matrix: (name, dict(cell knobs), gated-A/B?).  Cells
#: come in spec-off/spec-on pairs over the SAME request stream; the
#: steady greedy pairs carry the committed tokens-per-step gate.
def cell_matrix(full: bool):
    base = [
        ("ctx128_steady_greedy",
         dict(context=128, new_tokens=16, arrival="steady",
              sampling="greedy", kv8=False, churn=False), True),
        ("ctx128_burst_greedy",
         dict(context=128, new_tokens=16, arrival="burst",
              sampling="greedy", kv8=False, churn=False), False),
        ("ctx128_steady_mixed",
         dict(context=128, new_tokens=16, arrival="steady",
              sampling="mixed", kv8=False, churn=False), False),
        ("ctx128_burst_churn",
         dict(context=128, new_tokens=16, arrival="burst",
              sampling="greedy", kv8=False, churn=True,
              num_slots=3), False),
        # the prefix-sharing columns: multi-turn chat (turn 2 reuses
        # the whole turn-1 history through the content index) and a
        # common-system-prompt burst (every request shares a
        # block-aligned prefix) — each carries its cell-level
        # prefix_hit_rate, schema-validated against its own counts
        ("ctx128_multiturn_chat",
         dict(context=128, new_tokens=16, arrival="steady",
              sampling="greedy", kv8=False, churn=False,
              chat=True), False),
        ("ctx128_burst_sysprompt",
         dict(context=128, new_tokens=16, arrival="burst",
              sampling="greedy", kv8=False, churn=False,
              sysprompt=True), False),
        ("ctx512_steady_greedy",
         dict(context=512, new_tokens=16, arrival="steady",
              sampling="greedy", kv8=False, churn=False), True),
        ("ctx512_steady_kv8",
         dict(context=512, new_tokens=16, arrival="steady",
              sampling="greedy", kv8=True, churn=False), False),
        ("ctx2048_steady_greedy",
         dict(context=2048, new_tokens=8, arrival="steady",
              sampling="greedy", kv8=False, churn=False), True),
    ]
    if full:
        # the 32k cell: the slow lane (minutes of chunked prefill on
        # CPU); bigger blocks keep the page table sane at this reach
        base.append(("ctx32k_steady_greedy",
                     dict(context=32768, new_tokens=4, arrival="steady",
                          sampling="greedy", kv8=False, churn=False,
                          block_size=64, n_requests=1, num_slots=1),
                     False))
    return base


def sweep(tiny: bool, full: bool, spec_k: int, verbose: bool = True):
    """Run the whole matrix; returns ``(cells, ab)`` for the
    artifact."""
    from apex_tpu.serve import truncated_draft

    cfg, params, ids = trained_model(tiny)
    draft = truncated_draft(params, cfg, max(1, cfg.num_layers - 1))
    cells, ab = {}, []
    for name, knobs, gated in cell_matrix(full):
        knobs = dict(knobs)
        num_slots = knobs.pop("num_slots", 2)
        n_requests = knobs.pop("n_requests", None)
        block_size = knobs.pop("block_size", 4)
        sysprompt = knobs.pop("sysprompt", False)
        chat = knobs.pop("chat", False)
        # churn cells run half-context requests (the pool is sized to
        # cover exactly two of their footprints — see run_cell); chat
        # cells too, so turn 2 (history + reply + next turn) still
        # fits the per-slot footprint; config.context stays the
        # cell's context CAPACITY
        req_ctx = knobs["context"] // 2 if (knobs["churn"] or chat) \
            else knobs["context"]
        reqs = _requests(ids, req_ctx, knobs["new_tokens"],
                         n_requests or 2 * num_slots, knobs["sampling"],
                         shared_system=sysprompt,
                         block_size=block_size)
        pair = {}
        for spec in (False, True):
            cell_name = f"{name}_spec" if spec else name
            t0 = time.perf_counter()
            rec = run_cell(cfg, params, draft, list(reqs),
                           num_slots=num_slots, block_size=block_size,
                           spec=spec, spec_k=spec_k, chat=chat,
                           **knobs)
            cells[cell_name] = rec
            pair[spec] = (cell_name, rec)
            if verbose:
                print(f"  {cell_name}: tok/step "
                      f"{rec['tokens_per_step']} p50 {rec['p50_ms']}ms "
                      f"p99 {rec['p99_ms']}ms ok={rec['gate']['ok']} "
                      f"({time.perf_counter() - t0:.1f}s)",
                      file=sys.stderr)
        on_name, on = pair[True]
        off_name, off = pair[False]
        ab.append({
            "on": on_name, "off": off_name,
            "tokens_per_step_on": on["tokens_per_step"],
            "tokens_per_step_off": off["tokens_per_step"],
            "spec_wins": bool(on["tokens_per_step"]
                              > off["tokens_per_step"]),
            "gated": bool(gated),
        })
    return cfg, cells, ab


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--emit-json", default=None,
                    metavar="SCENARIO_rN.json",
                    help="write the committed gate artifact")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="gpt_tiny trained in-process (the committed-"
                         "r01 shape); default gpt_small_tpu")
    ap.add_argument("--full", action="store_true",
                    help="add the 32k-context cell (slow)")
    ap.add_argument("--spec-k", type=int, default=3,
                    help="draft proposals per speculation round")
    opts = ap.parse_args(argv)

    cfg, cells, ab = sweep(opts.cpu_smoke, opts.full, opts.spec_k)
    cells_ok = all(c["gate"]["ok"] for c in cells.values())
    gated = [r["spec_wins"] for r in ab if r["gated"]]
    ab_ok = bool(gated) and all(gated)
    # fleet-level SLO verdict: every cell's objective block clean
    slo_ok = all(c.get("slo", {}).get("ok", True)
                 for c in cells.values())
    doc = {
        "slo": {"decode_p99_budget_s": SLO_DECODE_P99_S,
                "min_acceptance": SLO_MIN_ACCEPTANCE,
                "ok": bool(slo_ok)},
        "round": 0,
        "platform": jax.devices()[0].platform,
        "model": "gpt_tiny" if opts.cpu_smoke else "gpt_small_tpu",
        "gate_k": GATE_K,
        "cells": cells,
        "ab": ab,
        "gate": {"cells_ok": bool(cells_ok), "ab_ok": bool(ab_ok),
                 "ok": bool(cells_ok and ab_ok)},
        "note": (
            "CPU smoke: wall-clock latencies are host-core numbers; "
            "what the cells pin structurally is the tail bound (no "
            "mid-serve retrace/host-sync), retraces==1 across every "
            "arrival/sampling/churn/kv8/spec combination, and the "
            "spec-vs-baseline tokens-per-decode-step win at equal "
            "work.  The chip round re-runs the same matrix at "
            "gpt_small_tpu scale."
            if jax.devices()[0].platform == "cpu" else
            "on-chip scenario matrix at serving scale"),
    }
    if opts.emit_json:
        m = re.search(r"_r(\d+)\.json$", os.path.basename(opts.emit_json))
        doc["round"] = int(m.group(1)) if m else 0
        from apex_tpu.analysis.scenario import validate_scenario
        problems = validate_scenario(doc)
        if problems:
            print(f"serve_scenarios: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
        with open(opts.emit_json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"scenario artifact written: {opts.emit_json} "
              f"({len(cells)} cells)", file=sys.stderr)
    print(json.dumps(doc))
    return 0 if doc["gate"]["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
