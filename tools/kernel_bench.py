"""Per-kernel microbenchmarks: fused optimizers, multi-tensor ops,
fused LayerNorm — step time + achieved HBM bandwidth vs roofline.

The model-level bench (``bench.py``) folds optimizer cost into full
train steps, where a 2%-of-step kernel regression hides inside chip-day
variance (VERDICT r4 missing #3).  This tool isolates each Pallas
kernel on HBM-resident flat buffers and records per-step time, analytic
bytes moved, achieved GB/s, and the fraction of the chip's HBM roofline
— all these kernels are elementwise/reduction passes, so bandwidth IS
their roofline (BASELINE.md: "FusedAdam step time — tracked per chip").

Method (tunnel-safe, see the axon notes): each kernel runs inside a
jitted ``lax.scan`` of K chained steps — the kernel's outputs feed the
next iteration's inputs, so the loop body cannot be hoisted — timed by
a scalar fetch around the whole scan (``block_until_ready`` does not
drain the pipeline over this transport).  The per-call ~100 ms tunnel
RTT would still inflate ``total/K`` by RTT/K, so the per-step time is
taken as a **difference quotient**: best-of-trials at K and at 6K,
``(t_6K - t_K) / 5K`` — the constant per-call overhead cancels exactly
and RTT jitter amortizes over 5K steps.

Gate: ``--compare KERNELBENCH_rN.json`` fails (exit 2) when any
kernel's per-step time worsens by more than ``--threshold`` (default
10%, calibrated like bench.py's: chip-day variance is ±2-4%).

CAVEAT on reading the optimizer numbers: the chained scans here leave
every input dead after its call, so ``input_output_aliases`` donation
would measure ~2x — but the PRODUCTION train step wraps the optimizer
in the loss-scale skip-``cond``, whose untaken branch returns the old
state, keeping p/m/v live across the update; XLA then materializes
full copies and the "win" inverts (measured on chip: BERT-large
105 -> 54 seq/s with aliased LAMB kernels, and chunk-32768 packing
OOM'd the b16 step outright).  The multi-tensor scale/axpby kernels DO
alias in production — their callers run before the skip decision — and
their numbers here reflect it.

Bytes accounting per kernel (N = elements, fp32 flats unless noted):

- ``fused_adam``    R p+m+v+g (16N)  W p+m+v (12N) + bf16 copy (2N)
- ``lamb_stage1``   R g+p+m+v (16N)  W u+m+v (12N) + the fused per-chunk
  norm tables (with_norms — the production driver config; ~N/chunk·8 B,
  accounted as 0)
- ``lamb_stage2``   R p+u (8N)       W p (4N) + bf16 copy (2N)
- ``mt_scale``      R 4N             W 4N
- ``mt_axpby``      R 8N             W 4N
- ``mt_sumsq``      R 4N             W ~0
- ``layernorm_fwd`` (B,H) bf16: R 2S  W 2S + 8B/row stats (S = B*H)
- ``layernorm_fwd_bwd`` adds R dy+x+stats, W dx (+ the dw/db partial
  reduction XLA appends) — accounted as 6S + fwd

Geometry: every record carries the resolved block geometry (the shared
selector's choice, ``apex_tpu.ops.pallas.geometry``) so the artifact
states the shape it measured; ``--autotune`` sweeps each retunable
kernel's geometry knob over its candidate ladder (short timings), picks
the fastest, and records the sweep alongside the final full-length
timing.

Floors: ``KERNEL_FLOORS`` publishes a per-kernel roofline-fraction
floor (the KERNELBENCH_r05 measured values, MFU_FLOORS convention:
gate = floor × (1 − band); floors only move with BENCH_VARIANCE.json
evidence — tests/l1/test_bench_units.py pins the no-ratchet-down rule).
The ``floors`` block is always recorded; ``--assert-floors`` makes a
violation exit 2 (the ``gate_exit_code`` pattern bench.py's absolute
gates use).  Roofline fractions are only meaningful on TPU — off-chip
the floors block records ``skipped`` and never gates.

Usage: python tools/kernel_bench.py [--out KERNELBENCH.json]
       [--compare KERNELBENCH_rN.json] [--threshold 0.10] [--tiny]
       [--autotune] [--assert-floors]
"""

import argparse
import json
import re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

CHUNK = 2048 * 32   # the multi-tensor chunk (reference semantics const)


def _hbm_peak() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, bw in {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9,
                    "v5p": 2765e9, "v6": 1640e9}.items():
        if key in kind:
            return bw
    return 819e9


def _sync(out) -> float:
    """Drain the pipeline via a TRUE scalar fetch: slice one element ON
    DEVICE, transfer 4 bytes.  ``np.asarray(out)`` would ship the whole
    256 MB result over the ~25-50 MB/s tunnel (~7 s, with enough wire
    jitter to bury the difference quotient); ``block_until_ready`` does
    not drain at all on this transport (axon notes)."""
    leaf = jax.tree.leaves(out)[0]
    return float(leaf.ravel()[0].astype(jnp.float32))


def _time_scan_at(build, k: int, trials: int) -> float:
    """Best-of-``trials`` wall seconds for one compiled scan(k) call,
    synced by a scalar fetch."""
    run, args = build(k)
    compiled = jax.jit(run).lower(*args).compile()
    _sync(compiled(*args))
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        _sync(compiled(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _time_scan(build, iters: int, trials: int = 3) -> float:
    """Per-step seconds as the difference quotient between scan(iters)
    and scan(6*iters): the constant per-call tunnel overhead (dispatch
    + RTT + fetch) cancels; only the 5*iters extra steps remain."""
    t_short = _time_scan_at(build, iters, trials)
    t_long = _time_scan_at(build, 6 * iters, trials)
    return max(t_long - t_short, 1e-9) / (5 * iters)


def _lint_candidate(build) -> list:
    """Rule ids the Pallas sanitizer rejects a candidate geometry for.

    Traces one tiny ``scan(2)`` step through
    :mod:`apex_tpu.analysis.pallas_lint` — trace only, no compile, no
    execution — and returns the sorted error-severity rule ids (empty
    = clean).  ``--autotune`` refuses to time or record a knob entry
    the sanitizer rejects: an over-budget or racy geometry must never
    win a sweep on a lucky interpret-mode timing and land in the knob
    table (the export-gate treatment, applied to autotune)."""
    from apex_tpu.analysis import pallas_lint
    run, args = build(2)
    report = pallas_lint.lint_fn(run, *args)
    return sorted({f.op for f in report.findings
                   if f.severity == "error" and f.op})


def bench_fused_adam(n: int, block_rows: "int | None" = None):
    from apex_tpu.ops.pallas.adam_kernel import adam_geometry, packed_adam

    geom = adam_geometry(n, with_copy=True, block_rows=block_rows)

    def build(k):
        key = jax.random.PRNGKey(0)
        p = jax.random.normal(key, (n,), jnp.float32)
        g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)

        def run(p, m, v, g):
            def body(carry, _):
                p, m, v = carry
                p, m, v, _copy = packed_adam(
                    p, m, v, g, step_size=1e-3, beta1=0.9, beta2=0.999,
                    eps=1e-8, scale=1.0, weight_decay=0.0, eps_mode=1,
                    p_copy_dtype=jnp.bfloat16, block_rows=block_rows)
                return (p, m, v), None
            (p, m, v), _ = jax.lax.scan(body, (p, m, v), None, length=k)
            return p
        return run, (p, m, v, g)

    return build, 30.0 * n, geom.asdict()


def bench_lamb_stage1(n: int, chunks_per_block: "int | None" = None):
    from apex_tpu.ops.pallas.lamb_kernels import (grown_chunk,
                                                  packed_lamb_stage1,
                                                  stage1_geometry)

    chunk = grown_chunk(n)   # the chunk the production driver packs at n
    geom = stage1_geometry(n, chunk, chunks_per_block)

    def build(k):
        g = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
        p = jax.random.normal(jax.random.PRNGKey(3), (n,), jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)
        decay = jnp.zeros((n // chunk,), jnp.float32)

        def run(g, p, m, v):
            def body(carry, _):
                g, m, v = carry
                # with_norms: the production driver config — the fused
                # per-chunk ‖p‖²/‖u‖² tables ride along
                u, m, v, _psq, _usq = packed_lamb_stage1(
                    g, p, m, v, decay, beta1=0.9, beta2=0.999, eps=1e-6,
                    inv_scale=1.0, bc1=1.0, bc2=1.0, chunk_size=chunk,
                    chunks_per_block=chunks_per_block, with_norms=True)
                return (u, m, v), None   # update feeds the next "grad"
            (u, m, v), _ = jax.lax.scan(body, (g, m, v), None, length=k)
            return u
        return run, (g, p, m, v)

    return build, 28.0 * n, geom.asdict()


def bench_lamb_stage2(n: int, chunks_per_block: "int | None" = None):
    from apex_tpu.ops.pallas.lamb_kernels import (grown_chunk,
                                                  packed_lamb_stage2,
                                                  stage2_geometry)

    chunk = grown_chunk(n)
    geom = stage2_geometry(n, chunk, with_copy=True,
                           chunks_per_block=chunks_per_block)

    def build(k):
        p = jax.random.normal(jax.random.PRNGKey(4), (n,), jnp.float32)
        u = jax.random.normal(jax.random.PRNGKey(5), (n,), jnp.float32)
        ratio = jnp.full((n // chunk,), 1e-3, jnp.float32)

        def run(p, u):
            def body(carry, _):
                p2, _copy = packed_lamb_stage2(
                    carry, u, ratio, chunk_size=chunk,
                    p_copy_dtype=jnp.bfloat16,
                    chunks_per_block=chunks_per_block)
                return p2, None
            p, _ = jax.lax.scan(body, p, None, length=k)
            return p
        return run, (p, u)

    return build, 14.0 * n, geom.asdict()


def _chunk_geometry(n: int) -> dict:
    """Geometry of the fixed-chunk multi-tensor kernels (one CHUNK-sized
    block per grid step, 128-lane view)."""
    from apex_tpu.ops.pallas.geometry import StreamGeometry
    return StreamGeometry(block_rows=CHUNK // 128, lanes=128,
                          grid=n // CHUNK).asdict()


def bench_mt_scale(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_scale

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(6), (n,), jnp.float32)

        def run(x):
            def body(carry, _):
                out, _flag = packed_scale(carry, 1.0000001, CHUNK,
                                          jnp.float32)
                return out, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    return build, 8.0 * n, _chunk_geometry(n)


def bench_mt_axpby(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_axpby

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(7), (n,), jnp.float32)
        y = jax.random.normal(jax.random.PRNGKey(8), (n,), jnp.float32)

        def run(x, y):
            def body(carry, _):
                out, _flag = packed_axpby(carry, y, 0.999, 0.001, CHUNK,
                                          jnp.float32)
                return out, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x, y)

    return build, 12.0 * n, _chunk_geometry(n)


def bench_mt_sumsq(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_sumsq

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(9), (n,), jnp.float32)

        def run(x):
            def body(carry, _):
                x, s = carry
                # O(1)-traffic dependence: the result feeds one element
                # back (scaled so the write is non-trivial but the value
                # drift is ~1e-13) — a literal *0.0 constant-folds away
                # and lets XLA hoist the whole kernel out of the loop
                # (measured: "1.3x roofline")
                r = packed_sumsq(x, CHUNK)
                x = x.at[0].add(r * 1e-20)
                return (x, s + r), None
            (x, s), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), None,
                                     length=k)
            return s
        return run, (x,)

    return build, 4.0 * n, _chunk_geometry(n)


def _ln_geometry(rows: int, hidden: int,
                 block_rows: "int | None" = None) -> dict:
    from apex_tpu.ops.pallas.geometry import StreamGeometry
    from apex_tpu.ops.pallas.layer_norm_kernels import fwd_block_rows
    br = fwd_block_rows(rows, hidden, jnp.bfloat16, block_rows)
    return StreamGeometry(block_rows=br, lanes=hidden,
                          grid=-(-rows // br)).asdict()


def bench_layernorm_fwd(rows: int, hidden: int,
                        block_rows: "int | None" = None):
    from apex_tpu.ops.pallas import layer_norm_kernels as lnk

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(10), (rows, hidden),
                              jnp.bfloat16)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)

        def run(x):
            def body(carry, _):
                # the kernel itself (the wrapper's reshape is free) so the
                # autotune sweep can pass the block override through
                y, _mean, _inv = lnk._forward(carry, w, b, 1e-5, True,
                                              block_rows=block_rows)
                return y, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    s = rows * hidden
    return build, 4.0 * s + 8.0 * rows, _ln_geometry(rows, hidden,
                                                     block_rows)


def bench_layernorm_fwd_bwd(rows: int, hidden: int):
    from apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine)

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(11), (rows, hidden),
                              jnp.bfloat16)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)

        def run(x):
            def body(carry, _):
                y, f_vjp = jax.vjp(
                    lambda t: fused_layer_norm_affine(t, w, b, hidden),
                    carry)
                (dx,) = f_vjp(y)   # dx feeds the next iteration
                return dx, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    s = rows * hidden
    # fwd geometry selected; bwd pinned at 128 rows (dγ/dβ accumulation
    # order is part of the digest contract)
    geom = _ln_geometry(rows, hidden)
    geom["bwd_block_rows"] = 128
    return build, 10.0 * s + 16.0 * rows, geom


#: Per-kernel autotune knob + candidate ladder (the geometry axis each
#: retuned kernel exposes as a static kwarg).  Fixed-chunk kernels have
#: no knob and are never swept.
AUTOTUNE_KNOBS = {
    "fused_adam": ("block_rows", (8, 32, 64, 128, 256)),
    "lamb_stage1": ("chunks_per_block", (1, 2, 4, 8, 16)),
    "lamb_stage2": ("chunks_per_block", (1, 2, 4, 8, 16)),
    "layernorm_fwd": ("block_rows", (64, 128, 256, 512)),
}


def suite_specs(tiny: bool = False) -> dict:
    """``{name: (bench_fn, args, iters)}`` — THE kernel suite table,
    shared with ``tools/bench_variance.py`` so a kernel added here (and
    to ``KERNEL_FLOORS``) is automatically variance-measurable.

    Buffers must EXCEED VMEM (~128 MB) or XLA keeps the scan carry
    resident and the measurement reads VMEM bandwidth, not HBM
    (observed: a 16 MB layer-norm carry "achieved" 18.7 TB/s).
    difference-quotient span: 5*iters extra device-seconds must dwarf
    the per-call RTT jitter (~10 ms); cheap kernels need more steps,
    the ~20 ms LAMB stage-1 pass far fewer."""
    n = (1 << 16) if tiny else (1 << 26)            # 256 MB fp32 flats
    rows, hidden = (64, 512) if tiny else (1 << 17, 1024)  # 256 MB bf16

    def it(fast):
        return 4 if tiny else fast
    return {
        "fused_adam": (bench_fused_adam, (n,), it(60)),
        "lamb_stage1": (bench_lamb_stage1, (n,), it(30)),
        "lamb_stage2": (bench_lamb_stage2, (n,), it(40)),
        "mt_scale": (bench_mt_scale, (n,), it(150)),
        "mt_axpby": (bench_mt_axpby, (n,), it(150)),
        "mt_sumsq": (bench_mt_sumsq, (n,), it(300)),
        "layernorm_fwd": (bench_layernorm_fwd, (rows, hidden), it(150)),
        "layernorm_fwd_bwd": (bench_layernorm_fwd_bwd, (rows, hidden),
                              it(80)),
    }


def run_suite(tiny: bool = False, autotune: bool = False) -> dict:
    n = (1 << 16) if tiny else (1 << 26)
    rows, hidden = (64, 512) if tiny else (1 << 17, 1024)
    suite = suite_specs(tiny)
    bw = _hbm_peak()
    kernels = {}
    for name, (fn, args, iters) in suite.items():
        try:
            kw, sweep = {}, None
            if autotune and name in AUTOTUNE_KNOBS:
                knob, cands = AUTOTUNE_KNOBS[name]
                sweep = {}
                for cand in cands:
                    # per-candidate isolation: one over-budget geometry
                    # (e.g. a block whose double-buffered streams blow
                    # VMEM and fail Mosaic) must cost only its sweep
                    # entry, never the kernel's default-geometry record
                    # or its floor-gate coverage
                    try:
                        build, _, _ = fn(*args, **{knob: cand})
                        rejected = _lint_candidate(build)
                        if rejected:
                            # sanitizer-rejected geometry: recorded as
                            # a dict entry, so it is excluded from the
                            # timed table and can never be chosen
                            sweep[str(cand)] = \
                                {"lint_rejected": rejected}
                            continue
                        # short sweep timings (fewer steps, 2 trials):
                        # the knob's effect is way above the quotient's
                        # noise
                        sec = _time_scan(build, max(iters // 3, 2),
                                         trials=2)
                        sweep[str(cand)] = round(sec * 1e3, 4)
                    except Exception as e:  # noqa: BLE001
                        sweep[str(cand)] = \
                            {"error": f"{type(e).__name__}: {e}"[:120]}
                timed = {c: ms for c, ms in sweep.items()
                         if not isinstance(ms, dict)}
                if timed:  # all-failed sweep -> selector's default
                    kw = {knob: int(min(timed, key=timed.get))}
            build, nbytes, geom = fn(*args, **kw)
            sec = _time_scan(build, iters)
            gbps = nbytes / sec / 1e9
            kernels[name] = {
                "ms_per_step": round(sec * 1e3, 4),
                "gb_moved": round(nbytes / 1e9, 4),
                "gbps": round(gbps, 1),
                "roofline_frac": round(gbps * 1e9 / bw, 4),
                "iters": iters,
                "geometry": geom,
            }
            if sweep is not None:
                kernels[name]["autotune"] = {"swept_ms": sweep,
                                             "chosen": kw}
        except Exception as e:  # noqa: BLE001 - per-kernel isolation
            kernels[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return {"platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", ""),
            "n_elements": n, "ln_shape": [rows, hidden],
            "hbm_gbps_peak": bw / 1e9, "kernels": kernels}


#: Published per-kernel roofline-fraction floors — the KERNELBENCH_r05
#: measured values rounded to two places (MFU_FLOORS convention: the
#: floor is the bar, the band absorbs chip-day variance; the gate trips
#: at floor × (1 − band)).  Floors RATCHET UP when a retune lands a
#: measured gain and may only move DOWN with a BENCH_VARIANCE.json entry
#: justifying the band (tests/l1/test_bench_units.py pins the rule).
KERNEL_FLOOR_BAND = 0.05
KERNEL_FLOORS = {
    "fused_adam": 0.30,
    "lamb_stage1": 0.17,
    "lamb_stage2": 0.12,
    "mt_scale": 0.75,
    "mt_axpby": 0.80,
    "mt_sumsq": 0.63,
    "layernorm_fwd": 0.34,
    "layernorm_fwd_bwd": 0.51,
}


def effective_kernel_floors(
        search_dir: "str | None" = None) -> "tuple[dict, dict]":
    """``({kernel: floor}, bands)`` — KERNEL_FLOORS after consulting
    the committed ``BENCH_VARIANCE_r*.json`` in ``search_dir``
    (default: this checkout) through ``bench.derive_floor_bands``
    (statistical floors where a qualifying ``kernel:<name>`` entry
    carries a ``roofline_frac`` stats block; the hand table as the
    frozen fallback, protected by the no-ratchet-down rule).  Falls
    back to the hand table when bench is unimportable — the gate must
    never silently disarm."""
    try:
        # bench.py may BE the running __main__ (python bench.py):
        # `import bench` would then re-execute its whole module —
        # resolve the already-loaded instance first
        bench = sys.modules.get("bench")
        if bench is None or not hasattr(bench, "effective_floors"):
            main_mod = sys.modules.get("__main__")
            if main_mod is not None and \
                    hasattr(main_mod, "effective_floors") and \
                    hasattr(main_mod, "derive_floor_bands"):
                bench = main_mod
            else:
                if str(REPO) not in sys.path:
                    sys.path.insert(0, str(REPO))
                import bench
        floors, bands = bench.effective_floors(
            KERNEL_FLOORS, search_dir or str(REPO), kind="kernel",
            stat="roofline_frac")
        return floors, bands
    except Exception:  # noqa: BLE001 - hand floors always stand
        return dict(KERNEL_FLOORS), {
            n: {"floor": f, "source": "hand", "provisional": False}
            for n, f in KERNEL_FLOORS.items()}


def check_kernel_floors(kernels: dict,
                        floors: "dict | None" = None) -> dict:
    """Absolute per-kernel efficiency gate: every measured kernel with a
    published floor must hold ``roofline_frac >= floor * (1 - band)``.
    ``floors`` overrides the hand table (``bench.py`` and ``main``
    pass the variance-derived effective floors; ``None`` = the
    published hand values).

    A gated kernel PRESENT in the map but errored (no roofline_frac —
    e.g. a geometry change that fails Mosaic compilation) fails the gate
    too, listed under ``errored``: a kernel that stops running entirely
    is the worst regression, and a gate that skips it fails open.
    Kernels absent from the map (partial runs) are merely not judged."""
    checked, violations, errored = {}, [], []
    for name, floor in (floors if floors is not None
                        else KERNEL_FLOORS).items():
        cur = kernels.get(name)
        if cur is None:
            continue
        if not isinstance(cur, dict) or not cur.get("roofline_frac"):
            errored.append(name)
            continue
        gate = floor * (1.0 - KERNEL_FLOOR_BAND)
        ok = cur["roofline_frac"] >= gate
        checked[name] = {"roofline_frac": cur["roofline_frac"],
                         "floor": floor, "gate": round(gate, 4), "ok": ok}
        if not ok:
            violations.append(name)
    return {"band": KERNEL_FLOOR_BAND, "checked": checked,
            "violations": violations, "errored": errored,
            "ok": not (violations or errored)}


def compare_kernels(prior_path: str, kernels: dict,
                    threshold: float = 0.10,
                    geometry: "dict | None" = None) -> dict:
    """Per-kernel step-time gate: worsening >threshold fails; faster is
    fine; kernels present on only one side are listed, never gated.

    ``geometry`` (``{"n_elements": ..., "ln_shape": ...}`` of the
    CURRENT run) must match the baseline's, or every delta would just
    measure the size change — mismatched baselines are recorded and
    never gated."""
    try:
        with open(prior_path) as f:
            doc = json.load(f)
        prior = doc.get("kernels")
        if not isinstance(prior, dict):
            raise ValueError("no kernels map")
    except (OSError, ValueError, TypeError) as e:
        return {"baseline": prior_path, "ok": True,
                "error": f"baseline unreadable: {e}"}
    if geometry is not None:
        prior_geom = {k: doc.get(k) for k in geometry}
        if prior_geom != geometry:
            return {"baseline": Path(prior_path).name, "ok": True,
                    "error": f"geometry mismatch: baseline {prior_geom}"
                             f" vs current {geometry} — not comparable"}
    deltas, regressions, uncompared = {}, [], []
    for name, cur in kernels.items():
        old = prior.get(name)
        if not (isinstance(old, dict) and old.get("ms_per_step")
                and isinstance(cur, dict) and cur.get("ms_per_step")):
            uncompared.append(name)
            continue
        delta = cur["ms_per_step"] / old["ms_per_step"] - 1.0
        deltas[name] = round(delta, 4)
        if delta > threshold:
            regressions.append(name)
    uncompared += [k for k in prior if k not in kernels]
    return {"baseline": Path(prior_path).name, "threshold": threshold,
            "deltas": deltas, "regressions": regressions,
            "uncompared": uncompared, "ok": not regressions}


def gate_exit_code(result: dict, compare_given: bool,
                   assert_floors: bool) -> int:
    """2 when the run must fail, else 0 — the bench.py pattern: the
    floor gate is ABSOLUTE (needs no baseline) once armed via
    ``--assert-floors``; the step-time delta gate stays opt-in via
    ``--compare``."""
    floors = result.get("floors") or {}
    if assert_floors and not floors.get("ok", True):
        return 2
    if compare_given and not result.get("compare", {}).get("ok", True):
        return 2
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "KERNELBENCH.json"))
    ap.add_argument("--compare", default=None)
    ap.add_argument("--threshold", type=float, default=0.10)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny shapes (CPU smoke; numbers meaningless)")
    ap.add_argument("--autotune", action="store_true",
                    help="sweep each retunable kernel's geometry knob "
                         "and record the sweep alongside the winner")
    ap.add_argument("--assert-floors", action="store_true",
                    help="exit 2 when any kernel sits under its "
                         "published roofline-fraction floor (on-chip "
                         "gate; off-TPU the floors block is skipped)")
    args = ap.parse_args(argv)

    # A determinism-lint round name on a kernel-bench document is the
    # armed-gate-asserts-nothing failure: gate_hygiene would validate
    # the file against the DETLINT schema (and reject it), but until
    # then a DETLINT_rN.json full of microbenchmark timings asserts
    # nothing about tie-breaks or reduction shapes.  Refuse the name;
    # the sweep lives in tools/det_lint.py.
    if re.match(r"DETLINT_r\d+\.json$", Path(args.out).name):
        ap.error(f"--out {args.out}: DETLINT_rN.json is the "
                 "bitwise-determinism lint artifact family (emitted by "
                 "tools/det_lint.py or graph_lint --emit-json); a "
                 "kernel-bench document under that name would be "
                 "schema-rejected by gate_hygiene and, until then, "
                 "assert nothing the name promises")

    result = run_suite(tiny=args.tiny, autotune=args.autotune)
    # The floors block is ALWAYS recorded; roofline fractions are only
    # meaningful against a real HBM (off-chip the interpret-mode timings
    # measure the host), so off-TPU it records skipped and never gates.
    if result["platform"] == "tpu":
        # the gate consults the committed variance artifact: derived
        # statistical floors where evidence qualifies, the published
        # hand table otherwise (never looser without evidence)
        eff, bands = effective_kernel_floors()
        result["floors"] = check_kernel_floors(result["kernels"],
                                               floors=eff)
        result["floors"]["floor_sources"] = {
            n: b["source"] for n, b in bands.items()}
    else:
        result["floors"] = {
            "ok": True,
            "skipped": f"platform {result['platform']!r}: roofline "
                       "fractions only meaningful on TPU"}
    if args.compare:
        result["compare"] = compare_kernels(
            args.compare, result["kernels"], args.threshold,
            geometry={"n_elements": result["n_elements"],
                      "ln_shape": result["ln_shape"]})
    Path(args.out).write_text(json.dumps(result, indent=1))
    print(json.dumps(result))
    rc = gate_exit_code(result, bool(args.compare), args.assert_floors)
    if rc:
        print("kernel_bench: gate failed: step-time regressions "
              f"{result.get('compare', {}).get('regressions', [])}, "
              "floor violations "
              f"{result['floors'].get('violations', [])}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
