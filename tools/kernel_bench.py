"""Per-kernel microbenchmarks: fused optimizers, multi-tensor ops,
fused LayerNorm — step time + achieved HBM bandwidth vs roofline.

The model-level bench (``bench.py``) folds optimizer cost into full
train steps, where a 2%-of-step kernel regression hides inside chip-day
variance (VERDICT r4 missing #3).  This tool isolates each Pallas
kernel on HBM-resident flat buffers and records per-step time, analytic
bytes moved, achieved GB/s, and the fraction of the chip's HBM roofline
— all these kernels are elementwise/reduction passes, so bandwidth IS
their roofline (BASELINE.md: "FusedAdam step time — tracked per chip").

Method (tunnel-safe, see the axon notes): each kernel runs inside a
jitted ``lax.scan`` of K chained steps — the kernel's outputs feed the
next iteration's inputs, so the loop body cannot be hoisted — timed by
a scalar fetch around the whole scan (``block_until_ready`` does not
drain the pipeline over this transport).  The per-call ~100 ms tunnel
RTT would still inflate ``total/K`` by RTT/K, so the per-step time is
taken as a **difference quotient**: best-of-trials at K and at 6K,
``(t_6K - t_K) / 5K`` — the constant per-call overhead cancels exactly
and RTT jitter amortizes over 5K steps.

Gate: ``--compare KERNELBENCH_rN.json`` fails (exit 2) when any
kernel's per-step time worsens by more than ``--threshold`` (default
10%, calibrated like bench.py's: chip-day variance is ±2-4%).

CAVEAT on reading the optimizer numbers: the chained scans here leave
every input dead after its call, so ``input_output_aliases`` donation
would measure ~2x — but the PRODUCTION train step wraps the optimizer
in the loss-scale skip-``cond``, whose untaken branch returns the old
state, keeping p/m/v live across the update; XLA then materializes
full copies and the "win" inverts (measured on chip: BERT-large
105 -> 54 seq/s with aliased LAMB kernels, and chunk-32768 packing
OOM'd the b16 step outright).  The multi-tensor scale/axpby kernels DO
alias in production — their callers run before the skip decision — and
their numbers here reflect it.

Bytes accounting per kernel (N = elements, fp32 flats unless noted):

- ``fused_adam``    R p+m+v+g (16N)  W p+m+v (12N) + bf16 copy (2N)
- ``lamb_stage1``   R g+p+m+v (16N)  W u+m+v (12N)
- ``lamb_stage2``   R p+u (8N)       W p (4N) + bf16 copy (2N)
- ``mt_scale``      R 4N             W 4N
- ``mt_axpby``      R 8N             W 4N
- ``mt_sumsq``      R 4N             W ~0
- ``layernorm_fwd`` (B,H) bf16: R 2S  W 2S + 8B/row stats (S = B*H)
- ``layernorm_fwd_bwd`` adds R dy+x+stats, W dx (+ the dw/db partial
  reduction XLA appends) — accounted as 6S + fwd

Usage: python tools/kernel_bench.py [--out KERNELBENCH.json]
       [--compare KERNELBENCH_rN.json] [--threshold 0.10] [--tiny]
"""

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

CHUNK = 2048 * 32   # the multi-tensor chunk (reference semantics const)


def _hbm_peak() -> float:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, bw in {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9,
                    "v5p": 2765e9, "v6": 1640e9}.items():
        if key in kind:
            return bw
    return 819e9


def _sync(out) -> float:
    """Drain the pipeline via a TRUE scalar fetch: slice one element ON
    DEVICE, transfer 4 bytes.  ``np.asarray(out)`` would ship the whole
    256 MB result over the ~25-50 MB/s tunnel (~7 s, with enough wire
    jitter to bury the difference quotient); ``block_until_ready`` does
    not drain at all on this transport (axon notes)."""
    leaf = jax.tree.leaves(out)[0]
    return float(leaf.ravel()[0].astype(jnp.float32))


def _time_scan_at(build, k: int, trials: int) -> float:
    """Best-of-``trials`` wall seconds for one compiled scan(k) call,
    synced by a scalar fetch."""
    run, args = build(k)
    compiled = jax.jit(run).lower(*args).compile()
    _sync(compiled(*args))
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        _sync(compiled(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _time_scan(build, iters: int, trials: int = 3) -> float:
    """Per-step seconds as the difference quotient between scan(iters)
    and scan(6*iters): the constant per-call tunnel overhead (dispatch
    + RTT + fetch) cancels; only the 5*iters extra steps remain."""
    t_short = _time_scan_at(build, iters, trials)
    t_long = _time_scan_at(build, 6 * iters, trials)
    return max(t_long - t_short, 1e-9) / (5 * iters)


def bench_fused_adam(n: int):
    from apex_tpu.ops.pallas.adam_kernel import packed_adam

    def build(k):
        key = jax.random.PRNGKey(0)
        p = jax.random.normal(key, (n,), jnp.float32)
        g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)

        def run(p, m, v, g):
            def body(carry, _):
                p, m, v = carry
                p, m, v, _copy = packed_adam(
                    p, m, v, g, step_size=1e-3, beta1=0.9, beta2=0.999,
                    eps=1e-8, scale=1.0, weight_decay=0.0, eps_mode=1,
                    p_copy_dtype=jnp.bfloat16)
                return (p, m, v), None
            (p, m, v), _ = jax.lax.scan(body, (p, m, v), None, length=k)
            return p
        return run, (p, m, v, g)

    return build, 30.0 * n


def bench_lamb_stage1(n: int):
    from apex_tpu.ops.pallas.lamb_kernels import (grown_chunk,
                                                  packed_lamb_stage1)

    chunk = grown_chunk(n)   # the chunk the production driver packs at n
    def build(k):
        g = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
        p = jax.random.normal(jax.random.PRNGKey(3), (n,), jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)
        decay = jnp.zeros((n // chunk,), jnp.float32)

        def run(g, p, m, v):
            def body(carry, _):
                g, m, v = carry
                u, m, v = packed_lamb_stage1(
                    g, p, m, v, decay, beta1=0.9, beta2=0.999, eps=1e-6,
                    inv_scale=1.0, bc1=1.0, bc2=1.0, chunk_size=chunk)
                return (u, m, v), None   # update feeds the next "grad"
            (u, m, v), _ = jax.lax.scan(body, (g, m, v), None, length=k)
            return u
        return run, (g, p, m, v)

    return build, 28.0 * n


def bench_lamb_stage2(n: int):
    from apex_tpu.ops.pallas.lamb_kernels import (grown_chunk,
                                                  packed_lamb_stage2)

    chunk = grown_chunk(n)
    def build(k):
        p = jax.random.normal(jax.random.PRNGKey(4), (n,), jnp.float32)
        u = jax.random.normal(jax.random.PRNGKey(5), (n,), jnp.float32)
        ratio = jnp.full((n // chunk,), 1e-3, jnp.float32)

        def run(p, u):
            def body(carry, _):
                p2, _copy = packed_lamb_stage2(
                    carry, u, ratio, chunk_size=chunk,
                    p_copy_dtype=jnp.bfloat16)
                return p2, None
            p, _ = jax.lax.scan(body, p, None, length=k)
            return p
        return run, (p, u)

    return build, 14.0 * n


def bench_mt_scale(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_scale

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(6), (n,), jnp.float32)

        def run(x):
            def body(carry, _):
                out, _flag = packed_scale(carry, 1.0000001, CHUNK,
                                          jnp.float32)
                return out, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    return build, 8.0 * n


def bench_mt_axpby(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_axpby

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(7), (n,), jnp.float32)
        y = jax.random.normal(jax.random.PRNGKey(8), (n,), jnp.float32)

        def run(x, y):
            def body(carry, _):
                out, _flag = packed_axpby(carry, y, 0.999, 0.001, CHUNK,
                                          jnp.float32)
                return out, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x, y)

    return build, 12.0 * n


def bench_mt_sumsq(n: int):
    from apex_tpu.ops.pallas.multi_tensor_kernels import packed_sumsq

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(9), (n,), jnp.float32)

        def run(x):
            def body(carry, _):
                x, s = carry
                # O(1)-traffic dependence: the result feeds one element
                # back (scaled so the write is non-trivial but the value
                # drift is ~1e-13) — a literal *0.0 constant-folds away
                # and lets XLA hoist the whole kernel out of the loop
                # (measured: "1.3x roofline")
                r = packed_sumsq(x, CHUNK)
                x = x.at[0].add(r * 1e-20)
                return (x, s + r), None
            (x, s), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), None,
                                     length=k)
            return s
        return run, (x,)

    return build, 4.0 * n


def bench_layernorm_fwd(rows: int, hidden: int):
    from apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine)

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(10), (rows, hidden),
                              jnp.bfloat16)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)

        def run(x):
            def body(carry, _):
                y = fused_layer_norm_affine(carry, w, b, hidden)
                return y, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    s = rows * hidden
    return build, 4.0 * s + 8.0 * rows


def bench_layernorm_fwd_bwd(rows: int, hidden: int):
    from apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine)

    def build(k):
        x = jax.random.normal(jax.random.PRNGKey(11), (rows, hidden),
                              jnp.bfloat16)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)

        def run(x):
            def body(carry, _):
                y, f_vjp = jax.vjp(
                    lambda t: fused_layer_norm_affine(t, w, b, hidden),
                    carry)
                (dx,) = f_vjp(y)   # dx feeds the next iteration
                return dx, None
            x, _ = jax.lax.scan(body, x, None, length=k)
            return x
        return run, (x,)

    s = rows * hidden
    return build, 10.0 * s + 16.0 * rows


def run_suite(tiny: bool = False) -> dict:
    # Buffers must EXCEED VMEM (~128 MB) or XLA keeps the scan carry
    # resident and the measurement reads VMEM bandwidth, not HBM
    # (observed: a 16 MB layer-norm carry "achieved" 18.7 TB/s).
    n = (1 << 16) if tiny else (1 << 26)            # 256 MB fp32 flats
    rows, hidden = (64, 512) if tiny else (1 << 17, 1024)  # 256 MB bf16
    # difference-quotient span: 5*iters extra device-seconds must dwarf
    # the per-call RTT jitter (~10 ms); cheap kernels need more steps,
    # the ~20 ms LAMB stage-1 pass far fewer
    def it(fast):
        return 4 if tiny else fast
    suite = {
        "fused_adam": bench_fused_adam(n) + (it(60),),
        "lamb_stage1": bench_lamb_stage1(n) + (it(30),),
        "lamb_stage2": bench_lamb_stage2(n) + (it(40),),
        "mt_scale": bench_mt_scale(n) + (it(150),),
        "mt_axpby": bench_mt_axpby(n) + (it(150),),
        "mt_sumsq": bench_mt_sumsq(n) + (it(300),),
        "layernorm_fwd": bench_layernorm_fwd(rows, hidden) + (it(150),),
        "layernorm_fwd_bwd": bench_layernorm_fwd_bwd(rows, hidden)
        + (it(80),),
    }
    bw = _hbm_peak()
    kernels = {}
    for name, (build, nbytes, iters) in suite.items():
        try:
            sec = _time_scan(build, iters)
            gbps = nbytes / sec / 1e9
            kernels[name] = {
                "ms_per_step": round(sec * 1e3, 4),
                "gb_moved": round(nbytes / 1e9, 4),
                "gbps": round(gbps, 1),
                "roofline_frac": round(gbps * 1e9 / bw, 4),
                "iters": iters,
            }
        except Exception as e:  # noqa: BLE001 - per-kernel isolation
            kernels[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return {"platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", ""),
            "n_elements": n, "ln_shape": [rows, hidden],
            "hbm_gbps_peak": bw / 1e9, "kernels": kernels}


def compare_kernels(prior_path: str, kernels: dict,
                    threshold: float = 0.10,
                    geometry: "dict | None" = None) -> dict:
    """Per-kernel step-time gate: worsening >threshold fails; faster is
    fine; kernels present on only one side are listed, never gated.

    ``geometry`` (``{"n_elements": ..., "ln_shape": ...}`` of the
    CURRENT run) must match the baseline's, or every delta would just
    measure the size change — mismatched baselines are recorded and
    never gated."""
    try:
        with open(prior_path) as f:
            doc = json.load(f)
        prior = doc.get("kernels")
        if not isinstance(prior, dict):
            raise ValueError("no kernels map")
    except (OSError, ValueError, TypeError) as e:
        return {"baseline": prior_path, "ok": True,
                "error": f"baseline unreadable: {e}"}
    if geometry is not None:
        prior_geom = {k: doc.get(k) for k in geometry}
        if prior_geom != geometry:
            return {"baseline": Path(prior_path).name, "ok": True,
                    "error": f"geometry mismatch: baseline {prior_geom}"
                             f" vs current {geometry} — not comparable"}
    deltas, regressions, uncompared = {}, [], []
    for name, cur in kernels.items():
        old = prior.get(name)
        if not (isinstance(old, dict) and old.get("ms_per_step")
                and isinstance(cur, dict) and cur.get("ms_per_step")):
            uncompared.append(name)
            continue
        delta = cur["ms_per_step"] / old["ms_per_step"] - 1.0
        deltas[name] = round(delta, 4)
        if delta > threshold:
            regressions.append(name)
    uncompared += [k for k in prior if k not in kernels]
    return {"baseline": Path(prior_path).name, "threshold": threshold,
            "deltas": deltas, "regressions": regressions,
            "uncompared": uncompared, "ok": not regressions}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "KERNELBENCH.json"))
    ap.add_argument("--compare", default=None)
    ap.add_argument("--threshold", type=float, default=0.10)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny shapes (CPU smoke; numbers meaningless)")
    args = ap.parse_args(argv)

    result = run_suite(tiny=args.tiny)
    if args.compare:
        result["compare"] = compare_kernels(
            args.compare, result["kernels"], args.threshold,
            geometry={"n_elements": result["n_elements"],
                      "ln_shape": result["ln_shape"]})
    Path(args.out).write_text(json.dumps(result, indent=1))
    print(json.dumps(result))
    if args.compare and not result["compare"]["ok"]:
        print("kernel_bench: step-time regressions "
              f"{result['compare']['regressions']}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
