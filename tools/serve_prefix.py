"""Prefix-sharing gate artifact: the shared-system-prompt c16 A/B plus
the bitwise drill, committed as ``PREFIXCACHE_r*.json``.

Runs ``bench.bench_serve_prefix`` — the SAME sweep the
``gpt_small_tpu_serve_prefix_c16`` bench config runs on chip — then
drills fidelity: the sharing engine (CoW fork included — one request
resubmits the previous full prompt) must stream every output BITWISE
equal to solo ``generate()``.  Sharing is a perf optimization, never a
fidelity trade, and the artifact carries the proof.

The emitted document (schema ``apex_tpu/analysis/prefixcache.py``,
validated by ``tools/gate_hygiene.py`` in tier-1) carries the gates in
machine-checked form:

- ``gate.hit_rate_ok`` — the content index actually matched
  (``hit_rate > 0``, re-derived from the per-request spans);
- ``gate.ab_ok`` — the sharing arm dispatched FEWER prefill tokens
  and admitted MORE requests per resident block than the sharing-off
  arm on the identical stream, at one decode trace each;
- ``gate.bitwise_ok`` — the drill's outputs greedy-match solo.

A verdict contradicting the recorded spans is schema-invalid, so the
artifact cannot rot into an "ok" nobody re-derived.

Usage:
    python tools/serve_prefix.py --emit-json PREFIXCACHE_r01.json \
        [--cpu-smoke] [--slots 16] [--prefill 512] [--new-tokens 128]

``--cpu-smoke`` is the committed-r01 shape: gpt_tiny at full c16
concurrency — the sharing topology is the real thing, the model is
test-scale.  Without it the sweep runs gpt_small_tpu (a chip-round
config).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))
jax.config.update("jax_threefry_partitionable", True)


def bitwise_drill(tiny: bool, prefill: int, new_tokens: int) -> dict:
    """Serve a shared-prefix burst — partial hits AND a full-prompt
    CoW fork — through the sharing engine and check every streamed
    output bitwise against solo ``generate()``.  Returns the drill
    record for the artifact's ``bitwise_ok`` evidence trail."""
    from apex_tpu import amp
    from apex_tpu.models.generate import generate
    from apex_tpu.models.gpt import GPTModel, gpt_small_tpu, gpt_tiny
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    cfg = gpt_tiny() if tiny else gpt_small_tpu()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    block = 4 if tiny else 16
    mb = -(-(prefill + new_tokens) // block)
    scfg = ServeConfig(num_slots=4, block_size=block,
                       num_blocks=4 * mb + 1, max_blocks_per_slot=mb,
                       prefill_chunk=min(prefill, 8 if tiny else 128),
                       prefix_cache=True)
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    rng = np.random.RandomState(7)
    sys_len = max((prefill // 2) // block * block, block)
    system = rng.randint(0, cfg.vocab_size, (sys_len,))
    prompts = [np.concatenate(
        [system, rng.randint(0, cfg.vocab_size,
                             (max(prefill - sys_len, 1) // (i + 1),))])
        for i in range(3)]
    prompts.append(prompts[0].copy())     # full-prompt match: CoW fork
    for i, p in enumerate(prompts):
        eng.submit(Request(uid=f"d{i}", prompt=p,
                           max_new_tokens=new_tokens))
    out = eng.run()
    bitwise = True
    for i, p in enumerate(prompts):
        want = np.asarray(generate(
            params, cfg, jnp.asarray(p[None]),
            new_tokens))[0, len(p):]
        if not np.array_equal(out[f"d{i}"], want):
            bitwise = False
    return {"requests": len(prompts),
            "cow_copies": int(eng.metrics.counter(
                "serve_prefix_cow_copies_total").value),
            "hits": int(eng.sched.prefix_hits),
            "bitwise_ok": bool(bitwise)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--emit-json", default=None,
                    metavar="PREFIXCACHE_rN.json",
                    help="write the committed gate artifact")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="gpt_tiny model at full c16 concurrency (the "
                         "committed-r01 shape); default gpt_small_tpu")
    ap.add_argument("--slots", type=int, default=16,
                    help="concurrent requests (= engine slots)")
    ap.add_argument("--prefill", type=int, default=None,
                    help="prompt-length budget (default 512; 64 under "
                         "--cpu-smoke)")
    ap.add_argument("--new-tokens", type=int, default=None,
                    help="generation budget (default 128; 16 under "
                         "--cpu-smoke)")
    opts = ap.parse_args(argv)
    prefill = opts.prefill if opts.prefill is not None \
        else (64 if opts.cpu_smoke else 512)
    new_tokens = opts.new_tokens if opts.new_tokens is not None \
        else (16 if opts.cpu_smoke else 128)

    import bench

    rec = bench.bench_serve_prefix(
        warmup=1, iters=1, peak=0.0, num_slots=opts.slots,
        prefill=prefill, new_tokens=new_tokens, tiny=opts.cpu_smoke)
    drill = bitwise_drill(opts.cpu_smoke, prefill, new_tokens)
    sharing = dict(rec["sharing"])
    doc = {
        "round": 0,
        "platform": jax.devices()[0].platform,
        "config": {
            "model": "gpt_tiny" if opts.cpu_smoke else "gpt_small_tpu",
            "concurrency": int(rec["batch"]),
            "system_prompt_tokens": int(rec["system_prompt_tokens"]),
            "prefill": int(prefill),
            "new_tokens": int(new_tokens),
            "block_size": int(rec["block_size"]),
        },
        "sharing": sharing,
        "baseline": rec["baseline"],
        "drill": drill,
        "bitwise_ok": drill["bitwise_ok"],
        "gate": {
            "hit_rate_ok": sharing["prefix"]["hit_rate"] > 0.0,
            "ab_ok": bool(rec["ab_ok"]),
            "bitwise_ok": drill["bitwise_ok"],
            "ok": bool(sharing["prefix"]["hit_rate"] > 0.0
                       and rec["ab_ok"] and drill["bitwise_ok"]),
        },
        "note": (
            "CPU smoke: the gated numbers are deterministic "
            "token/block counts (prefill tokens dispatched, admitted "
            "requests per resident block), identical on chip — the "
            "wall-clock percentiles ride along as context only."
            if jax.devices()[0].platform == "cpu" else
            "on-chip shared-system-prompt A/B at equal device count"),
    }
    if opts.emit_json:
        m = re.search(r"_r(\d+)\.json$",
                      os.path.basename(opts.emit_json))
        doc["round"] = int(m.group(1)) if m else 0
        from apex_tpu.analysis.prefixcache import validate_prefixcache
        problems = validate_prefixcache(doc)
        if problems:
            print(f"serve_prefix: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
        with open(opts.emit_json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"prefix-cache artifact written: {opts.emit_json}",
              file=sys.stderr)
    print(json.dumps(doc))
    return 0 if doc["gate"]["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
