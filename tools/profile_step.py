"""Profile one training-step config and print the top device-time ops.

Usage: python tools/profile_step.py [resnet50|gpt|bert] [opt_level]

Captures an XProf trace of a few steps, parses the xplane protobuf
directly (tensorflow's tsl proto is in the image; no tensorboard UI
needed) and aggregates device time by HLO category and by op on the
TPU plane — the "profile one step and act on the top hotspot" loop of
VERDICT r1 item 3.  The chrome-trace JSON export is lossy here (op-level
events are missing for large programs); the xplane is complete.
"""

import json
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# the xplane/chrome-trace walk lives in the obs library now (one
# parser for every profile tool; behavior pinned by the obs fixture
# tests) — this tool only drives the capture and prints the table
from apex_tpu.obs.xplane import parse_xplane  # noqa: E402


def build(model_name: str, opt_level: str):
    import bench
    peak = bench.chip_peak_flops()
    if model_name == "gpt":
        # same config as bench.py's headline GPT entry (keep in sync)
        fn = lambda: bench.bench_gpt(batch=8, seq=2048, warmup=2, iters=8,
                                     peak=peak, tiny=False)
    elif model_name == "bert":
        fn = lambda: bench.bench_bert(batch=16, seq=512, warmup=2, iters=8,
                                      peak=peak, tiny=False)
    else:
        fn = lambda: bench.bench_resnet(opt_level, batch=256, size=224,
                                        warmup=2, iters=8, peak=peak)
    return fn


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    opt_level = sys.argv[2] if len(sys.argv) > 2 else "O2"
    fn = build(model_name, opt_level)
    fn()  # warm compile outside the trace
    logdir = f"/tmp/apex_tpu_prof_{model_name}_{opt_level}"
    import shutil
    shutil.rmtree(logdir, ignore_errors=True)  # stale xplanes would
    # double-count: the parser aggregates every file under the logdir
    with jax.profiler.trace(logdir):
        out = fn()
    time.sleep(1)
    print(json.dumps(out))
    by_name, by_cat, total = parse_xplane(logdir)
    print(f"device XLA-op time by category, total {total / 1e12:.3f}s:")
    for cat, dur in by_cat.most_common():
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{cat}")
    print("top ops:")
    for name, dur in by_name.most_common(25):
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{name[:100]}")


if __name__ == "__main__":
    main()
