"""Profile one training-step config and print the top device-time ops.

Usage: python tools/profile_step.py [resnet50|gpt|bert] [opt_level]
       python tools/profile_step.py --train-buckets [mlp|gpt|bert|resnet50]
           [--opt-level O1] [--iters 4]

Captures an XProf trace of a few steps, parses the xplane protobuf
directly (tensorflow's tsl proto is in the image; no tensorboard UI
needed) and aggregates device time by HLO category and by op on the
TPU plane — the "profile one step and act on the top hotspot" loop of
VERDICT r1 item 3.  The chrome-trace JSON export is lossy here (op-level
events are missing for large programs); the xplane is complete.

``--train-buckets`` is the op-level lane: it lowers the EXACT amp
train step graph_lint lints (``graph_lint.build_train_step``),
captures its dispatches, and folds the measured op times into the
pinned train-step vocabulary — fwd / bwd / optimizer / collectives /
host_gap — through the SHARED classifier
(:class:`apex_tpu.obs.stepclass.TrainStepClassifier`, built from the
compiled HLO's ``op_name`` metadata scopes).  The continuous profiler
(:mod:`apex_tpu.obs.contprof`) buckets its online training windows
through the same class, so this offline table and the live sentinel
can never disagree about what "bwd" means; the classifier's behavior
is pinned by the fixture test in ``tests/l0/test_contprof.py``.
"""

import argparse
import json
import shutil
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

# the xplane/chrome-trace walk lives in the obs library now (one
# parser for every profile tool; behavior pinned by the obs fixture
# tests) — this tool only drives the capture and prints the table
from apex_tpu.obs.xplane import (  # noqa: E402
    bucket_op_times,
    op_times,
    parse_xplane,
)


def build(model_name: str, opt_level: str):
    import bench
    peak = bench.chip_peak_flops()
    if model_name == "gpt":
        # same config as bench.py's headline GPT entry (keep in sync)
        fn = lambda: bench.bench_gpt(batch=8, seq=2048, warmup=2, iters=8,
                                     peak=peak, tiny=False)
    elif model_name == "bert":
        fn = lambda: bench.bench_bert(batch=16, seq=512, warmup=2, iters=8,
                                      peak=peak, tiny=False)
    else:
        fn = lambda: bench.bench_resnet(opt_level, batch=256, size=224,
                                        warmup=2, iters=8, peak=peak)
    return fn


def category_profile(model_name: str, opt_level: str) -> None:
    """The historical lane: capture a bench config, print device time
    by hlo_category and the top ops."""
    fn = build(model_name, opt_level)
    fn()  # warm compile outside the trace
    logdir = f"/tmp/apex_tpu_prof_{model_name}_{opt_level}"
    shutil.rmtree(logdir, ignore_errors=True)  # stale xplanes would
    # double-count: the parser aggregates every file under the logdir
    with jax.profiler.trace(logdir):
        out = fn()
    time.sleep(1)
    print(json.dumps(out))
    by_name, by_cat, total = parse_xplane(logdir)
    print(f"device XLA-op time by category, total {total / 1e12:.3f}s:")
    for cat, dur in by_cat.most_common():
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{cat}")
    print("top ops:")
    for name, dur in by_name.most_common(25):
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{name[:100]}")


def train_bucket_profile(family: str, opt_level: str,
                         iters: int = 4) -> dict:
    """The op-level lane: capture the exact graph_lint train step and
    fold measured op time into the pinned train vocabulary through
    the SHARED classifier (the one the continuous profiler uses)."""
    import graph_lint

    from apex_tpu.obs.stepclass import TRAIN_BUCKETS, TrainStepClassifier

    step, args, _props = graph_lint.build_train_step(
        family, opt_level=opt_level)
    state, *batch = args
    compiled_txt = step.lower(state, *batch).compile().as_text()
    clf = TrainStepClassifier(compiled_txt)

    state, metrics = step(state, *batch)       # compile outside trace
    jax.block_until_ready(metrics["loss"])
    logdir = f"/tmp/apex_tpu_prof_train_{family}_{opt_level}"
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        # wall of the STEPS only — trace start/stop is capture
        # overhead (the contprof OBS lane gates it), not step time
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, *batch)
        jax.block_until_ready(metrics["loss"])
        wall_s = time.perf_counter() - t0
    time.sleep(0.5)

    times = op_times(logdir)
    step_ops = clf.step_ops()
    step_times = {n: ps for n, ps in times.by_op.items()
                  if n in step_ops}
    named = [b for b in TRAIN_BUCKETS if b not in ("other",
                                                   "host_gap")]
    table = bucket_op_times(step_times, clf, buckets=named)
    bucket_ps = dict(table["bucket_ps"])
    total = table["total_ps"]
    # host_gap = the wall the capture held that no attributed device
    # op explains (thread-summed CPU captures can exceed wall: 0)
    gap = max(0, int(wall_s * 1e12) - total)
    bucket_ps["host_gap"] = gap
    total += gap
    return {
        "family": family, "opt_level": opt_level, "iters": iters,
        "source": times.source,
        "wall_s": round(wall_s, 4),
        "bucket_ps": {b: int(bucket_ps.get(b, 0))
                      for b in TRAIN_BUCKETS},
        "fractions": {b: (round(bucket_ps.get(b, 0) / total, 4)
                          if total else 0.0) for b in TRAIN_BUCKETS},
        "matched_frac": round(table["matched_ps"]
                              / max(table["total_ps"], 1), 4),
        "step_ops_profiled": len(step_times),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("model", nargs="?", default="resnet50")
    ap.add_argument("opt_level", nargs="?", default="O2")
    ap.add_argument("--train-buckets", metavar="FAMILY", default=None,
                    help="bucket the FAMILY amp train step's measured "
                         "op time into the pinned fwd/bwd/optimizer/"
                         "collectives/host_gap vocabulary (shared "
                         "classifier) instead of the category table")
    ap.add_argument("--opt-level", dest="opt_flag", default=None)
    ap.add_argument("--iters", type=int, default=4)
    opts = ap.parse_args(argv)
    if opts.train_buckets:
        doc = train_bucket_profile(
            opts.train_buckets, opts.opt_flag or opts.opt_level,
            iters=opts.iters)
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    category_profile(opts.model, opts.opt_level)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
