"""Profile one training-step config and print the top device-time ops.

Usage: python tools/profile_step.py [resnet50|gpt] [opt_level]

Captures an XProf trace of a few steps, then parses the trace-event JSON
directly (no tensorboard needed) and aggregates self-time by HLO op
category on the device track — the "profile one step and act on the top
hotspot" loop of VERDICT r1 item 3.
"""

import collections
import glob
import gzip
import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def build(model_name: str, opt_level: str):
    import bench
    peak = bench.chip_peak_flops()
    if model_name == "gpt":
        # same config as bench.py's headline GPT entry (keep in sync)
        fn = lambda: bench.bench_gpt(batch=8, seq=2048, warmup=2, iters=8,
                                     peak=peak, tiny=False)
    else:
        fn = lambda: bench.bench_resnet(opt_level, batch=256, size=224,
                                        warmup=2, iters=8, peak=peak)
    return fn


def parse_traces(logdir: str):
    """Aggregate wall-duration by event name from the xplane-exported
    trace.json.gz files."""
    events = collections.Counter()
    total = 0.0
    for path in glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True):
        with gzip.open(path, "rt") as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            pid_name = ev.get("pid")
            name = ev.get("name", "?")
            events[name] += ev["dur"]
            total += ev["dur"]
    return events, total


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    opt_level = sys.argv[2] if len(sys.argv) > 2 else "O2"
    fn = build(model_name, opt_level)
    fn()  # warm compile outside the trace
    logdir = f"/tmp/apex_tpu_prof_{model_name}_{opt_level}"
    with jax.profiler.trace(logdir):
        out = fn()
    time.sleep(1)
    print(json.dumps(out))
    events, total = parse_traces(logdir)
    print(f"top events by accumulated duration (us), total {total:.0f}:")
    for name, dur in events.most_common(25):
        print(f"  {dur:12.0f}  {100 * dur / max(total, 1):5.1f}%  {name[:110]}")


if __name__ == "__main__":
    main()
