"""Profile one training-step config and print the top device-time ops.

Usage: python tools/profile_step.py [resnet50|gpt|bert] [opt_level]

Captures an XProf trace of a few steps, parses the xplane protobuf
directly (tensorflow's tsl proto is in the image; no tensorboard UI
needed) and aggregates device time by HLO category and by op on the
TPU plane — the "profile one step and act on the top hotspot" loop of
VERDICT r1 item 3.  The chrome-trace JSON export is lossy here (op-level
events are missing for large programs); the xplane is complete.
"""

import collections
import glob
import json
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def build(model_name: str, opt_level: str):
    import bench
    peak = bench.chip_peak_flops()
    if model_name == "gpt":
        # same config as bench.py's headline GPT entry (keep in sync)
        fn = lambda: bench.bench_gpt(batch=8, seq=2048, warmup=2, iters=8,
                                     peak=peak, tiny=False)
    elif model_name == "bert":
        fn = lambda: bench.bench_bert(batch=16, seq=512, warmup=2, iters=8,
                                      peak=peak, tiny=False)
    else:
        fn = lambda: bench.bench_resnet(opt_level, batch=256, size=224,
                                        warmup=2, iters=8, peak=peak)
    return fn


def parse_trace_json(logdir: str):
    """Lossy fallback: aggregate the chrome-trace JSON export (op-level
    events can be missing for large programs — prefer the xplane)."""
    import gzip
    by_name = collections.Counter()
    by_cat = collections.Counter()
    total = 0
    for path in glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True):
        trace = json.loads(gzip.open(path, "rt").read())
        events = trace.get("traceEvents", [])
        # Mirror parse_xplane's filter: only the device planes' "XLA Ops"
        # line (metadata events map pid -> process/plane name and
        # (pid, tid) -> thread/line name); counting every complete event
        # would double-count ops inside step markers and mix in host
        # threads.
        proc = {}
        thread = {}
        for ev in events:
            if ev.get("ph") != "M":
                continue
            name = ev.get("args", {}).get("name", "")
            if ev.get("name") == "process_name":
                proc[ev.get("pid")] = name
            elif ev.get("name") == "thread_name":
                thread[(ev.get("pid"), ev.get("tid"))] = name
        for ev in events:
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            if not proc.get(ev.get("pid"), "").startswith("/device:"):
                continue
            if thread.get((ev.get("pid"), ev.get("tid"))) != "XLA Ops":
                continue
            d = int(ev["dur"] * 1e6)            # us -> ps, match xplane
            by_name[ev.get("name", "?")] += d
            by_cat[ev.get("args", {}).get("hlo_category", "?")] += d
            total += d
    return by_name, by_cat, total


def parse_xplane(logdir: str):
    """Aggregate device-plane op durations from the xplane protobuf.
    Falls back to the lossy chrome-trace JSON when the tensorflow/tsl
    xplane proto is not importable (ADVICE r2)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:
        print(f"warning: xplane proto unavailable ({e}); falling back to "
              f"the lossy chrome-trace JSON parser (install tensorflow "
              f"for the complete tsl xplane protobuf path)",
              file=sys.stderr)
        return parse_trace_json(logdir)

    paths = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    by_name = collections.Counter()
    by_cat = collections.Counter()
    total = 0
    for path in paths:
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(path, "rb").read())
        for plane in xs.planes:
            if not plane.name.startswith("/device:"):
                continue
            emeta, smeta = plane.event_metadata, plane.stat_metadata
            cat_id = next((k for k, v in smeta.items()
                           if v.name == "hlo_category"), None)
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    d = ev.duration_ps
                    name = emeta[ev.metadata_id].name
                    # strip the "%op = type{layout} ..." HLO dump down to
                    # the op name for aggregation
                    short = name.split(" = ")[0].lstrip("%")
                    by_name[short] += d
                    total += d
                    cat = "?"
                    for st in list(ev.stats) + \
                            list(emeta[ev.metadata_id].stats):
                        if st.metadata_id != cat_id:
                            continue
                        which = st.WhichOneof("value")
                        val = getattr(st, which)
                        cat = (smeta[val].name if which == "ref_value"
                               else str(val))
                        break
                    by_cat[cat] += d
    return by_name, by_cat, total


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    opt_level = sys.argv[2] if len(sys.argv) > 2 else "O2"
    fn = build(model_name, opt_level)
    fn()  # warm compile outside the trace
    logdir = f"/tmp/apex_tpu_prof_{model_name}_{opt_level}"
    import shutil
    shutil.rmtree(logdir, ignore_errors=True)  # stale xplanes would
    # double-count: the parser aggregates every file under the logdir
    with jax.profiler.trace(logdir):
        out = fn()
    time.sleep(1)
    print(json.dumps(out))
    by_name, by_cat, total = parse_xplane(logdir)
    print(f"device XLA-op time by category, total {total / 1e12:.3f}s:")
    for cat, dur in by_cat.most_common():
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{cat}")
    print("top ops:")
    for name, dur in by_name.most_common(25):
        print(f"  {dur / 1e9:10.1f}ms {100 * dur / max(total, 1):5.1f}%  "
              f"{name[:100]}")


if __name__ == "__main__":
    main()
