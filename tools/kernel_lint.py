#!/usr/bin/env python
"""Sweep every hand-written Pallas kernel through the sanitizer.

Traces each kernel — adam, lamb stage-1/2, layer_norm fwd/bwd,
multi_tensor, flash_attention, and the three ``experimental/`` kernels
— across the geometry ladder (explicit row-block / chunks-per-block
overrides at the ladder's extremes plus the selector's own pick) and
adversarial ragged shapes, runs all six
:mod:`apex_tpu.analysis.pallas_lint` rules over every ``pallas_call``
found, and writes the per-kernel verdict as ``KERNLINT_r*.json``
(schema: :mod:`apex_tpu.analysis.kernlint`, validated by
``tools/gate_hygiene.py`` in tier-1).

Tracing only — nothing is compiled or executed, so the sweep is cheap
enough for CI and runs identically on CPU and TPU (the jaxpr-level
``pallas_call`` carries the same grid/BlockSpec metadata either way).

Usage::

    python tools/kernel_lint.py --out KERNLINT_r01.json
    python tools/kernel_lint.py            # print verdicts, no file

Exit code 1 when any kernel records an unwaived finding (or a config
fails to trace), so the sweep can gate CI directly.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the kernels under test must BE pallas (graph_lint's jnp default would
# trace fallback einsums instead of kernels), and the experimental
# kernels only route when opted in
os.environ["APEX_TPU_KERNELS"] = "pallas"
os.environ["APEX_TPU_EXPERIMENTAL"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

from apex_tpu.analysis import pallas_lint               # noqa: E402
from apex_tpu.analysis.kernlint import (                # noqa: E402
    RULES, validate_kernlint)

#: documented waivers: kernel -> {rule id -> reason}.  A waiver only
#: validates when the rule actually fired (the schema rejects stale
#: ones), so this table is empty while the sweep is clean.
WAIVERS: dict = {}


# ---------------------------------------------------------------------------
# the config table: kernel -> [(config label, fn, args)]
# ---------------------------------------------------------------------------

def _adam_configs():
    from apex_tpu.ops.pallas import adam_kernel as ak
    f32 = jnp.float32
    cfgs = []
    for n, br, donate in [
            (ak.ADAM_PAD, None, False),        # selector's own pick
            (ak.ADAM_PAD * 3, 256, True),      # donated, autotune max
            (ak.ADAM_PAD * 3, 8, False),       # ladder bottom, ragged
    ]:
        p = jnp.zeros((n,), f32)
        args = (p, jnp.zeros_like(p), jnp.zeros_like(p),
                jnp.ones_like(p))

        def fn(p, m, v, g, _br=br, _d=donate):
            return ak.packed_adam(
                p, m, v, g, step_size=1e-3, beta1=0.9, beta2=0.999,
                eps=1e-8, scale=1.0, weight_decay=0.01, eps_mode=0,
                p_copy_dtype=jnp.bfloat16, block_rows=_br, donate=_d)

        cfgs.append((f"n={n} block_rows={br} donate={donate}", fn, args))
    return cfgs


def _lamb_configs():
    from apex_tpu.ops.pallas import lamb_kernels as lk
    f32 = jnp.float32
    cfgs = []
    for n_chunks, cpb, with_norms in [(8, None, False), (8, 1, True),
                                      (16, 4, True)]:
        n = lk.LAMB_CHUNK * n_chunks
        g = jnp.ones((n,), f32)
        args = (g, jnp.ones_like(g), jnp.zeros_like(g),
                jnp.zeros_like(g), jnp.full((n_chunks,), 0.01, f32))

        def fn(g, p, m, v, d, _cpb=cpb, _wn=with_norms):
            return lk.packed_lamb_stage1(
                g, p, m, v, d, beta1=0.9, beta2=0.999, eps=1e-6,
                inv_scale=1.0, bc1=1.0, bc2=1.0,
                chunks_per_block=_cpb, with_norms=_wn)

        cfgs.append((f"stage1 n_chunks={n_chunks} cpb={cpb} "
                     f"norms={with_norms}", fn, args))
    for n_chunks, cpb in [(8, None), (16, 4)]:
        n = lk.LAMB_CHUNK * n_chunks
        p = jnp.ones((n,), f32)
        args = (p, jnp.ones_like(p), jnp.ones((n_chunks,), f32))

        def fn(p, u, r, _cpb=cpb):
            return lk.packed_lamb_stage2(
                p, u, r, p_copy_dtype=jnp.bfloat16,
                chunks_per_block=_cpb)

        cfgs.append((f"stage2 n_chunks={n_chunks} cpb={cpb}", fn, args))
    return cfgs


def _layer_norm_configs():
    from apex_tpu.ops.pallas import layer_norm_kernels as lnk
    cfgs = []
    # forward across the row ladder + ragged rows; fwd+bwd via vjp at
    # the widest shapes supported() admits per dtype — the sanitizer is
    # exactly why wider ones route to the jnp fallback
    shapes = [(256, 1024, jnp.float32), (100, 512, jnp.bfloat16),
              (256, 5376, jnp.float32),      # fp32 backward boundary
              (256, 10752, jnp.bfloat16)]    # bf16 backward boundary
    for n1, n2, dt in shapes:
        assert lnk.supported(n2, dt), (n2, dt)
        x = jnp.ones((n1, n2), dt)
        w = jnp.ones((n2,), dt)
        b = jnp.zeros((n2,), dt)

        def fwd(x, w, b):
            return lnk._forward(x, w, b, 1e-5, affine=True)

        def fwd_bwd(x, w, b):
            y, vjp = jax.vjp(
                lambda x, w, b: lnk.layer_norm_fwd_vjp(x, w, b, 1e-5),
                x, w, b)
            return vjp(y)

        name = jnp.dtype(dt).name
        cfgs.append((f"fwd {n1}x{n2} {name}", fwd, (x, w, b)))
        cfgs.append((f"fwd+bwd {n1}x{n2} {name}", fwd_bwd, (x, w, b)))
    return cfgs


def _multi_tensor_configs():
    from apex_tpu.ops.pallas import multi_tensor_kernels as mtk
    f32 = jnp.float32
    ch = 2048
    flat = jnp.ones((ch * 7,), f32)    # prime chunk count: ragged grid
    s = jnp.float32(2.0)
    return [
        ("scale", lambda f, s: mtk.packed_scale(f, s, ch, f32),
         (flat, s)),
        ("axpby", lambda x, y, a, b: mtk.packed_axpby(
            x, y, a, b, ch, f32, arg_to_check=0), (flat, flat, s, s)),
        ("sumsq", lambda f: mtk.packed_sumsq(f, ch), (flat,)),
        ("sumsq_per_chunk",
         lambda f: mtk.packed_sumsq_per_chunk(f, ch), (flat,)),
    ]


def _flash_configs():
    from apex_tpu.ops.pallas.flash_attention import flash_attention
    bf16 = jnp.bfloat16
    cfgs = []
    for b, l, h, d, causal in [(2, 384, 2, 64, True),   # ragged L
                               (1, 512, 4, 128, False)]:
        q = jnp.ones((b, l, h, d), bf16)
        mask = jnp.ones((b, l), jnp.bool_)

        def fwd(q, k, v, m, _c=causal):
            return flash_attention(q, k, v, causal=_c, kv_mask=m)

        def fwd_bwd(q, k, v, m, _c=causal):
            y, vjp = jax.vjp(
                lambda q, k, v: flash_attention(q, k, v, causal=_c,
                                                kv_mask=m), q, k, v)
            return vjp(y)

        tag = f"b{b} l{l} h{h} d{d} causal={causal}"
        cfgs.append((f"fwd {tag}", fwd, (q, q, q, mask)))
        cfgs.append((f"fwd+bwd {tag}", fwd_bwd, (q, q, q, mask)))
    return cfgs


def _conv1x1_configs():
    from apex_tpu.ops.pallas.experimental import conv1x1 as cv
    bf16 = jnp.bfloat16

    def fwd_bwd(x, w):
        y, vjp = jax.vjp(cv.conv1x1, x, w)
        return vjp(y)

    cfgs = []
    for b, hw, cin, cout in [(2, 16, 64, 128), (1, 32, 128, 256)]:
        x = jnp.ones((b, hw, hw, cin), bf16)
        w = jnp.ones((1, 1, cin, cout), bf16)
        cfgs.append((f"bwd b{b} {hw}x{hw} {cin}->{cout}", fwd_bwd,
                     (x, w)))
    return cfgs


def _finite_pack_configs():
    from apex_tpu.ops.pallas.experimental import finite_pack as fp
    flat = jnp.ones((fp.FINITE_CHUNK * 3,), jnp.float32)
    return [("nonfinite", lambda f: fp.packed_nonfinite(f), (flat,))]


def _flash_mh_configs():
    from apex_tpu.ops.pallas.experimental.flash_mh import \
        flash_attention_mh
    bf16 = jnp.bfloat16
    cfgs = []
    for b, l, h, d in [(1, 256, 2, 64), (1, 384, 12, 64)]:
        q = jnp.ones((b, l, h, d), bf16)

        def fwd_bwd(q, k, v):
            y, vjp = jax.vjp(
                lambda q, k, v: flash_attention_mh(q, k, v,
                                                   causal=True),
                q, k, v)
            return vjp(y)

        cfgs.append((f"fwd+bwd b{b} l{l} h{h} d{d}", fwd_bwd,
                     (q, q, q)))
    return cfgs


KERNELS = {
    "fused_adam": _adam_configs,
    "fused_lamb": _lamb_configs,
    "layer_norm": _layer_norm_configs,
    "multi_tensor": _multi_tensor_configs,
    "flash_attention": _flash_configs,
    "conv1x1": _conv1x1_configs,
    "finite_pack": _finite_pack_configs,
    "flash_mh": _flash_mh_configs,
}


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def sweep_kernel(name: str, configs, verbose: bool = False) -> dict:
    """One kernel's KERNLINT record: per-rule error counts over every
    config, the number of pallas_calls actually linted, the verdict."""
    findings = {rule: 0 for rule in RULES}
    calls = 0
    error = None
    waivers = dict(WAIVERS.get(name, {}))
    for label, fn, args in configs:
        try:
            report = pallas_lint.lint_fn(fn, *args)
        except Exception as e:  # noqa: BLE001 - record, don't crash sweep
            error = f"{label}: {type(e).__name__}: {e}"
            break
        for f in report.findings:
            if f.op == "pallas-call" and f.count != 0:
                calls += 1
            if f.severity == "error" and f.op in findings:
                findings[f.op] += 1
                if verbose:
                    print(f"  [{name}] {label}: {f.op}: {f.message}",
                          file=sys.stderr)
    unwaived = sum(c for rule, c in findings.items()
                   if rule not in waivers)
    rec = {"ok": unwaived == 0 and error is None,
           "configs": len(configs), "calls": calls,
           "findings": findings}
    if waivers:
        rec["waivers"] = waivers
    if error is not None:
        rec["error"] = error
    return rec


def run_sweep(verbose: bool = False) -> dict:
    kernels = {}
    for name, build in KERNELS.items():
        try:
            configs = build()
        except Exception as e:  # noqa: BLE001 - config build counts too
            kernels[name] = {"ok": False, "configs": 0, "calls": 0,
                             "findings": {rule: 0 for rule in RULES},
                             "error": f"config build: "
                                      f"{type(e).__name__}: {e}"}
            continue
        kernels[name] = sweep_kernel(name, configs, verbose=verbose)
    clean = sum(1 for rec in kernels.values() if rec["ok"])
    return {
        "round": None,           # filled from --out / --round in main
        "platform": jax.default_backend(),
        "budget_mb": round(pallas_lint.vmem_ceiling() / (1 << 20), 2),
        "rules": list(RULES),
        "kernels": kernels,
        "gate": {"ok": clean == len(kernels), "kernels_clean": clean,
                 "kernels_total": len(kernels)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Pallas kernel sanitizer sweep -> KERNLINT_r*.json")
    ap.add_argument("--out", default=None,
                    help="write the KERNLINT JSON here (round parsed "
                         "from a KERNLINT_rNN.json name)")
    ap.add_argument("--round", type=int, default=None,
                    help="round number (default: parsed from --out, "
                         "else 1)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every error finding as it is counted")
    opts = ap.parse_args(argv)

    rnd = opts.round
    if rnd is None and opts.out:
        m = re.search(r"KERNLINT_r(\d+)", os.path.basename(opts.out))
        rnd = int(m.group(1)) if m else None
    doc = run_sweep(verbose=opts.verbose)
    doc["round"] = rnd if rnd is not None else 1

    problems = validate_kernlint(doc)
    for name, rec in doc["kernels"].items():
        bad = {rule: c for rule, c in rec["findings"].items() if c}
        status = "ok" if rec["ok"] else "FAIL"
        extra = f" findings={bad}" if bad else ""
        extra += f" error={rec['error']!r}" if "error" in rec else ""
        print(f"{name:16s} {status}  configs={rec['configs']} "
              f"calls={rec['calls']}{extra}")
    gate = doc["gate"]
    print(f"gate: ok={gate['ok']} "
          f"({gate['kernels_clean']}/{gate['kernels_total']} clean)")
    if problems:      # a self-emitted doc failing its own schema is a bug
        for p in problems:
            print(f"schema: {p}", file=sys.stderr)
        return 2
    if opts.out:
        with open(opts.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {opts.out}")
    return 0 if gate["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
