"""Attribute profiled device time to conv layers for the RN50 campaign.

Joins two artifacts of one bench step:
- the compiled HLO: every convolution sits in its own fused computation;
  the fusion instruction name is what the profiler reports, and the
  conv's ``metadata op_name`` carries the flax module path (layer +
  fwd/bwd role), and
- an xplane profile of a few steps (op name -> device time),

and prints per-conv time + achieved MFU *in situ* — no microbenchmark
artifacts (dispatch overhead, CSE, false dependencies); the numbers are
the real step's.  This is how the 73%-convolution-fusion profile
(`tools/profile_step.py`) decomposes into actionable layers.

FLOPs per conv: 2 * prod(output dims) * prod(window sizes) * C_contract,
where C_contract is the lhs dim labeled ``f`` in dim_labels — correct
for forward, input-grad and filter-grad spellings alike.

Usage: python tools/conv_attrib.py [resnet50|resnet50_s2d] [O2] [batch]
"""

import collections
import json
import re
import shutil
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

_SHAPE_RE = re.compile(r"(bf16|f16|f32|s8|u8|s32)\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (\S+)")
_CONV_RE = re.compile(
    r"convolution\(%?([\w.\-]+), %?([\w.\-]+)\).*?"
    r"window={size=([0-9x]+)[^}]*}.*?dim_labels=(\S+?),.*?"
    r"op_name=\"([^\"]+)\"")
_CALLS_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = .*? fusion\(.*calls=%?([\w.\-]+)")


def _dims(shape_str):
    m = _SHAPE_RE.search(shape_str)
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def parse_hlo(hlo: str):
    """-> {fusion instr name: conv record} for every convolution."""
    comp_shapes = collections.defaultdict(dict)   # comp -> name -> dims
    comp_convs = {}                               # comp -> record
    cur = None
    for raw in hlo.splitlines():
        line = raw.strip()
        if line.endswith("{") and " = " not in line and "(" in line:
            cur = line.split()[0].lstrip("%").split("(")[0]
            continue
        im = _INSTR_RE.match(raw)
        if im:
            comp_shapes[cur][im.group(1)] = _dims(im.group(2))
        cm = _CONV_RE.search(line)
        if cm and im:
            lhs, rhs, window, dim_labels, op_name = cm.groups()
            out = _dims(im.group(2))
            lhs_dims = comp_shapes[cur].get(lhs)
            if out is None or lhs_dims is None:
                continue
            # Per-output contraction = rhs "i" dim (robust to grouped/
            # depthwise convs, where the lhs "f" dim overcounts by the
            # group count — same rule as fusion_roofline._conv_flops_in)
            rhs_dims = comp_shapes[cur].get(rhs)
            rhs_label = dim_labels.split("_")[1].split("->")[0]
            if rhs_dims is not None and "i" in rhs_label:
                cin = rhs_dims[rhs_label.index("i")]
            else:
                lhs_label = dim_labels.split("_")[0]
                cin = lhs_dims[lhs_label.index("f")]
            win = 1
            for w in window.split("x"):
                win *= int(w)
            flops = 2.0 * cin * win
            for d in out:
                flops *= d
            layer = re.sub(r"^jit\(\w+\)/", "", op_name)
            comp_convs[cur] = {
                "layer": layer, "flops": flops,
                # the true forward is the jvp spelling; dgrad is ALSO
                # b01f (rhs_reversal + base dilation), so dim_labels
                # can't distinguish them — the op_name can
                "fwd": not layer.startswith("transpose"),
                "out": out, "window": window, "cin": cin}
    # The naive flops formula is only trustworthy for the forward
    # spelling (b01f lhs); gradient convs use full-correlation spellings
    # whose padded window taps would massively overcount.  dgrad and
    # wgrad each cost the same MACs as their forward conv, so assign
    # every transpose conv its layer's forward figure.
    fwd_flops = {}
    for rec in comp_convs.values():
        if rec["fwd"]:
            layer = rec["layer"].split(")/")[-1]
            fwd_flops[layer] = rec["flops"]
    for rec in comp_convs.values():
        if not rec["fwd"]:
            layer = rec["layer"].split(")/")[-1]
            rec["flops"] = fwd_flops.get(layer, rec["flops"])
    # fusion instruction -> computation
    result = {}
    for raw in hlo.splitlines():
        fm = _CALLS_RE.match(raw)
        if fm and fm.group(2) in comp_convs:
            result[fm.group(1)] = comp_convs[fm.group(2)]
    return result


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    opt_level = sys.argv[2] if len(sys.argv) > 2 else "O2"
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    import bench
    from apex_tpu import amp
    from apex_tpu.models.resnet import ARCHS
    from apex_tpu.optimizers import FusedAdam
    import jax.numpy as jnp

    peak = bench.chip_peak_flops()
    m = ARCHS[model]()
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, 224, 224, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
    variables = m.init(jax.random.PRNGKey(2), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level=opt_level,
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, xb, yb):
        logits, _ = m.apply({"params": p, "batch_stats": batch_stats},
                            xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))
    compiled = step.lower(state, x, y).compile()
    convs = parse_hlo(compiled.as_text())

    iters = 6
    st, _ = compiled(state, x, y)
    jax.block_until_ready(st)
    logdir = "/tmp/apex_tpu_conv_attrib"
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        for _ in range(iters):
            st, mtr = compiled(st, x, y)
        jax.block_until_ready(st)
    time.sleep(1)

    from apex_tpu.obs.xplane import parse_xplane
    by_name, _, total = parse_xplane(logdir)

    rows = []
    conv_time = 0.0
    matched = set()
    for name, dur_ps in by_name.items():
        rec = convs.get(name)
        if rec is None:
            continue
        matched.add(name)
        dur_s = dur_ps / 1e12 / iters
        conv_time += dur_s
        rows.append({"op": name, "layer": rec["layer"],
                     "ms": round(dur_s * 1e3, 3),
                     "mfu": round(rec["flops"] / dur_s / peak, 3),
                     "gflops": round(rec["flops"] / 1e9, 1),
                     "out": rec["out"], "win": rec["window"],
                     "cin": rec["cin"]})
    rows.sort(key=lambda r: -r["ms"])
    for r in rows:
        print(json.dumps(r))
    step_s = total / 1e12 / iters
    print(json.dumps({
        "conv_ms_per_step": round(conv_time * 1e3, 2),
        "device_ms_per_step": round(step_s * 1e3, 2),
        "conv_frac": round(conv_time / step_s, 3),
        "hlo_convs": len(convs), "profiled_convs": len(rows),
        "conv_mfu": round(sum(c["flops"] for c in convs.values())
                          / (conv_time + 1e-12) / peak, 4)
        if len(rows) == len(convs) else None}))


if __name__ == "__main__":
    main()
