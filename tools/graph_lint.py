"""Graph lint over the in-tree model families' train and decode lanes.

Runs every :mod:`apex_tpu.analysis` pass over the four model families
(MLP, ResNet, GPT, BERT — tiny configs, CPU-safe, seconds per family):

- the **graph passes** (donation, sharding, collectives,
  constant-capture) and the **memlint passes** (memory, cost, syncs)
  run on the full O1/O2 ``amp.make_train_step`` programs with the Amp
  state donated — the program production actually runs, lowered and
  compiled ONCE per lane on the host backend (no device execution);
  every pass shares that single :class:`~apex_tpu.analysis.PassContext`;
- the **policy pass** runs on the O1 *forward* (the audit's documented
  scope — the AD-generated backward legitimately accumulates in the
  wire dtype, see ``apex_tpu/analysis/policy.py``), sharing the model
  builders with ``tools/policy_audit.py``;
- the **decode lanes** lint the jitted KV-cached generation step
  (``apex_tpu.models.generate._generate_impl``) at bench-shaped tiny
  configs, and ``--emit-json`` additionally lowers the
  ``dryrun_multichip`` slices on the 8-device virtual CPU mesh to
  record each slice's static per-device HBM;
- the **serve lanes** lint the continuous-batching engine's compiled
  programs (``apex_tpu.serve.ServeEngine``: paged KV pools, page
  tables, fused sampling epilogue, donated carries) — the serving
  static-shape contract's static half: no host callback and no
  retrace hazard on the token loop.  Since the disaggregated fleet
  (``apex_tpu.serve.router``) split the phases onto separate mesh
  slices, the lane family covers BOTH split steps: ``serve_step``
  (monolithic shape) + ``serve_decode`` (decode-replica shape) for
  the decode program, and ``serve_prefill`` for the prefill worker's
  chunked-prefill program.

Per-family collective byte budgets are pinned at zero: a single-chip
train step has no collectives, so ANY appearing is a comm-volume
regression (multi-chip programs get their budgets where their meshes
are built — the dryrun slices in ``__graft_entry__.py``).

``--memory-budget [BYTES]`` arms the per-device peak-HBM gate on every
lane (bare flag = the v5e 16 GiB default; suffixes ``KiB``/``MiB``/
``GiB`` accepted).  ``--emit-json MEMLINT_rN.json`` writes the
committed memory-lint artifact — per-lane ``peak_hbm_bytes``,
donation-aliasing table, cost-model flops/bytes, the multichip slice
table, and the gate-calibration audit (committed KERNELBENCH/BENCH
floors must sit under the cost-model ceiling) — validated by
``tools/gate_hygiene.py`` against ``apex_tpu/analysis/memlint.py``.

One JSON line per lane plus a human summary; exit 1 on any finding of
``error`` severity — wired as ``tests/l0/test_graph_lint.py`` so the
clean-program guarantee is continuously enforced.

The **precision pass** (``apex_tpu/analysis/precision.py``) also runs
on every lane, with the lane's resolved ``amp.policy.Properties`` in
the PassContext: forced sub-f32 matmul accumulation, long 16-bit
reductions, f32→16→f32 double rounds, non-f32 masters/moments under
O2, and loss-scale placement (scale dominates the backward, unscale
dominates the update).  ``--passes precision`` defaults to the full
O0–O4 train matrix plus decode (o4 is the fp8 regime — delayed-scaling
state, e4m3/e5m2 quantizes — carrying the three fp8 contract rules);
``--emit-json PRECLINT_rN.json``
writes the committed precision artifact (schema in
``apex_tpu/analysis/preclint.py``, validated by gate hygiene).

The **export-compat pass** (``apex_tpu/analysis/export.py``) is
registered too — ``--passes export-compat`` lints any lane's
AOT-serializability (host callbacks, platform-pinned custom calls,
static captures, baked constants); ``tools/aot_export.py`` runs it as
part of the export gate that builds the content-addressed executable
cache from these same lanes.

Usage:
    python tools/graph_lint.py [--families mlp,gpt] [--passes donation,...]
                               [--lanes o0,o1,o2,o3,decode,serve]
                               [--no-compile]
                               [--memory-budget [BYTES]]
                               [--emit-json MEMLINT_r01.json|PRECLINT_r01.json]
                               [-v]
"""

import argparse
import json
import os
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))

# CPU-safe by default: lint lowers/compiles for the host platform unless
# the caller pins a real chip (same env knob as the test suite).  Must
# happen before any jax backend initialization; the env-level
# JAX_PLATFORMS pin (sitecustomize) is overridden at the config level.
# The multichip lanes additionally need 8 virtual host devices, which
# only an XLA_FLAGS set before backend init can provide.
os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

from apex_tpu import amp, analysis  # noqa: E402
from apex_tpu.analysis import cost as cost_mod  # noqa: E402
from apex_tpu.analysis import memory as memory_mod  # noqa: E402
from apex_tpu.optimizers import FusedAdam  # noqa: E402

import policy_audit  # noqa: E402  (sibling tool: shared model builders)

GRAPH_PASSES = ("donation", "sharding", "collectives", "constant-capture")
#: the compiled-evidence memory/cost/sync passes — run on every lane,
#: sharing the lane's single lowering+compilation with the graph passes
MEMLINT_PASSES = ("memory", "cost", "syncs")
#: the precision-flow pass runs on every lane too (lowering-only; the
#: lane's resolved amp policy rides in the PassContext), as does the
#: SPMD deadlock-shape check (a collective under a rank-divergent
#: predicate — trivially clean on single-chip lanes, load-bearing on
#: the fleet lanes)
ALL_PASSES = GRAPH_PASSES + MEMLINT_PASSES + ("precision",
                                              "spmd-consistency",
                                              "policy")

#: train lanes the CLI can run (opt levels); decode rides separately.
#: o4 = the fp8 regime (apex_tpu.quant): delayed-scaling state in the
#: donated AmpState, e4m3/e5m2 quantizes in the lowered program — the
#: lane the three fp8 precision rules run against.
TRAIN_LANES = ("o0", "o1", "o2", "o3", "o4")

#: single-chip train steps imply ZERO collective bytes; any regression
#: that introduces one (an accidental psum, a sharding annotation leak)
#: fails the gate like an MFU-floor violation fails the bench.
COLLECTIVE_BUDGETS = {"mlp": {"total": 0}, "resnet": {"total": 0},
                      "gpt": {"total": 0}, "bert": {"total": 0}}

FAMILIES = tuple(policy_audit.RAW_CASES)

#: decode lanes: (batch, prefill, new_tokens, kv_dtype) at the tiny
#: config — the static analog of the bench's gpt_small_tpu_decode_b{1,8}
#: lanes; decode_b1_kv8 is the int8-KV path (quantize-on-write,
#: dequant fused into the attention read — the kv8 bench config's
#: program, machine-checked like the dense one).
DECODE_LANES = {"decode_b1": (1, 8, 8, None),
                "decode_b2": (2, 8, 8, None),
                "decode_b1_kv8": (1, 8, 8, "int8")}

#: serve lanes: (num_slots, block_size, num_blocks, max_blocks_per_slot)
#: — the continuous-batching engine's compiled decode step
#: (``apex_tpu.serve.ServeEngine``) at a tiny config.  The lane is the
#: static half of the serving static-shape contract: the step must
#: carry no host callback on the token loop and no statically-bound
#: numeric scalar (either would serialize or retrace the serving
#: fleet's hot loop); the runtime half (one trace across a whole
#: admit/retire stream) lives in tests/l0/test_serve_engine.py.
#: ``serve_step`` is the monolithic engine's shape; ``serve_decode``
#: is the SAME program class at a disaggregated decode-replica shape
#: (``apex_tpu.serve.router.DecodeReplica`` — more slots, its own
#: pool), so the split fleet's decode half is machine-checked at its
#: own geometry.
SERVE_LANES = {"serve_step": (2, 4, 9, 4),
               "serve_decode": (4, 4, 17, 4)}

#: the split fleet's OTHER compiled program: the prefill worker's
#: chunked prefill (``ServeEngine._prefill_chunk`` — what
#: ``apex_tpu.serve.router.PrefillWorker`` dispatches per chunk on the
#: prefill mesh slice).  Same tuple shape as SERVE_LANES; the chunk
#: length is the config's ``prefill_chunk`` (= block_size here).
SERVE_PREFILL_LANES = {"serve_prefill": (2, 4, 9, 4)}

#: the speculative-decoding verifier (``apex_tpu.serve.spec.
#: SpecEngine._verify_step``): the b×(k+1) multi-token cached forward
#: that scores every slot's draft proposals in ONE dispatch, samples
#: the target's draw at each position with the slot's key ladder, and
#: returns the accepted counts — the serve engine's third compiled
#: program class.  Tuple = (num_slots, block_size, num_blocks,
#: max_blocks_per_slot, k); lints through the same full pass matrix
#: as the decode step (no host callback / no static scalar on the
#: speculation loop, donated carry fully aliased).
SERVE_VERIFY_LANES = {"serve_verify": (2, 4, 9, 4, 3)}


def build_train_step(family: str, raw=None, opt_level: str = "O1"):
    """(jitted_step, example_args, properties): the full train step —
    FusedAdam, dynamic loss scaling, Amp state donated — for one model
    family at ``opt_level``, plus the resolved policy for the
    precision pass's :class:`~apex_tpu.analysis.PassContext`.  ``raw``
    reuses an already-built ``(loss_fn, params, batch)``."""
    loss_fn, params, batch = raw or policy_audit.RAW_CASES[family]()
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level=opt_level,
                       verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=0)
    return step, (state, *batch), a.properties


def build_decode_step(batch: int = 1, prefill: int = 8,
                      new_tokens: int = 8, kv_dtype=None):
    """(jitted_decode, args, kwargs, properties): the KV-cached
    generation step at a tiny config in the bf16 serving layout — the
    program ``apex_tpu.models.generate.generate`` dispatches — plus
    the O2 serving policy it was cast under.  ``kv_dtype="int8"``
    builds the int8-KV variant (per-position scales, fused dequant)."""
    from importlib import import_module
    gen = import_module("apex_tpu.models.generate")   # the module —
    # ``apex_tpu.models`` re-exports the ``generate`` FUNCTION under
    # the same name, shadowing a ``from ... import generate``
    from apex_tpu.models.gpt import GPTModel, gpt_tiny

    cfg = gpt_tiny()
    model = GPTModel(cfg)
    prompt = jnp.zeros((batch, prefill), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)   # bf16, the serving layout
    stacked = gen._stack_layer_params(params, cfg.num_layers)
    top = {k: v for k, v in params.items()
           if not k.startswith("block_") and k != "layers"}
    args = (top, stacked, prompt, jnp.float32(0.0),
            jax.random.PRNGKey(0))
    kwargs = dict(cfg=cfg, max_new_tokens=new_tokens, sample=False,
                  kv_dtype=kv_dtype)
    return gen._generate_impl, args, kwargs, a.properties


def build_serve_engine(num_slots: int = 2, block_size: int = 4,
                       num_blocks: int = 9,
                       max_blocks_per_slot: int = 4,
                       prefill_chunk: int = None, registry=None):
    """(engine, properties): the ONE construction of the tiny-gpt
    serve engine every serve lane shares — gpt_tiny init, the O2
    serving cast, ``ServeConfig`` — used by the lint lanes here, the
    obs_report overhead/lint lanes, and ``tools/continuous_profile``,
    so a carry or scheduler change can never leave an overhead lane
    measuring a different engine than the one the serve gate lints."""
    from apex_tpu.models.gpt import GPTModel, gpt_tiny
    from apex_tpu.serve import ServeConfig, ServeEngine

    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)
    scfg = ServeConfig(num_slots=num_slots, block_size=block_size,
                       num_blocks=num_blocks,
                       max_blocks_per_slot=max_blocks_per_slot,
                       prefill_chunk=prefill_chunk or block_size)
    eng = ServeEngine(params, cfg, scfg, registry=registry)
    return eng, a.properties


def build_serve_step(num_slots: int = 2, block_size: int = 4,
                     num_blocks: int = 9, max_blocks_per_slot: int = 4):
    """(jitted_step, args, properties): the serve engine's compiled
    continuous-batching decode step at a tiny config — paged KV pools
    + per-slot page tables + fused sampling epilogue, carries donated —
    plus the O2 serving policy the params were cast under."""
    eng, props = build_serve_engine(num_slots, block_size, num_blocks,
                                    max_blocks_per_slot)
    return eng._decode_step, eng.decode_step_args(), props


def build_serve_prefill(num_slots: int = 2, block_size: int = 4,
                        num_blocks: int = 9,
                        max_blocks_per_slot: int = 4):
    """(jitted_chunk, args, properties): the serve engine's compiled
    chunked-prefill program — one ``(1, prefill_chunk)`` prompt chunk
    written through a slot's page table, KV pools donated — the
    program the disaggregated fleet's prefill worker dispatches on its
    own mesh slice.  ``start``/``n_valid`` are DYNAMIC int32 args
    (one executable per chunk shape, never per position)."""
    eng, a_props = build_serve_engine(num_slots, block_size,
                                      num_blocks, max_blocks_per_slot)
    scfg = eng.scfg
    s = eng.sched
    args = (eng.top, eng.stacked, eng.carry["kc"], eng.carry["vc"],
            eng.carry.get("ks"), eng.carry.get("vs"),
            jnp.asarray(s.page_table[0]),
            jnp.zeros((1, scfg.prefill_chunk), jnp.int32),
            jnp.int32(0), jnp.int32(scfg.prefill_chunk))
    return eng._prefill_chunk, args, a_props


def build_serve_verify(num_slots: int = 2, block_size: int = 4,
                       num_blocks: int = 9, max_blocks_per_slot: int = 4,
                       k: int = 3):
    """(jitted_verify, args, properties): the speculative-decoding
    verify step at a tiny config — the target model scoring ``k``
    draft proposals per slot in one b×(k+1) dispatch (KV written for
    every fed position through the paged pools, acceptance computed
    on device, carry donated) — plus the O2 serving policy.  The
    draft is the target's truncated first layer (the layer-skip
    self-draft), which shapes the proposal argument without needing a
    second checkpoint."""
    from apex_tpu.models.gpt import GPTModel, gpt_tiny
    from apex_tpu.serve import (ServeConfig, SpecConfig, SpecEngine,
                                truncated_draft)

    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)
    scfg = ServeConfig(num_slots=num_slots, block_size=block_size,
                       num_blocks=num_blocks,
                       max_blocks_per_slot=max_blocks_per_slot,
                       prefill_chunk=block_size)
    dp, dcfg = truncated_draft(params, cfg, max(1, cfg.num_layers - 1))
    eng = SpecEngine(params, cfg, scfg, dp, dcfg, SpecConfig(k=k))
    s = eng.sched
    args = (eng.top, eng.stacked, eng.carry,
            jnp.zeros((num_slots, k), jnp.int32),
            jnp.asarray(s.last_tok), jnp.asarray(s.lengths),
            jnp.asarray(s.active), jnp.asarray(s.page_table),
            jnp.asarray(s.temperature), jnp.asarray(s.top_k),
            jnp.asarray(s.top_p))
    return eng._verify_step, args, a.properties


def _lint_serve_program(lane: str, fn, args, props, passes, compile,
                        memory_budget, _collect):
    passes = tuple(
        p for p in (passes or GRAPH_PASSES + MEMLINT_PASSES
                    + ("precision",))
        if p not in ("policy", "pallas-kernel"))
    if not passes:
        return analysis.Report()
    lowered = analysis.lower_quiet(fn, *args)
    ctx = analysis.build_context(lowered, compile=compile, policy=props)
    options = {"collectives": {"budget": {"total": 0}}}
    options.update(_memlint_options(memory_budget))
    report = analysis.run_passes(ctx, passes=passes, options=options)
    if _collect is not None:
        _collect[lane] = _lane_record(ctx, report)
    return report


def lint_serve(lane: str, passes=None, compile: bool = True,
               memory_budget=None, _collect=None):
    """Lint one serve decode-step lane (graph + memlint + precision
    passes; no policy — the serving step is a bf16 forward by design,
    like the decode lanes)."""
    if passes is not None and not tuple(
            p for p in passes if p not in ("policy", "pallas-kernel")):
        return analysis.Report()
    slots, bs, nb, mb = SERVE_LANES[lane]
    fn, args, props = build_serve_step(slots, bs, nb, mb)
    return _lint_serve_program(lane, fn, args, props, passes, compile,
                               memory_budget, _collect)


def lint_serve_prefill(lane: str, passes=None, compile: bool = True,
                       memory_budget=None, _collect=None):
    """Lint one serve prefill-chunk lane — the split fleet's other
    compiled program, under the same pass matrix as the decode
    lanes."""
    if passes is not None and not tuple(
            p for p in passes if p not in ("policy", "pallas-kernel")):
        return analysis.Report()
    slots, bs, nb, mb = SERVE_PREFILL_LANES[lane]
    fn, args, props = build_serve_prefill(slots, bs, nb, mb)
    return _lint_serve_program(lane, fn, args, props, passes, compile,
                               memory_budget, _collect)


def lint_serve_verify(lane: str, passes=None, compile: bool = True,
                      memory_budget=None, _collect=None):
    """Lint one speculative-verify lane — the b×(k+1) verifier step
    the spec engine dispatches once per speculation round, under the
    same pass matrix as the decode lanes."""
    if passes is not None and not tuple(
            p for p in passes if p not in ("policy", "pallas-kernel")):
        return analysis.Report()
    slots, bs, nb, mb, k = SERVE_VERIFY_LANES[lane]
    fn, args, props = build_serve_verify(slots, bs, nb, mb, k)
    return _lint_serve_program(lane, fn, args, props, passes, compile,
                               memory_budget, _collect)


def _memlint_options(memory_budget=None):
    opts = {}
    if memory_budget is not None:
        opts["memory"] = {"budget_bytes": int(memory_budget)}
    return opts


def _lane_record(ctx, report) -> dict:
    """The MEMLINT lane record for one analyzed program (see
    ``apex_tpu/analysis/memlint.py`` for the schema)."""
    stats = memory_mod.context_memory_stats(ctx) \
        if ctx.compiled is not None else None
    ct = cost_mod.context_cost_table(ctx) \
        if ctx.compiled is not None else None
    rec = {
        "ok": report.ok,
        "peak_hbm_bytes": int(stats["peak_hbm_bytes"]) if stats else 0,
        "breakdown": {k: v for k, v in (stats or {}).items()
                      if k != "peak_hbm_bytes"},
        # None = numbering ambiguous on this jax version; the memory
        # pass records that as its own finding
        "donation": memory_mod.donation_table(ctx) or [],
        "cost": ct or {},
        "findings": report.to_dict()["counts"],
    }
    return rec


def lint_family(family: str, passes=ALL_PASSES, compile: bool = True,
                opt_level: str = "O1", memory_budget=None,
                raw=None, _collect=None):
    """Run the requested passes over one family; returns the merged
    :class:`~apex_tpu.analysis.Report` (train-step graph+memlint passes
    + forward policy pass).  The model is built once (``raw`` reuses an
    already-built ``(loss_fn, params, batch)`` across lanes); the train
    step is lowered ONCE and compiled at most once, and every
    non-policy pass shares that PassContext (the policy pass analyzes
    the forward — a different program — and is the only second
    lowering)."""
    step_passes = tuple(p for p in passes if p != "policy")
    run_policy = "policy" in passes and opt_level == "O1"
    if not step_passes and not run_policy:
        # nothing to run on this lane: skip before paying the model
        # build (main() reports the empty report as a skipped lane)
        return analysis.Report()
    raw = loss_fn, params, batch = \
        raw or policy_audit.RAW_CASES[family]()
    report = analysis.Report()
    ctx = None
    if step_passes:
        step, args, props = build_train_step(family, raw=raw,
                                             opt_level=opt_level)
        closed_jaxpr = None
        if "pallas-kernel" in step_passes:
            # the pallas pass reads jaxpr-level BlockSpec structure,
            # and the step must TRACE with the pallas kernels routed
            # in (the CLI pins APEX_TPU_KERNELS=jnp for the text
            # passes) — a fresh jit wrapper keeps the jnp trace/lower
            # cache unpolluted
            prev = os.environ.get("APEX_TPU_KERNELS")
            os.environ["APEX_TPU_KERNELS"] = "pallas"
            try:
                pstep, pargs, _ = build_train_step(
                    family, raw=raw, opt_level=opt_level)
                closed_jaxpr = pstep.trace(*pargs).jaxpr
            except Exception:  # noqa: BLE001 - degrades to "skipped"
                closed_jaxpr = None
            finally:
                if prev is None:
                    os.environ.pop("APEX_TPU_KERNELS", None)
                else:
                    os.environ["APEX_TPU_KERNELS"] = prev
        lowered = analysis.lower_quiet(step, *args)
        ctx = analysis.build_context(lowered, compile=compile,
                                     policy=props,
                                     closed_jaxpr=closed_jaxpr)
        options = {"collectives":
                   {"budget": COLLECTIVE_BUDGETS.get(family, {})}}
        options.update(_memlint_options(memory_budget))
        report = analysis.run_passes(ctx, passes=step_passes,
                                     options=options)
    if run_policy:
        a = amp.initialize(opt_level="O1", verbosity=0)
        fwd = lambda p, *b: a.run(loss_fn, p, *b)  # noqa: E731
        report = report.merged(analysis.analyze(
            fwd, params, *batch, passes=("policy",), compile=False))
    if _collect is not None and ctx is not None:
        # the MERGED report: a policy error must show in the lane
        # record's ok/findings, or the CLI's "see the artifact"
        # failure message would point at a clean document
        _collect[f"{family}_{opt_level.lower()}_train"] = \
            _lane_record(ctx, report)
    return report


def lint_decode(lane: str, passes=None, compile: bool = True,
                memory_budget=None, _collect=None):
    """Lint one decode lane (graph + memlint passes; no policy — the
    decode program is a bf16 serving forward by design)."""
    passes = tuple(
        p for p in (passes or GRAPH_PASSES + MEMLINT_PASSES
                    + ("precision",))
        if p not in ("policy", "pallas-kernel"))
    if not passes:
        # e.g. --passes policy: nothing applies to a decode lane —
        # skip before paying the build + XLA compilation
        return analysis.Report()
    batch, prefill, new_tokens, kv_dtype = DECODE_LANES[lane]
    fn, args, kwargs, props = build_decode_step(batch, prefill,
                                                new_tokens, kv_dtype)
    lowered = fn.lower(*args, **kwargs)
    ctx = analysis.build_context(lowered, compile=compile, policy=props)
    options = {"collectives": {"budget": {"total": 0}}}
    options.update(_memlint_options(memory_budget))
    report = analysis.run_passes(ctx, passes=passes, options=options)
    if _collect is not None:
        _collect[lane] = _lane_record(ctx, report)
    return report


def multichip_slice_table(n_devices: int = 8) -> dict:
    """Static per-device HBM of each ``dryrun_multichip`` slice: build
    and lower+compile every slice on the virtual CPU mesh (nothing
    executes) and read XLA's memory analysis — the
    ``hbm_bytes_per_device`` column of ``MULTICHIP_SLICES.json``,
    derived from analysis instead of hand-waving.  A slice that cannot
    build/compile on this jax version records its error and moves on,
    exactly like the dryrun itself."""
    import __graft_entry__ as graft

    devices = jax.devices("cpu")[:n_devices]
    if len(devices) < n_devices:
        # same hazard __graft_entry__._dryrun_impl guards: if another
        # caller initialized jax's backends before this module's
        # XLA_FLAGS append, the virtual mesh is missing and every
        # per-device number would be silently wrong — fail, never
        # commit wrong gate memory under an "n_devices": 8 header
        raise RuntimeError(
            f"need {n_devices} CPU devices for the multichip slice "
            f"table, have {len(devices)}; jax's backends initialized "
            f"before xla_force_host_platform_device_count could take "
            f"effect — run tools/graph_lint.py as the entry point")
    out = {}
    for name, build in graft.SLICE_BUILDERS:
        try:
            step, args, _check = build(devices)
            compiled = step.lower(*args).compile()
            stats = memory_mod.per_device_stats(compiled)
            rec = {"ok": True}
            if stats:
                rec["hbm_bytes_per_device"] = stats["peak_hbm_bytes"]
                rec["breakdown"] = {k: v for k, v in stats.items()
                                    if k != "peak_hbm_bytes"}
            out[name] = rec
        except Exception as e:  # noqa: BLE001 - per-slice isolation
            out[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {e}"[:200]}
    return out


#: ranks the fleet lanes simulate: every rank of a data-parallel fleet
#: lowers the SAME program, so each lane lowers the step once per rank
#: on the virtual mesh and cross-checks the collective schedules —
#: exactly what the runtime preflight
#: (:func:`apex_tpu.parallel.multiproc.spmd_preflight`) does with an
#: all-gather on a real cluster.
FLEET_RANKS = 8

#: fleet lanes: the DDP O1/O2 train steps (per-rank schedule
#: consistency + the conditional-collective deadlock check) and the
#: elastic reshape pair (8→4 shrink / 4→8 regrow — the
#: DurableCheckpointManager reshape lanes, which must stay
#: opcode-consistent even though groups/bytes legally change).
FLEET_LANES = ("ddp_o1_train", "ddp_o2_train",
               "reshape_8to4", "reshape_4to8")


def build_fleet_step(opt_level: str = "O1", n_devices: int = 8):
    """(jitted_step, example_args, properties): the DDP + amp train
    step under ``shard_map`` on the first ``n_devices`` of the virtual
    mesh — the program every rank of a data-parallel fleet compiles
    (grads reduced through ``DistributedDataParallel.reduce``, loss
    ``pmean``-ed, so the lowering carries the fleet's real collective
    schedule)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.utils.jax_compat import shard_map

    devices = jax.devices("cpu")[:n_devices]
    if len(devices) < n_devices:
        # same hazard as multichip_slice_table: a mesh missing devices
        # would silently lower a different (smaller) schedule
        raise RuntimeError(
            f"need {n_devices} CPU devices for the fleet lanes, have "
            f"{len(devices)}; run tools/graph_lint.py as the entry "
            f"point so xla_force_host_platform_device_count applies")
    mesh = Mesh(np.array(devices), ("data",))
    params = {"w1": jax.random.normal(jax.random.PRNGKey(0), (8, 16)),
              "w2": jax.random.normal(jax.random.PRNGKey(1), (16, 8))}

    def loss_fn(p, xb):
        h = jax.nn.relu(xb @ p["w1"])
        return jnp.mean(jnp.square(h @ p["w2"]))

    ddp = DistributedDataParallel(axis_name="data")
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3),
                       opt_level=opt_level, verbosity=0)
    state = a.init(params)
    step = amp.make_train_step(a, loss_fn, axis_name="data",
                               reduce_fn=ddp.reduce)

    def inner(s, xb):
        s2, m = step(s, xb[0])
        return s2, jax.lax.pmean(m["loss"], "data")

    fn = jax.jit(shard_map(inner, mesh=mesh,
                           in_specs=(P(), P("data")),
                           out_specs=(P(), P())))
    x = jax.random.normal(jax.random.PRNGKey(2), (n_devices, 4, 8))
    return fn, (state, x), a.properties


def _fleet_rank_schedule(opt_level: str, n_devices: int):
    """(stablehlo_text, collective_schedule) of one rank's lowering."""
    from apex_tpu.analysis import spmd as spmd_mod

    fn, args, _props = build_fleet_step(opt_level, n_devices)
    text = analysis.lower_quiet(fn, *args).as_text()
    return text, spmd_mod.collective_schedule(text)


def fleet_lane_result(lane: str, n_ranks: int = FLEET_RANKS):
    """(findings, lane_record) for one fleet lane — the shared core of
    :func:`lint_fleet` (CLI verdict) and :func:`emit_fleetlint` (the
    committed artifact), so the two can never diverge.  ``lane_record``
    matches the FLEETLINT schema's per-lane shape
    (:mod:`apex_tpu.analysis.fleetlint`), its ``consistent`` verdict
    re-derivable from the recorded per-rank hashes."""
    from apex_tpu.analysis import spmd as spmd_mod

    findings = []
    mismatches = []
    if lane in ("ddp_o1_train", "ddp_o2_train"):
        opt = lane.split("_")[1].upper()
        compare, div_keys = "schedule", spmd_mod._IDENTITY_KEYS
        scheds = {}
        ref_text = None
        for r in range(n_ranks):
            text, sched = _fleet_rank_schedule(opt, 8)
            if ref_text is None:
                ref_text = text
            scheds[str(r)] = sched
        findings.extend(spmd_mod.conditional_collective_findings(ref_text))
    elif lane in ("reshape_8to4", "reshape_4to8"):
        compare, div_keys = "opcodes", ("kind", "variant")
        text8, s8 = _fleet_rank_schedule("O2", 8)
        text4, s4 = _fleet_rank_schedule("O2", 4)
        scheds = {"mesh8": s8, "mesh4": s4} if lane == "reshape_8to4" \
            else {"mesh4": s4, "mesh8": s8}
        findings.extend(spmd_mod.conditional_collective_findings(
            text8 if lane == "reshape_8to4" else text4))
    else:
        raise KeyError(f"unknown fleet lane {lane!r}; have {FLEET_LANES}")

    labels = list(scheds)
    ref = labels[0]
    for lbl in labels[1:]:
        if compare == "schedule":
            findings.extend(spmd_mod.diff_schedules(
                f"rank {ref}", scheds[ref], f"rank {lbl}", scheds[lbl]))
        else:
            findings.extend(spmd_mod.reshape_pair_findings(
                ref, scheds[ref], lbl, scheds[lbl]))
        d = spmd_mod.first_divergence(scheds[ref], scheds[lbl], div_keys)
        if d is not None:
            mismatches.append({"ranks": [ref, lbl], "index": d[0],
                               "a": d[1], "b": d[2]})

    ranks = {
        lbl: {"schedule_hash": spmd_mod.schedule_fingerprint(s),
              "opcode_hash": spmd_mod.schedule_fingerprint(
                  s, opcodes_only=True),
              "n_collectives": len(s)}
        for lbl, s in scheds.items()}
    key = "schedule_hash" if compare == "schedule" else "opcode_hash"
    consistent = len({rec[key] for rec in ranks.values()}) == 1
    if compare == "schedule" and consistent:
        findings.append(analysis.Finding(
            "spmd-consistency", "info",
            f"{len(ranks)} per-rank lowerings schedule-consistent "
            f"({ranks[ref]['n_collectives']} collective(s), fingerprint "
            f"{ranks[ref]['schedule_hash'][:12]})",
            op="fleet", count=len(ranks)))
    return findings, {"compare": compare, "consistent": consistent,
                      "ranks": ranks, "mismatches": mismatches}


def lint_fleet(lane: str, passes=None, n_ranks: int = FLEET_RANKS,
               _collect=None):
    """Lint one fleet lane: per-rank lowerings of the DDP train step
    (or the reshape pair) diffed for SPMD schedule consistency.  Only
    the ``spmd-consistency`` pass applies — any other requested pass
    set skips the lane."""
    from apex_tpu.analysis.report import make_report

    if passes is not None and "spmd-consistency" not in passes:
        return analysis.Report()
    findings, rec = fleet_lane_result(lane, n_ranks=n_ranks)
    report = make_report(findings, ("spmd-consistency",))
    if _collect is not None:
        counts: dict = {}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        _collect[lane] = dict(rec, findings=counts)
    return report


def emit_fleetlint(path: str, verbose: bool = False) -> int:
    """Write the FLEETLINT artifact: every fleet lane's per-rank
    schedule fingerprints, mismatch rows naming the first diverging op,
    and the re-derivable gate verdict.  Returns the number of error
    findings across all lanes."""
    lanes: dict = {}
    n_errors = 0
    for lane in FLEET_LANES:
        findings, rec = fleet_lane_result(lane)
        counts: dict = {}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        lanes[lane] = dict(rec, findings=counts)
        n_errors += counts.get("error", 0)
        if verbose or counts.get("error", 0):
            print(f"--- {lane} ---", file=sys.stderr)
            for f in findings:
                print(f"  [{f.severity}] {f.op}: {f.message}",
                      file=sys.stderr)
    bad = sorted(n for n, rec in lanes.items() if not rec["consistent"])
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    doc = {
        "round": int(m.group(1)) if m else 0,
        "platform": jax.devices()[0].platform,
        "n_ranks": FLEET_RANKS,
        "lanes": lanes,
        "gate": {"ok": not bad, "inconsistent_lanes": len(bad)},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"fleetlint artifact written: {path} ({len(lanes)} lanes)",
          file=sys.stderr)
    return n_errors


def _calibration_audit() -> "list":
    """Gate-calibration findings: committed KERNELBENCH/BENCH floors
    and measurements vs the cost-model ceilings.  An unimportable
    floor table degrades to a WARNING finding in the artifact — the
    audit keeps running, but never silently narrows to a clean
    verdict with the floor half of the check off."""
    from apex_tpu.analysis.report import Finding

    repo = str(Path(__file__).resolve().parents[1])
    kernel_floors = mfu_floors = None
    skipped = []
    try:
        import kernel_bench
        kernel_floors = kernel_bench.KERNEL_FLOORS
    except Exception as e:  # noqa: BLE001 - audit degrades, never crashes
        skipped.append(f"kernel_bench.KERNEL_FLOORS ({e})")
    try:
        import bench
        mfu_floors = bench.MFU_FLOORS
    except Exception as e:  # noqa: BLE001
        skipped.append(f"bench.MFU_FLOORS ({e})")
    out = cost_mod.audit_floor_artifacts(repo,
                                         kernel_floors=kernel_floors,
                                         mfu_floors=mfu_floors)
    for what in skipped:
        out.append(Finding(
            "cost", "warning",
            f"floor table unimportable — {what}; published floors NOT "
            f"audited this round", op="roofline"))
    return out


def emit_memlint(path: str, families, memory_budget=None,
                 verbose: bool = False) -> int:
    """Write the MEMLINT artifact: every family's O1+O2 train lanes,
    the decode lanes, the multichip slice table, and the calibration
    audit.  Returns the number of error findings across all lanes."""
    lanes: dict = {}
    n_errors = 0
    for family in families:
        raw = policy_audit.RAW_CASES[family]()   # one build, three lanes
        for opt_level in ("O1", "O2", "O4"):
            rep = lint_family(family, compile=True, opt_level=opt_level,
                              memory_budget=memory_budget,
                              raw=raw, _collect=lanes)
            n_errors += len(rep.errors)
            if verbose:
                print(f"--- {family} {opt_level} ---\n{rep.format()}",
                      file=sys.stderr)
    for lane in DECODE_LANES:
        rep = lint_decode(lane, memory_budget=memory_budget,
                          _collect=lanes)
        n_errors += len(rep.errors)
        if verbose:
            print(f"--- {lane} ---\n{rep.format()}", file=sys.stderr)
    for lane in SERVE_LANES:
        rep = lint_serve(lane, memory_budget=memory_budget,
                         _collect=lanes)
        n_errors += len(rep.errors)
        if verbose:
            print(f"--- {lane} ---\n{rep.format()}", file=sys.stderr)
    for lane in SERVE_PREFILL_LANES:
        rep = lint_serve_prefill(lane, memory_budget=memory_budget,
                                 _collect=lanes)
        n_errors += len(rep.errors)
        if verbose:
            print(f"--- {lane} ---\n{rep.format()}", file=sys.stderr)
    for lane in SERVE_VERIFY_LANES:
        rep = lint_serve_verify(lane, memory_budget=memory_budget,
                                _collect=lanes)
        n_errors += len(rep.errors)
        if verbose:
            print(f"--- {lane} ---\n{rep.format()}", file=sys.stderr)

    calibration = _calibration_audit()
    n_errors += sum(1 for f in calibration if f.severity == "error")

    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    doc = {
        "round": int(m.group(1)) if m else 0,
        "platform": jax.devices()[0].platform,
        "budget_bytes": int(memory_budget) if memory_budget else None,
        "lanes": lanes,
        "multichip": {"n_devices": 8,
                      "slices": multichip_slice_table(8)},
        "calibration": {
            "ok": not any(f.severity == "error" for f in calibration),
            "findings": [f.to_dict() for f in calibration]},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"memlint artifact written: {path} ({len(lanes)} lanes)",
          file=sys.stderr)
    return n_errors


def emit_preclint(path: str, families, verbose: bool = False) -> int:
    """Write the PRECLINT artifact: the precision pass over every
    family's O0–O3 train lanes plus both decode lanes (lowering only —
    the precision pass needs no compiled executable, so the full
    18-lane matrix costs 18 lowerings and zero compiles).  Returns the
    number of error findings across all lanes."""
    from apex_tpu.analysis import precision as precision_mod

    lanes: dict = {}
    n_errors = 0

    def record(name, ctx):
        nonlocal n_errors
        findings, stats = precision_mod.precision_report(ctx)
        counts: dict = {}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        ok = counts.get("error", 0) == 0
        n_errors += counts.get("error", 0)
        lanes[name] = {"ok": ok, "findings": counts, "checked": stats}
        if verbose or not ok:
            print(f"--- {name} ---", file=sys.stderr)
            for f in findings:
                print(f"  [{f.severity}] {f.op}: {f.message}",
                      file=sys.stderr)

    for family in families:
        raw = policy_audit.RAW_CASES[family]()   # one build, five lanes
        for opt_level in ("O0", "O1", "O2", "O3", "O4"):
            step, args, props = build_train_step(family, raw=raw,
                                                 opt_level=opt_level)
            lowered = analysis.lower_quiet(step, *args)
            ctx = analysis.build_context(lowered, compile=False,
                                         policy=props)
            record(f"{family}_{opt_level.lower()}_train", ctx)
    for lane, (b, p, n, kvd) in DECODE_LANES.items():
        fn, args, kwargs, props = build_decode_step(b, p, n, kvd)
        lowered = fn.lower(*args, **kwargs)
        ctx = analysis.build_context(lowered, compile=False, policy=props)
        record(lane, ctx)
    for lane, (slots, bs, nb, mb) in SERVE_LANES.items():
        fn, args, props = build_serve_step(slots, bs, nb, mb)
        lowered = analysis.lower_quiet(fn, *args)
        ctx = analysis.build_context(lowered, compile=False, policy=props)
        record(lane, ctx)
    for lane, (slots, bs, nb, mb) in SERVE_PREFILL_LANES.items():
        fn, args, props = build_serve_prefill(slots, bs, nb, mb)
        lowered = analysis.lower_quiet(fn, *args)
        ctx = analysis.build_context(lowered, compile=False, policy=props)
        record(lane, ctx)
    for lane, (slots, bs, nb, mb, k) in SERVE_VERIFY_LANES.items():
        fn, args, props = build_serve_verify(slots, bs, nb, mb, k)
        lowered = analysis.lower_quiet(fn, *args)
        ctx = analysis.build_context(lowered, compile=False, policy=props)
        record(lane, ctx)

    import numpy as np
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    doc = {
        "round": int(m.group(1)) if m else 0,
        "platform": jax.devices()[0].platform,
        "half_dtype": np.dtype(jnp.bfloat16).name,
        "lanes": lanes,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"preclint artifact written: {path} ({len(lanes)} lanes)",
          file=sys.stderr)
    return n_errors


def parse_bytes(text: str) -> int:
    """``"16GiB"`` / ``"512MiB"`` / ``"1048576"`` -> bytes."""
    m = re.fullmatch(r"\s*([0-9.]+)\s*([KMG]i?B)?\s*", text)
    if not m:
        raise ValueError(f"unparsable byte size {text!r}")
    mult = {None: 1, "KB": 10**3, "MB": 10**6, "GB": 10**9,
            "KiB": 1 << 10, "MiB": 1 << 20, "GiB": 1 << 30}[m.group(2)]
    return int(float(m.group(1)) * mult)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--families", default=",".join(FAMILIES),
                    help=f"comma list from {FAMILIES}")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help=f"comma list from {ALL_PASSES}; 'pallas' (= "
                         f"pallas-kernel) additionally runs the Pallas "
                         f"kernel sanitizer over the train lanes "
                         f"(opt-in: it re-traces the step with the "
                         f"pallas kernels routed in)")
    ap.add_argument("--lanes", default=None,
                    help="comma list from o0,o1,o2,o3,o4,decode,serve,"
                         "fleet (train opt levels incl. the fp8 O4 "
                         "regime + the decode lanes [decode_b1_kv8 = "
                         "int8 KV] + the serve-engine step + the "
                         "cross-rank SPMD fleet lanes); default "
                         "o1,decode,serve — except --passes precision, "
                         "whose contract is the full O0–O4 matrix, "
                         "where the default is "
                         "o0,o1,o2,o3,o4,decode,serve")
    ap.add_argument("--no-compile", action="store_true",
                    help="lower only (donation falls back to lowering-"
                         "time aliasing; sharding/collectives/memory/"
                         "cost passes report themselves skipped)")
    ap.add_argument("--memory-budget", nargs="?", default=None,
                    const=str(memory_mod.V5E_HBM_BYTES),
                    metavar="BYTES",
                    help="arm the per-device peak-HBM gate (bare flag "
                         "= v5e 16 GiB; 512MiB / 2GiB forms accepted)")
    ap.add_argument("--emit-json", default=None,
                    metavar="MEMLINT_rN.json|PRECLINT_rN.json|"
                            "FLEETLINT_rN.json|DETLINT_rN.json",
                    help="write a committed lint artifact, dispatched "
                         "on the file name: MEMLINT_r*.json = all "
                         "passes over O1+O2 train + decode + serve + "
                         "multichip slices + calibration audit; "
                         "PRECLINT_r*.json = the precision pass over "
                         "every O0–O4 train lane + decode + serve "
                         "(lowering only); FLEETLINT_r*.json = the "
                         "cross-rank SPMD consistency lanes (per-rank "
                         "DDP O1/O2 schedules + the reshape pair, "
                         "lowering only); DETLINT_r*.json = the "
                         "determinism pass + cross-lane reduction "
                         "comparator over every gated decode/serve "
                         "lane (lowering only, via tools/det_lint.py)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every finding, not just errors")
    opts = ap.parse_args(argv)

    families = [f.strip() for f in opts.families.split(",") if f.strip()]
    passes = tuple("pallas-kernel" if p.strip() == "pallas"
                   else p.strip()
                   for p in opts.passes.split(",") if p.strip())
    lanes_explicit = opts.lanes is not None
    if opts.lanes is None:
        # the precision pass's documented contract is the full O0–O3
        # matrix; every other pass combination keeps the historical
        # o1,decode default (+ the serve-engine step)
        if passes == ("precision",):
            opts.lanes = "o0,o1,o2,o3,o4,decode,serve"
        elif passes == ("determinism",):
            # the bitwise-gated programs: every decode + serve lane
            # (train steps emit no tokens; nothing there is gated
            # on bitwise equality)
            opts.lanes = "decode,serve"
        else:
            opts.lanes = "o1,decode,serve"
    lanes = [x.strip().lower() for x in opts.lanes.split(",") if x.strip()]
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        ap.error(f"unknown families {unknown}; have {FAMILIES}")
    bad_lanes = [x for x in lanes
                 if x not in TRAIN_LANES + ("decode", "serve", "fleet")]
    if bad_lanes or not lanes:
        ap.error(f"unknown lanes {bad_lanes or opts.lanes!r}; have "
                 f"{', '.join(TRAIN_LANES)}, decode, serve, fleet — a "
                 f"typo'd lane list must not pass the gate by linting "
                 f"nothing")
    try:
        budget = parse_bytes(opts.memory_budget) \
            if opts.memory_budget is not None else None
    except ValueError as e:
        ap.error(str(e))
    if budget is not None and opts.no_compile:
        ap.error("--memory-budget needs the compiled executable's "
                 "memory analysis; it cannot combine with "
                 "--no-compile (an armed budget that asserts nothing "
                 "must not pass the gate)")
    # lowering-only pass sets never read the compiled executable: skip
    # the (expensive) per-lane XLA compilation the same way the
    # PRECLINT artifact path does — but an armed memory budget with no
    # memory pass requested must be refused, not silently unasserted
    lowering_only = set(passes) <= {"precision", "policy",
                                    "constant-capture", "export-compat",
                                    "spmd-consistency", "pallas-kernel",
                                    "determinism"}
    if lowering_only and budget is not None:
        ap.error("--memory-budget needs the memory pass; the requested "
                 f"--passes {','.join(passes)} never reads it (an "
                 "armed budget that asserts nothing must not pass "
                 "the gate)")
    if lowering_only and opts.emit_json is None:
        # (not under --emit-json: the artifact branches own their
        # compile story and their --passes diagnostics)
        opts.no_compile = True

    if opts.emit_json and \
            os.path.basename(opts.emit_json).startswith("DETLINT"):
        # the determinism artifact's contract is the full gated-lane
        # matrix + every comparator pair under the determinism pass
        # alone — a restricted run must be refused, never silently
        # committed as a full document (the armed-gate-asserts-nothing
        # class)
        if passes not in (ALL_PASSES, ("determinism",)):
            ap.error("--emit-json DETLINT_r*.json runs exactly the "
                     "determinism pass over the gated-program lanes; "
                     "drop --passes (or pass --passes determinism)")
        if tuple(families) != FAMILIES:
            ap.error("--families does not apply to the determinism "
                     "lanes (they lower the decode/serve programs, "
                     "not a model family); drop --families")
        if lanes_explicit:
            ap.error("--emit-json DETLINT_r*.json always writes every "
                     "gated lane (decode b1/b8/kv8 + serve step/"
                     "decode/prefill/verify) and every comparator "
                     "pair; drop --lanes")
        if budget is not None:
            ap.error("--memory-budget does not apply to the "
                     "determinism artifact (lowering-only; no "
                     "compiled memory analysis) — an armed budget "
                     "that asserts nothing must not pass the gate")
        import det_lint                       # sibling tool: the sweep
        rc = det_lint.main(["--out", opts.emit_json]
                           + (["-v"] if opts.verbose else []))
        if rc:
            print("graph lint FAILED: determinism sweep recorded "
                  "unwaived findings, an undocumented lane-shape "
                  "variant, or schema problems — see the artifact",
                  file=sys.stderr)
        return rc

    if opts.emit_json and \
            os.path.basename(opts.emit_json).startswith("FLEETLINT"):
        # the fleet artifact's contract is every fleet lane under the
        # spmd-consistency pass alone — a restricted run must be
        # refused, never silently committed as a full document
        if passes not in (ALL_PASSES, ("spmd-consistency",)):
            ap.error("--emit-json FLEETLINT_r*.json runs exactly the "
                     "spmd-consistency pass over the fleet lanes; drop "
                     "--passes (or pass --passes spmd-consistency)")
        if tuple(families) != FAMILIES:
            ap.error("--families does not apply to the fleet lanes "
                     "(they lower the DDP step, not a model family); "
                     "drop --families")
        if lanes_explicit and lanes != ["fleet"]:
            ap.error("--emit-json FLEETLINT_r*.json always writes "
                     "every fleet lane; drop --lanes (or pass "
                     "--lanes fleet)")
        if budget is not None:
            ap.error("--memory-budget does not apply to the fleet "
                     "artifact (lowering-only; no compiled memory "
                     "analysis) — an armed budget that asserts "
                     "nothing must not pass the gate")
        n_errors = emit_fleetlint(opts.emit_json, verbose=opts.verbose)
        if n_errors:
            print(f"graph lint FAILED: {n_errors} SPMD consistency "
                  f"error finding(s) — see the artifact",
                  file=sys.stderr)
            return 1
        return 0

    if opts.emit_json and \
            os.path.basename(opts.emit_json).startswith("PRECLINT"):
        # the precision artifact's contract is the full O0–O3 + decode
        # matrix under the precision pass alone — a restricted run
        # must be refused, never silently committed as a full document
        if passes not in (ALL_PASSES, ("precision",)):
            ap.error("--emit-json PRECLINT_r*.json runs exactly the "
                     "precision pass over every lane; drop --passes "
                     "(or pass --passes precision)")
        if tuple(families) != FAMILIES:
            ap.error("--emit-json PRECLINT_r*.json covers every model "
                     "family; drop --families")
        if lanes_explicit:
            ap.error("--emit-json PRECLINT_r*.json always writes every "
                     "lane (O0–O4 train + decode + serve); drop "
                     "--lanes")
        if budget is not None:
            ap.error("--memory-budget does not apply to the precision "
                     "artifact (lowering-only; no compiled memory "
                     "analysis) — an armed budget that asserts "
                     "nothing must not pass the gate")
        n_errors = emit_preclint(opts.emit_json, families,
                                 verbose=opts.verbose)
        if n_errors:
            print(f"graph lint FAILED: {n_errors} precision error "
                  f"finding(s) — see the artifact", file=sys.stderr)
            return 1
        return 0

    if opts.emit_json:
        # the memlint artifact's contract is the FULL matrix (all
        # passes, every lane, compiled evidence) — silently honoring a
        # restricted --passes or --no-compile would commit a partial
        # document under the full schema
        if opts.no_compile:
            ap.error("--emit-json needs compiled evidence (memory/"
                     "cost tables); it cannot combine with "
                     "--no-compile")
        if passes != ALL_PASSES:
            ap.error("--emit-json always runs the full pass matrix; "
                     "drop --passes (restricted lint is the "
                     "per-lane mode)")
        if tuple(families) != FAMILIES:
            ap.error("--emit-json covers every model family; drop "
                     "--families (a partial lane set would commit a "
                     "schema-valid artifact with most of the HBM "
                     "story silently missing)")
        if lanes_explicit:
            ap.error("--emit-json always writes every lane (O1+O2+O4 "
                     "train, decode, serve, multichip); drop --lanes")
        if budget is None:
            # the artifact's whole point is the asserted per-device
            # budget — a regeneration that forgot --memory-budget
            # must not quietly replace a gated round with an
            # unarmed one
            budget = memory_mod.V5E_HBM_BYTES
        n_errors = emit_memlint(opts.emit_json, families,
                                memory_budget=budget,
                                verbose=opts.verbose)
        if n_errors:
            print(f"graph lint FAILED: {n_errors} error finding(s) — "
                  f"see the artifact", file=sys.stderr)
            return 1
        return 0

    failed = []
    linted = []

    def run(label, fn):
        report = fn()
        if not report.passes:
            # e.g. --passes policy on a decode lane: the requested
            # pass set legitimately doesn't apply — SKIP the lane
            # (no "ok" line for a program nothing looked at); the
            # no-lane-linted-anything check below still fails the run
            # where EVERY lane skips
            print(f"--- {label} --- skipped: no requested pass "
                  f"applies to this lane", file=sys.stderr)
            return
        linted.append(label)
        print(json.dumps({"lane": label, **report.to_dict()}))
        if not report.ok:
            failed.append(label)
            print(f"--- {label} ---\n{report.format()}", file=sys.stderr)
        elif opts.verbose:
            print(f"--- {label} ---\n{report.format()}", file=sys.stderr)

    for family in families:
        for opt_level in ("O0", "O1", "O2", "O3", "O4"):
            if opt_level.lower() not in lanes:
                continue
            run(f"{family}_{opt_level.lower()}",
                lambda f=family, o=opt_level: lint_family(
                    f, passes=passes, compile=not opts.no_compile,
                    opt_level=o, memory_budget=budget))
    if "decode" in lanes:
        for lane in DECODE_LANES:
            run(lane, lambda ln=lane: lint_decode(
                ln, passes=passes, compile=not opts.no_compile,
                memory_budget=budget))
    if "serve" in lanes:
        for lane in SERVE_LANES:
            run(lane, lambda ln=lane: lint_serve(
                ln, passes=passes, compile=not opts.no_compile,
                memory_budget=budget))
        for lane in SERVE_PREFILL_LANES:
            run(lane, lambda ln=lane: lint_serve_prefill(
                ln, passes=passes, compile=not opts.no_compile,
                memory_budget=budget))
        for lane in SERVE_VERIFY_LANES:
            run(lane, lambda ln=lane: lint_serve_verify(
                ln, passes=passes, compile=not opts.no_compile,
                memory_budget=budget))
    if "fleet" in lanes:
        for lane in FLEET_LANES:
            run(lane, lambda ln=lane: lint_fleet(ln, passes=passes))
    if failed:
        print(f"graph lint FAILED for: {failed}", file=sys.stderr)
        return 1
    if not linted:
        print("graph lint FAILED: no requested pass applied to ANY "
              "selected lane (ran zero passes) — linting nothing "
              "must not pass the gate", file=sys.stderr)
        return 1
    print(f"graph lint: all lanes OK "
          f"({', '.join(families)}; lanes: {', '.join(lanes)}; "
          f"passes: {', '.join(passes)})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
