"""Graph lint over the in-tree model families' O1 train steps.

Runs every :mod:`apex_tpu.analysis` pass over the four model families
(MLP, ResNet, GPT, BERT — tiny configs, CPU-safe, seconds per family):

- the **graph passes** (donation, sharding, collectives,
  constant-capture) run on the full O1 ``amp.make_train_step`` program
  with the Amp state donated — the program production actually runs,
  lowered and compiled on the host backend (no device execution);
- the **policy pass** runs on the O1 *forward* (the audit's documented
  scope — the AD-generated backward legitimately accumulates in the
  wire dtype, see ``apex_tpu/analysis/policy.py``), sharing the model
  builders with ``tools/policy_audit.py``.

Per-family collective byte budgets are pinned at zero: a single-chip
train step has no collectives, so ANY appearing is a comm-volume
regression (multi-chip programs get their budgets where their meshes
are built — the dryrun slices in ``__graft_entry__.py``).

One JSON line per family plus a human summary; exit 1 on any finding of
``error`` severity — wired as ``tests/l0/test_graph_lint.py`` so the
clean-program guarantee is continuously enforced.

Usage:
    python tools/graph_lint.py [--families mlp,gpt] [--passes donation,...]
                               [--no-compile] [-v]
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))

# CPU-safe by default: lint lowers/compiles for the host platform unless
# the caller pins a real chip (same env knob as the test suite).  Must
# happen before any jax backend initialization; the env-level
# JAX_PLATFORMS pin (sitecustomize) is overridden at the config level.
os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

from apex_tpu import amp, analysis  # noqa: E402
from apex_tpu.optimizers import FusedAdam  # noqa: E402

import policy_audit  # noqa: E402  (sibling tool: shared model builders)

GRAPH_PASSES = ("donation", "sharding", "collectives", "constant-capture")
ALL_PASSES = GRAPH_PASSES + ("policy",)

#: single-chip train steps imply ZERO collective bytes; any regression
#: that introduces one (an accidental psum, a sharding annotation leak)
#: fails the gate like an MFU-floor violation fails the bench.
COLLECTIVE_BUDGETS = {"mlp": {"total": 0}, "resnet": {"total": 0},
                      "gpt": {"total": 0}, "bert": {"total": 0}}

FAMILIES = tuple(policy_audit.RAW_CASES)


def build_train_step(family: str, raw=None):
    """(jitted_step, example_args): the full O1 train step — FusedAdam,
    dynamic loss scaling, Amp state donated — for one model family.
    ``raw`` reuses an already-built ``(loss_fn, params, batch)``."""
    loss_fn, params, batch = raw or policy_audit.RAW_CASES[family]()
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O1",
                       verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=0)
    return step, (state, *batch)


def lint_family(family: str, passes=ALL_PASSES, compile: bool = True):
    """Run the requested passes over one family; returns the merged
    :class:`~apex_tpu.analysis.Report` (train-step graph passes +
    forward policy pass).  The model is built once and shared between
    the two analyzed programs."""
    graph = tuple(p for p in passes if p != "policy")
    raw = loss_fn, params, batch = policy_audit.RAW_CASES[family]()
    report = analysis.Report()
    if graph:
        step, args = build_train_step(family, raw=raw)
        report = analysis.analyze(
            step, *args, passes=graph, compile=compile,
            options={"collectives":
                     {"budget": COLLECTIVE_BUDGETS.get(family, {})}})
    if "policy" in passes:
        a = amp.initialize(opt_level="O1", verbosity=0)
        fwd = lambda p, *b: a.run(loss_fn, p, *b)  # noqa: E731
        report = report.merged(analysis.analyze(
            fwd, params, *batch, passes=("policy",), compile=False))
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--families", default=",".join(FAMILIES),
                    help=f"comma list from {FAMILIES}")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help=f"comma list from {ALL_PASSES}")
    ap.add_argument("--no-compile", action="store_true",
                    help="lower only (donation falls back to lowering-"
                         "time aliasing; sharding/collectives passes "
                         "report themselves skipped)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every finding, not just errors")
    opts = ap.parse_args(argv)

    families = [f.strip() for f in opts.families.split(",") if f.strip()]
    passes = tuple(p.strip() for p in opts.passes.split(",") if p.strip())
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        ap.error(f"unknown families {unknown}; have {FAMILIES}")

    failed = []
    for family in families:
        report = lint_family(family, passes=passes,
                             compile=not opts.no_compile)
        print(json.dumps({"family": family, **report.to_dict()}))
        if not report.ok:
            failed.append(family)
            print(f"--- {family} ---\n{report.format()}", file=sys.stderr)
        elif opts.verbose:
            print(f"--- {family} ---\n{report.format()}", file=sys.stderr)
    if failed:
        print(f"graph lint FAILED for: {failed}", file=sys.stderr)
        return 1
    print(f"graph lint: all families OK "
          f"({', '.join(families)}; passes: {', '.join(passes)})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
