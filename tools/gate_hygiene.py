"""Gate-artifact hygiene check: the gate's memory must be committed.

VERDICT r5 weak #7: ``BENCH_LADDER_BASELINES.json`` and
``SCALING_SWEEP.json`` were left modified-but-uncommitted at round end —
and the ladder file is the regression gate's MEMORY.  An uncommitted
gate baseline is a gate that can drift silently: the next round compares
against whatever happens to be on disk, not against what review saw.

This check fails (exit 1) when

- a REQUIRED gate-baseline artifact is missing or untracked, or
- ANY gate-baseline artifact (required or optional, e.g. the
  round-numbered ``KERNELBENCH_r*.json`` kernel-gate artifacts or
  ``BENCH_VARIANCE.json``) is modified, staged-but-uncommitted, or —
  for round-numbered artifacts — present but never added, or
- a committed ``INCIDENT_r*.json`` does not validate against the
  incident schema (``apex_tpu/resilience/incidents.py``: status, utc or
  date, non-empty evidence) — chaos-run artifacts must not rot into
  prose nobody can machine-check, or
- a committed ``MEMLINT_r*.json`` does not validate against the
  memory-lint schema (``apex_tpu/analysis/memlint.py``: round,
  platform, non-empty lanes each carrying ``peak_hbm_bytes`` / the
  donation-aliasing table / cost-model numbers) — the static HBM
  story of every lane is gate memory the same way the kernel floors
  are, or
- a committed ``PRECLINT_r*.json`` does not validate against the
  precision-lint schema (``apex_tpu/analysis/preclint.py``: round,
  platform, half_dtype, non-empty lanes each carrying the verdict,
  finding counts, and the pass's evidence counters) — the
  mixed-precision contract verdict of every O0–O3 lane is gate
  memory too, or
- a committed ``DECODE_DECOMPOSE_r*.json`` does not validate against
  the decode-decomposition schema
  (``apex_tpu/analysis/decode_decompose.py``: config, complete bucket
  table, >= 90% named-bucket coverage) — the explanation of the b8
  decode gap must stay machine-checked, not prose, or
- a committed ``OBS_r*.json`` does not validate against the
  observability schema (``apex_tpu/analysis/obs.py``: instrumentation
  overhead under the 1% budget, a clean syncs table over the
  instrumented lanes, a non-empty metric-catalog export) — the
  telemetry layer's own cost is gate memory too, or
- a committed ``DECODE_PROFILE_r*.json`` does not validate against the
  decode-profile schema (``apex_tpu/analysis/decode_profile.py``:
  capture provenance, the DECODE_DECOMPOSE bucket vocabulary, a
  stated verdict) — the measured half of the decode decomposition
  stays machine-checked like the static half, or
- a committed ``CONVERGENCE_r*.json`` does not validate against the
  convergence schema (``apex_tpu/analysis/convergence.py``: platform,
  ``all_ok`` consistent with every lane's ``ok`` — legacy
  single-record round-2 shape accepted) — the loss-curve /
  decode-fidelity evidence is gate memory like everything else, or
- a committed ``EXPORT_r*.json`` does not validate against the
  AOT-export schema (``apex_tpu/analysis/export_schema.py``: per-lane
  cache keys, gating lint verdicts consistent with ``export_ok`` —
  an exported lane with a failing lint report, or without a passing
  bitwise round trip, is a CONTRADICTORY verdict and schema-invalid —
  refused lanes naming the documented finding id, and a ``cold_start``
  block whose ``ok`` agrees with its own load-vs-compile numbers) —
  the executable cache's build evidence is gate memory too, or
- a committed ``SERVE_DISAGG_r*.json`` does not validate against the
  disaggregated-serving schema (``apex_tpu/analysis/serve_disagg.py``:
  disjoint slice topology, both arms' percentile records, the chaos
  drill, and a ``gate`` whose ``p99_ok``/``ok`` AGREE with the
  recorded numbers — a verdict contradicting its own A/B is
  schema-invalid) — the p99 gate of the disaggregated fleet is gate
  memory like every other floor, or
- a committed ``SCENARIO_r*.json`` does not validate against the
  serve scenario-matrix schema (``apex_tpu/analysis/scenario.py``:
  >= 10 cells each carrying config/percentiles and a gate verdict
  that AGREES with its own numbers, a spec-vs-baseline A/B whose
  ``spec_wins`` rows agree with the tokens-per-step numbers they
  cite) — "handles many scenarios" and the speculative-decoding
  latency win are gate memory, not prose, or
- a committed ``TRACE_r*.json`` does not validate against the
  request-trace schema (``apex_tpu/analysis/trace.py``: per-request
  lifecycles whose span trees NEST, token accounting that equals the
  engines' own ``serve_tokens_total`` deltas, every reroute naming a
  killed replica, and a gate agreeing with its own numbers — a
  contradictory trace is schema-invalid) — the fleet's request-level
  forensic record is gate memory like every other artifact.  The
  incident schema's grown optional ``flight`` field (the
  flight-recorder tail) is validated through the same committed
  ``INCIDENT_r*.json`` check above, or
- a committed ``BENCH_VARIANCE_r*.json`` does not validate against
  the variance schema (``apex_tpu/analysis/variance.py``: recorded
  mean/min/max/std/rel_spread must re-derive from the recorded
  samples — a spread wide enough to excuse a floor drop cannot be
  typed in) — the statistics every derived floor and band width ride
  are gate memory like the floors themselves, or
- a committed ``PROFILE_DRIFT_r*.json`` does not validate against
  the continuous-profile drift schema
  (``apex_tpu/analysis/profile_drift.py``: band + k, a clean session
  and a seeded-regression session whose recorded windows REPLAY to
  the stated verdicts under the one sentinel rule — a quiet verdict
  over a recorded out-of-band window run, an invented drift, or a
  first drift not naming the seeded bucket is CONTRADICTORY and
  schema-invalid) — the live drift tripwire's evidence is gate
  memory like the offline profiles, or
- a committed ``FLEETLINT_r*.json`` does not validate against the
  cross-rank SPMD lint schema (``apex_tpu/analysis/fleetlint.py``:
  per-rank collective-schedule hashes, a ``consistent`` verdict that
  RE-DERIVES from those hashes, mismatch rows naming the first
  diverging op in both spellings, and a gate agreeing with its own
  lanes — a contradictory fleet verdict is schema-invalid) — "every
  rank compiles the same collective schedule" is gate memory, not
  prose, or
- a committed ``PREFIXCACHE_r*.json`` does not validate against the
  prefix-sharing schema (``apex_tpu/analysis/prefixcache.py``: the
  headline hit/skip counters must RE-DERIVE from the recorded
  per-request spans, and the ``gate`` verdict from the recorded
  arms — a hit rate the spans refute, a skipped-token total they
  don't add up to, or a typed-in "ok" is CONTRADICTORY and
  schema-invalid) — the KV-dedup A/B and its bitwise drill are gate
  memory like every other floor, or
- a committed ``TRAINFLEET_r*.json`` does not validate against the
  elastic-training-fleet schema (``apex_tpu/analysis/trainfleet.py``:
  generation chain whose member sets strictly shrink/regrow, recovery
  rows whose ``steps_lost`` re-derive from the kill/restore steps and
  stay within one checkpoint interval, bitwise verdicts that re-derive
  from the recorded state digests, and a ``gate`` agreeing with its
  own bitwise table — a typed-in "survived the kill" is CONTRADICTORY
  and schema-invalid) — the chaos drill's shrink/regrow evidence is
  gate memory like every other floor, or
- a committed ``TIMELINE_r*.json`` does not validate against the
  timeline schema (``apex_tpu/analysis/timeline.py``: every
  regression row must cite a series whose recorded points actually
  cross its band, no gated series crossing its band may lack a row,
  and ``gate.ok`` must re-derive from the table), or the NEWEST
  committed timeline's coverage table is missing ANY committed
  round-numbered artifact — the cross-round view must never silently
  go stale as new families/rounds land.

It is wired into tier-1 (``tests/l0/test_gate_hygiene.py``), so a round
cannot go green with dirty gate memory.  Best-effort on the VCS side:
outside a git checkout (a tarball export, a read-only mirror) the check
records that and passes — hygiene of a repo is meaningless without one.

Usage: python tools/gate_hygiene.py [--repo DIR]
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

#: Artifacts that MUST exist and be tracked: the model-gate ladder
#: memory and the scaling-law baseline.
REQUIRED = ("BENCH_LADDER_BASELINES.json", "SCALING_SWEEP.json")

#: All gate-baseline patterns whose working-tree copies must match HEAD
#: (round-numbered artifacts included: a fresh KERNELBENCH_rN.json is
#: gate memory the moment it exists; incident records are round
#: evidence the same way).
PATTERNS = ("BENCH_LADDER_BASELINES.json", "SCALING_SWEEP.json",
            "BENCH_VARIANCE.json", "BENCH_VARIANCE_r*.json",
            "KERNELBENCH_r*.json",
            "BENCH_r*.json", "INCIDENT_r*.json", "MEMLINT_r*.json",
            "PRECLINT_r*.json", "DECODE_DECOMPOSE_r*.json",
            "OBS_r*.json", "DECODE_PROFILE_r*.json",
            "CONVERGENCE_r*.json", "EXPORT_r*.json",
            "SERVE_DISAGG_r*.json", "SCENARIO_r*.json",
            "TRACE_r*.json", "TIMELINE_r*.json",
            "PROFILE_DRIFT_r*.json", "FLEETLINT_r*.json",
            "PREFIXCACHE_r*.json", "TRAINFLEET_r*.json",
            "KERNLINT_r*.json", "DETLINT_r*.json")

#: Round-numbered incident artifacts additionally get schema-validated.
INCIDENT_PATTERN = "INCIDENT_r*.json"

#: ... and so do the memory-lint artifacts (graph_lint --emit-json) ...
MEMLINT_PATTERN = "MEMLINT_r*.json"

#: ... and the precision-lint artifacts ...
PRECLINT_PATTERN = "PRECLINT_r*.json"

#: ... and the decode-decomposition artifacts ...
DECOMPOSE_PATTERN = "DECODE_DECOMPOSE_r*.json"

#: ... and the observability artifacts ...
OBS_PATTERN = "OBS_r*.json"

#: ... and the measured decode-profile artifacts ...
PROFILE_PATTERN = "DECODE_PROFILE_r*.json"

#: ... and the convergence-evidence artifacts ...
CONVERGENCE_PATTERN = "CONVERGENCE_r*.json"

#: ... and the AOT-export artifacts ...
EXPORT_PATTERN = "EXPORT_r*.json"

#: ... and the disaggregated-serving gate artifacts ...
SERVE_DISAGG_PATTERN = "SERVE_DISAGG_r*.json"

#: ... and the serve scenario-matrix gate artifacts ...
SCENARIO_PATTERN = "SCENARIO_r*.json"

#: ... and the fleet request-trace artifacts ...
TRACE_PATTERN = "TRACE_r*.json"

#: ... and the recorded-variance artifacts (the statistics under the
#: derived floors) ...
VARIANCE_PATTERN = "BENCH_VARIANCE_r*.json"

#: ... and the longitudinal perf-timeline artifacts ...
TIMELINE_PATTERN = "TIMELINE_r*.json"

#: ... and the continuous-profile drift artifacts ...
PROFILE_DRIFT_PATTERN = "PROFILE_DRIFT_r*.json"

#: ... and the cross-rank SPMD consistency artifacts ...
FLEETLINT_PATTERN = "FLEETLINT_r*.json"

#: ... and the cross-request prefix-sharing gate artifacts ...
PREFIXCACHE_PATTERN = "PREFIXCACHE_r*.json"

#: ... and the elastic-training-fleet chaos-drill artifacts ...
TRAINFLEET_PATTERN = "TRAINFLEET_r*.json"

#: ... and the Pallas kernel-sanitizer sweep artifacts ...
KERNLINT_PATTERN = "KERNLINT_r*.json"

#: ... and the bitwise-determinism lint artifacts.
DETLINT_PATTERN = "DETLINT_r*.json"


def _load_by_path(repo: str, *rel: str):
    """Load a stdlib-only schema module directly by file path so this
    tool never imports jax; ``None`` outside a full checkout."""
    import importlib.util
    mod_path = Path(repo).joinpath(*rel)
    if not mod_path.exists():  # best-effort outside a full checkout
        return None
    spec = importlib.util.spec_from_file_location(
        "_apex_" + mod_path.stem, mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _validate_incidents(repo: str) -> "list[str]":
    """Schema problems over every present INCIDENT_r*.json, as
    ``path: problem`` strings."""
    incidents = _load_by_path(repo, "apex_tpu", "resilience",
                              "incidents.py")
    if incidents is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(INCIDENT_PATTERN)):
        for msg in incidents.validate_incident_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_memlints(repo: str) -> "list[str]":
    """Schema problems over every present MEMLINT_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/memlint.py``)."""
    memlint = _load_by_path(repo, "apex_tpu", "analysis", "memlint.py")
    if memlint is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(MEMLINT_PATTERN)):
        for msg in memlint.validate_memlint_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_preclints(repo: str) -> "list[str]":
    """Schema problems over every present PRECLINT_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/preclint.py``)."""
    preclint = _load_by_path(repo, "apex_tpu", "analysis", "preclint.py")
    if preclint is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(PRECLINT_PATTERN)):
        for msg in preclint.validate_preclint_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_decomposes(repo: str) -> "list[str]":
    """Schema problems over every present DECODE_DECOMPOSE_r*.json, as
    ``path: problem`` strings
    (``apex_tpu/analysis/decode_decompose.py`` — which also enforces
    the >= 90% named-bucket coverage acceptance bar)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "decode_decompose.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(DECOMPOSE_PATTERN)):
        for msg in schema.validate_decompose_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_obs(repo: str) -> "list[str]":
    """Schema problems over every present OBS_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/obs.py`` — which
    also enforces the <1% overhead budget and the clean-syncs bar)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "obs.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(OBS_PATTERN)):
        for msg in schema.validate_obs_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_profiles(repo: str) -> "list[str]":
    """Schema problems over every present DECODE_PROFILE_r*.json, as
    ``path: problem`` strings
    (``apex_tpu/analysis/decode_profile.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "decode_profile.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(PROFILE_PATTERN)):
        for msg in schema.validate_profile_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_convergences(repo: str) -> "list[str]":
    """Schema problems over every present CONVERGENCE_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/convergence.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "convergence.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(CONVERGENCE_PATTERN)):
        for msg in schema.validate_convergence_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_exports(repo: str) -> "list[str]":
    """Schema problems over every present EXPORT_r*.json, as
    ``path: problem`` strings
    (``apex_tpu/analysis/export_schema.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "export_schema.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(EXPORT_PATTERN)):
        for msg in schema.validate_export_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_serve_disaggs(repo: str) -> "list[str]":
    """Schema problems over every present SERVE_DISAGG_r*.json, as
    ``path: problem`` strings
    (``apex_tpu/analysis/serve_disagg.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "serve_disagg.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(SERVE_DISAGG_PATTERN)):
        for msg in schema.validate_serve_disagg_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_scenarios(repo: str) -> "list[str]":
    """Schema problems over every present SCENARIO_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/scenario.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "scenario.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(SCENARIO_PATTERN)):
        for msg in schema.validate_scenario_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_traces(repo: str) -> "list[str]":
    """Schema problems over every present TRACE_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/trace.py`` — which
    also enforces the span-nesting / token-accounting / reroute
    contradiction rejections)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "trace.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(TRACE_PATTERN)):
        for msg in schema.validate_trace_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_variances(repo: str) -> "list[str]":
    """Schema problems over every present BENCH_VARIANCE_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/variance.py``)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "variance.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(VARIANCE_PATTERN)):
        for msg in schema.validate_variance_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_timelines(repo: str) -> "list[str]":
    """Schema problems over every present TIMELINE_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/timeline.py``).
    Only the NEWEST round is held to coverage-completeness against
    the checkout's committed artifacts (older rounds were complete
    when written; they stay valid on internal consistency)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "timeline.py")
    if schema is None:
        return []
    rounds = []
    for p in sorted(Path(repo).glob(TIMELINE_PATTERN)):
        parsed = schema.parse_artifact_name(p.name)
        rounds.append((parsed[1] if parsed else -1, p))
    rounds.sort()
    problems = []
    for i, (_, p) in enumerate(rounds):
        newest = i == len(rounds) - 1
        for msg in schema.validate_timeline_file(
                str(p), repo_dir=repo if newest else None):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_profile_drifts(repo: str) -> "list[str]":
    """Schema problems over every present PROFILE_DRIFT_r*.json, as
    ``path: problem`` strings
    (``apex_tpu/analysis/profile_drift.py`` — which also replays the
    sentinel rule over the recorded windows)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "profile_drift.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(PROFILE_DRIFT_PATTERN)):
        for msg in schema.validate_profile_drift_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_fleetlints(repo: str) -> "list[str]":
    """Schema problems over every present FLEETLINT_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/fleetlint.py`` —
    which also re-derives every ``consistent`` verdict from the
    recorded per-rank schedule hashes)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "fleetlint.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(FLEETLINT_PATTERN)):
        for msg in schema.validate_fleetlint_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_prefixcaches(repo: str) -> "list[str]":
    """Schema problems over every present PREFIXCACHE_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/prefixcache.py`` —
    which also re-derives the hit/skip counters from the recorded
    per-request spans)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "prefixcache.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(PREFIXCACHE_PATTERN)):
        for msg in schema.validate_prefixcache_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_trainfleets(repo: str) -> "list[str]":
    """Schema problems over every present TRAINFLEET_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/trainfleet.py`` —
    which also re-derives the bitwise verdicts, the generation chain,
    and the steps-lost bound from the recorded events and digests)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis",
                           "trainfleet.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(TRAINFLEET_PATTERN)):
        for msg in schema.validate_trainfleet_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_kernlints(repo: str) -> "list[str]":
    """Schema problems over every present KERNLINT_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/kernlint.py`` —
    which also re-derives every per-kernel ``ok`` verdict from the
    recorded per-rule finding counts and waivers, and ``gate.ok``
    from the verdicts)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "kernlint.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(KERNLINT_PATTERN)):
        for msg in schema.validate_kernlint_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _validate_detlints(repo: str) -> "list[str]":
    """Schema problems over every present DETLINT_r*.json, as
    ``path: problem`` strings (``apex_tpu/analysis/detlint.py`` —
    which also re-derives every per-lane ``ok`` verdict from the
    recorded finding counts and waivers, every comparator verdict
    from the recorded signature streams, and ``gate.ok`` from
    both)."""
    schema = _load_by_path(repo, "apex_tpu", "analysis", "detlint.py")
    if schema is None:
        return []
    problems = []
    for p in sorted(Path(repo).glob(DETLINT_PATTERN)):
        for msg in schema.validate_detlint_file(str(p)):
            problems.append(f"{p.name}: {msg}")
    return problems


def _git(repo: str, *args: str) -> "str | None":
    """stdout of a git command, or None when git/The repo is unavailable
    (the best-effort contract)."""
    try:
        out = subprocess.run(
            ["git", "-C", repo, *args], capture_output=True, text=True,
            timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return out.stdout


def check(repo: str = str(REPO)) -> dict:
    """``{"ok": bool, "missing": [...], "untracked": [...],
    "dirty": [...], "invalid_incidents": [...],
    "invalid_memlints": [...], "invalid_preclints": [...]}`` — see the
    module docstring for the rules."""
    tracked_raw = _git(repo, "ls-files", "--", *PATTERNS)
    if tracked_raw is None:
        return {"ok": True, "skipped": "not a git checkout (or no git): "
                                       "hygiene unverifiable", "missing": [],
                "untracked": [], "dirty": [], "invalid_incidents": [],
                "invalid_memlints": [], "invalid_preclints": [],
                "invalid_decomposes": [], "invalid_obs": [],
                "invalid_profiles": [], "invalid_convergences": [],
                "invalid_exports": [], "invalid_serve_disaggs": [],
                "invalid_scenarios": [], "invalid_traces": [],
                "invalid_variances": [], "invalid_timelines": [],
                "invalid_profile_drifts": [], "invalid_fleetlints": [],
                "invalid_prefixcaches": [], "invalid_trainfleets": [],
                "invalid_kernlints": [], "invalid_detlints": []}
    tracked = set(tracked_raw.split())
    missing = [f for f in REQUIRED
               if not (Path(repo) / f).exists() or f not in tracked]

    # -uall: surface untracked round artifacts too (a new
    # KERNELBENCH_rN.json must be committed, not parked)
    status_raw = _git(repo, "status", "--porcelain", "-uall", "--",
                      *PATTERNS) or ""
    untracked, dirty = [], []
    for line in status_raw.splitlines():
        if len(line) < 4:
            continue
        code, path = line[:2], line[3:].strip()
        if not any(fnmatch.fnmatch(Path(path).name, p) for p in PATTERNS):
            continue
        if code == "??":
            untracked.append(path)
        else:
            dirty.append(path)
    invalid = _validate_incidents(repo)
    invalid_mem = _validate_memlints(repo)
    invalid_prec = _validate_preclints(repo)
    invalid_dec = _validate_decomposes(repo)
    invalid_obs = _validate_obs(repo)
    invalid_prof = _validate_profiles(repo)
    invalid_conv = _validate_convergences(repo)
    invalid_exp = _validate_exports(repo)
    invalid_disagg = _validate_serve_disaggs(repo)
    invalid_scen = _validate_scenarios(repo)
    invalid_trace = _validate_traces(repo)
    invalid_var = _validate_variances(repo)
    invalid_tl = _validate_timelines(repo)
    invalid_pd = _validate_profile_drifts(repo)
    invalid_fl = _validate_fleetlints(repo)
    invalid_pc = _validate_prefixcaches(repo)
    invalid_tf = _validate_trainfleets(repo)
    invalid_kl = _validate_kernlints(repo)
    invalid_dl = _validate_detlints(repo)
    return {"ok": not (missing or untracked or dirty or invalid
                       or invalid_mem or invalid_prec or invalid_dec
                       or invalid_obs or invalid_prof or invalid_conv
                       or invalid_exp or invalid_disagg
                       or invalid_scen or invalid_trace
                       or invalid_var or invalid_tl
                       or invalid_pd or invalid_fl or invalid_pc
                       or invalid_tf or invalid_kl or invalid_dl),
            "missing": missing, "untracked": untracked, "dirty": dirty,
            "invalid_incidents": invalid,
            "invalid_memlints": invalid_mem,
            "invalid_preclints": invalid_prec,
            "invalid_decomposes": invalid_dec,
            "invalid_obs": invalid_obs,
            "invalid_profiles": invalid_prof,
            "invalid_convergences": invalid_conv,
            "invalid_exports": invalid_exp,
            "invalid_serve_disaggs": invalid_disagg,
            "invalid_scenarios": invalid_scen,
            "invalid_traces": invalid_trace,
            "invalid_variances": invalid_var,
            "invalid_timelines": invalid_tl,
            "invalid_profile_drifts": invalid_pd,
            "invalid_fleetlints": invalid_fl,
            "invalid_prefixcaches": invalid_pc,
            "invalid_trainfleets": invalid_tf,
            "invalid_kernlints": invalid_kl,
            "invalid_detlints": invalid_dl}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=str(REPO))
    args = ap.parse_args(argv)
    verdict = check(args.repo)
    print(json.dumps(verdict))
    if not verdict["ok"]:
        print("gate_hygiene: gate-baseline artifacts must be committed — "
              f"missing/untracked {verdict['missing'] + verdict['untracked']},"
              f" modified {verdict['dirty']}; invalid incident records "
              f"{verdict.get('invalid_incidents', [])}; invalid memlint "
              f"records {verdict.get('invalid_memlints', [])}; invalid "
              f"preclint records {verdict.get('invalid_preclints', [])}; "
              f"invalid decode-decompose records "
              f"{verdict.get('invalid_decomposes', [])}; invalid obs "
              f"records {verdict.get('invalid_obs', [])}; invalid "
              f"decode-profile records "
              f"{verdict.get('invalid_profiles', [])}; invalid "
              f"convergence records "
              f"{verdict.get('invalid_convergences', [])}; invalid "
              f"export records {verdict.get('invalid_exports', [])}; "
              f"invalid serve-disagg records "
              f"{verdict.get('invalid_serve_disaggs', [])}; invalid "
              f"scenario records {verdict.get('invalid_scenarios', [])}; "
              f"invalid trace records "
              f"{verdict.get('invalid_traces', [])}; invalid variance "
              f"records {verdict.get('invalid_variances', [])}; "
              f"invalid/stale timeline records "
              f"{verdict.get('invalid_timelines', [])}; invalid "
              f"profile-drift records "
              f"{verdict.get('invalid_profile_drifts', [])}; invalid "
              f"fleetlint records "
              f"{verdict.get('invalid_fleetlints', [])}; invalid "
              f"prefix-cache records "
              f"{verdict.get('invalid_prefixcaches', [])}; invalid "
              f"train-fleet records "
              f"{verdict.get('invalid_trainfleets', [])}; invalid "
              f"kernlint records "
              f"{verdict.get('invalid_kernlints', [])}; invalid "
              f"detlint records "
              f"{verdict.get('invalid_detlints', [])}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
