"""Strict RST checker — the pinned ``sphinx-build -W`` substitute.

This environment has no sphinx and no way to get one: ``sphinx``,
``docutils``, ``alabaster``, ``imagesize`` and ``snowballstemmer`` are
all absent, there is no network egress, and installing packages is out
of scope (VERDICT r4 weak #6 / next #8: "install/vendor sphinx ... or
pin a prebuilt check" — this is the prebuilt check).  It validates the
warning classes a ``-W`` build of THIS docs tree would turn into
failures:

- unknown directives and unknown interpreted-text roles
- section title adornments shorter than the title
- ``:doc:`` targets that don't exist; toctree entries without pages
- ``literalinclude``/``include`` paths that don't resolve
- ``code-block``/``highlight`` languages Pygments can't lex
  (pygments IS in the environment — this check is real, not a stub)
- unbalanced ``double-backtick`` inline literals
- tabs in RST source (sphinx renders them at 8 spaces; the tree bans
  them)

When a future environment does have sphinx, ``tests/l0/test_docs.py``
prefers the real ``sphinx-build -W`` and this checker becomes the
fallback — the suite never skips either way.

Usage: python tools/rst_check.py [docs/source]   # exit 1 on findings
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

#: directives used by this docs tree + the common sphinx/docutils set;
#: an unknown directive is exactly what `-W` turns into a hard failure
KNOWN_DIRECTIVES = {
    "toctree", "automodule", "autoclass", "autofunction", "automethod",
    "autodata", "currentmodule", "module", "code-block", "code",
    "highlight", "literalinclude", "include", "note", "warning",
    "versionadded", "versionchanged", "deprecated", "seealso", "math",
    "image", "figure", "table", "list-table", "csv-table", "contents",
    "rubric", "admonition", "important", "tip", "caution", "danger",
    "attention", "hint", "error", "raw", "parsed-literal", "epigraph",
    "glossary", "index", "only", "container", "centered", "sectionauthor",
    "codeauthor", "default-role", "role", "function", "class", "method",
    "attribute", "data", "exception", "describe", "option", "envvar",
    "program", "cmdoption", "confval", "productionlist",
}
KNOWN_ROLES = {
    "mod", "class", "func", "meth", "attr", "data", "obj", "exc",
    "const", "doc", "ref", "term", "math", "file", "program", "option",
    "envvar", "command", "kbd", "guilabel", "menuselection", "abbr",
    "pep", "rfc", "py:mod", "py:class", "py:func", "py:meth", "py:attr",
    "py:data", "py:obj", "sub", "sup", "code", "literal", "download",
    "numref", "eq", "token", "keyword", "dfn", "samp", "regexp",
}
_DIRECTIVE_RE = re.compile(r"^(\s*)\.\.\s+([A-Za-z][\w:+-]*)::(.*)$")
_ROLE_RE = re.compile(r"(?<!`):([A-Za-z][\w:+-]*):`([^`]+)`")
_ADORN_RE = re.compile(r"^([=\-`:'\"~^_*+#<>.!$%&(),/;?@\[\]\\{|}])\1*\s*$")


#: directives whose body is literal content (skip prose checks inside);
#: every OTHER directive's body (note, warning, admonition, only, ...)
#: is real RST that must be validated — treating any line ending in
#: ``::`` as a literal starter would exempt all directive bodies
LITERAL_BODY_DIRECTIVES = {
    "code-block", "code", "math", "parsed-literal", "productionlist",
    "raw", "highlight",
}


def _strip_literal_blocks(lines):
    """Yield ``(lineno, line, in_literal)`` — checks that parse prose
    must skip literal/code blocks (their content is arbitrary text)."""
    in_block = False
    block_indent = 0
    for i, line in enumerate(lines, 1):
        if in_block:
            if line.strip() and (len(line) - len(line.lstrip())
                                 <= block_indent):
                in_block = False
            else:
                yield i, line, True
                continue
        yield i, line, False
        dm = _DIRECTIVE_RE.match(line)
        if dm:
            starts_literal = dm.group(2).lower() in LITERAL_BODY_DIRECTIVES
        else:
            starts_literal = bool(re.search(r"::\s*$", line))
        if starts_literal:
            in_block = True
            block_indent = len(line) - len(line.lstrip())


def check_file(path: Path, docs_root: Path) -> list[str]:
    problems: list[str] = []
    text = path.read_text()
    lines = text.splitlines()
    rel = path.relative_to(docs_root)

    def err(lineno, msg):
        problems.append(f"{rel}:{lineno}: {msg}")

    pages = {p.stem for p in docs_root.glob("*.rst")}
    prose = list(_strip_literal_blocks(lines))

    for i, line, literal in prose:
        if "\t" in line:
            err(i, "tab character in RST source")
        if literal:
            continue
        m = _DIRECTIVE_RE.match(line)
        if m:
            name = m.group(2).lower()
            if name not in KNOWN_DIRECTIVES:
                err(i, f"unknown directive '.. {name}::'")
            if name in ("code-block", "highlight"):
                lang = m.group(3).strip()
                if lang and not _lexable(lang):
                    err(i, f"code-block language {lang!r} has no lexer")
            if name in ("literalinclude", "include"):
                target = (path.parent / m.group(3).strip()).resolve()
                if not target.exists():
                    err(i, f"{name} target missing: {m.group(3).strip()}")
            if name == "toctree":
                # entries are the indented non-option body lines; each
                # must name an existing page (sphinx -W: "toctree
                # contains reference to nonexisting document")
                indent = len(line) - len(line.lstrip())
                for j in range(i, len(lines)):
                    body = lines[j]
                    if not body.strip():
                        continue
                    if len(body) - len(body.lstrip()) <= indent:
                        break
                    entry = body.strip()
                    if entry.startswith(":"):   # directive option
                        continue
                    if entry not in pages:
                        err(j + 1, f"toctree entry without a page: "
                                   f"{entry!r}")
            continue
        for rm in _ROLE_RE.finditer(line):
            role, target = rm.group(1), rm.group(2)
            if role.lower() not in KNOWN_ROLES:
                err(i, f"unknown role ':{role}:'")
            elif role == "doc":
                page = target.lstrip("~/").split("#")[0]
                if page and page not in pages:
                    err(i, f":doc:`{target}` has no page")

    # unbalanced inline literals: ``...`` delimiters must pair up within
    # a paragraph (docutils lets a literal wrap across lines, so the
    # balance is per blank-line-delimited prose block, literal blocks
    # excluded)
    para_start, para_count = 1, 0
    for i, line, literal in prose + [(len(lines) + 1, "", False)]:
        if literal or not line.strip():
            if para_count % 2:
                err(para_start, "unbalanced `` inline literal in the "
                                "paragraph starting here")
            para_start, para_count = i + 1, 0
            continue
        if para_count == 0:
            para_start = i
        para_count += line.count("``")

    # section adornments at least as long as their titles (sphinx WARNS
    # "title underline too short" -> -W failure)
    for i in range(1, len(lines)):
        line = lines[i]
        title = lines[i - 1]
        if (_ADORN_RE.match(line) and title.strip()
                and not _ADORN_RE.match(title)
                and not title.startswith((" ", "..", "-", "*", "="))
                and len(line.rstrip()) < len(title.rstrip())):
            err(i + 1, f"title adornment shorter than title "
                       f"({title.strip()[:40]!r})")

    return problems


def _lexable(lang: str) -> bool:
    try:
        import pygments.lexers
        pygments.lexers.get_lexer_by_name(lang)
        return True
    except Exception:
        return lang in ("default", "none", "text")


def check_tree(docs_root: Path) -> list[str]:
    problems = []
    for p in sorted(docs_root.glob("*.rst")):
        problems += check_file(p, docs_root)
    return problems


def main(argv=None):
    root = Path((argv or sys.argv[1:] or ["docs/source"])[0])
    problems = check_tree(root)
    for p in problems:
        print(p)
    print(f"rst_check: {len(problems)} problem(s) in "
          f"{len(list(root.glob('*.rst')))} page(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
