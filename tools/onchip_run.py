"""Run the hardware-gated test selection on the real chip and record a
machine-readable log (ONCHIP_r{N}.json) — the auditable artifact VERDICT r1
asked for in place of PARITY.md's unrecorded "on-chip green" claim.

Usage:  python tools/onchip_run.py [round_number]

Selects every test that skips off-chip (Mosaic-compiled Pallas kernels,
pallas-under-shard_map, AOT layout regressions) plus the kernel fuzz tiers
in pallas mode, runs them with ``APEX_TPU_TEST_PLATFORM=axon``, and writes
platform/device/test-by-test outcomes as JSON.
"""

import json
import os
import subprocess
import sys
import time
import xml.etree.ElementTree as ET
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

#: the on-chip selection: hardware-gated tests + the fuzz suites whose
#: pallas paths run interpret-mode everywhere else
SELECTION = [
    "tests/l0/test_fused_lamb.py",
    "tests/l0/test_flash_attention.py",
    # production head-major layout pins (bhld dispatch, rope MXU
    # spelling, head-major projections) — the experimental flash_mh /
    # conv1x1 kernels keep ONE numerics pin each (VERDICT r3 #8) so
    # drift is caught without spending chip minutes on shelf inventory
    "tests/l0/test_flash_mh.py::test_bhld_layout_matches_blhd",
    "tests/l0/test_flash_mh.py::test_attention_dispatcher_bhld_routes_and_falls_back",
    "tests/l0/test_flash_mh.py::test_bhld_cross_attention_falls_back",
    "tests/l0/test_flash_mh.py::test_rope_mxu_matches_concat_spelling",
    "tests/l0/test_flash_mh.py::test_head_major_projections_match_dense_split",
    "tests/l0/test_flash_mh.py::test_mh_forward_matches_reference[True]",
    # KV-cached generation vs the naive full-forward oracle (the two
    # cheapest cases: full-file naive recompiles per length are slow
    # through the remote compile helper)
    "tests/l1/test_generate.py::test_single_token_decode",
    "tests/l1/test_generate.py::test_temperature_sampling_deterministic_and_varied",
    "tests/l0/test_conv1x1.py::test_bwd_matches_lax_transpose[2-8-64-256]",
    # parked flat-packed finite check: one Mosaic numerics pin
    "tests/l0/test_scaler.py::TestAllFinitePacked::test_mixed_dtype_groups",
    "tests/l0/test_multi_tensor.py",
    "tests/l0/test_fused_adam.py",
    # cross-commit numerical drift gate on the hardware platform
    # (VERDICT r2 item 4a: the stored-baseline axis of the reference's
    # tests/L1/common/compare.py, on the platform that matters)
    "tests/l1/test_golden_digests.py",
    "tests/distributed/test_ring_attention.py::test_ring_flash_kernel_on_tpu",
    "tests/distributed/test_onchip_pallas_shardmap.py",
]


def main():
    rnd = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    xml_path = "/tmp/onchip_junit.xml"
    env = dict(os.environ, APEX_TPU_TEST_PLATFORM="axon")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *SELECTION, "-q",
         f"--junitxml={xml_path}"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=3600)
    wall = round(time.time() - t0, 1)

    tests = []
    counts = {"passed": 0, "failed": 0, "error": 0, "skipped": 0}
    if os.path.exists(xml_path):
        for case in ET.parse(xml_path).getroot().iter("testcase"):
            outcome = "passed"
            for tag in ("failure", "error", "skipped"):
                if case.find(tag) is not None:
                    outcome = tag if tag != "failure" else "failed"
                    break
            counts[outcome] += 1
            tests.append({
                "nodeid": f"{case.get('classname')}::{case.get('name')}",
                "outcome": outcome,
                "time_s": float(case.get("time", 0.0)),
            })

    # after the subprocess: record what the chip looks like (guarded — a
    # wedged device lease blocks PJRT init forever with no error; reuse
    # bench.py's watchdog)
    sys.path.insert(0, str(REPO))
    info = {"platform": "unknown", "device_kind": "unknown", "jax": "?"}
    try:
        import jax
        info["jax"] = jax.__version__  # known even if the probe blocks

        from bench import probe_devices
        devices = probe_devices(120)
        if devices is not None:
            info.update(platform=devices[0].platform,
                        device_kind=getattr(devices[0], "device_kind", "?"),
                        jax=jax.__version__)
        else:
            info["platform"] = "unknown (backend init blocked >120s)"
    except Exception as e:  # noqa: BLE001 - record, don't lose the log
        info["platform"] = f"unknown (init error: {type(e).__name__}: {e})"

    out = {
        "artifact": "on-chip test run log (VERDICT r1 item 4/5)",
        "platform": info["platform"],
        "device_kind": info["device_kind"],
        "jax": info["jax"],
        "env": {"APEX_TPU_TEST_PLATFORM": "axon"},
        "cmd": "python tools/onchip_run.py " + str(rnd),
        "selection": SELECTION,
        "wall_s": wall,
        "rc": proc.returncode,
        "counts": counts,
        # skips count against ok: on hardware NOTHING in the selection
        # may skip — in particular the golden-digest drift gate
        # pytest.skip()s when no baseline exists for the reported
        # platform, and an all-skipped gate must not read as green
        "ok": proc.returncode == 0 and counts["failed"] == 0
              and counts["error"] == 0 and counts["skipped"] == 0
              and counts["passed"] > 0,
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "tail": proc.stdout[-1500:],
        "tests": tests,
    }
    path = REPO / f"ONCHIP_r{rnd:02d}.json"
    path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"{path}: ok={out['ok']} {counts}")
    sys.exit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
