"""Run the disaggregated chaos drill with request tracing on and emit
the committed ``TRACE_r*.json`` lifecycle artifact.

The drill is PR 10's replica-kill scenario at the c16 fleet topology
(1 prefill slice + 2 decode replicas x 8 slots on the virtual
16-device CPU platform — the tool forces
``--xla_force_host_platform_device_count=16`` exactly like
``tools/serve_disagg.py``), with :class:`apex_tpu.obs.RequestTracer`
and :class:`apex_tpu.obs.FlightRecorder` attached: a request stream is
admitted, the busiest decode replica is killed mid-stream, the router
rebuilds its in-flight requests from the streamed-token log and
re-prefills them elsewhere, and every output is checked BITWISE
against solo ``generate()``.

The emitted document (schema ``apex_tpu/analysis/trace.py``, enforced
on committed copies by ``tools/gate_hygiene.py``) reconstructs each
request's FULL lifecycle — enqueue at the router, chunked prefill, the
KV shipment, decode steps with per-slot token attribution, the
reroute naming the killed replica, the re-prefill on the surviving
replica, retirement — and is contradiction-rejecting: span trees must
nest, the trace's token accounting must equal the engines' own
``serve_tokens_total`` deltas, and every reroute must name a killed
replica.  ``--chrome PATH`` additionally writes the same lifecycles as
chrome-trace JSON for ``chrome://tracing`` / Perfetto.

Usage:
    python tools/trace_report.py --emit-json TRACE_r01.json \
        [--chrome trace.json] [--n-replicas 2] [--slots 8]
        [--prefill 24] [--new-tokens 12] [--requests 16]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# 16 virtual host devices BEFORE any jax backend initialization: the
# c16 fleet topology, CPU-testable end to end.
os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=16").strip()
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))
jax.config.update("jax_threefry_partitionable", True)


def run_traced_drill(n_replicas: int = 2, slots: int = 8,
                     prefill: int = 24, new_tokens: int = 12,
                     n_requests: int = 16, kill_after: int = 3,
                     incident_path=None) -> dict:
    """The traced c16 chaos drill; returns the full TRACE document
    (un-rounded — the caller stamps ``round`` from the emit path) plus
    the tracer under ``"_tracer"`` for the chrome export."""
    from apex_tpu import amp
    from apex_tpu.models import GPTModel, gpt_tiny
    from apex_tpu.models.generate import generate
    from apex_tpu.obs import FlightRecorder, RequestTracer, fleet
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import (DisaggRouter, Request, RouterConfig,
                                ServeConfig)

    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    block = 4
    mb = -(-(prefill + new_tokens) // block)
    scfg = ServeConfig(num_slots=slots, block_size=block,
                       num_blocks=slots * mb + 1,
                       max_blocks_per_slot=mb, prefill_chunk=8)
    tracer = RequestTracer()
    flight = FlightRecorder()
    router = DisaggRouter(
        params, cfg, scfg,
        RouterConfig(n_decode_replicas=n_replicas, transfer="ship",
                     incident_path=incident_path),
        registry=Registry(), tracer=tracer, flight=flight)

    labels = ["prefill"] + [f"replica{i}" for i in range(n_replicas)]
    regs = [router.prefill.eng.metrics] + [r.eng.metrics
                                           for r in router.replicas]
    tok0 = [r.counter("serve_tokens_total").value for r in regs]

    rng = np.random.RandomState(3)
    reqs = []
    for i in range(n_requests):
        plen = max(2, int(prefill * (0.5 + 0.5 * (i % 2))))
        reqs.append((rng.randint(0, cfg.vocab_size, (plen,)),
                     new_tokens))
    for i, (p, n) in enumerate(reqs):
        router.submit(Request(uid=f"c{i}", prompt=p, max_new_tokens=n))
    for _ in range(kill_after):
        router.step()
    victim = max(router.replicas,
                 key=lambda r: r.eng.sched.n_active()).index
    rerouted = router.kill_replica(victim)
    out = router.run()

    bitwise = True
    divergent = []
    for i, (p, n) in enumerate(reqs):
        want = np.asarray(generate(params, cfg, jnp.asarray(p[None]),
                                   n))[0, len(p):]
        if not np.array_equal(out[f"c{i}"], want):
            bitwise = False
            divergent.append(f"c{i}")

    per = {lbl: round(reg.counter("serve_tokens_total").value - t0)
           for lbl, reg, t0 in zip(labels, regs, tok0)}
    delta = round(sum(per.values()))
    doc_reqs = tracer.to_doc_requests()
    trace_tokens = sum(r["tokens"] for r in doc_reqs.values())
    tokens_ok = delta == trace_tokens

    # the fleet-merged registry (obs.fleet): the ONE merge
    # implementation cross-checks the per-engine table it was built
    # from — counter sums through merge_registries, not hand math
    merged = fleet.merge_registries(regs)
    merged_total = round(
        merged.counter("serve_tokens_total").value - sum(tok0))

    return {
        "round": 0,
        "platform": jax.devices()[0].platform,
        "config": {
            "model": "gpt_tiny",
            "concurrency": n_requests,
            "topology": {"n_devices": len(jax.devices()),
                         **router.slices.describe()},
            "n_replicas": n_replicas, "slots_per_replica": slots,
            "prefill": prefill, "new_tokens": new_tokens,
            "block_size": block, "kill_after_steps": kill_after,
        },
        "requests": doc_reqs,
        "engine": {"serve_tokens_total": per, "delta_total": delta,
                   "fleet_merged_total": merged_total},
        "chaos": {"killed": [int(victim)], "rerouted": rerouted,
                  "divergent": divergent},
        "gate": {"bitwise_ok": bool(bitwise),
                 "tokens_ok": bool(tokens_ok),
                 "ok": bool(bitwise and tokens_ok)},
        "note": (
            "Request-trace artifact of the c16 disaggregated "
            "replica-kill drill: every lifecycle host-recorded at the "
            "existing step boundaries (zero added device syncs — the "
            "compiled programs are unchanged, OBS_r02 carries the "
            "syncs verdict), token accounting closed against the "
            "engines' own counters, rerouted requests reconstructed "
            "across two replicas with outputs bitwise vs solo "
            "generate().  Regenerate with tools/trace_report.py "
            "--emit-json TRACE_rN.json."),
        "_tracer": tracer,
        "_flight": flight,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--emit-json", default=None,
                    metavar="TRACE_rN.json",
                    help="write the committed gate artifact")
    ap.add_argument("--chrome", default=None, metavar="PATH",
                    help="also write the lifecycles as chrome-trace "
                         "JSON (chrome://tracing / Perfetto)")
    ap.add_argument("--n-replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=12)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--kill-after", type=int, default=3)
    opts = ap.parse_args(argv)

    doc = run_traced_drill(
        n_replicas=opts.n_replicas, slots=opts.slots,
        prefill=opts.prefill, new_tokens=opts.new_tokens,
        n_requests=opts.requests, kill_after=opts.kill_after)
    tracer = doc.pop("_tracer")
    doc.pop("_flight")

    if opts.chrome:
        with open(opts.chrome, "w") as f:
            json.dump(tracer.to_chrome_trace(), f)
        print(f"chrome trace written: {opts.chrome}", file=sys.stderr)

    if opts.emit_json:
        m = re.search(r"_r(\d+)\.json$",
                      os.path.basename(opts.emit_json))
        doc["round"] = int(m.group(1)) if m else 0
        from apex_tpu.analysis.trace import validate_trace
        problems = validate_trace(doc)
        if problems:
            print(f"trace_report: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
        with open(opts.emit_json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"trace artifact written: {opts.emit_json}",
              file=sys.stderr)

    summary = {"gate": doc["gate"], "chaos": doc["chaos"],
               "engine": doc["engine"],
               "requests": len(doc["requests"]),
               "events": sum(len(r["events"])
                             for r in doc["requests"].values())}
    print(json.dumps(summary))
    return 0 if doc["gate"]["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
