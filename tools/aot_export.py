"""Lint-gated AOT export of the laned entry points.

The build step of the content-addressed executable cache
(:mod:`apex_tpu.analysis.export`): every selected lane is lowered
once, compiled once (timed — the cold-start cost a serving replica
pays today), run through the full gate matrix including the
``export-compat`` pass, and — only when the gate is clean — the
compiled executable is AOT-serialized into the cache with a manifest
embedding its sha256 and the gating lint Report.  Each exported lane
is then RELOADED from the cache (timed — the cold-start cost a
replica pays with the cache) and its outputs checked BITWISE against
the freshly compiled executable's on identical inputs.

Default lanes: the mlp O1/O2 train steps and the serve engine's
decode step (``tools/graph_lint.py``'s builders — the export pipeline
and the lint share one definition of "lane"), plus
``seeded_io_callback``: a deliberately non-exportable program (an
injected ``io_callback``) that must be REFUSED from the cache with
the documented ``export-host-callback`` finding id — the refusal
path is round evidence, not just a test.

``--emit-json EXPORT_rN.json`` writes the committed artifact
(schema: ``apex_tpu/analysis/export_schema.py``, validated by
``tools/gate_hygiene.py``): per-lane cache keys, gating verdicts,
compile-vs-load wall clock, the bitwise round-trip verdict, and the
``cold_start`` block ``bench.py`` sources its serve cold-start gate
from (load must cost <= 0.5x compile on this host).

``--verify-reload KEY --io FILE.pkl`` is the fresh-process check: it
loads ONLY the cache entry (no model build, no trace), calls it on
the pickled inputs, and compares bitwise against the pickled expected
outputs — run it in a subprocess to prove the round trip across a
process boundary (tests/l0/test_aot_export.py does).

Usage:
    python tools/aot_export.py [--cache-dir DIR]
                               [--lanes mlp_o1,mlp_o2,serve,seeded]
                               [--emit-json EXPORT_r01.json] [-v]
    python tools/aot_export.py --verify-reload KEY --io IO.pkl
                               [--cache-dir DIR]
"""

import argparse
import json
import os
import pickle
import re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import graph_lint  # noqa: E402  (sets platform/env before jax init)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from apex_tpu.analysis import export as aot  # noqa: E402
from apex_tpu.analysis.core import (  # noqa: E402
    PassContext,
    _args_info,
    _out_info,
    _static_scalars,
    run_passes,
)
from apex_tpu.analysis.export_schema import COLD_START_RATIO_MAX  # noqa: E402

#: CLI lane name -> artifact lane name
LANE_NAMES = {"mlp_o1": "mlp_o1_train", "mlp_o2": "mlp_o2_train",
              "serve": "serve_step", "seeded": "seeded_io_callback"}
DEFAULT_LANES = ("mlp_o1", "mlp_o2", "serve", "seeded")

#: the serve lane is the cold-start story's lane: a scale-out replica
#: pays exactly this compile before serving its first token
COLD_START_LANE = "serve_step"


def default_cache_dir() -> str:
    return os.environ.get(aot.CACHE_ENV) or str(REPO / ".aot_cache")


def build_seeded_io_callback():
    """A lane with an injected host callback — compiles fine, must be
    refused from the cache (the acceptance path for the
    ``export-host-callback`` finding)."""
    from jax.experimental import io_callback

    def step(x):
        y = x * 2.0
        io_callback(lambda v: None, None, y.sum(), ordered=True)
        return y.sum()

    return jax.jit(step), (jnp.ones((16, 16), jnp.float32),), None


def build_lane(cli_name: str):
    """(jitted, args, lint_policy, key_policy) for one CLI lane name.

    ``key_policy`` is what enters the cache key; for the serve lane it
    is ``None`` — the engine's startup probe has no resolved amp
    policy in hand (the params are already cast), so the tool must key
    the entry the way the engine will look it up, or a replica could
    never hit the entry this tool built.  The LINT still runs with the
    real O2 serving policy."""
    if cli_name == "mlp_o1":
        step, args, props = graph_lint.build_train_step(
            "mlp", opt_level="O1")
        return step, args, props, props
    if cli_name == "mlp_o2":
        step, args, props = graph_lint.build_train_step(
            "mlp", opt_level="O2")
        return step, args, props, props
    if cli_name == "serve":
        fn, args, props = graph_lint.build_serve_step(
            *graph_lint.SERVE_LANES["serve_step"])
        return fn, args, props, None
    if cli_name == "seeded":
        jitted, args, props = build_seeded_io_callback()
        return jitted, args, props, props
    raise KeyError(f"unknown lane {cli_name!r}; have {DEFAULT_LANES}")


def _copy_args(tree):
    """Deep-copy the array leaves so a donated executable can be
    called repeatedly on identical inputs (donation consumes the
    originals)."""
    return jax.tree.map(
        lambda x: jnp.asarray(np.asarray(x)) if hasattr(x, "shape")
        else x, tree)


def _bitwise_equal(a, b) -> bool:
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(fa) != len(fb):
        return False
    for x, y in zip(fa, fb):
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.dtype != ya.dtype or xa.shape != ya.shape \
                or xa.tobytes() != ya.tobytes():
            return False
    return True


def export_lane(name: str, jitted, args, policy, cache_dir,
                key_policy=None, verbose: bool = False) -> dict:
    """One lane through the pipeline: lower, compile (timed), gate,
    export-or-refuse, reload (timed), bitwise round trip.  Returns
    the artifact lane record."""
    lowered = aot.lower_quiet(jitted, *args)
    text = lowered.as_text()
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0

    ctx = PassContext(
        stablehlo_text=text, hlo_text=compiled.as_text(),
        args=_args_info(lowered), outputs=_out_info(lowered),
        compiled=compiled, policy=policy,
        static_scalars=_static_scalars(args, {}, lowered.args_info))
    # single-chip lanes: any collective is a regression (the
    # graph_lint budget), so the gate matrix here matches the lint's
    report = run_passes(ctx, passes=aot.gate_passes_for(policy),
                        options={"collectives": {"budget": {"total": 0}}})
    parts = aot.key_parts(text, mesh=aot.mesh_descriptor(lowered),
                          policy=key_policy)
    key = aot.cache_key(parts)
    counts = report.to_dict()["counts"]
    rec = {"lint": {"ok": report.ok, "passes": list(report.passes),
                    "counts": counts}}
    if verbose or not report.ok:
        print(f"--- {name} ---\n{report.format()}", file=sys.stderr)
    try:
        manifest = aot.write_entry(cache_dir, key, parts, compiled,
                                   report, lane=name)
    except aot.ExportRefused as e:
        rec.update(export_ok=False, refused=e.finding_id)
        print(f"{name}: REFUSED from the cache ({e.finding_id})",
              file=sys.stderr)
        return rec

    t0 = time.perf_counter()
    hit = aot.load_entry(cache_dir, key)
    load_s = time.perf_counter() - t0
    if hit is None:   # just-written entry must verify — else our bug
        raise RuntimeError(f"{name}: freshly written cache entry "
                           f"{key[:16]}… failed verification")
    loaded, _ = hit
    out_fresh = compiled(*_copy_args(args))
    out_cache = loaded(*_copy_args(args))
    bitwise = _bitwise_equal(out_fresh, out_cache)
    rec.update(export_ok=True, cache_key=key,
               module_sha256=parts["module_sha256"],
               sha256=manifest["sha256"],
               compile_s=round(compile_s, 4), load_s=round(load_s, 4),
               load_ratio=round(load_s / compile_s, 4)
               if compile_s else 0.0,
               bitwise_equal=bool(bitwise))
    print(f"{name}: exported {key[:16]}… compile {compile_s:.3f}s "
          f"load {load_s:.3f}s bitwise={bitwise}", file=sys.stderr)
    return rec


def run_lanes(cli_lanes, cache_dir, verbose: bool = False) -> dict:
    lanes = {}
    for cli_name in cli_lanes:
        jitted, args, policy, key_policy = build_lane(cli_name)
        lanes[LANE_NAMES[cli_name]] = export_lane(
            LANE_NAMES[cli_name], jitted, args, policy, cache_dir,
            key_policy=key_policy, verbose=verbose)
    return lanes


def cold_start_block(lanes: dict) -> "dict | None":
    rec = lanes.get(COLD_START_LANE)
    if not isinstance(rec, dict) or not rec.get("export_ok"):
        return None
    ratio = rec["load_ratio"]
    return {"lane": COLD_START_LANE, "compile_s": rec["compile_s"],
            "load_s": rec["load_s"], "load_ratio": ratio,
            "budget": COLD_START_RATIO_MAX,
            "ok": ratio <= COLD_START_RATIO_MAX}


def emit_export(path: str, lanes: dict, cache_dir) -> int:
    """Write the committed EXPORT artifact; returns the number of
    problems (a lane that should have exported but didn't, a missing
    cold-start block, a failed bitwise check)."""
    cs = cold_start_block(lanes)
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    doc = {
        "round": int(m.group(1)) if m else 0,
        "platform": jax.devices()[0].platform,
        "versions": aot.runtime_versions(),
        "cache": {"dir": os.path.relpath(str(cache_dir), str(REPO))
                  if str(cache_dir).startswith(str(REPO))
                  else str(cache_dir),
                  "entries": len(aot.list_entries(cache_dir))},
        "lanes": lanes,
        "cold_start": cs,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"export artifact written: {path} ({len(lanes)} lanes)",
          file=sys.stderr)
    problems = 0
    for name, rec in lanes.items():
        if name == LANE_NAMES["seeded"]:
            if rec.get("export_ok") is not False:
                print(f"{name}: the seeded violation EXPORTED — the "
                      f"gate is broken", file=sys.stderr)
                problems += 1
        elif not (rec.get("export_ok") and rec.get("bitwise_equal")):
            print(f"{name}: export/round-trip failed — see record",
                  file=sys.stderr)
            problems += 1
    if cs is None or not cs["ok"]:
        print(f"cold_start gate failed: {cs}", file=sys.stderr)
        problems += 1
    return problems


def verify_reload(cache_dir, key: str, io_path: str) -> int:
    """Fresh-process half of the round trip: load ONLY the cache entry
    (no build, no trace), run it on the pickled inputs, compare
    bitwise with the pickled expected outputs."""
    hit = aot.load_entry(cache_dir, key)
    if hit is None:
        print(json.dumps({"hit": False}))
        print(f"verify-reload: no verified entry for {key[:16]}…",
              file=sys.stderr)
        return 1
    compiled, manifest = hit
    with open(io_path, "rb") as f:
        io = pickle.load(f)
    treedef = jax.tree_util.tree_structure(compiled.args_info)
    args, kwargs = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(x) for x in io["inputs"]])
    out = compiled(*args, **kwargs)
    got = [np.asarray(x) for x in jax.tree.leaves(out)]
    exp = [np.asarray(x) for x in io["expected"]]
    ok = len(got) == len(exp) and all(
        g.dtype == e.dtype and g.shape == e.shape
        and g.tobytes() == e.tobytes() for g, e in zip(got, exp))
    print(json.dumps({"hit": True, "bitwise_equal": bool(ok),
                      "lane": manifest.get("lane")}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cache-dir", default=None,
                    help=f"cache root (default ${aot.CACHE_ENV} or "
                         f"<repo>/.aot_cache)")
    ap.add_argument("--lanes", default=",".join(DEFAULT_LANES),
                    help=f"comma list from {DEFAULT_LANES}")
    ap.add_argument("--emit-json", default=None,
                    metavar="EXPORT_rN.json",
                    help="write the committed export artifact (always "
                         "the full default lane set)")
    ap.add_argument("--verify-reload", default=None, metavar="KEY",
                    help="load the entry KEY from the cache and check "
                         "it bitwise against --io (fresh-process mode: "
                         "no model build, no trace)")
    ap.add_argument("--io", default=None, metavar="IO.pkl",
                    help="pickled {'inputs': [...], 'expected': [...]} "
                         "for --verify-reload")
    ap.add_argument("-v", "--verbose", action="store_true")
    opts = ap.parse_args(argv)

    cache_dir = opts.cache_dir or default_cache_dir()
    if opts.verify_reload:
        if not opts.io:
            ap.error("--verify-reload needs --io")
        return verify_reload(cache_dir, opts.verify_reload, opts.io)

    cli_lanes = [x.strip() for x in opts.lanes.split(",") if x.strip()]
    unknown = [x for x in cli_lanes if x not in LANE_NAMES]
    if unknown or not cli_lanes:
        ap.error(f"unknown lanes {unknown or opts.lanes!r}; have "
                 f"{DEFAULT_LANES}")
    if opts.emit_json and tuple(cli_lanes) != DEFAULT_LANES:
        # the committed artifact's contract is the full lane set —
        # the refusal lane included (the gate's negative evidence)
        ap.error("--emit-json always writes the full default lane "
                 "set; drop --lanes")
    os.makedirs(cache_dir, exist_ok=True)
    lanes = run_lanes(cli_lanes, cache_dir, verbose=opts.verbose)
    if opts.emit_json:
        return 1 if emit_export(opts.emit_json, lanes, cache_dir) \
            else 0
    bad = [n for n, r in lanes.items()
           if n != LANE_NAMES["seeded"]
           and not (r.get("export_ok") and r.get("bitwise_equal"))]
    bad += [n for n, r in lanes.items()
            if n == LANE_NAMES["seeded"] and r.get("export_ok")]
    if bad:
        print(f"aot export FAILED for: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
