"""O1 policy audit over the in-tree model families.

The reference guarantees O1 coverage by patching the whole ``torch``
namespace (``apex/amp/amp.py:68-177``); apex_tpu's equivalent guarantee
is checkable instead of structural: :func:`apex_tpu.amp.audit` walks the
lowered StableHLO of an O1 forward and flags FP32-list-category work
executing in 16-bit (the ``policy`` pass of :mod:`apex_tpu.analysis`).

This tool runs that audit over the four in-tree model families' O1
forwards (MLP, ResNet, GPT, BERT — tiny configs; lowering only, nothing
executes) and prints one JSON line per family plus a summary.  Exit
status 1 if any family has violations — wired as a test in
``tests/l0/test_policy_audit.py`` so the guarantee is continuously
enforced, and runnable standalone on user models:

    from apex_tpu import amp
    a = amp.initialize(opt_level="O1", verbosity=0)
    report = amp.audit(lambda p, x: a.run(model.apply, p, x), params, x)
    print(amp.format_report(report))

``RAW_CASES`` exposes the un-wrapped ``(loss_fn, params, batch)`` per
family so ``tools/graph_lint.py`` can build full O1 *train steps* from
the same models for the whole-program graph passes; ``CASES`` keeps the
original ``() -> (audited_fn, args)`` shape the tests pin.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from apex_tpu import amp  # noqa: E402


def _wrap(a, loss_fn):
    """The audited program: the O1 forward exactly as make_train_step
    runs it (cast context active), loss included."""
    return lambda params, *batch: a.run(loss_fn, params, *batch)


def mlp_raw():
    from apex_tpu.models.mlp import MLP, cross_entropy_loss
    model = MLP(features=(32,))
    x = jnp.ones((4, 28, 28, 1), jnp.float32)
    y = jnp.zeros((4,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)
    return loss_fn, params, (x, y)


def resnet_raw():
    from apex_tpu.models.resnet import ResNet
    model = ResNet(stage_sizes=(1, 1), num_classes=10, width=8)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    y = jnp.zeros((2,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, xb, yb):
        logits, _ = model.apply({"params": p, "batch_stats": batch_stats},
                                xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))
    return loss_fn, params, (x, y)


def gpt_raw():
    from apex_tpu.models.gpt import GPTModel, gpt_tiny, lm_loss
    model = GPTModel(gpt_tiny())
    ids = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, xb):
        logits = model.apply({"params": p}, xb)
        return lm_loss(logits[:, :-1], xb[:, 1:])
    return loss_fn, params, (ids,)


def bert_raw():
    from apex_tpu.models.bert import (BertForPreTraining, bert_tiny,
                                      pretraining_loss)
    model = BertForPreTraining(bert_tiny())
    ids = jnp.zeros((2, 32), jnp.int32)
    mlm_labels = jnp.zeros((2, 32), jnp.int32)
    mlm_mask = jnp.ones((2, 32), jnp.float32)
    nsp_labels = jnp.zeros((2,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:, :8])["params"]

    def loss_fn(p, ids, mlm_labels, nsp_labels, mlm_mask):
        mlm_logits, nsp_logits = model.apply({"params": p}, ids)
        return pretraining_loss(mlm_logits, nsp_logits, mlm_labels,
                                nsp_labels, mlm_mask)
    return loss_fn, params, (ids, mlm_labels, nsp_labels, mlm_mask)


#: family -> () -> (loss_fn, params, batch) — the un-wrapped pieces,
#: shared with tools/graph_lint.py's train-step builders.
RAW_CASES = {
    "mlp": mlp_raw,
    "resnet": resnet_raw,
    "gpt": gpt_raw,
    "bert": bert_raw,
}


def _make_case(raw):
    def case():
        loss_fn, params, batch = raw()
        a = amp.initialize(opt_level="O1", verbosity=0)
        return _wrap(a, loss_fn), (params, *batch)
    return case


#: family -> () -> (audited_fn, args): the O1 forward under the cast
#: context (the original shape tests/l0/test_policy_audit.py pins).
CASES = {name: _make_case(raw) for name, raw in RAW_CASES.items()}


def run_all() -> dict:
    reports = {}
    for name, case in CASES.items():
        fn, args = case()
        reports[name] = amp.audit(fn, *args)
    return reports


def main() -> int:
    reports = run_all()
    for name, rep in reports.items():
        print(json.dumps({"family": name, **rep}))
    bad = [n for n, r in reports.items() if not r["ok"]]
    if bad:
        print(f"policy audit FAILED for: {bad}", file=sys.stderr)
        for n in bad:
            print(f"--- {n} ---\n{amp.format_report(reports[n])}",
                  file=sys.stderr)
        return 1
    print("policy audit: all families OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
