"""Per-fusion roofline audit of a compiled train step (RN50 campaign).

For every profiled top-level instruction of the compiled step this tool
computes two floors and compares them with the measured device time:

- **byte floor** — (unique operand bytes + output bytes) / HBM peak
  bandwidth: the time a perfect kernel would need just to stream the
  fusion's operands once.  Optimistic: it assumes full-bandwidth
  streaming with no re-reads, so real kernels sit above it.
- **compute floor** — analytic convolution FLOPs / chip peak (only
  convolutions contribute; elementwise FLOPs never bind on the MXU).

``gap = measured - max(floors)`` is the only time ANY kernel rewrite
could recover.  Aggregating min(measured, max(floor)) over the whole
step yields the **achievable step-time floor and the MFU ceiling** —
the number that decides whether a target like "RN50 at 0.38 MFU" is
engineering debt or physics (VERDICT r3 item 1: the fused
bottleneck-block kernel cannot reduce the byte floor, because
BatchNorm's batch-global statistics force every inter-conv tensor
through HBM — VMEM holds ~16 MB against the 103-411 MB stage-0/1
activations at b256).

Usage: python tools/fusion_roofline.py [resnet50|resnet50_s2d] [O2] [256]
Prints JSON lines (worst gaps first) then an aggregate record.
"""

import collections
import json
import re
import shutil
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

#: v5e HBM peak (bytes/s); the roofline denominator.  Other chips can be
#: added by device-kind match like bench.chip_peak_flops does for FLOPs.
HBM_BYTES_PER_S = {"v5 lite": 819e9, "v5e": 819e9, "v4": 1228e9,
                   "v6": 1640e9}

_DTYPE_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "s32": 4,
                "u32": 4, "s8": 1, "u8": 1, "pred": 1, "s16": 2,
                "u16": 2, "s64": 8, "u64": 8, "u2": 1}

_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (.*)$")


def hbm_peak() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, bw in HBM_BYTES_PER_S.items():
        if key in kind:
            return bw
    return 819e9


def _shape_bytes(type_str: str) -> int:
    """Total bytes of a (possibly tuple) HLO type string."""
    total = 0
    for m in _SHAPE_RE.finditer(type_str):
        dt, dims = m.groups()
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _conv_flops_in(comp_lines, outer_shapes=None) -> float:
    """Analytic FLOPs of convolutions inside a computation body:
    2 * prod(out) * prod(window) * C_contract, with C_contract read
    from the **rhs ``i`` dim** of ``dim_labels``.  The rhs input-feature
    size is the per-output-element contraction for every conv variant —
    plain (i = C_in), grouped/depthwise (i = C_in / groups), and the
    kernel-gradient convs XLA emits for the backward pass (labels like
    ``f01b_i01o`` where i = batch); the lhs ``f`` size over-counts the
    latter two by the group count."""
    total = 0.0
    conv_re = re.compile(
        r"= (\S+) convolution\(%?([\w.\-]+), %?([\w.\-]+)\).*?"
        r"window={size=([0-9x]+)[^}]*}.*?dim_labels=(\S+?)[,}]")
    # A bare (unfused) conv arrives as a one-line body whose operands
    # are defined elsewhere in its computation — resolve through the
    # caller-supplied scope then.
    shape_of = dict(outer_shapes or {})
    for raw in comp_lines:
        m = _DEF_RE.match(raw)
        if m:
            shape_of[m.group(1)] = m.group(2).split(" ", 1)[0]

    def _dims(name):
        sm = _SHAPE_RE.search(shape_of.get(name, "") or "")
        return ([int(d) for d in sm.group(2).split(",") if d]
                if sm else [])

    for raw in comp_lines:
        m = conv_re.search(raw)
        if not m:
            continue
        out_t, lhs, rhs, win, labels = m.groups()
        out_dims = [int(d) for d in _SHAPE_RE.search(out_t).group(2)
                    .split(",") if d]
        window = [int(w) for w in win.split("x")]
        lhs_labels = labels.split("_")[0]
        rhs_labels = labels.split("_")[1].split("->")[0]
        rhs_dims = _dims(rhs)
        i_pos = rhs_labels.index("i") if "i" in rhs_labels else -1
        if 0 <= i_pos < len(rhs_dims):
            c_contract = rhs_dims[i_pos]
        else:  # fallback: lhs f dim (correct for ungrouped forward convs)
            lhs_dims = _dims(lhs)
            f_pos = lhs_labels.index("f") if "f" in lhs_labels else -1
            c_contract = (lhs_dims[f_pos]
                          if 0 <= f_pos < len(lhs_dims) else 1)
        flops = 2.0 * c_contract
        for d in out_dims:
            flops *= d
        for w in window:
            flops *= w
        total += flops
    return total


def parse_step(hlo: str):
    """-> records {instr: {read_b, write_b, conv_flops, meta, op}},
    indexed across every computation in the module (the train-step body
    lives inside the loss-scale cond, not ENTRY)."""
    lines = hlo.splitlines()
    comps = {}
    comp_order = []
    cur = None
    for raw in lines:
        s = raw.strip()
        if s.endswith("{") and " = " not in s and "(" in s:
            cur = s.split()[0].lstrip("%").split("(")[0]
            comps[cur] = []
            comp_order.append(cur)
        elif cur is not None:
            comps[cur].append(raw)
            if s == "}":
                cur = None
    del comp_order
    # The scheduler profiles fusions/ops wherever they live (the train
    # step's body sits inside the loss-scale cond, not ENTRY) — index
    # every computation, resolving operand shapes within its own scope.
    records = {}
    for cname, clines in comps.items():
        shape_of = {}
        for raw in clines:
            dm = _DEF_RE.match(raw)
            if dm:
                shape_of[dm.group(1)] = dm.group(2).split(" ", 1)[0]
        for raw in clines:
            dm = _DEF_RE.match(raw)
            if not dm:
                continue
            name, rest = dm.groups()
            # Tuple-output types start with "(" and contain spaces and
            # parens (layout annotations like T(8,128)), so the op name
            # is found as the first lowercase identifier followed by an
            # opening paren, and the output type is everything before it.
            opm = re.search(r" ([a-z][a-z0-9\-]*)\(", rest)
            if not opm:
                continue
            op = opm.group(1)
            out_t = rest[:opm.start()]
            if op in ("parameter", "constant", "get-tuple-element",
                      "tuple", "bitcast", "after-all", "iota"):
                continue
            # operand segment: balanced-paren scan from the op's "("
            q = opm.end() - 1
            depth = 0
            end = q
            for j in range(q, len(rest)):
                if rest[j] == "(":
                    depth += 1
                elif rest[j] == ")":
                    depth -= 1
                    if depth == 0:
                        end = j
                        break
            read_b = 0
            seen = set()
            for a in re.findall(r"%([\w.\-]+)", rest[q:end]):
                if a in shape_of and a not in seen:
                    seen.add(a)
                    read_b += _shape_bytes(shape_of[a])
            conv_flops = 0.0
            body = None
            cm = re.search(r"calls=%?([\w.\-]+)", rest)
            if cm and cm.group(1) in comps:
                body = comps[cm.group(1)]
            elif "convolution(" in rest:
                body = [raw]
            if body is not None:
                conv_flops = _conv_flops_in(body, outer_shapes=shape_of)
            meta = ""
            mm = re.search(r'op_name="([^"]+)"', rest)
            if mm:
                meta = mm.group(1)
            records[name] = {"read_b": read_b,
                            "write_b": _shape_bytes(out_t),
                            "conv_flops": conv_flops, "meta": meta,
                            "op": op}
    return records


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    opt_level = sys.argv[2] if len(sys.argv) > 2 else "O2"
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    import jax.numpy as jnp

    import bench
    from apex_tpu import amp
    from apex_tpu.models.resnet import ARCHS
    from apex_tpu.optimizers import FusedAdam

    peak = bench.chip_peak_flops()
    bw = hbm_peak()
    m = ARCHS[model_name]()
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, 224, 224, 3),
                          jnp.float32)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 1000)
    variables = m.init(jax.random.PRNGKey(2), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level=opt_level,
                       verbosity=0)
    state = a.init(params)

    def loss_fn(p, xb, yb):
        logits, _ = m.apply({"params": p, "batch_stats": batch_stats},
                            xb, train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=(0,))
    compiled = step.lower(state, x, y).compile()
    records = parse_step(compiled.as_text())
    total_flops = bench.step_flops(compiled, fallback=0.0)

    iters = 6
    st, _ = compiled(state, x, y)
    jax.block_until_ready(st)
    logdir = "/tmp/apex_tpu_fusion_roofline"
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        for _ in range(iters):
            st, mtr = compiled(st, x, y)
        jax.block_until_ready(st)
    time.sleep(1)

    from apex_tpu.obs.xplane import parse_xplane
    by_name, _, total = parse_xplane(logdir)

    rows = []
    floor_s = 0.0
    measured_s = 0.0
    unmatched_s = 0.0
    for name, dur_ps in by_name.items():
        dur = dur_ps / 1e12 / iters
        measured_s += dur
        rec = records.get(name)
        if rec is None:
            # profiler-only entries (infeed, host, dma) — keep measured
            unmatched_s += dur
            floor_s += dur
            continue
        byte_floor = (rec["read_b"] + rec["write_b"]) / bw
        comp_floor = rec["conv_flops"] / peak
        fl = max(byte_floor, comp_floor)
        floor_s += min(dur, fl) if fl > 0 else dur
        rows.append({
            "op": name, "meta": rec["meta"][:90],
            "ms": round(dur * 1e3, 3),
            "floor_ms": round(fl * 1e3, 3),
            "gap_ms": round((dur - fl) * 1e3, 3),
            "bound": ("bytes" if byte_floor >= comp_floor else "flops"),
            "gb": round((rec["read_b"] + rec["write_b"]) / 1e9, 3),
            "gflops": round(rec["conv_flops"] / 1e9, 1),
        })
    rows.sort(key=lambda r: -r["gap_ms"])
    for r in rows[:40]:
        print(json.dumps(r))
    step_s = total / 1e12 / iters
    mfu_now = total_flops / step_s / peak if step_s else None
    mfu_ceiling = total_flops / floor_s / peak if floor_s else None
    print(json.dumps({
        "device_ms_per_step": round(step_s * 1e3, 2),
        "profiled_ms": round(measured_s * 1e3, 2),
        "floor_ms": round(floor_s * 1e3, 2),
        "unmatched_ms": round(unmatched_s * 1e3, 2),
        "recoverable_ms": round((measured_s - floor_s) * 1e3, 2),
        "mfu_now": round(mfu_now, 4) if mfu_now else None,
        "mfu_ceiling_optimistic": (round(mfu_ceiling, 4)
                                   if mfu_ceiling else None),
        "hbm_gb_per_s": bw / 1e9, "peak_tflops": peak / 1e12,
        "note": "floor assumes every op streams unique operands once at "
                "full HBM bandwidth (no re-reads) or hits 100% MXU — "
                "real kernels cannot reach it; the ceiling is optimistic",
    }))


if __name__ == "__main__":
    main()
