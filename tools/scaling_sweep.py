"""Virtual-mesh scaling sweep: the seven dryrun slices at 8-64 devices.

BASELINE.md names a "Scaling sweep 8 -> 64 chips" metric; real multi-chip
hardware is not reachable from this rig, so the sweep runs every slice of
``__graft_entry__.dryrun_multichip`` on virtual CPU meshes of n in
{8, 16, 32, 64} devices and **asserts the analytic collective-volume
scaling laws** a correct sharding implies.  Each world size runs in a
fresh subprocess (``--xla_force_host_platform_device_count`` must be set
before backend init), compiles + executes one step, and reports the
per-device HLO collective audit.

The slices scale the axis under test with the world size while holding
every per-device shard constant, so the per-device *static* collective
volumes obey exact laws:

- ``dp_syncbn`` (data axis = n): gradient + BatchNorm-stat all-reduce
  bytes are **constant** — per-device volume independent of world size
  is exactly what makes data parallelism scale.
- ``dp_sp_ring`` (ring sp = n/4, fixed L/sp shard): per-iteration
  ``collective-permute`` bytes constant; the ring loop runs ``sp`` trips
  (`lax.fori_loop``), so the **executed** ring volume derived as
  ``static x sp`` grows linearly — the ring law.  DP grad all-reduce
  stays constant.
- ``dp_tp_pjit`` (model axis = n/4, hidden = 16*tp): activation
  partial-sum + grad all-reduce bytes constant (Megatron sharding keeps
  both activations and weight shards per-device constant).
- ``pipeline`` (depth = n, constant microbatch): per-tick permute bytes
  constant; executed volume derived as ``static x (M + S - 1)`` per the
  GPipe schedule (M = S microbatches).
- ``expert`` (experts = 2n, constant per-device tokens): ``all-to-all``
  bytes follow the capacity formula ``E_global * C * d`` with
  ``C = max(1, ceil(cf * T_local / E_global))`` — constant while the
  per-expert capacity is above its floor, then **linear in expert
  count** once ``C`` hits 1 (here at n >= 16): the capacity-quantization
  cliff, the reason production MoE scales tokens-per-device with the
  expert count.  The sweep asserts the formula, cliff included.
- ``fsdp`` (hidden = 16n, constant shard): the compute all-gather
  reconstitutes the FULL parameter, so its bytes grow **linearly with
  n** — ZeRO-3's bandwidth cost — while grad reduction stays constant
  per device.
- ``dp_tp_sp_3d``: permute + all-reduce constant (composition preserves
  the per-axis laws).

At world 64 the sweep additionally runs ``dp_syncbn`` with
``gradient_predivide_factor=64`` (pre-divide by f, post-divide by
world/f — the large-world overflow-headroom knob, reference
``apex/parallel/distributed.py:387-393``) and asserts the updated master
params match the default reduction to fp32 round-off.

Usage:
  python tools/scaling_sweep.py              # full sweep 8..64 + laws
  python tools/scaling_sweep.py --ns 8 16    # subset (tests use this)
  python tools/scaling_sweep.py --child 16   # one world size (internal)

Writes ``SCALING_SWEEP.json`` at the repo root and exits nonzero if any
slice fails or any law is violated.
"""

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

RECORD_TAG = "SWEEP_RECORD "
DEFAULT_NS = (8, 16, 32, 64)
PREDIVIDE_WORLD = 64
#: const-law tolerance: per-device programs are shape-identical across n,
#: so audits should match to the byte; a small band absorbs incidental
#: scalar bookkeeping (loss counters) XLA may fold differently.
CONST_RTOL = 0.02
#: linear-law tolerance (fsdp all-gather, derived executed volumes)
LINEAR_RTOL = 0.05


def sweep_topology(n: int) -> dict:
    """Axis sizes under test per slice at world n (doc table above)."""
    return {"sp": max(2, n // 4), "tp": max(2, n // 4), "stages": n}


def expert_alltoall_scale(n: int) -> float:
    """Analytic per-device all-to-all buffer volume of the expert slice,
    up to a constant factor: ``E_global * C`` with the slice's
    ``T_local=16, e_local=2, capacity_factor=2`` (see
    ``apex_tpu/parallel/moe.py:84`` and the module docstring's
    capacity-cliff note)."""
    import math
    t_local, e_local, cf = 16, 2, 2.0
    e_global = e_local * n
    cap = max(1, math.ceil(cf * t_local / e_global))
    return float(e_global * cap)


def child_main(n: int) -> None:
    """Run the scaled slices on an n-device virtual CPU mesh; print one
    JSON record per slice (``SWEEP_RECORD`` lines; parent parses)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        .replace("--xla_force_host_platform_device_count=8", "").strip()
        + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["APEX_TPU_KERNELS"] = "jnp"  # see dryrun_multichip

    import numpy as np

    import __graft_entry__ as graft

    devices = jax.devices("cpu")[:n]
    if len(devices) < n:
        raise RuntimeError(f"need {n} CPU devices, have {len(devices)}")

    topo = sweep_topology(n)
    sp, tp, stages = topo["sp"], topo["tp"], topo["stages"]
    slices = [
        ("dp_syncbn", lambda d: graft._build_dp_syncbn(d)),
        ("dp_sp_ring", lambda d: graft._build_dp_sp(d, sp=sp)),
        ("dp_tp_pjit", lambda d: graft._build_dp_tp(d, tp=tp)),
        ("pipeline", lambda d: graft._build_pp(d, n_stages=stages)),
        ("expert", lambda d: graft._build_ep(d)),
        ("fsdp", lambda d: graft._build_fsdp(d)),
        ("dp_tp_sp_3d", lambda d: graft._build_dp_tp_sp(d, sp=sp)),
    ]
    for name, build in slices:
        rec = graft._run_slice(name, build, devices)
        rec["n"] = n
        rec["topology"] = topo
        print(RECORD_TAG + json.dumps(rec), flush=True)

    if n >= PREDIVIDE_WORLD:
        rec = {"name": "predivide_parity", "n": n, "ok": False}
        try:
            step_a, args_a, _ = graft._build_dp_syncbn(devices)
            out_a = step_a(*args_a)
            jax.block_until_ready(out_a)
            step_b, args_b, _ = graft._build_dp_syncbn(
                devices, predivide=float(n))
            out_b = step_b(*args_b)
            jax.block_until_ready(out_b)
            # out = (state, stats, loss, scale); master params fp32
            diffs = [
                float(np.max(np.abs(np.asarray(la) - np.asarray(lb))))
                for la, lb in zip(
                    jax.tree.leaves(out_a[0].master_params),
                    jax.tree.leaves(out_b[0].master_params))
            ]
            rec["max_abs_param_diff"] = max(diffs)
            rec["loss_a"] = float(out_a[2])
            rec["loss_b"] = float(out_b[2])
            rec["gradient_predivide_factor"] = float(n)
            # predivide only reassociates the mean (g/f summed, then
            # x f/world) — parity is fp32 round-off away from exact;
            # Adam-normalized updates bound any drift by ~2*lr
            rec["ok"] = bool(rec["max_abs_param_diff"] < 2.5e-3
                             and abs(rec["loss_a"] - rec["loss_b"]) < 1e-5)
        except Exception as e:  # noqa: BLE001 - recorded, parent fails
            rec["error"] = f"{type(e).__name__}: {e}"
        print(RECORD_TAG + json.dumps(rec), flush=True)


def run_child(n: int, timeout: int = 1200):
    """-> (records, error|None) from a fresh-process child at world n."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "scaling_sweep.py"),
         "--child", str(n)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO))
    records = [json.loads(line[len(RECORD_TAG):])
               for line in p.stdout.splitlines()
               if line.startswith(RECORD_TAG)]
    if p.returncode != 0 and not records:
        tail = (p.stderr or p.stdout or "").strip().splitlines()
        return [], f"child n={n} rc={p.returncode}: " + \
            "; ".join(tail[-3:])
    return records, None


def _get(rec, kind, field="bytes"):
    return ((rec.get("collectives") or {}).get(kind) or {}).get(field, 0)


def _ratio_ok(actual, expected, rtol):
    if expected == 0:
        return actual == 0
    return abs(actual / expected - 1.0) <= rtol


def check_laws(by_n: dict) -> list:
    """Assert the per-slice scaling laws over {n: {slice: record}}.

    Returns a list of law records ``{law, slice, ok, detail}`` — one per
    (slice, law) pair — computed against the smallest world size as the
    reference point.
    """
    ns = sorted(by_n)
    n0 = ns[0]
    laws = []

    def law(name, slice_name, kind, expected_fn, rtol, derived_fn=None):
        base = _get(by_n[n0].get(slice_name, {}), kind)
        series = {}
        ok = base > 0
        for n in ns:
            rec = by_n[n].get(slice_name)
            if rec is None or not rec.get("ok"):
                ok = False
                continue
            actual = _get(rec, kind)
            if derived_fn is not None:
                actual = derived_fn(n, actual)
                expected = derived_fn(n0, base) * expected_fn(n) \
                    / expected_fn(n0)
            else:
                expected = base * expected_fn(n) / expected_fn(n0)
            series[str(n)] = {"bytes": actual,
                              "expected": round(expected, 1)}
            if not _ratio_ok(actual, expected, rtol):
                ok = False
        laws.append({"law": name, "slice": slice_name, "kind": kind,
                     "ok": bool(ok), "series": series})

    const = (lambda n: 1.0)
    # data parallelism: per-device reduction volume independent of world
    law("dp allreduce const/device", "dp_syncbn", "all-reduce",
        const, CONST_RTOL)
    # ring attention: per-iteration permute const; executed volume
    # (static x sp trips of the fori_loop ring) grows with the ring
    law("ring permute const/iteration", "dp_sp_ring",
        "collective-permute", const, CONST_RTOL)
    law("ring executed volume ~ sp", "dp_sp_ring", "collective-permute",
        lambda n: sweep_topology(n)["sp"], LINEAR_RTOL,
        derived_fn=lambda n, b: b * sweep_topology(n)["sp"])
    law("ring dp-grad allreduce const", "dp_sp_ring", "all-reduce",
        const, CONST_RTOL)
    # tensor parallelism: Megatron sharding keeps per-device volumes flat
    law("tp allreduce const/device", "dp_tp_pjit", "all-reduce",
        const, CONST_RTOL)
    # pipeline: per-tick permute const; executed = static x (M + S - 1)
    law("pipe permute const/tick", "pipeline", "collective-permute",
        const, CONST_RTOL)
    law("pipe executed volume ~ 2S-1", "pipeline", "collective-permute",
        lambda n: 2 * n - 1, LINEAR_RTOL,
        derived_fn=lambda n, b: b * (2 * n - 1))
    # expert parallelism: the capacity formula — constant until the
    # per-expert capacity floors at 1, then linear in expert count
    # (the capacity-quantization cliff; module docstring)
    law("expert all-to-all ~ E*C capacity formula", "expert",
        "all-to-all", expert_alltoall_scale, LINEAR_RTOL)
    # fsdp: the compute all-gather reconstitutes the FULL (growing)
    # parameter — the one law that is linear in the static audit itself
    law("fsdp all-gather ~ params", "fsdp", "all-gather",
        lambda n: n, LINEAR_RTOL)
    # 3-D composition preserves the per-axis laws
    law("3d permute const/iteration", "dp_tp_sp_3d",
        "collective-permute", const, CONST_RTOL)
    law("3d allreduce const/device", "dp_tp_sp_3d", "all-reduce",
        const, CONST_RTOL)
    return laws


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", type=int, default=None)
    ap.add_argument("--ns", type=int, nargs="*", default=None)
    ap.add_argument("--out", default=str(REPO / "SCALING_SWEEP.json"))
    args = ap.parse_args(argv)

    if args.child is not None:
        child_main(args.child)
        return 0

    ns = tuple(args.ns) if args.ns else DEFAULT_NS
    by_n = {}
    errors = []
    for n in ns:
        print(f"--- world {n} ---", flush=True)
        records, err = run_child(n)
        if err:
            errors.append(err)
            print(err, flush=True)
        by_n[n] = {r["name"]: r for r in records}
        for r in records:
            print(json.dumps(r), flush=True)

    laws = check_laws(by_n)
    failed_slices = [f"n={n}:{name}" for n, recs in by_n.items()
                     for name, r in recs.items() if not r.get("ok")]
    failed_laws = [f"{lw['slice']}: {lw['law']}" for lw in laws
                   if not lw["ok"]]
    parity = next((r for recs in by_n.values()
                   for r in recs.values()
                   if r.get("name") == "predivide_parity"), None)
    verdict = {
        "ns": list(ns),
        "slices": {str(n): recs for n, recs in by_n.items()},
        "laws": laws,
        "predivide_parity": parity,
        "failed_slices": failed_slices,
        "failed_laws": failed_laws,
        "errors": errors,
        "ok": not (failed_slices or failed_laws or errors
                   or (max(ns) >= PREDIVIDE_WORLD
                       and not (parity or {}).get("ok"))),
    }
    Path(args.out).write_text(json.dumps(verdict, indent=1))
    summary = {"scaling_sweep": {
        "ns": list(ns), "ok": verdict["ok"],
        "laws_ok": sum(1 for lw in laws if lw["ok"]),
        "laws_total": len(laws),
        "failed_laws": failed_laws, "failed_slices": failed_slices,
        "predivide_parity_ok": (parity or {}).get("ok"),
    }}
    print(json.dumps(summary), flush=True)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
