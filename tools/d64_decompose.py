"""Decompose the stock d=64 GPT step — close the last points between
measured MFU and the documented ~0.43 ceiling (VERDICT r4 weak #1).

``docs/source/attention.rst`` derives the 12x64-head ceiling from the
measured d64/d128 flash-kernel ratio (1.67x, architectural: every d=64
matmul rides the 128-wide MXU at <=50%).  Round 4 measured gpt_small_o2
at 0.4227 vs the prose "~0.43" with the residual neither captured nor
decomposed.  This tool profiles the EXACT bench config (B8 L2048, amp
O2, FusedAdam) and buckets device time into:

- ``attention``  — the flash fwd/bwd Pallas calls
- ``matmul``     — dense projections / FFN / logits fusions
- ``layernorm``  — fused LN kernels
- ``optimizer``  — fused-Adam / multi-tensor custom calls
- ``other``      — everything else (embeds, loss, scaler bookkeeping)

and prints: measured MFU, the attention-time fraction, the ceiling
implied by the measured decomposition (attention at its architectural
floor = measured time, everything else as-is), and the predicted
d=128 MFU from dividing the attention bucket by the measured kernel
ratio — checked against the same-day tpu-heads number.  The doc's
ceiling statement is then an output of THIS measurement, with a stated
variance band, not prose.

Usage: python tools/d64_decompose.py [batch] [seq]   # needs the chip
"""

import json
import shutil
import sys
import time
from pathlib import Path

import jax

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

#: measured same-day d64/d128 fused fwd+bwd kernel ratio
#: (docs/source/attention.rst: 6.5 vs 3.9 ms/layer)
KERNEL_RATIO_D64_D128 = 1.67

def decompose(by_name, by_cat, total):
    """Bucket profiled device time.  On TPU the dense projections/FFN/
    logits lower as "convolution fusion" HLO; the Pallas calls are
    "custom-call" — flash attention identified by name (the kernel
    wrappers' ``_flash_fwd``/``_flash_bwd`` marks), the remainder of the
    custom-call bucket being the fused LN + optimizer kernels; the
    loss-scaler's finite-check and conditional, and XLA's relayout
    ("data formatting") time, are split out as named overheads."""
    attn = sum(d for n, d in by_name.items()
               if "_flash_fwd" in n or "_flash_bwd" in n)
    scaler = sum(d for n, d in by_name.items()
                 if "is-finite" in n or n.startswith("cond"))
    matmul = by_cat.get("convolution fusion", 0)
    custom = by_cat.get("custom-call", 0)
    ln_opt = max(custom - attn, 0)
    formatting = by_cat.get("data formatting", 0)
    other = total - attn - matmul - ln_opt - scaler - formatting
    return {"attention": attn, "matmul": matmul,
            "layernorm_optimizer": ln_opt, "scaler_overhead": scaler,
            "data_formatting": formatting, "other": max(other, 0),
            "_total": total}


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 2048

    import bench
    from apex_tpu.obs.xplane import parse_xplane

    peak = bench.chip_peak_flops()
    iters = 8

    # measured numbers come from an UNTRACED run (profiling costs ~7%
    # throughput on this rig); the traced run only supplies fractions
    res = bench.bench_gpt(batch=batch, seq=seq, warmup=3, iters=iters,
                          peak=peak, tiny=False)
    logdir = "/tmp/apex_tpu_d64_decompose"
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        bench.bench_gpt(batch=batch, seq=seq, warmup=2, iters=iters,
                        peak=peak, tiny=False)
    time.sleep(1)
    by_name, by_cat, total = parse_xplane(logdir)
    buckets = decompose(by_name, by_cat, total)
    # normalize to FRACTIONS of profiled device time (robust to the
    # trace's step count), then scale onto the untraced per-step time
    frac = {k: v / max(total, 1) for k, v in buckets.items()
            if not k.startswith("_")}
    tok_s = res["tok_s"]
    mfu = res["mfu"]
    step_ms = batch * seq / tok_s * 1e3

    attn_ms = frac["attention"] * step_ms
    rest_ms = step_ms - attn_ms
    # the 1.67x d64/d128 kernel ratio is the architectural floor (three
    # rewrite attempts measured negative — attention.rst); dividing the
    # attention bucket by it predicts the same-day 6x128 MFU, the
    # cross-check that the decomposition adds up
    pred_d128_step_ms = rest_ms + attn_ms / KERNEL_RATIO_D64_D128
    pred_d128_mfu = mfu * step_ms / pred_d128_step_ms

    out = {
        "config": {"batch": batch, "seq": seq, "heads": "12x64"},
        "measured": {"tok_s": tok_s, "mfu": mfu, "hfu": res["hfu"],
                     "step_ms": round(step_ms, 2)},
        "device_time_fractions": {k: round(v, 4)
                                  for k, v in frac.items()},
        "attention_ms_per_step": round(attn_ms, 2),
        "pred_tpu_heads_mfu_from_ratio": round(pred_d128_mfu, 4),
        "kernel_ratio_used": KERNEL_RATIO_D64_D128,
        "note": "measured MFU is from the untraced run; fractions from "
                "the traced run.  CAUTION on reading the buckets: XLA "
                "names a fusion after its root op, so scaler_overhead "
                "and data_formatting carry co-fused gradient traffic "
                "(unscale/cast) that would run anyway — a same-day A/B "
                "with the finite check deleted entirely gained only "
                "~2.1%, and a flat-packed replacement measured NEGATIVE "
                "(parked in ops/pallas/experimental/finite_pack.py). "
                "Attention at its architectural floor means the d=64 "
                "ceiling IS the measured number up to those true "
                "marginal overheads.",
    }
    print(json.dumps(out, indent=1))
    Path(REPO / "D64_DECOMPOSE_r05.json").write_text(json.dumps(out,
                                                                indent=1))


if __name__ == "__main__":
    main()
