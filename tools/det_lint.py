#!/usr/bin/env python
"""Sweep every gated program lane through the determinism lint.

Lowers each lane of the bitwise-gated program matrix — the solo /
batched / int8-KV decode steps and the serve decode / prefill /
speculative-verify steps — runs the four per-lane
:mod:`apex_tpu.analysis.determinism` rules over each lowering, diffs
the cross-lane reduction signatures for the comparator pairs
(``det-lane-shape-variant``: the ``_attn_cached`` b1-vs-b8 suspect,
the kv8 tolerance class, spec's step-vs-verify agreement), and writes
the verdict as ``DETLINT_r*.json`` (schema:
:mod:`apex_tpu.analysis.detlint`, validated by
``tools/gate_hygiene.py`` in tier-1).

Lowering only — nothing is compiled or executed, so the sweep is
cheap enough for CI and runs identically on CPU and TPU (the
pre-optimization StableHLO is the program the user asked for, printed
identically across backends).

Usage::

    python tools/det_lint.py --out DETLINT_r01.json
    python tools/det_lint.py            # print verdicts, no file

Exit code 1 when any lane records an unwaived finding (or fails to
lower) or any comparator pair is an undocumented variant, so the
sweep can gate CI directly.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))
sys.path.insert(0, _TOOLS)

import graph_lint                                       # noqa: E402
from apex_tpu import analysis                           # noqa: E402
from apex_tpu.analysis import determinism as det        # noqa: E402
from apex_tpu.analysis.determinism import LANE_RULES    # noqa: E402
from apex_tpu.analysis.detlint import (                 # noqa: E402
    RULES, pair_ok, validate_detlint)

import jax                                              # noqa: E402

#: documented waivers: lane -> {rule id -> reason}.  A waiver only
#: validates when the rule actually fired (the schema rejects stale
#: ones), so this table is empty while the sweep is clean.
WAIVERS: dict = {}

#: the evidence counters the pass emits -> the 'checked' keys the
#: artifact records (a lane that counted nothing everywhere is
#: unexamined, not clean — the schema enforces it)
_CHECKED = {"det-epilogue-sites": "epilogue_sites",
            "det-scatter-sites": "scatter_sites",
            "det-rng-calls": "rng_calls",
            "det-barriers": "barriers"}

#: the full lane matrix: every gated program.  decode_b8 is built here
#: (graph_lint's decode lanes stop at b2) — the b1-vs-b8 comparator
#: pair IS the ``_attn_cached`` shape-lucky-accumulation suspect.
DET_LANES = {
    "decode_b1": ("decode", (1, 8, 8, None)),
    "decode_b8": ("decode", (8, 8, 8, None)),
    "decode_b1_kv8": ("decode", (1, 8, 8, "int8")),
    "serve_step": ("serve", (2, 4, 9, 4)),
    "serve_decode": ("serve", (4, 4, 17, 4)),
    "serve_prefill": ("prefill", (2, 4, 9, 4)),
    "serve_verify": ("verify", (2, 4, 9, 4, 3)),
}

#: the comparator pairs and why each is worth a recorded verdict
PAIRS = (
    ("decode_b1", "decode_b8"),        # the _attn_cached b1/b8 suspect
    ("decode_b1", "decode_b1_kv8"),    # the documented kv8 tolerance
    ("serve_step", "serve_decode"),    # slot-count scaling
    ("serve_step", "serve_verify"),    # spec's step-vs-verify contract
)

#: pairs whose signature variants are a DOCUMENTED tolerance class:
#: pair key -> reason.  An expected variant passes the gate with its
#: reason recorded; an undocumented variant fails it.
EXPECTED_VARIANTS = {
    "decode_b1|decode_b1_kv8":
        "the int8-KV dequant path: the QK contraction reads "
        "dequantized f32 operands instead of bf16 and the cache "
        "quantizer adds per-position max-abs scale reduces — the "
        "kv8 lane's documented tolerance class, now mechanical",
}


def lane_text(kind: str, cfg: tuple) -> str:
    """One lane's pre-optimization StableHLO text (lowering only)."""
    if kind == "decode":
        fn, args, kwargs, _props = graph_lint.build_decode_step(*cfg)
        return fn.lower(*args, **kwargs).as_text()
    if kind == "serve":
        fn, args, _props = graph_lint.build_serve_step(*cfg)
    elif kind == "prefill":
        fn, args, _props = graph_lint.build_serve_prefill(*cfg)
    elif kind == "verify":
        fn, args, _props = graph_lint.build_serve_verify(*cfg)
    else:
        raise ValueError(f"unknown lane kind {kind!r}")
    return analysis.lower_quiet(fn, *args).as_text()


def sweep_lane(name: str, text: str, verbose: bool = False) -> dict:
    """One lane's DETLINT record: per-rule error counts, the evidence
    counters, the verdict."""
    findings = {rule: 0 for rule in LANE_RULES}
    checked = {key: 0 for key in _CHECKED.values()}
    waivers = dict(WAIVERS.get(name, {}))
    for f in det.determinism_findings(text):
        if f.op in _CHECKED:
            checked[_CHECKED[f.op]] += f.count
        elif f.severity == "error" and f.op in findings:
            findings[f.op] += 1
            if verbose:
                print(f"  [{name}] {f.op}: {f.message}",
                      file=sys.stderr)
    unwaived = sum(c for rule, c in findings.items()
                   if rule not in waivers)
    rec = {"ok": unwaived == 0, "findings": findings,
           "checked": checked}
    if waivers:
        rec["waivers"] = waivers
    return rec


def compare_pair(a: str, text_a: str, b: str, text_b: str) -> dict:
    """One comparator pair's DETLINT record, evidence included."""
    sa = det.reduction_signatures(text_a)
    sb = det.reduction_signatures(text_b)
    res = det.compare_signatures(a, sa, b, sb)
    rec = {"lanes": [a, b],
           "signatures": {a: det.signature_json(sa),
                          b: det.signature_json(sb)},
           "verdict": res["verdict"], "positional": res["positional"],
           "variants": res["variants"]}
    if res["verdict"] == "variant":
        key = f"{a}|{b}"
        rec["expected"] = key in EXPECTED_VARIANTS
        if rec["expected"]:
            rec["reason"] = EXPECTED_VARIANTS[key]
    return rec


def run_sweep(verbose: bool = False) -> dict:
    lanes = {}
    texts = {}
    for name, (kind, cfg) in DET_LANES.items():
        try:
            texts[name] = lane_text(kind, cfg)
        except Exception as e:  # noqa: BLE001 - record, don't crash sweep
            lanes[name] = {
                "ok": False,
                "findings": {rule: 0 for rule in LANE_RULES},
                "checked": {key: 0 for key in _CHECKED.values()},
                "error": f"lowering: {type(e).__name__}: {e}"}
            continue
        lanes[name] = sweep_lane(name, texts[name], verbose=verbose)
    pairs = {}
    for a, b in PAIRS:
        if a in texts and b in texts:
            pairs[f"{a}|{b}"] = compare_pair(a, texts[a], b, texts[b])
    clean = sum(1 for rec in lanes.values() if rec["ok"])
    p_ok = sum(1 for rec in pairs.values() if pair_ok(rec))
    return {
        "round": None,           # filled from --out / --round in main
        "platform": jax.default_backend(),
        "rules": list(RULES),
        "lanes": lanes,
        "pairs": pairs,
        "gate": {"ok": clean == len(lanes) and p_ok == len(pairs),
                 "lanes_clean": clean, "lanes_total": len(lanes),
                 "pairs_ok": p_ok, "pairs_total": len(pairs)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="determinism lint sweep -> DETLINT_r*.json")
    ap.add_argument("--out", default=None,
                    help="write the DETLINT JSON here (round parsed "
                         "from a DETLINT_rNN.json name)")
    ap.add_argument("--round", type=int, default=None,
                    help="round number (default: parsed from --out, "
                         "else 1)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="print every error finding as it is counted")
    opts = ap.parse_args(argv)

    rnd = opts.round
    if rnd is None and opts.out:
        m = re.search(r"DETLINT_r(\d+)", os.path.basename(opts.out))
        rnd = int(m.group(1)) if m else None
    doc = run_sweep(verbose=opts.verbose)
    doc["round"] = rnd if rnd is not None else 1

    problems = validate_detlint(doc)
    for name, rec in doc["lanes"].items():
        bad = {rule: c for rule, c in rec["findings"].items() if c}
        status = "ok" if rec["ok"] else "FAIL"
        extra = f" findings={bad}" if bad else ""
        extra += f" error={rec['error']!r}" if "error" in rec else ""
        print(f"{name:16s} {status}  checked={rec['checked']}{extra}")
    for key, rec in doc["pairs"].items():
        tag = rec["verdict"]
        if tag == "variant":
            tag += " (expected)" if rec.get("expected") \
                else " (UNDOCUMENTED)"
        print(f"{key:32s} {tag}  "
              f"positional={rec['positional']} "
              f"variants={len(rec['variants'])}")
    gate = doc["gate"]
    print(f"gate: ok={gate['ok']} "
          f"({gate['lanes_clean']}/{gate['lanes_total']} lanes clean, "
          f"{gate['pairs_ok']}/{gate['pairs_total']} pairs ok)")
    if problems:      # a self-emitted doc failing its own schema is a bug
        for p in problems:
            print(f"schema: {p}", file=sys.stderr)
        return 2
    if opts.out:
        with open(opts.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {opts.out}")
    return 0 if gate["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
