"""Repeated-timing variance recorder — the statistics under the floors.

VERDICT r5 weak #6: every gate width in the repo (the 5% MFU band, each
floor, the kernel-bench 10% threshold) was calibrated from anecdote — a
same-day spread measured informally once, for one config, cited in a
commit message.  This tool records the statistic: N repeated timings per
config, written to ``BENCH_VARIANCE.json`` with mean/min/max and the
relative spread, so floor and band widths are DERIVED from recorded
variance — and so lowering a floor requires pointing at an entry (the
no-ratchet-down rule ``tests/l1/test_bench_units.py`` enforces over
``bench.py``'s floor tables).

Two entry kinds:

- ``kernel:<name>`` — repeats of ``tools/kernel_bench.py``'s per-kernel
  difference-quotient timing (ms_per_step).  Cheap enough for N≥5 on
  chip; the CPU-tiny smoke keeps the tool runnable in tier-1.
- ``config:<name>`` — repeats of a ``bench.py`` model config's rate
  metric (img_s / tok_s / seq_s) and MFU.  Chip-expensive; run a small
  set across the round's days.

The artifact is a gate baseline: ``tools/gate_hygiene.py`` fails tier-1
when it is modified-but-uncommitted, and round-numbered artifacts
(``--round N`` → ``BENCH_VARIANCE_rNN.json``) are additionally
schema-validated (``apex_tpu/analysis/variance.py``: recorded
mean/min/max/std/rel_spread must agree with the recorded samples — a
spread that excuses a floor drop must be derivable, not typed in).

Each entry records ``std`` (sample standard deviation) next to the
spread, plus the gate statistics the floors actually ride: kernels
carry a ``roofline_frac`` sub-stat block, configs an ``mfu`` and (for
decode configs) an ``hbm_frac`` block — so
``bench.derive_floor_bands()`` computes ``floor = mean − k·std`` on
exactly the gated statistic, and ``tools/perf_timeline.py`` reads
per-series band widths from the same entries.

Usage: python tools/bench_variance.py [--out BENCH_VARIANCE.json]
       [--round N] [--n 5] [--kernels fused_adam,mt_scale,...]
       [--configs resnet50_o2,gpt_small_o2] [--tiny]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import jax  # noqa: E402


def _stats(values):
    # summarize the ROUNDED samples the record actually stores, so the
    # schema validator (apex_tpu/analysis/variance.py) can re-derive
    # every summary statistic from the recorded values exactly.
    # SIGNIFICANT digits, not fixed decimals: a sub-microsecond tiny-
    # smoke timing must not round to 0.0 and destroy the stats block
    values = [float(f"{v:.6g}") for v in values]
    mean = sum(values) / len(values)
    # sample standard deviation: the "spread" in the derived-floor
    # formula floor = mean - k*std (0.0 for a single sample — which
    # derive_floor_bands refuses anyway via its min-samples rule)
    std = (sum((v - mean) ** 2 for v in values)
           / (len(values) - 1)) ** 0.5 if len(values) > 1 else 0.0
    return {
        "n": len(values),
        "values": values,
        "mean": float(f"{mean:.6g}"),
        "min": min(values),
        "max": max(values),
        "std": float(f"{std:.6g}"),
        # the band-width statistic: worst-case same-artifact swing
        "rel_spread": round((max(values) - min(values)) / mean, 4)
        if mean else None,
    }


def measure_kernels(names, n: int, tiny: bool) -> dict:
    """N independent difference-quotient timings per kernel (each repeat
    re-times both scan lengths, so the spread includes the quotient's
    own noise — the statistic the kernel floor band must cover).  The
    suite table is ``kernel_bench.suite_specs`` itself, so every gated
    kernel is variance-measurable by construction."""
    import kernel_bench as kb

    specs = kb.suite_specs(tiny)
    entries = {}
    for name in names:
        if name not in specs:
            entries[f"kernel:{name}"] = {"error": "unknown kernel"}
            continue
        try:
            fn, args, iters = specs[name]
            build, nbytes, geom = fn(*args)
            vals = [kb._time_scan(build, iters) * 1e3 for _ in range(n)]
            entry = {"metric": "ms_per_step", "geometry": geom,
                     **_stats(vals)}
            # the GATED statistic: per-repeat roofline fraction (the
            # KERNEL_FLOORS unit), so derive_floor_bands computes
            # mean - k*std on exactly what the floor gates.  A repeat
            # whose difference quotient collapsed to <= 0 (tiny-smoke
            # noise) has no meaningful fraction — skip the block
            # rather than divide by it
            if all(ms > 0 for ms in vals):
                bw = kb._hbm_peak()
                entry["roofline_frac"] = _stats(
                    [nbytes / (ms * 1e-3) / bw for ms in vals])
            entries[f"kernel:{name}"] = entry
        except Exception as e:  # noqa: BLE001 - per-entry isolation
            entries[f"kernel:{name}"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}
    return entries


def measure_configs(names, n: int, tiny: bool) -> dict:
    """N repeats of a bench.py model config's rate + MFU (the model-gate
    statistic).  Uses the same bench functions and argument sets as
    ``bench.py main`` so the variance is measured on exactly the gated
    config."""
    import bench

    on_tpu = not tiny and jax.devices()[0].platform == "tpu"
    peak = bench.chip_peak_flops() if on_tpu else None
    if on_tpu:
        rn = dict(batch=256, size=224, warmup=4, iters=20)
        gpt = dict(batch=8, seq=2048, warmup=3, iters=12, tiny=False)
        bert = dict(batch=16, seq=512, warmup=3, iters=10, tiny=False)
    else:
        rn = dict(batch=8, size=64, warmup=1, iters=3)
        gpt = dict(batch=2, seq=64, warmup=1, iters=3, tiny=True)
        bert = dict(batch=2, seq=64, warmup=1, iters=3, tiny=True)
    # every MFU_FLOORS config is measurable here (the no-ratchet-down
    # rule requires an entry to lower any floor), args mirroring
    # bench.py main's
    fns = {
        "resnet50_o2": lambda: bench.bench_resnet(opt_level="O2",
                                                  peak=peak, **rn),
        "resnet50_o3": lambda: bench.bench_resnet(opt_level="O3",
                                                  peak=peak, **rn),
        "resnet50_s2d_o2": lambda: bench.bench_resnet(
            opt_level="O2", s2d=True, peak=peak, **rn),
        "gpt_small_o2": lambda: bench.bench_gpt(peak=peak, **gpt),
        "gpt_small_tpu_heads_o2": lambda: bench.bench_gpt(
            tpu_heads=True, peak=peak, **gpt),
        "gpt_small_tpu_heads_L8192_o2": lambda: bench.bench_gpt(
            tpu_heads=True, remat=True, peak=peak,
            **dict(gpt, batch=2 if on_tpu else gpt["batch"],
                   seq=8192 if on_tpu else gpt["seq"])),
        "gpt_small_tpu_heads_L16384_o2": lambda: bench.bench_gpt(
            tpu_heads=True, remat=True, peak=peak,
            **dict(gpt, batch=1 if on_tpu else gpt["batch"],
                   seq=16384 if on_tpu else gpt["seq"])),
        "gpt_medium_tpu_o2": lambda: bench.bench_gpt(
            tpu_heads="medium" if on_tpu else True, peak=peak, **gpt),
        "bert_large_lamb_o2": lambda: bench.bench_bert(peak=peak, **bert),
        "bert_large_tpu_heads_lamb_o2": lambda: bench.bench_bert(
            tpu_heads=True, peak=peak, **bert),
    }
    # the DECODE_FLOORS configs: hbm_frac is their gated statistic, so
    # a chip round can justify (or refuse) a decode-floor move with
    # the same recorded-variance rule the MFU floors ride — including
    # the kv8 config whose CPU-seeded placeholder floor stays
    # provisional until an entry lands here
    if on_tpu:
        dec = dict(batch=8, prefill=2048, new_tokens=256, warmup=1,
                   iters=4, tiny=False)
    else:
        dec = dict(batch=2, prefill=16, new_tokens=8, warmup=0,
                   iters=1, tiny=True)
    fns.update({
        "gpt_small_tpu_decode_b1": lambda: bench.bench_generate(
            peak=peak, **dict(dec, batch=1)),
        "gpt_small_tpu_decode_b8": lambda: bench.bench_generate(
            peak=peak, **dec),
        "gpt_small_tpu_decode_kv8": lambda: bench.bench_generate(
            peak=peak, kv_dtype="int8", **dec),
    })
    entries = {}
    for name in names:
        if name not in fns:
            entries[f"config:{name}"] = {"error": "unknown config"}
            continue
        try:
            rates, mfus, fracs, key = [], [], [], None
            for _ in range(n):
                res = fns[name]()
                key = next(k for k in bench.RATE_KEYS if res.get(k))
                rates.append(float(res[key]))
                if res.get("mfu"):
                    mfus.append(float(res["mfu"]))
                if isinstance(res.get("hbm_frac"), (int, float)):
                    fracs.append(float(res["hbm_frac"]))
            entries[f"config:{name}"] = {"metric": key, **_stats(rates)}
            if mfus:
                entries[f"config:{name}"]["mfu"] = _stats(mfus)
            if fracs:
                entries[f"config:{name}"]["hbm_frac"] = _stats(fracs)
        except Exception as e:  # noqa: BLE001 - per-entry isolation
            entries[f"config:{name}"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}
    return entries


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="output path (default BENCH_VARIANCE.json, or "
                         "BENCH_VARIANCE_rNN.json with --round)")
    ap.add_argument("--round", type=int, default=None,
                    help="emit the round-numbered, schema-validated "
                         "gate artifact BENCH_VARIANCE_rNN.json")
    ap.add_argument("--n", type=int, default=5)
    ap.add_argument("--kernels", default="fused_adam,lamb_stage1,mt_scale")
    ap.add_argument("--configs", default="",
                    help="comma-separated bench.py configs (chip-"
                         "expensive; empty = none)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny shapes (CPU smoke; spreads meaningless)")
    args = ap.parse_args(argv)
    if args.out is None:
        args.out = str(REPO / (f"BENCH_VARIANCE_r{args.round:02d}.json"
                               if args.round is not None
                               else "BENCH_VARIANCE.json"))

    entries = {}
    if args.kernels:
        entries.update(measure_kernels(
            [k for k in args.kernels.split(",") if k], args.n, args.tiny))
    if args.configs:
        entries.update(measure_configs(
            [c for c in args.configs.split(",") if c], args.n, args.tiny))
    result = {
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "tiny": args.tiny,
        "entries": entries,
    }
    if args.round is not None:
        result["round"] = args.round
        # a round-numbered artifact is gate memory: refuse to write an
        # invalid one (the same pre-flight serve_scenarios runs)
        from apex_tpu.analysis.variance import validate_variance
        problems = validate_variance(result)
        if problems:
            print(f"bench_variance: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
    Path(args.out).write_text(json.dumps(result, indent=1) + "\n")
    print(json.dumps(result))
    # errors are per-entry records, not exit failures: partial variance
    # evidence beats none after the chip time is spent
    return 0


if __name__ == "__main__":
    sys.exit(main())
