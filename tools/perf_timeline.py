"""Longitudinal perf timeline: one history over every committed
artifact family, with statistical regression attribution.

Builds the normalized metric timeline (``apex_tpu/analysis/
timeline.py``) over EVERY round-numbered artifact committed next to
``bench.py`` — one registered adapter per schema family; a committed
``*_r*.json`` family with no adapter is a **lint error** (exit 1), so
a new gate family cannot land without joining the timeline — and emits
a schema-valid ``TIMELINE_r*.json`` carrying:

- per-series trajectories, each round's point tagged with the commit
  that introduced its artifact (``git log --diff-filter=A``);
- the **regression table**: every gated series (configs carrying
  ``bench.MFU_FLOORS``/``bench.DECODE_FLOORS`` entries on their rate
  and ``hbm_frac`` metrics, kernels carrying
  ``kernel_bench.KERNEL_FLOORS`` on ``roofline_frac``) whose newest
  value sits below its statistical band — band = the recorded relative
  spread from the newest committed ``BENCH_VARIANCE_r*.json`` when a
  non-tiny entry covers the series, else the documented default
  (``timeline.DEFAULT_BAND``).  Each row names the first round where
  the value dropped and the **suspect commits** between the two
  rounds' artifact commits — the gpt −3.2% / bert_lamb −3.6% r04→r05
  finding (VERDICT r5 weak #1), rediscovered mechanically;
- the **coverage table** proving every committed family and file was
  ingested (``tools/gate_hygiene.py`` holds the newest committed
  timeline to this bar against the checkout, so the timeline can
  never silently go stale).

Usage: python tools/perf_timeline.py [--emit-json TIMELINE_rN.json]
       [--repo DIR] [--band 0.03] [--gate] [--max-suspects 30]

``--gate`` exits 2 when the regression table is non-empty (the driver
round's blocking mode); without it the table is attribution evidence
and the exit code only covers lint errors.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu.analysis import timeline  # noqa: E402


def _git(repo: str, *args: str) -> "str | None":
    try:
        out = subprocess.run(["git", "-C", repo, *args],
                             capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return out.stdout


def added_commit(repo: str, name: str) -> "str | None":
    """Short hash of the commit that INTRODUCED ``name`` (the round
    tag's anchor: artifacts are committed once, in the round commit
    that produced them)."""
    out = _git(repo, "log", "--diff-filter=A", "--format=%h", "--",
               name)
    lines = (out or "").split()
    return lines[-1] if lines else None


def commits_between(repo: str, frm: str, to: str,
                    limit: int = 30) -> list:
    """``[{"commit", "subject"}, ...]`` for every commit in
    ``frm..to`` (oldest first) — the suspect range between two rounds'
    artifact commits."""
    out = _git(repo, "log", "--reverse", "--format=%h\x1f%s",
               f"{frm}..{to}")
    rows = []
    for line in (out or "").splitlines():
        h, _, subject = line.partition("\x1f")
        if h:
            rows.append({"commit": h, "subject": subject[:120]})
    if len(rows) > limit:
        rows = rows[:limit] + [{"commit": "...",
                                "subject": f"({len(rows) - limit} "
                                           f"more omitted)"}]
    return rows


def resolve_commits(repo: str, coverage: dict) -> dict:
    """``{(family, round): short_hash}`` for every covered artifact."""
    commits = {}
    for family, rec in coverage.items():
        for name in rec.get("files", []):
            parsed = timeline.parse_artifact_name(name)
            if parsed is None:
                continue
            h = added_commit(repo, name)
            if h:
                commits[(family, parsed[1])] = h
    return commits


def gated_series_keys(series: dict,
                      repo: str) -> "tuple[list, dict, list, str]":
    """``(gated_keys, per_series_bands, provisional_floors, source)``
    — this checkout's published floor tables define WHICH series are
    gated; the TARGET repo's committed variance artifact defines how
    wide their bands are (and names itself as ``source`` when it
    qualifies: non-tiny AND on-chip, the derive_floor_bands bar)."""
    import bench
    import kernel_bench

    variance = bench.load_variance(repo)
    usable = isinstance(variance, dict) and not variance.get("tiny") \
        and variance.get("platform") == "tpu"
    entries = (variance or {}).get("entries") or {}

    def band_for(kind, name, stat=None):
        if not usable:
            return None
        e = entries.get(f"{kind}:{name}")
        if not isinstance(e, dict):
            return None
        if stat and isinstance(e.get(stat), dict):
            e = e[stat]
        spread = e.get("rel_spread")
        return float(spread) if isinstance(spread, (int, float)) \
            and spread > 0 else None

    gated, bands = [], {}
    provisional = sorted(getattr(bench, "PROVISIONAL_FLOORS", ()))
    for cfg in sorted({**bench.MFU_FLOORS, **bench.DECODE_FLOORS}):
        for metric in timeline.RATE_METRICS:
            key = timeline.series_key("BENCH", cfg, metric)
            if key in series:
                gated.append(key)
                b = band_for("config", cfg)
                if b is not None:
                    bands[key] = b
    for cfg in sorted(bench.DECODE_FLOORS):
        key = timeline.series_key("BENCH", cfg, "hbm_frac")
        if key in series:
            gated.append(key)
            b = band_for("config", cfg, stat="hbm_frac")
            if b is not None:
                bands[key] = b
    for kern in sorted(kernel_bench.KERNEL_FLOORS):
        key = timeline.series_key("KERNELBENCH", kern, "roofline_frac")
        if key in series:
            gated.append(key)
            b = band_for("kernel", kern, stat="roofline_frac")
            if b is not None:
                bands[key] = b
    src = None
    if usable:
        src = os.path.basename(
            bench.find_variance_artifact(repo) or "")
    return gated, bands, provisional, src


def build_timeline(repo: str, default_band: float = timeline.DEFAULT_BAND,
                   round_no: int = 0, max_suspects: int = 30,
                   gated: "list | None" = None,
                   bands: "dict | None" = None) -> dict:
    """The whole pipeline: ingest every family, correlate commits,
    detect band crossings, attribute suspects.  Raises ``ValueError``
    on an unknown committed family (the staleness lint).  ``gated`` /
    ``bands`` override the floor-table-derived sets (tests plant
    their own)."""
    ingested = timeline.ingest_repo(repo)
    if ingested["unknown"]:
        raise ValueError(
            f"unknown committed artifact famil(ies) — register a "
            f"timeline adapter for: {ingested['unknown']}")
    if ingested["unreadable"]:
        raise ValueError(
            f"unreadable/adapter-failed committed artifact(s) — a "
            f"corrupt gate artifact must be fixed, not skipped: "
            f"{ingested['unreadable']}")
    commits = resolve_commits(repo, ingested["coverage"])
    series = timeline.build_series(ingested["rows"], commits=commits)

    provisional, source = [], None
    if gated is None:
        gated, derived_bands, provisional, source = \
            gated_series_keys(series, repo)
        if bands is None:
            bands = derived_bands
    bands = bands or {}
    for key in gated:
        if key in series:
            series[key]["gated"] = True

    regressions = timeline.detect_regressions(
        series, gated, bands=bands, default_band=default_band)
    for row in regressions:
        family = row["series"].split("|", 1)[0]
        frm = commits.get((family, row["from_round"]))
        to = commits.get((family, row["drop_round"]))
        row["from_commit"] = frm
        row["drop_commit"] = to
        row["suspects"] = commits_between(repo, frm, to,
                                          limit=max_suspects) \
            if frm and to else []

    head = (_git(repo, "rev-parse", "--short", "HEAD") or "").strip() \
        or None
    doc = {
        "round": round_no,
        "head": head,
        "bands": {"default": default_band,
                  "source": source,
                  "per_series": {k: round(v, 4)
                                 for k, v in sorted(bands.items())}},
        "series": {k: series[k] for k in sorted(series)},
        "regressions": regressions,
        "coverage": ingested["coverage"],
        "unreadable": ingested["unreadable"],
        "provisional_floors": provisional,
        "gate": {"regressions": len(regressions),
                 "ok": not regressions},
        "note": (
            "Gated series = configs/kernels carrying published floors "
            "(bench.MFU_FLOORS / bench.DECODE_FLOORS rate+hbm_frac, "
            "kernel_bench.KERNEL_FLOORS roofline_frac).  Band = "
            "recorded rel_spread from the newest non-tiny "
            "BENCH_VARIANCE_r*.json entry when present, else the "
            "default (the lower edge of the documented ±2–4% chip-day "
            "variance).  provisional_floors are CPU-smoke-seeded gate "
            "entries with no on-chip measurement behind them — "
            "reported as unmeasured, not as floors."),
    }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=str(REPO))
    ap.add_argument("--emit-json", default=None,
                    metavar="TIMELINE_rN.json",
                    help="write the committed timeline artifact "
                         "(schema-validated before writing)")
    ap.add_argument("--band", type=float, default=timeline.DEFAULT_BAND,
                    help="default band width for gated series without "
                         "a variance entry")
    ap.add_argument("--gate", action="store_true",
                    help="exit 2 when the regression table is "
                         "non-empty (driver-round blocking mode)")
    ap.add_argument("--max-suspects", type=int, default=30)
    args = ap.parse_args(argv)

    round_no = 0
    if args.emit_json:
        m = re.search(r"_r(\d+)\.json$",
                      os.path.basename(args.emit_json))
        round_no = int(m.group(1)) if m else 0
    try:
        doc = build_timeline(args.repo, default_band=args.band,
                             round_no=round_no,
                             max_suspects=args.max_suspects)
    except ValueError as e:
        print(f"perf_timeline: LINT ERROR: {e}", file=sys.stderr)
        return 1

    for row in doc["regressions"]:
        suspects = ", ".join(s["commit"] for s in row["suspects"])
        print(f"REGRESSION {row['series']}: "
              f"{row['best_value']} (r{row['best_round']:02d}) -> "
              f"{row['newest_value']} (r{row['newest_round']:02d}), "
              f"-{row['drop_frac'] * 100:.2f}% > band "
              f"{row['band'] * 100:.1f}%; first dropped "
              f"r{row['drop_round']:02d}; suspects: {suspects}",
              file=sys.stderr)

    if args.emit_json:
        problems = timeline.validate_timeline(doc, repo_dir=args.repo)
        if problems:
            print(f"perf_timeline: REFUSING schema-invalid artifact: "
                  f"{problems}", file=sys.stderr)
            return 1
        with open(args.emit_json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"timeline artifact written: {args.emit_json} "
              f"({len(doc['series'])} series, "
              f"{len(doc['regressions'])} regression(s), "
              f"{len(doc['coverage'])} families)", file=sys.stderr)
    summary = {"series": len(doc["series"]),
               "families": sorted(doc["coverage"]),
               "regressions": doc["regressions"],
               "gate": doc["gate"]}
    print(json.dumps(summary))
    if args.gate and doc["regressions"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
