"""Profile the decode bench program and bucket measured device time
into the DECODE_DECOMPOSE named buckets.

``tools/decode_decompose.py`` *predicted* where the b8 decode step's
time goes by walking the lowered StableHLO (kv_read 0.69 of the ideal
step, plus a 709 MB residual matching the per-layer KV slice-copy
materialization).  This tool closes the loop with a MEASUREMENT: it
runs the exact same bench program (``generate._generate_impl`` at
gpt_small_tpu b8 — same lowering entry, same shapes), captures an
XProf trace, aggregates op-level device time through
:mod:`apex_tpu.obs.xplane`, and classifies every instruction of the
decode loop's while-body into the same seven buckets via a classifier
built from the compiled HLO (operand/result shape markers: the cache
pool, cache-slice materializations, the vocab dimension, the context
length).

Scope discipline: only instructions belonging to the decode while-loop
body (transitively through called computations) are bucketed — the
prefill forward and host/infra time are reported separately as
``non_step_ps`` so the bucket table stays comparable to the static
walk's per-token step.

On **CPU** (this environment; the tier-1 smoke) the capture has no
device plane — the xplane library harvests the host XLA executor
lines; times are thread-summed and say nothing about HBM, so the
artifact's verdict is explicitly "pipeline smoke".  On a **TPU** the
same invocation measures real device time and the verdict compares
the measured ``kv_read``/slice-copy share against the walk's 709 MB
residual attribution — the next driver round's one-command job:

    python tools/profile_decode.py --emit DECODE_PROFILE_r02.json

The emitted ``DECODE_PROFILE_r*.json`` is validated against
``apex_tpu/analysis/decode_profile.py`` (stdlib-only; gate hygiene
enforces it on committed copies) and refuses to write an invalid
document.

Usage:
    python tools/profile_decode.py [--batch 8] [--prefill 2048]
        [--new-tokens 256] [--tiny] [--iters 2]
        [--emit DECODE_PROFILE_rN.json] [--logdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("APEX_TPU_KERNELS", "jnp")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("APEX_TPU_TEST_PLATFORM", "cpu"))

import decode_decompose  # noqa: E402  (sibling tool: shared lowering)
from apex_tpu.analysis.decode_profile import BUCKETS  # noqa: E402
from apex_tpu.obs import xplane  # noqa: E402
# the compiled-HLO shape classifier lives in the obs library now (the
# continuous profiler runs the same bucketing online; one copy means
# the offline tool and the live sentinel can never disagree) — this
# tool only drives the capture and emits the artifact
from apex_tpu.obs.stepclass import StepClassifier  # noqa: E402


def build_and_run(batch: int, prefill: int, new_tokens: int,
                  tiny: bool, iters: int, logdir: str):
    """Lower/compile the exact bench decode program, run ``iters``
    captures, return ``(compiled, cfg, capture_source_dir)``."""
    lowered, cfg = decode_decompose.lower_decode(batch, prefill,
                                                 new_tokens, tiny=tiny)
    compiled = lowered.compile()
    # the lowering came from ShapeDtypeStructs; materialize zero-filled
    # arrays of those shapes (traffic, not token quality, is measured)
    in_args, in_kwargs = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        lowered.args_info,
        is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype"))
    out = compiled(*in_args, **in_kwargs)    # warm run outside capture
    jax.block_until_ready(out)
    shutil.rmtree(logdir, ignore_errors=True)   # stale planes would
    # double-count: the parser aggregates every file under the logdir
    with jax.profiler.trace(logdir):
        for _ in range(iters):
            out = compiled(*in_args, **in_kwargs)
        jax.block_until_ready(out)
    time.sleep(1.0)                          # let the trace flush
    return compiled, cfg


def profile(batch: int, prefill: int, new_tokens: int, tiny: bool,
            iters: int, logdir: str) -> dict:
    compiled, cfg = build_and_run(batch, prefill, new_tokens, tiny,
                                  iters, logdir)
    m_ctx = prefill + new_tokens
    clf = StepClassifier(compiled.as_text(), cfg, batch, m_ctx)
    times = xplane.op_times(logdir)
    step_ops = clf.step_ops()
    step_times = {n: ps for n, ps in times.by_op.items()
                  if n in step_ops}
    non_step_ps = times.total_ps - sum(step_times.values())
    table = xplane.bucket_op_times(step_times, clf,
                                   buckets=list(BUCKETS))
    slice_copy_ps = sum(ps for n, ps in step_times.items()
                       if n in clf.slice_copy_ops)

    platform = jax.devices()[0].platform
    fractions = {k: table["fractions"].get(k, 0.0) for k in BUCKETS}
    coverage = round(1.0 - fractions["other"], 4)

    ref = None
    ref_path = max(REPO.glob("DECODE_DECOMPOSE_r*.json"), default=None)
    if ref_path is not None:
        try:
            with open(ref_path) as f:
                ref_doc = json.load(f)
            ref = {"file": ref_path.name,
                   "device_time_fractions":
                       ref_doc.get("device_time_fractions"),
                   "residual_frac_of_step":
                       (ref_doc.get("gap_attribution") or {}).get(
                           "residual_frac_of_step")}
        except (OSError, ValueError):
            ref = None

    if platform == "cpu":
        verdict = (
            "CPU-xplane smoke: capture -> obs.xplane -> named buckets "
            "pipeline proven end-to-end on the exact bench decode "
            "program (thread-summed host-executor times; no HBM "
            "claim).  The on-chip capture that confirms or refutes "
            "the kv-slice-copy residual is the next driver round: "
            "run this tool unchanged on a TPU host with --emit "
            "DECODE_PROFILE_r02.json")
    else:
        kvr = fractions["kv_read"]
        want = None
        if ref and ref.get("device_time_fractions"):
            want = ref["device_time_fractions"].get("kv_read")
        comp = (f" vs the walk's ideal {want}" if want is not None
                else "")
        scf = slice_copy_ps / max(table["total_ps"], 1)
        verdict = (
            f"on-chip capture: measured kv_read fraction {kvr}{comp}; "
            f"materialized cache-slice ops carry {scf:.4f} of the "
            f"step — "
            + ("CONFIRMS the slice-copy attribution (residual-scale "
               "time in materialized cache-slice ops)" if scf >= 0.1
               else "REFUTES residual-scale slice-copy time; "
                    "re-attribute the decompose residual"))

    return {
        "round": 1,
        "platform": platform,
        "config": {"batch": batch, "prefill": prefill,
                   "new_tokens": new_tokens,
                   "model": "gpt_tiny" if tiny else "gpt_small_tpu"},
        "method": "xplane-capture",
        "capture": {"iters": iters, "total_ps": int(times.total_ps),
                    "step_ps": int(sum(step_times.values())),
                    "non_step_ps": int(non_step_ps),
                    "matched_frac": round(
                        table["matched_ps"]
                        / max(table["total_ps"], 1), 4),
                    "source": times.source,
                    "step_ops_profiled": len(step_times),
                    "step_ops_known": len(step_ops)},
        "device_time_ps": {k: int(table["bucket_ps"].get(k, 0))
                           for k in BUCKETS},
        "device_time_fractions": fractions,
        "coverage": coverage,
        "slice_copy": {"ps": int(slice_copy_ps),
                       "ops": len(clf.slice_copy_ops)},
        "decompose_ref": ref,
        "verdict": verdict,
        "note": (
            "Buckets cover ONLY the decode while-body's instructions "
            "(prefill + infra reported as non_step_ps) so the table "
            "reconciles bucket-by-bucket with the static walk "
            "(DECODE_DECOMPOSE).  Classifier: compiled-HLO shape "
            "markers; fusions classified by their dominant cache/"
            "weight/vocab content.  CPU captures harvest host XLA "
            "executor lines (thread-summed)."),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prefill", type=int, default=2048)
    ap.add_argument("--new-tokens", type=int, default=256)
    ap.add_argument("--tiny", action="store_true",
                    help="gpt_tiny config (tests / CPU smoke)")
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--logdir", default="/tmp/apex_tpu_profile_decode")
    ap.add_argument("--emit", default=None,
                    metavar="DECODE_PROFILE_rN.json",
                    help="write the committed artifact (validated "
                         "against apex_tpu/analysis/decode_profile.py; "
                         "refuses an invalid document)")
    opts = ap.parse_args(argv)

    doc = profile(opts.batch, opts.prefill, opts.new_tokens, opts.tiny,
                  opts.iters, opts.logdir)
    if opts.emit:
        m = re.search(r"_r(\d+)\.json$", os.path.basename(opts.emit))
        if m:
            doc["round"] = int(m.group(1))
        from apex_tpu.analysis import decode_profile as schema
        problems = schema.validate_profile(doc)
        if problems:
            print(f"refusing to write {opts.emit}: {problems}",
                  file=sys.stderr)
            return 1
        with open(opts.emit, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"decode profile written: {opts.emit}", file=sys.stderr)
    else:
        print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
