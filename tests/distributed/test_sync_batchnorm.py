"""SyncBatchNorm distributed tests.

Port of ``tests/distributed/synced_batchnorm/``: the single-device unit test
against a hand-rolled reference (``single_gpu_unit_test.py:94-145``), the
sharded-batch vs whole-batch comparison (``two_gpu_unit_test.py``, here
8-way), and group sub-partitioning (``test_groups.py``) — all on the virtual
CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import (
    SyncBatchNorm,
    create_syncbn_process_group,
    data_parallel_mesh,
    welford_parallel,
)
from apex_tpu.utils.jax_compat import shard_map

WORLD = 8
TOL = dict(rtol=1e-5, atol=1e-5)  # fp32 tolerance from two_gpu_unit_test.py


@pytest.fixture(scope="module")
def mesh():
    # first 8 devices only: the platform carries 16 virtual devices
    # (the disaggregated-serving fleet topology); the process groups
    # and batch shapes here are built for an 8-wide mesh
    return data_parallel_mesh(num_devices=8)


def ref_bn(x, ch_axis=-1, eps=1e-5):
    """Hand-rolled whole-batch reference (numpy)."""
    x = np.asarray(x, np.float32)
    axes = tuple(a for a in range(x.ndim) if a != (ch_axis % x.ndim))
    mean = x.mean(axis=axes, keepdims=True)
    var = x.var(axis=axes, keepdims=True)
    return (x - mean) / np.sqrt(var + eps), mean.squeeze(), var.squeeze()


def test_local_bn_matches_reference():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 6, 6, 4).astype(np.float32))
    bn = SyncBatchNorm(use_running_average=False)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    y, updated = bn.apply(vars_, x, mutable=["batch_stats"])
    ref_y, ref_mean, ref_var = ref_bn(x)
    np.testing.assert_allclose(np.asarray(y), ref_y, **TOL)
    # running stats after one step: (1-m)*init + m*batch, unbiased var
    n = 16 * 36
    m = 0.1
    np.testing.assert_allclose(
        np.asarray(updated["batch_stats"]["mean"]), m * ref_mean, **TOL)
    np.testing.assert_allclose(
        np.asarray(updated["batch_stats"]["var"]),
        (1 - m) * 1.0 + m * ref_var * n / (n - 1), **TOL)


def test_welford_parallel_merge():
    rng = np.random.RandomState(1)
    chunks = [rng.randn(5, 3).astype(np.float32) for _ in range(4)]
    means = jnp.asarray([c.mean(0) for c in chunks])
    vars_ = jnp.asarray([c.var(0) for c in chunks])
    counts = jnp.full((4, 1), 5.0)
    mean, var = welford_parallel(means, vars_, counts)
    full = np.concatenate(chunks, 0)
    np.testing.assert_allclose(np.asarray(mean), full.mean(0), **TOL)
    np.testing.assert_allclose(np.asarray(var), full.var(0), **TOL)


def test_sharded_batch_matches_whole_batch(mesh):
    """8-way batch shard == single-process whole batch
    (two_gpu_unit_test.py generalization)."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(WORLD * 4, 5, 5, 3).astype(np.float32))

    bn_sync = SyncBatchNorm(use_running_average=False, axis_name="data")
    bn_local = SyncBatchNorm(use_running_average=False)
    vars_ = bn_local.init(jax.random.PRNGKey(0), x)

    def fwd(v, xx):
        y, upd = bn_sync.apply(v, xx, mutable=["batch_stats"])
        return y, upd["batch_stats"]

    y_sh, stats_sh = shard_map(
        fwd, mesh=mesh, in_specs=(P(), P("data")),
        out_specs=(P("data"), P()))(vars_, x)
    y_ref, stats_ref = bn_local.apply(vars_, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref), **TOL)
    np.testing.assert_allclose(
        np.asarray(stats_sh["mean"]),
        np.asarray(stats_ref["batch_stats"]["mean"]), **TOL)
    np.testing.assert_allclose(
        np.asarray(stats_sh["var"]),
        np.asarray(stats_ref["batch_stats"]["var"]), **TOL)


def test_sync_bn_gradients_match_whole_batch(mesh):
    """Backward through the synced stats == whole-batch backward
    (the reference's two-stage reduce_bn/batchnorm_backward correctness)."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(WORLD * 2, 4, 3).astype(np.float32))
    bn_sync = SyncBatchNorm(use_running_average=False, axis_name="data")
    bn_local = SyncBatchNorm(use_running_average=False)
    vars_ = bn_local.init(jax.random.PRNGKey(0), x)

    def sharded_loss(v, xx):
        def inner(v, xb):
            y, _ = bn_sync.apply(v, xb, mutable=["batch_stats"])
            # psum the local loss so the total matches the whole-batch loss
            return jax.lax.psum(jnp.sum(jnp.sin(y)), "data")
        return shard_map(
            inner, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P())(v, xx)

    def whole_loss(v, xx):
        y, _ = bn_local.apply(v, xx, mutable=["batch_stats"])
        return jnp.sum(jnp.sin(y))

    g_sh = jax.grad(lambda v: sharded_loss(v, x))(vars_)
    g_ref = jax.grad(lambda v: whole_loss(v, x))(vars_)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_process_groups(mesh):
    """group_size=4 → two independent stat groups (test_groups.py)."""
    groups = create_syncbn_process_group(4, WORLD)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(WORLD * 2, 3).astype(np.float32))
    bn = SyncBatchNorm(use_running_average=False, axis_name="data",
                       process_group=groups)
    bn_local = SyncBatchNorm(use_running_average=False)
    vars_ = bn_local.init(jax.random.PRNGKey(0), x)

    def fwd(v, xx):
        y, _ = bn.apply(v, xx, mutable=["batch_stats"])
        return y

    y = shard_map(fwd, mesh=mesh, in_specs=(P(), P("data")),
                      out_specs=P("data"))(vars_, x)
    # Each half of the batch normalized with its own group's stats.
    y_ref0, _, _ = ref_bn(np.asarray(x)[:8])
    y_ref1, _, _ = ref_bn(np.asarray(x)[8:])
    np.testing.assert_allclose(np.asarray(y)[:8], y_ref0, **TOL)
    np.testing.assert_allclose(np.asarray(y)[8:], y_ref1, **TOL)


def test_process_group_gradients_match_per_group_reference(mesh):
    """Backward through GROUPED stats == per-group whole-batch backward —
    pins the hand-written grouped collectives in _bn_train_bwd (group
    all_gather+mean for mean_dy/mean_dy_xmu, full-axis psum for gw/gb)."""
    groups = create_syncbn_process_group(4, WORLD)
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(WORLD * 2, 3).astype(np.float32))
    bn = SyncBatchNorm(use_running_average=False, axis_name="data",
                       process_group=groups)
    bn_local = SyncBatchNorm(use_running_average=False)
    vars_ = bn_local.init(jax.random.PRNGKey(0), x)

    def sharded_loss(v, xx):
        def inner(v, xb):
            y, _ = bn.apply(v, xb, mutable=["batch_stats"])
            return jax.lax.psum(jnp.sum(jnp.sin(y)), "data")
        return shard_map(inner, mesh=mesh,
                             in_specs=(P(), P("data")),
                             out_specs=P())(v, xx)

    def grouped_ref_loss(v, xx):
        # Each group is an independent whole-batch BN over its half.
        total = 0.0
        for half in (xx[:8], xx[8:]):
            y, _ = bn_local.apply(v, half, mutable=["batch_stats"])
            total = total + jnp.sum(jnp.sin(y))
        return total

    g_sh = jax.grad(lambda v: sharded_loss(v, x))(vars_)
    g_ref = jax.grad(lambda v: grouped_ref_loss(v, x))(vars_)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_group_validation():
    with pytest.raises(ValueError):
        create_syncbn_process_group(3, WORLD)
    with pytest.raises(ValueError):
        create_syncbn_process_group(16, WORLD)
    assert create_syncbn_process_group(0, WORLD) is None


def test_eval_uses_running_stats():
    x = jnp.ones((4, 3)) * 5.0
    bn = SyncBatchNorm(use_running_average=True)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    y = bn.apply(vars_, x)
    # running mean 0, var 1 → y == x
    np.testing.assert_allclose(np.asarray(y), 5.0, rtol=1e-3)


def test_channels_first_layout():
    """The reference needed separate NCHW kernels; here channel_axis=1."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 3, 6, 6).astype(np.float32))
    bn = SyncBatchNorm(use_running_average=False, channel_axis=1)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(vars_, x, mutable=["batch_stats"])
    ref_y, _, _ = ref_bn(x, ch_axis=1)
    np.testing.assert_allclose(np.asarray(y), ref_y, **TOL)


def test_fp16_running_buffers():
    x = jnp.asarray(np.random.RandomState(6).randn(8, 4).astype(np.float32))
    bn = SyncBatchNorm(use_running_average=False, running_dtype=jnp.bfloat16)
    vars_ = bn.init(jax.random.PRNGKey(0), x)
    _, upd = bn.apply(vars_, x, mutable=["batch_stats"])
    assert upd["batch_stats"]["mean"].dtype == jnp.bfloat16


def test_reduce_bn_backward_blocks_match_autodiff():
    """The exported backward split (reduce_bn → batchnorm_backward,
    welford.cu:323-411) must equal autodiff's grad_input for a local BN."""
    from apex_tpu.parallel import (batchnorm_backward, batchnorm_forward,
                                   reduce_bn, welford_mean_var)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(8, 5, 5, 3).astype(np.float32))
    w = jnp.asarray(rng.rand(3).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(3).astype(np.float32))
    dy = jnp.asarray(rng.randn(8, 5, 5, 3).astype(np.float32))
    eps = 1e-5

    def fwd(x):
        mean, var, _ = welford_mean_var(x, (0, 1, 2))
        invstd = jax.lax.rsqrt(var + eps)
        return batchnorm_forward(x, mean, invstd, w, b, -1)

    _, vjp = jax.vjp(fwd, x)
    (auto_gi,) = vjp(dy)

    mean, var, _ = welford_mean_var(x, (0, 1, 2))
    invstd = jax.lax.rsqrt(var + eps)
    mean_dy, mean_dy_xmu, gw, gb = reduce_bn(dy, x, mean, invstd, w, -1)
    gi = batchnorm_backward(dy, x, mean, invstd, w,
                            mean_dy, mean_dy_xmu, -1)
    np.testing.assert_allclose(np.asarray(gi), np.asarray(auto_gi),
                               rtol=1e-4, atol=1e-4)

    # grad_weight / grad_bias against autodiff on (w, b) with stats fixed
    def fwd_wb(w_, b_):
        return batchnorm_forward(x, mean, invstd, w_, b_, -1)
    _, vjp_wb = jax.vjp(fwd_wb, w, b)
    auto_gw, auto_gb = vjp_wb(dy)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(auto_gw),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(auto_gb),
                               rtol=1e-4, atol=1e-4)


def test_c_last_aliases_match_generic():
    from apex_tpu.parallel import (batchnorm_forward_c_last,
                                   welford_mean_var_c_last)
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(4, 3, 3, 5).astype(np.float32))
    mean, var, count = welford_mean_var_c_last(x)
    assert count == 4 * 9
    invstd = jax.lax.rsqrt(var + 1e-5)
    y = batchnorm_forward_c_last(x, mean, invstd, None, None)
    ref_y, _, _ = ref_bn(x)
    np.testing.assert_allclose(np.asarray(y), ref_y, **TOL)


class TestFusedBackwardFlag:
    """fused_backward=False (plain autodiff) must match the hand-written
    two-stage backward exactly in total derivative, locally and across a
    mesh axis; it is rejected with BN sub-groups (grouped gathered stats
    have no VMA-checkable transpose)."""

    def _grads(self, fused, axis_name=None):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 4, 6))
        bn = SyncBatchNorm(axis_name=axis_name, fused_backward=fused)
        v = bn.init(jax.random.PRNGKey(1), x, use_running_average=False)

        def loss(params, xin):
            def fwd(p, xb):
                y, _ = bn.apply(
                    {"params": p, "batch_stats": v["batch_stats"]}, xb,
                    use_running_average=False, mutable=["batch_stats"])
                return jnp.sum((y.astype(jnp.float32)) ** 2)
            if axis_name is None:
                return fwd(params, xin)
            mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]),
                                     (axis_name,))
            return shard_map(
                lambda p, xb: jax.lax.pmean(fwd(p, xb), axis_name),
                mesh=mesh, in_specs=(P(), P(axis_name)),
                out_specs=P())(params, xin)

        return jax.grad(loss, argnums=(0, 1))(v["params"], x)

    @pytest.mark.parametrize("axis_name", [None, "data"])
    def test_autodiff_matches_fused(self, axis_name):
        g_fused = self._grads(True, axis_name)
        g_auto = self._grads(False, axis_name)
        for a, b in zip(jax.tree.leaves(g_fused), jax.tree.leaves(g_auto)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_grouped_sync_rejects_autodiff_backward(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 4, 6))
        bn = SyncBatchNorm(axis_name="data",
                           process_group=((0, 1), (2, 3)),
                           fused_backward=False)
        v = bn.init(jax.random.PRNGKey(1), x, use_running_average=False)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
        with pytest.raises(ValueError, match="process_group"):
            shard_map(
                lambda p, xb: bn.apply(
                    {"params": p, "batch_stats": v["batch_stats"]}, xb,
                    use_running_average=False, mutable=["batch_stats"])[0],
                mesh=mesh, in_specs=(P(), P("data")),
                out_specs=P("data"))(v["params"], x)
