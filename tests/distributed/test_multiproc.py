"""Real multi-process launch test for the multiproc spawner.

The reference could only validate its launcher on a multi-GPU rig
(``tests/distributed/*/run*.sh`` via ``torch.distributed.launch``).  Here the
spawner launches two CPU-backend processes that form a real
``jax.distributed`` cluster and run a cross-process ``psum`` — exercising
``initialize``'s env contract, the rank-0-stdout convention, and the worker
log files (reference ``multiproc.py:22-35``).
"""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])

WORKER = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu.parallel import multiproc
    multiproc.initialize()   # picks up COORDINATOR_ADDRESS/WORLD_SIZE/RANK
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(jax.devices(), ("data",))
    f = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P())
    x = jnp.asarray([float(jax.process_index() + 1)] * len(jax.devices()))
    # global x = [1., 2.]; psum = 3 on every rank
    print("RANK", jax.process_index(), "PSUM", float(f(x)[0]), flush=True)
""")


@pytest.mark.skipif(os.environ.get("APEX_TPU_TEST_PLATFORM") not in (None, "cpu"),
                    reason="local spawner test runs on the CPU backend")
def test_spawn_two_process_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ, WORLD_SIZE="2",
               PYTHONPATH=REPO_ROOT + ":" + os.environ.get("PYTHONPATH", ""))
    # drop the single-process test config so workers form their own cluster
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", str(script)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    # rank 0 inherited the launcher's stdout
    assert "RANK 0 PSUM 3.0" in out.stdout, out.stdout
    # rank 1 logged to PROC_1.log (the reference's GPU_<i>.log convention)
    log = (tmp_path / "PROC_1.log").read_text()
    assert "RANK 1 PSUM 3.0" in log, log
