"""Real multi-process launch test for the multiproc spawner.

The reference could only validate its launcher on a multi-GPU rig
(``tests/distributed/*/run*.sh`` via ``torch.distributed.launch``).  Here the
spawner launches two CPU-backend processes that form a real
``jax.distributed`` cluster and run a cross-process ``psum`` — exercising
``initialize``'s env contract, the rank-0-stdout convention, and the worker
log files (reference ``multiproc.py:22-35``).
"""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])

WORKER = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    # the CPU backend only runs cross-process computations through the
    # gloo collectives implementation
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from apex_tpu.parallel import multiproc
    multiproc.initialize()   # picks up COORDINATOR_ADDRESS/WORLD_SIZE/RANK
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(jax.devices(), ("data",))
    f = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P())
    x = jnp.asarray([float(jax.process_index() + 1)] * len(jax.devices()))
    # global x = [1., 2.]; psum = 3 on every rank
    print("RANK", jax.process_index(), "PSUM", float(f(x)[0]), flush=True)
""")


class _Boom(RuntimeError):
    pass


def test_initialize_retries_with_backoff_then_names_missing_ranks(monkeypatch):
    """Bounded cluster init (ISSUE 3 satellite): a peer that never
    arrives must surface as ClusterInitError naming the candidate
    missing ranks after timeout x retries with backoff — not a hang."""
    from apex_tpu.parallel import multiproc

    calls = {"n": 0}
    sleeps = []

    def never_forms(coordinator_address=None, num_processes=None,
                    process_id=None, initialization_timeout=None):
        calls["n"] += 1
        # bounded per-attempt: the timeout knob must be threaded through
        # (initialize feature-detects it from this signature)
        assert initialization_timeout == 1
        raise _Boom("barrier timed out")

    monkeypatch.setattr(jax_distributed(), "initialize", never_forms)
    monkeypatch.setattr(multiproc.time, "sleep", sleeps.append)
    with pytest.raises(multiproc.ClusterInitError) as ei:
        multiproc.initialize(coordinator_address="localhost:1",
                             num_processes=4, process_id=1,
                             timeout_s=1.0, retries=2, backoff_s=0.5)
    msg = str(ei.value)
    assert "rank 1 of 4" in msg
    assert "[0, 2, 3]" in msg            # the ranks that can be missing
    assert "3 attempt(s)" in msg
    assert calls["n"] == 3
    assert sleeps == [0.5, 1.0]          # exponential backoff


def test_initialize_env_tunable_and_succeeds_mid_retry(monkeypatch):
    from apex_tpu.parallel import multiproc

    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("peer not yet up")

    monkeypatch.setattr(jax_distributed(), "initialize", flaky)
    monkeypatch.setattr(multiproc.time, "sleep", lambda s: None)
    monkeypatch.setenv("APEX_TPU_INIT_TIMEOUT_S", "7")
    monkeypatch.setenv("APEX_TPU_INIT_RETRIES", "5")
    monkeypatch.setenv("APEX_TPU_INIT_BACKOFF_S", "0.01")
    multiproc.initialize(coordinator_address="localhost:1",
                         num_processes=2, process_id=0)
    assert calls["n"] == 3               # recovered on the third attempt


def test_initialize_already_initialized_fails_fast(monkeypatch):
    """A double-initialize is a programming error, not weather: no
    retries, no backoff, no phantom missing-peer report."""
    from apex_tpu.parallel import multiproc

    calls = {"n": 0}

    def double(**kwargs):
        calls["n"] += 1
        raise RuntimeError("jax.distributed is already initialized")

    monkeypatch.setattr(jax_distributed(), "initialize", double)
    monkeypatch.setattr(multiproc.time, "sleep",
                        lambda s: pytest.fail("must not back off"))
    with pytest.raises(RuntimeError, match="already initialized"):
        multiproc.initialize(coordinator_address="localhost:1",
                             num_processes=2, process_id=0,
                             timeout_s=1.0, retries=5, backoff_s=9.0)
    assert calls["n"] == 1


def jax_distributed():
    import jax
    return jax.distributed


def test_spawn_reaps_zombie_peer_after_grace(tmp_path, monkeypatch):
    """Zombie-peer reaping (ISSUE 18 satellite): one rank exits clean,
    its peer wedges forever in a collective whose partner is gone —
    spawn must terminate the straggler within the grace window and
    raise a ClusterInitError naming it, not hang the launcher until
    test teardown."""
    import time

    from apex_tpu.parallel import multiproc

    script = tmp_path / "wedge.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ["RANK"] == "0":
            sys.exit(0)
        time.sleep(300)   # deliberately wedged: the peer is gone forever
    """))
    monkeypatch.chdir(tmp_path)           # PROC_*.log land in tmp
    monkeypatch.setenv("APEX_TPU_SPAWN_GRACE_S", "2")
    t0 = time.monotonic()
    with pytest.raises(multiproc.ClusterInitError) as ei:
        multiproc.spawn([str(script)], world_size=2)
    assert time.monotonic() - t0 < 60     # reaped within budget, no hang
    msg = str(ei.value)
    assert "ranks [1]" in msg
    assert "wedged" in msg
    assert "rank 0 exited cleanly" in msg


@pytest.mark.skipif(os.environ.get("APEX_TPU_TEST_PLATFORM") not in (None, "cpu"),
                    reason="local spawner test runs on the CPU backend")
def test_spawn_two_process_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ, WORLD_SIZE="2",
               PYTHONPATH=REPO_ROOT + ":" + os.environ.get("PYTHONPATH", ""))
    # drop the single-process test config so workers form their own cluster
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", str(script)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    # rank 0 inherited the launcher's stdout
    assert "RANK 0 PSUM 3.0" in out.stdout, out.stdout
    # rank 1 logged to PROC_1.log (the reference's GPU_<i>.log convention)
    log = (tmp_path / "PROC_1.log").read_text()
    assert "RANK 1 PSUM 3.0" in log, log
