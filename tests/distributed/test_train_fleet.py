"""Elastic training fleet (ISSUE 18) — the unit bars under the chaos
drill: the ledger's atomic/exclusive coordination files, the heartbeat
lease, the membership gate's shrink/regrow/plan detection, the
absolute-step checkpoint adapter, the digest contract that makes the
drill's bitwise audit possible, the ``train_fleet_*`` metric family at
``run_resilient``'s lag-resolved boundary, and the 8→4→8 mesh-reshape
round-trip of full amp-O4 state (optimizer moments, scaler, fp8
delayed-scaling state) with a passing post-restore SPMD preflight.

The real 2-process SIGKILL drill itself (``tools/train_fleet.py``)
rides the ``slow`` marker; its committed artifact is re-validated every
tier-1 run through ``tools/gate_hygiene.py``.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (DurableCheckpointManager, FleetConfig,
                                 FleetLedger, FleetMembershipChange,
                                 FleetMetrics, HeartbeatLease, RankKill,
                                 ResilienceConfig, latest_verified_step,
                                 membership_gate, run_resilient,
                                 snapshot_digest, state_digest)
from apex_tpu.resilience import fleet as fleet_mod

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


# ---------------------------------------------------------------------------
# ledger: atomic writes, exclusive plans, incarnations
# ---------------------------------------------------------------------------

def test_plan_write_is_exclusive_first_writer_wins(tmp_path):
    """Exactly one concurrent leader commits a generation plan: the
    O_EXCL link makes the second write a no-op returning False, and
    readers see the winner."""
    led = FleetLedger(str(tmp_path))
    won = led.write_plan({"gen": 1, "members": [0], "restore_step": 7})
    lost = led.write_plan({"gen": 1, "members": [0, 1], "restore_step": 3})
    assert won is True and lost is False
    assert led.read_plan(1)["members"] == [0]
    assert led.latest_plan()["gen"] == 1


def test_announce_increments_incarnation(tmp_path):
    """A relaunched supervisor re-announces with a bumped incarnation —
    the token that keeps it from adopting a plan written for its
    previous life."""
    led = FleetLedger(str(tmp_path))
    assert led.announce(0) == 0
    assert led.announce(1) == 0
    assert led.announce(1) == 1          # rank 1 came back
    assert led.incarnation(0) == 0
    assert led.incarnation(1) == 1
    assert sorted(led.announced()) == [0, 1]


def test_heartbeat_lease_fresh_then_stale(tmp_path):
    """The lease thread keeps the rank fresh while running; once
    stopped the lease ages past the TTL — liveness without ever
    touching a collective."""
    led = FleetLedger(str(tmp_path))
    led.announce(0)
    with HeartbeatLease(led, 0, interval_s=0.05,
                        info_fn=lambda: {"step": 3}):
        time.sleep(0.25)
        assert led.fresh(0, ttl_s=0.5)
        assert led.read_heartbeat(0)["step"] == 3
        assert led.live_ranks(ttl_s=0.5) == [0]
    time.sleep(0.3)
    assert not led.fresh(0, ttl_s=0.2)
    assert led.live_ranks(ttl_s=0.2) == []


def test_event_log_is_ordered_and_typed(tmp_path):
    led = FleetLedger(str(tmp_path))
    led.event(0, "kill", step=10)
    led.event(1, "restore", step=7)
    kinds = [e["kind"] for e in led.events()]
    assert kinds == ["kill", "restore"]
    assert all("utc" in e and "ts" in e for e in led.events())


# ---------------------------------------------------------------------------
# the membership gate
# ---------------------------------------------------------------------------

def _gate_cfg():
    # poll_s=0 disables throttling so every gate() call scans the ledger
    return FleetConfig(world_size=2, lease_ttl_s=0.2, poll_s=0.0)


def test_gate_raises_shrink_when_member_lease_stale(tmp_path):
    led = FleetLedger(str(tmp_path))
    led.announce(0), led.announce(1)
    led.heartbeat(0)                      # rank 1 never beats: dead
    seen = []
    gate = membership_gate(led, _gate_cfg(),
                           {"gen": 0, "members": [0, 1]}, rank=0,
                           on_change=lambda *a: seen.append(a))
    with pytest.raises(FleetMembershipChange) as ei:
        gate(11)
    assert ei.value.reason == "shrink"
    assert ei.value.ranks == [1] and ei.value.step == 11
    assert seen == [("shrink", [1], 11)]


def test_gate_raises_regrow_when_nonmember_lease_appears(tmp_path):
    led = FleetLedger(str(tmp_path))
    led.announce(0), led.heartbeat(0)
    gate = membership_gate(led, _gate_cfg(),
                           {"gen": 1, "members": [0]}, rank=0)
    gate(5)                               # alone: no change
    led.announce(1), led.heartbeat(1)     # the killed rank returns
    with pytest.raises(FleetMembershipChange) as ei:
        gate(6)
    assert ei.value.reason == "regrow" and ei.value.ranks == [1]


def test_gate_raises_on_newer_plan(tmp_path):
    led = FleetLedger(str(tmp_path))
    led.announce(0), led.heartbeat(0)
    gate = membership_gate(led, _gate_cfg(),
                           {"gen": 0, "members": [0]}, rank=0)
    led.write_plan({"gen": 1, "members": [0], "restore_step": 3})
    with pytest.raises(FleetMembershipChange) as ei:
        gate(4)
    assert ei.value.reason == "plan"


def test_gate_throttles_ledger_scans(tmp_path):
    """With a real poll interval the gate is nearly free: between polls
    it must not scan the ledger (a dead peer still raises at the NEXT
    poll — detection latency is lease_ttl + poll, not zero)."""
    led = FleetLedger(str(tmp_path))
    led.announce(0), led.heartbeat(0)
    cfg = FleetConfig(world_size=2, lease_ttl_s=0.2, poll_s=30.0)
    gate = membership_gate(led, cfg, {"gen": 0, "members": [0, 1]},
                           rank=0)
    with pytest.raises(FleetMembershipChange):
        gate(0)                           # first call always scans
    gate(1)                               # inside the poll window: silent


# ---------------------------------------------------------------------------
# absolute-step translation + fault parsing
# ---------------------------------------------------------------------------

class _FakeInner:
    def __init__(self):
        self.saved = []
        self.last_restore = None

    def save(self, step, state, extras=None):
        self.saved.append(step)

    def all_steps(self):
        return [3, 7, 11]

    def restore(self, template, step=None, extras=None):
        self.last_restore = {"step": 11 if step is None else step,
                             "skipped": []}
        return template, {}

    def wait(self):
        pass

    def close(self):
        pass


def test_step_offset_manager_translates_to_absolute_steps():
    inner = _FakeInner()
    mgr = fleet_mod._StepOffsetManager(inner, start=7)
    mgr.save(0, None)
    mgr.save(4, None)
    assert inner.saved == [7, 11]         # abs = start + local
    assert mgr.all_steps() == [0, 4]      # steps before start invisible
    mgr.restore(None, step=4)
    assert inner.last_restore["step"] == 11
    assert mgr.last_restore["step"] == 4  # translated back for the loop


def test_parse_fleet_faults_shift_and_vocabulary():
    out = fleet_mod._parse_fleet_faults(
        ["rank_kill@10:1", "rank_kill@3"], start=7)
    assert out == [RankKill(step=3, rank=1)]   # 10-7=3; step 3 < 7 dropped
    with pytest.raises(ValueError, match="not supported in the fleet"):
        fleet_mod._parse_fleet_faults(["nan_storm@5"], start=0)


# ---------------------------------------------------------------------------
# digest contract + pinned-step restore
# ---------------------------------------------------------------------------

def _tiny_state(steps=0, opt_level="O2"):
    params = {"w1": jax.random.normal(jax.random.PRNGKey(0), (4, 8)),
              "w2": jax.random.normal(jax.random.PRNGKey(1), (8, 4))}
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level=opt_level,
                       verbosity=0)
    step = jax.jit(amp.make_train_step(
        a, lambda p, xb: jnp.mean(jnp.square(
            jax.nn.relu(xb @ p["w1"]) @ p["w2"] - xb))))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4))
    state = a.init(params)
    for _ in range(steps):
        state, _ = step(state, x)
    return a, step, state, x


def test_state_digest_equals_snapshot_digest(tmp_path):
    """The drill's whole bitwise audit rides this: an in-memory state's
    digest equals the manifest-only digest of its committed snapshot,
    and a different state's does not."""
    _a, _step, state, _x = _tiny_state(steps=2)
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(3, state)
    mgr.wait()
    assert latest_verified_step(str(tmp_path)) == 3
    assert snapshot_digest(str(tmp_path), 3) == state_digest(state)
    _a2, step2, other, x2 = _tiny_state(steps=2)
    other, _ = step2(other, x2)           # one more step: different state
    assert state_digest(other) != state_digest(state)
    mgr.close()


def test_load_snapshot_state_restores_the_pinned_step(tmp_path):
    """Every member restores THE step its plan names — never "my
    newest", which async saves can skew across ranks."""
    a, step, state, x = _tiny_state(steps=1)
    mgr = DurableCheckpointManager(str(tmp_path), max_to_keep=4)
    mgr.save(1, state)
    later, _ = step(state, x)
    mgr.save(2, later)
    mgr.wait()
    got, _extras = fleet_mod.load_snapshot_state(
        str(tmp_path), 1, a.init({"w1": np.zeros((4, 8), np.float32),
                                  "w2": np.zeros((8, 4), np.float32)}))
    assert state_digest(got) == state_digest(state)
    assert state_digest(got) != state_digest(later)
    mgr.close()


def test_latest_verified_step_skips_corrupt_newest(tmp_path):
    a, step, state, x = _tiny_state(steps=1)
    mgr = DurableCheckpointManager(str(tmp_path), max_to_keep=4)
    mgr.save(1, state)
    later, _ = step(state, x)
    mgr.save(2, later)
    mgr.wait()
    mgr.close()
    # truncate a leaf of the newest snapshot: the plan must pin step 1
    from apex_tpu.resilience import durable
    step2_dir = tmp_path / durable._step_dirname(2)
    victim = next(p for p in step2_dir.iterdir()
                  if p.suffix == ".npy")
    victim.write_bytes(victim.read_bytes()[:10])
    assert latest_verified_step(str(tmp_path)) == 1


# ---------------------------------------------------------------------------
# the train_fleet_* metric family (satellite: run_resilient boundary)
# ---------------------------------------------------------------------------

def _metric(snap, name):
    return next(m for m in snap["metrics"] if m["name"] == name)


def test_fleet_metrics_family_shapes_and_counts():
    from apex_tpu.obs.metrics import Registry
    reg = Registry()
    fm = FleetMetrics(reg, active_ranks=2)
    fm.on_preemption()
    fm.on_recovery(1.5)
    fm.on_rewind()
    fm.set_active(1)
    fm.on_resolve()
    snap = reg.snapshot()
    assert _metric(snap, "train_fleet_active_ranks")["value"] == 1.0
    assert _metric(snap, "train_fleet_preemptions_total")["value"] == 1.0
    assert _metric(snap, "train_fleet_recoveries_total")["value"] == 1.0
    assert _metric(snap, "train_fleet_rewinds_total")["value"] == 1.0
    hist = _metric(snap, "train_fleet_recovery_seconds")
    assert hist["count"] == 1 and hist["sum"] == 1.5


def test_run_resilient_emits_fleet_metrics_at_resolve_boundary():
    """The loop re-asserts the active-ranks gauge at its existing
    lag-resolved boundary (a host int — no device read), and the
    instrumented step itself stays syncs-clean: fleet metrics ride the
    boundary the observability PR already paid for."""
    from apex_tpu import analysis
    from apex_tpu.obs.metrics import Registry

    a, step, state, x = _tiny_state()
    reg = Registry()
    fm = FleetMetrics(reg, active_ranks=2)
    result = run_resilient(
        step, state, lambda i: (x,), 4, amp_obj=a,
        config=ResilienceConfig(checkpoint_every=0,
                                watchdog_timeout_s=60.0),
        registry=reg, fleet_metrics=fm)
    assert result.steps_completed == 4
    snap = reg.snapshot()
    assert _metric(snap, "train_fleet_active_ranks")["value"] == 2.0
    assert _metric(snap, "train_fleet_rewinds_total")["value"] == 0.0
    # the step the loop dispatched carries no host callback / sync
    rep = analysis.analyze(step, state, x, passes=("syncs",))
    assert rep.ok, rep.format()


# ---------------------------------------------------------------------------
# satellite: shrink→regrow checkpoint round-trip across mesh sizes
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 devices (virtual CPU mesh)")
def test_shrink_regrow_roundtrip_8_4_8_bitwise_with_preflight(tmp_path):
    """The fleet's storage story end-to-end on one host: train amp-O4
    (fp8 delayed-scaling state included) replicated over an 8-device
    mesh, checkpoint, "shrink" onto a 4-device mesh via the fleet's
    pinned-step restore with every leaf bitwise (masters, moments,
    scaler, fp8 amax history), train on, checkpoint, "regrow" back onto
    8 devices bitwise again — and the post-restore SPMD preflight
    passes on the regrown lowering."""
    from apex_tpu.parallel.multiproc import spmd_preflight

    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O4",
                       verbosity=0)
    params = {"w1": jax.random.normal(jax.random.PRNGKey(0), (8, 16)),
              "w2": jax.random.normal(jax.random.PRNGKey(1), (16, 8))}
    step = jax.jit(amp.make_train_step(
        a, lambda p, xb: jnp.mean(jnp.square(
            jax.nn.relu(xb @ p["w1"]) @ p["w2"] - xb))))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 8))

    def mesh(n):
        return Mesh(np.array(jax.devices()[:n]), ("data",))

    def replicated(tree, m):
        sh = NamedSharding(m, P())
        return jax.tree.map(lambda t: jax.device_put(t, sh), tree)

    def batch(m):
        return jax.device_put(x, NamedSharding(m, P("data")))

    def host(tree):
        return jax.tree.map(np.asarray, tree)

    def assert_bitwise(got, want, msg):
        for (pa, la), (_pb, lb) in zip(
                jax.tree_util.tree_leaves_with_path(host(got)),
                jax.tree_util.tree_leaves_with_path(host(want))):
            np.testing.assert_array_equal(
                la, lb, err_msg=f"{msg}: {jax.tree_util.keystr(pa)}")

    mesh8, mesh4 = mesh(8), mesh(4)
    state = replicated(a.init(params), mesh8)
    assert state.fp8_state is not None
    # drive one overflow so the scaler state moves off its init too
    x_bad = batch(mesh8).at[0, 0].set(jnp.inf)
    state, m = step(state, x_bad)
    assert bool(m["overflow"])
    for _ in range(2):
        state, _ = step(state, batch(mesh8))

    mgr = DurableCheckpointManager(str(tmp_path), max_to_keep=4)
    mgr.save(3, state)
    mgr.wait()
    assert latest_verified_step(str(tmp_path)) == 3
    assert snapshot_digest(str(tmp_path), 3) == state_digest(state)

    # -- shrink: restore the pinned step onto the 4-device mesh ---------
    tmpl4 = replicated(a.init(params), mesh4)
    state4, _ = fleet_mod.load_snapshot_state(str(tmp_path), 3, tmpl4)
    assert_bitwise(state4, state, "4-dev restore vs saved")
    w1 = state4.master_params["w1"]
    assert len(w1.sharding.device_set) == 4
    assert float(state4.scaler_states[0].loss_scale) == \
        float(state.scaler_states[0].loss_scale)
    for _ in range(2):
        state4, _ = step(state4, batch(mesh4))
    mgr.save(5, state4)
    mgr.wait()

    # -- regrow: restore the shrunken run's snapshot onto 8 devices -----
    tmpl8 = replicated(a.init(params), mesh8)
    state8, _ = fleet_mod.load_snapshot_state(str(tmp_path), 5, tmpl8)
    assert_bitwise(state8, state4, "8-dev regrow restore vs 4-dev state")
    assert len(state8.master_params["w1"].sharding.device_set) == 8
    assert state_digest(state8) == snapshot_digest(str(tmp_path), 5)

    # -- the post-restore preflight the fleet runs after every re-form --
    rec = spmd_preflight(step.lower(state8, batch(mesh8)),
                         label="fleet_regrow")
    assert rec["ok"] and rec["schedule_hash"]
    # ...and training actually continues on the regrown mesh
    state8, m8 = step(state8, batch(mesh8))
    assert np.isfinite(float(m8["loss"]))
    mgr.close()


# ---------------------------------------------------------------------------
# replan leadership (review round): a returning minimum rank must not
# deadlock the regrow, and a dead generation must not strand a joiner
# ---------------------------------------------------------------------------

def _plan(gen, members, **kw):
    return {"gen": gen, "members": members, "port": 1,
            "restore_step": None, "reason": "initial",
            "created_by": members[0], "created_ts": time.time(),
            "incarnations": {str(r): 0 for r in members}, **kw}


def test_replan_leader_is_surviving_member_not_returning_min_rank(tmp_path):
    """Kill rank 0 and let it return: the regrow replan must be led by
    the SURVIVING member (rank 1), not by bare min(live)=0 — the
    returning rank sits in supervise's joiner branch and never writes
    plans, so electing it would leave the survivor waiting
    replan_window_s for a plan that cannot appear."""
    led = FleetLedger(str(tmp_path))
    cfg = FleetConfig(world_size=2, lease_ttl_s=5.0, poll_s=0.01,
                      replan_window_s=10.0)
    assert led.write_plan(_plan(0, [0, 1]))
    assert led.write_plan(_plan(1, [1], reason="shrink"))
    led.announce(0), led.heartbeat(0)     # rank 0 is back: lease fresh
    led.announce(1), led.heartbeat(1)
    t0 = time.monotonic()
    plan = fleet_mod._await_next_plan(led, cfg, rank=1, gen=1)
    # member preference decided immediately — not via the grace fallback
    assert time.monotonic() - t0 < cfg.replan_window_s / 2
    assert plan["gen"] == 2
    assert plan["members"] == [0, 1]
    assert plan["reason"] == "regrow"
    assert plan["created_by"] == 1


def test_replan_grace_lets_waiting_member_pass_a_stalled_leader(tmp_path):
    """The elected member (min live member) can itself be wedged while
    its supervisor lease stays fresh: after half the replan window any
    waiting member commits the plan itself (O_EXCL arbitrates), so the
    fleet replans instead of timing out."""
    led = FleetLedger(str(tmp_path))
    cfg = FleetConfig(world_size=2, lease_ttl_s=10.0, poll_s=0.02,
                      replan_window_s=2.0)
    assert led.write_plan(_plan(0, [0, 1]))
    led.announce(0), led.heartbeat(0)     # leader rank 0: fresh, silent
    led.announce(1), led.heartbeat(1)
    t0 = time.monotonic()
    plan = fleet_mod._await_next_plan(led, cfg, rank=1, gen=0)
    assert time.monotonic() - t0 >= cfg.replan_window_s / 2 - 0.1
    assert plan["created_by"] == 1 and plan["reason"] == "reform"
    assert plan["members"] == [0, 1]


def test_joiner_takes_over_only_when_every_member_lease_is_stale(tmp_path):
    """A joiner polling a generation whose members ALL crashed (every
    lease stale, nobody left in _await_next_plan) commits the next
    plan itself instead of waiting forever; while any member is fresh
    it stays a polite joiner."""
    led = FleetLedger(str(tmp_path))
    cfg = FleetConfig(lease_ttl_s=0.2, poll_s=0.0)
    led.announce(0), led.heartbeat(0)
    led.announce(1)
    plan = _plan(0, [0])
    assert led.write_plan(plan)
    led.heartbeat(1)
    assert not fleet_mod._take_over_dead_generation(led, cfg, 1, plan)
    time.sleep(0.3)                       # member 0's lease goes stale
    led.heartbeat(1)                      # the joiner stays fresh
    assert fleet_mod._take_over_dead_generation(led, cfg, 1, plan)
    nxt = led.read_plan(1)
    assert nxt["members"] == [1] and nxt["created_by"] == 1
    assert "takeover" in [e["kind"] for e in led.events()]


def test_formation_death_replans_instead_of_cascading_fatal(tmp_path):
    """A peer dying during cluster FORMATION must end in a replan, not
    total fleet death.  jax's distributed client LOG(FATAL)s the child
    (SIGABRT — no Python except path) when its peer never arrives, so
    the SUPERVISOR applies the lease classification to the hard exit:
    with the peer's lease stale it replans onto the smaller mesh and
    finishes, instead of recording rank_fatal and stopping its lease
    (which cascaded one rank's formation death into every rank's)."""
    from apex_tpu.parallel.multiproc import _free_port
    led = FleetLedger(str(tmp_path))
    cfg = FleetConfig(num_steps=3, checkpoint_every=2, world_size=2,
                      lease_ttl_s=0.5, heartbeat_s=0.1, poll_s=0.05,
                      init_timeout_s=2.0, init_retries=0,
                      replan_window_s=30.0)
    led.write_config(cfg)
    led.announce(0)
    led.heartbeat(0)      # stale long before init gives up: a dead peer
    # gen 0 plans ranks {0, 1}, but rank 0 is already gone and its
    # coordinator port has no listener: rank 1's child dies in
    # formation (SIGABRT from the distributed client)
    assert led.write_plan(_plan(0, [0, 1], port=_free_port()))
    env = dict(os.environ)
    for var in ("XLA_FLAGS", "COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK"):
        env.pop(var, None)
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.resilience.fleet",
         "--role", "supervisor", "--ledger", str(tmp_path),
         "--rank", "1"],
        env=env, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:],
                               r.stderr[-2000:])
    events = led.events()
    kinds = [e["kind"] for e in events]
    assert "child_death_reclassified" in kinds      # not rank_fatal
    assert "rank_fatal" not in kinds
    hard = next(e for e in events
                if e["kind"] == "child_death_reclassified")
    assert hard["reason"] == "shrink" and hard["ranks"] == [0]
    # the supervisor speaks the child's vocabulary: canonical
    # shrink_detected event + schema-valid fleet-shrink incident with
    # a flight tail (the child died too hard to write its own)
    shr = next(e for e in events if e["kind"] == "shrink_detected")
    assert shr["via"] == "supervisor" and shr["ranks"] == [0]
    from apex_tpu.resilience.incidents import validate_incident_file
    inc_dir = led.path("incidents")
    shrink_incs = [os.path.join(inc_dir, n) for n in os.listdir(inc_dir)
                   if "fleet-shrink" in n]
    assert shrink_incs and all(
        validate_incident_file(p) == [] for p in shrink_incs)
    with open(shrink_incs[0]) as f:
        tail = {ev["kind"] for ev in json.load(f)["flight"]["events"]}
    assert {"kill", "shrink_detected"} <= tail
    plan1 = led.read_plan(1)
    assert plan1["members"] == [1] and plan1["reason"] == "shrink"
    finals = led.finals()
    assert sorted(finals) == [1]
    assert finals[1]["step"] == cfg.num_steps - 1


# ---------------------------------------------------------------------------
# the real 2-process SIGKILL drill (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("APEX_TPU_TEST_PLATFORM") not in (None, "cpu"),
    reason="the drill spawns its own CPU-backend cluster")
def test_real_fleet_drill_kill_shrink_regrow_bitwise(tmp_path):
    """The acceptance drill as a test: a real rank SIGKILLed
    mid-training, the fleet shrinks, regrows, and the artifact
    validates with all bitwise verdicts true."""
    out = tmp_path / "TRAINFLEET_r01.json"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "train_fleet.py"),
         "--root", str(tmp_path / "drill"), "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-3000:])
    doc = json.loads(out.read_text())
    from apex_tpu.analysis.trainfleet import validate_trainfleet
    assert validate_trainfleet(doc) == []
    assert doc["gate"]["ok"] and all(doc["bitwise"].values())
    assert len(doc["generations"]) >= 3
