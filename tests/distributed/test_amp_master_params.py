"""amp + DDP master-param consistency (port of
``tests/distributed/amp_master_params/``): after O2 DDP training, every
rank holds identical params, and the half model params equal the fp32
masters cast to half.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.parallel import DistributedDataParallel, data_parallel_mesh
from apex_tpu.utils.jax_compat import shard_map

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    # first WORLD devices only: the platform carries 16 virtual devices
    # (the disaggregated-serving fleet topology); these WORLD=8-shaped
    # tests keep their original 8-wide mesh
    return data_parallel_mesh(num_devices=WORLD)


def test_master_and_model_params_consistent_across_ranks(mesh):
    model = MLP(features=(32,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                       verbosity=0)
    state = a.init(params)
    ddp = DistributedDataParallel(axis_name="data")
    inner = amp.make_train_step(
        a, lambda p, x, y: cross_entropy_loss(
            model.apply({"params": p}, x), y),
        axis_name="data", reduce_fn=ddp.reduce)

    def sharded(s, x, y):
        s2, m = inner(s, x, y)
        return s2, jax.lax.pmean(m["loss"], "data")

    step = jax.jit(shard_map(
        sharded, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))

    # rank-varying shards (the reference runs different data per rank)
    x = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 8, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (WORLD * 8,), 0, 10)
    for _ in range(5):
        state, _ = step(state, x, y)

    # 1) masters stay fp32 and are replicated: every device shard equal
    #    (reference compare.py: rank0 == rank1)
    for leaf in jax.tree.leaves(state.master_params):
        assert leaf.dtype == jnp.float32
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

    # 2) model params == masters cast to half (reference:
    #    model == master.half())
    model_p = a.model_params(state)
    for mp, ms in zip(jax.tree.leaves(model_p),
                      jax.tree.leaves(state.master_params)):
        assert mp.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(mp), np.asarray(ms.astype(jnp.bfloat16)))
