"""Pins the scaling-sweep law layer (``tools/scaling_sweep.py``).

Two tiers:

- Pure-unit: ``check_laws`` on canned audit records — the law table
  (which collective, which growth function, which tolerance) cannot
  drift without failing here.  A synthetic violation of each law class
  (const broken, linear broken) must be caught.
- Integration (slow): one real child at world 8 in-process is already
  covered by the dryrun tests; here a REAL subprocess child at world 16
  verifies the scaled topologies compile/execute and that the audits
  equal the world-8 dryrun values for every const-law collective — the
  empirical anchor for "per-device volume independent of world size".

The full 8-64 sweep (including ``gradient_predivide_factor`` parity at
world 64) runs via ``python tools/scaling_sweep.py`` and is recorded in
``SCALING_SWEEP.json`` each round.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

from scaling_sweep import (  # noqa: E402
    RECORD_TAG, check_laws, expert_alltoall_scale, sweep_topology,
)

#: the world-8 audits (== MULTICHIP_SLICES dryrun values for the shared
#: topologies), used as the canned baseline for the law-layer units
BASE = {
    "dp_syncbn": {"all-reduce": {"count": 28, "bytes": 26456}},
    "dp_sp_ring": {"collective-permute": {"count": 5, "bytes": 8208},
                   "all-reduce": {"count": 3, "bytes": 331020}},
    "dp_tp_pjit": {"all-reduce": {"count": 3, "bytes": 2310}},
    "pipeline": {"collective-permute": {"count": 2, "bytes": 256},
                 "all-reduce": {"count": 3, "bytes": 1032}},
    "expert": {"all-reduce": {"count": 4, "bytes": 528},
               "all-to-all": {"count": 3, "bytes": 3072}},
    "fsdp": {"all-gather": {"count": 1, "bytes": 1024},
             "all-reduce": {"count": 2, "bytes": 1026}},
    "dp_tp_sp_3d": {"collective-permute": {"count": 5, "bytes": 4112},
                    "all-reduce": {"count": 6, "bytes": 14348}},
}

CONST_KINDS = [
    ("dp_syncbn", "all-reduce"),
    ("dp_sp_ring", "collective-permute"),
    ("dp_sp_ring", "all-reduce"),
    ("dp_tp_pjit", "all-reduce"),
    ("pipeline", "collective-permute"),
    ("expert", "all-to-all"),   # capacity C=1 at both n=8 and n=16
    ("dp_tp_sp_3d", "collective-permute"),
    ("dp_tp_sp_3d", "all-reduce"),
]


def _records(n, *, mutate=None):
    recs = {}
    for name, coll in BASE.items():
        c = {k: dict(v) for k, v in coll.items()}
        # the statically-growing laws: fsdp's compute all-gather
        # (linear in params) and the expert all-to-all capacity formula
        # (constant until C floors at 1, then linear — the cliff)
        if name == "fsdp":
            c["all-gather"]["bytes"] = 1024 * n // 8
            c["all-reduce"]["bytes"] = 1026 * n // 8
        if name == "expert":
            c["all-to-all"]["bytes"] = int(
                3072 * expert_alltoall_scale(n) / expert_alltoall_scale(8))
        recs[name] = {"name": name, "ok": True, "collectives": c, "n": n}
    if mutate:
        mutate(recs)
    return recs


def _by_n(ns=(8, 16, 32, 64), mutate_at=None, mutate=None):
    return {n: _records(n, mutate=mutate if n == mutate_at else None)
            for n in ns}


def test_all_laws_pass_on_lawful_series():
    laws = check_laws(_by_n())
    assert laws, "law table is empty"
    failed = [lw for lw in laws if not lw["ok"]]
    assert not failed, failed


def test_const_law_catches_growth():
    # a DP implementation whose per-device all-reduce grows with world
    # size is the classic non-scalable bug — the law must fire
    def grow(recs):
        recs["dp_syncbn"]["collectives"]["all-reduce"]["bytes"] *= 2

    laws = check_laws(_by_n(mutate_at=64, mutate=grow))
    bad = [lw for lw in laws
           if lw["slice"] == "dp_syncbn" and not lw["ok"]]
    assert bad, "doubled world-64 DP all-reduce not caught"


def test_linear_law_catches_flatline():
    # an fsdp whose all-gather STOPS growing would mean it no longer
    # reconstitutes the full parameter — also a bug
    def flat(recs):
        recs["fsdp"]["collectives"]["all-gather"]["bytes"] = 1024

    laws = check_laws(_by_n(mutate_at=64, mutate=flat))
    bad = [lw for lw in laws if lw["slice"] == "fsdp" and not lw["ok"]]
    assert bad, "flat world-64 fsdp all-gather not caught"


def test_failed_slice_fails_its_laws():
    def broke(recs):
        recs["expert"]["ok"] = False

    laws = check_laws(_by_n(mutate_at=32, mutate=broke))
    bad = [lw for lw in laws
           if lw["slice"] == "expert" and not lw["ok"]]
    assert bad, "failed slice record passed its law"


def test_expert_capacity_cliff_formula():
    # E_global*C: C=2 at n=8, floors at 1 from n=16 -> const then linear
    assert expert_alltoall_scale(8) == 32.0    # 16 experts x C=2
    assert expert_alltoall_scale(16) == 32.0   # 32 experts x C=1
    assert expert_alltoall_scale(32) == 64.0
    assert expert_alltoall_scale(64) == 128.0
    # the REAL sweep numbers: 3072, 3072, 6144, 12288 bytes
    # (SCALING_SWEEP.json) — a dispatch layout that silently doubled
    # pre-cliff volume would violate the formula and fail the law
    def wrong(recs):
        recs["expert"]["collectives"]["all-to-all"]["bytes"] *= 2

    laws = check_laws(_by_n(mutate_at=16, mutate=wrong))
    bad = [lw for lw in laws
           if lw["slice"] == "expert" and not lw["ok"]]
    assert bad, "doubled pre-cliff expert all-to-all not caught"


def test_derived_executed_volumes_scale():
    laws = {(lw["slice"], lw["law"]): lw for lw in check_laws(_by_n())}
    ring = laws[("dp_sp_ring", "ring executed volume ~ sp")]
    # derived = static x sp: sp doubles 2->4->8->16 across the sweep
    s = ring["series"]
    assert s["16"]["bytes"] == 2 * s["8"]["bytes"]
    assert s["64"]["bytes"] == 8 * s["8"]["bytes"]
    pipe = laws[("pipeline", "pipe executed volume ~ 2S-1")]
    assert pipe["series"]["64"]["bytes"] == 256 * (2 * 64 - 1)


@pytest.mark.slow
def test_world16_child_matches_const_laws():
    """Real subprocess at world 16: scaled topologies (sp=4, tp=4,
    16-stage pipeline) compile, execute, and audit byte-identical to the
    world-8 baseline for every const-law collective."""
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "scaling_sweep.py"),
         "--child", "16"],
        capture_output=True, text=True, timeout=900, cwd=str(REPO))
    recs = {json.loads(line[len(RECORD_TAG):])["name"]:
            json.loads(line[len(RECORD_TAG):])
            for line in p.stdout.splitlines()
            if line.startswith(RECORD_TAG)}
    assert recs, f"no records; stderr tail: {p.stderr[-500:]}"
    failed = [r["name"] for r in recs.values() if not r["ok"]]
    assert not failed, (failed, [recs[f].get("error") for f in failed])
    assert sweep_topology(16) == {"sp": 4, "tp": 4, "stages": 16}
    for name, kind in CONST_KINDS:
        got = recs[name]["collectives"][kind]["bytes"]
        want = BASE[name][kind]["bytes"]
        assert got == want, (name, kind, got, want)
    # and the linear anchor: fsdp all-gather exactly doubles
    assert recs["fsdp"]["collectives"]["all-gather"]["bytes"] == 2048
