"""Sharded checkpoint/resume with mesh-shape change — VERDICT item 3's
"done" bar: save an FSDP-sharded :class:`AmpState` (masters, moments,
scaler) on the 8-device virtual mesh, restore it exactly, restore it
onto a *4-device* mesh, and continue training bit-consistently with the
unsharded reference run.

Why bitwise is attainable: the durable layer stores full gathered host
arrays per leaf and places them onto the *template's* shardings on
restore, so the restored values are the saved values, bit for bit, on
any mesh.  And on this suite's virtual CPU mesh the sharded training
step itself reproduces the unsharded step bitwise for these shapes
(pinned by ``test_sharded_step_matches_unsharded_bitwise`` below — if
an XLA change ever breaks that premise, THAT test names it, separating
"sharded arithmetic drifted" from "the checkpoint layer broke").
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp, checkpoint
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import DurableCheckpointManager

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs 8 devices (virtual CPU mesh or a pod slice)")


def _loss_fn(p, xb):
    h = jax.nn.relu(xb @ p["w1"])
    return jnp.mean(jnp.square(h @ p["w2"]))


def _fresh():
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(3), (8, 32)),
        "w2": jax.random.normal(jax.random.PRNGKey(4), (32, 8)),
    }
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    step = jax.jit(amp.make_train_step(a, _loss_fn))
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 8))
    return a, step, params, x


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _fsdp_put(state, mesh):
    """ZeRO-3 layout: params AND moments shard over "data" (w1 on its
    output dim, w2 on its input dim); scalars replicate."""
    shardings = {"w1": NamedSharding(mesh, P(None, "data")),
                 "w2": NamedSharding(mesh, P("data", None))}

    def put(path, leaf):
        key = jax.tree_util.keystr(path)
        for name, s in shardings.items():
            if name in key and getattr(leaf, "ndim", 0) == 2:
                return jax.device_put(leaf, s)
        return leaf
    return jax.tree_util.tree_map_with_path(put, state)


def _host(tree):
    return jax.tree.map(np.asarray, tree)


def _assert_states_equal(got, want, msg=""):
    for (pa, la), (_pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want)):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=f"{msg}: {jax.tree_util.keystr(pa)}")


def test_sharded_step_matches_unsharded_bitwise():
    """Premise pin: on this platform the FSDP-sharded train step equals
    the unsharded step bit-for-bit (exact-restore + reshape tests lean
    on this to demand bitwise continuation)."""
    a, step, params, x = _fresh()
    mesh = _mesh(8)
    st_sh = _fsdp_put(a.init(params), mesh)
    x_sh = jax.device_put(x, NamedSharding(mesh, P("data")))
    st_un = a.init(params)
    for _ in range(3):
        st_sh, m_sh = step(st_sh, x_sh)
        st_un, m_un = step(st_un, x)
    assert float(m_sh["loss"]) == float(m_un["loss"])
    _assert_states_equal(_host(st_sh), _host(st_un), "sharded vs unsharded")


def test_save_sharded_restore_exact_same_mesh(tmp_path):
    """Save at step 3 on the 8-device mesh; restore onto the SAME mesh:
    every leaf bitwise equal (scaler included), layouts preserved, and 3
    more steps match the uninterrupted run bitwise."""
    a, step, params, x = _fresh()
    mesh = _mesh(8)
    state = _fsdp_put(a.init(params), mesh)
    x_sh = jax.device_put(x, NamedSharding(mesh, P("data")))
    for _ in range(3):
        state, _ = step(state, x_sh)

    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(3, state)
    mgr.close()
    saved_host = _host(state)

    cont = state
    for _ in range(3):
        cont, _ = step(cont, x_sh)

    template = _fsdp_put(jax.tree.map(jnp.zeros_like, _host(state)), mesh)
    restored, _ = mgr.restore(template)
    _assert_states_equal(_host(restored), saved_host, "restored vs saved")
    assert restored.master_params["w1"].sharding.spec == P(None, "data")
    for _ in range(3):
        restored, _ = step(restored, x_sh)
    _assert_states_equal(_host(restored), _host(cont),
                         "resumed vs uninterrupted")


def test_restore_onto_smaller_mesh_bit_consistent_with_unsharded(tmp_path):
    """The reshape bar: save FSDP-sharded on 8 devices, restore onto a
    4-device mesh AND onto a single device; the restored leaves are
    bitwise the saved ones, the 4-device layout is real (4 distinct
    devices), and 3 further steps agree bitwise across 4-device,
    8-device-uninterrupted, and the unsharded reference."""
    a, step, params, x = _fresh()
    mesh8 = _mesh(8)
    state = _fsdp_put(a.init(params), mesh8)
    x8 = jax.device_put(x, NamedSharding(mesh8, P("data")))
    for _ in range(3):
        state, _ = step(state, x8)
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(3, state)
    mgr.wait()
    saved_host = _host(state)

    # uninterrupted 8-device continuation (the "what should have happened")
    cont8 = state
    for _ in range(3):
        cont8, _ = step(cont8, x8)

    # (a) restore onto the 4-device mesh and continue
    mesh4 = _mesh(4)
    template4 = _fsdp_put(a.init(params), mesh4)
    restored4, _ = mgr.restore(template4)
    _assert_states_equal(_host(restored4), saved_host, "4-dev vs saved")
    w1 = restored4.master_params["w1"]
    assert w1.sharding.spec == P(None, "data")
    assert len(w1.sharding.device_set) == 4
    x4 = jax.device_put(x, NamedSharding(mesh4, P("data")))
    for _ in range(3):
        restored4, _ = step(restored4, x4)

    # (b) restore unsharded (single device) and continue — the reference
    template1 = a.init(params)
    restored1, _ = mgr.restore(template1)
    _assert_states_equal(_host(restored1), saved_host, "unsharded vs saved")
    for _ in range(3):
        restored1, _ = step(restored1, x)

    _assert_states_equal(_host(restored4), _host(restored1),
                         "4-dev continuation vs unsharded reference")
    _assert_states_equal(_host(cont8), _host(restored1),
                         "8-dev uninterrupted vs unsharded reference")


def test_pipeline_stage_stacked_leaves_reshape(tmp_path):
    """Pipeline-style layout: stage-stacked leaves (leading stage axis,
    ``stack_stage_params``) sharded ``P("pipe")`` over an 8-way pipe
    mesh round-trip onto a 4-way pipe mesh (2 stages per device) with
    bitwise-identical values — the other sharded-state family the
    checkpoint layer must carry (VERDICT item 3 names FSDP *and*
    pipeline)."""
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    stages = {"stages": jax.random.normal(jax.random.PRNGKey(7), (8, 4, 4))}
    mesh8 = Mesh(np.array(jax.devices()[:8]), ("pipe",))

    def put(state, mesh):
        sh = NamedSharding(mesh, P("pipe"))
        return jax.tree.map(
            lambda t: jax.device_put(t, sh) if getattr(t, "ndim", 0) == 3
            else t, state)

    state = put(a.init(stages), mesh8)
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(0, state)
    mgr.wait()

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("pipe",))
    template = put(a.init(stages), mesh4)
    restored, _ = mgr.restore(template)
    _assert_states_equal(_host(restored), _host(state), "pipe reshape")
    got = restored.master_params["stages"]
    assert got.sharding.spec == P("pipe")
    assert len(got.sharding.device_set) == 4


def test_restore_scaler_state_travels_with_reshape(tmp_path):
    """The scaler (loss scale + unskipped) must survive the mesh change
    too — it is exactly the state the reference lost on restart."""
    a, step, params, x = _fresh()
    mesh8 = _mesh(8)
    state = _fsdp_put(a.init(params), mesh8)
    x8 = jax.device_put(x, NamedSharding(mesh8, P("data")))
    # drive an overflow so the scale moves off init
    x_bad = x8.at[0, 0].set(jnp.inf)
    state, m = step(state, x_bad)
    assert bool(m["overflow"])
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(1, state)
    mgr.wait()

    restored, _ = mgr.restore(a.init(params))   # single-device template
    assert float(restored.scaler_states[0].loss_scale) == \
        float(state.scaler_states[0].loss_scale) == 32768.0
    assert int(restored.scaler_states[0].unskipped) == \
        int(state.scaler_states[0].unskipped)
