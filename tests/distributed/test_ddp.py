"""Data-parallel gradient reduction tests on the 8-device CPU mesh.

Port of ``tests/distributed/DDP/ddp_race_condition_test.py:1-68`` (closed-form
expected gradients with rank-varying inputs) and the DDP knob semantics
(``apex/parallel/distributed.py:379-398``), run under ``shard_map`` — the
multi-device axis the reference could only test on a multi-GPU rig.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.parallel import (
    DistributedDataParallel,
    ReduceConfig,
    Reducer,
    broadcast,
    data_parallel_mesh,
    pvary_params,
    reduce_gradients,
)
from apex_tpu.utils.jax_compat import shard_map as _shard_map

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    # first WORLD devices only: the platform carries 16 virtual devices
    # (the disaggregated-serving fleet topology); these WORLD=8-shaped
    # tests keep their original 8-wide mesh
    return data_parallel_mesh(num_devices=WORLD)


def shmap(mesh, fn, in_specs, out_specs):
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)


def test_grad_allreduce_closed_form(mesh):
    """Rank-varying inputs → closed-form mean gradient (the race test's
    assertion style: expected grad computable by hand per iteration)."""
    # loss_r = w * (r+1) per rank r; d/dw = (r+1); mean over ranks = 4.5
    ranks = jnp.arange(WORLD, dtype=jnp.float32)

    def step(r):
        w = pvary_params(jnp.ones(()), "data")
        g = jax.grad(lambda w: w * (r[0] + 1.0))(w)
        return reduce_gradients(g, "data")

    out = shmap(mesh, step, (P("data"),), P())(ranks)
    np.testing.assert_allclose(np.asarray(out), 4.5)


@pytest.mark.parametrize("predivide", [1.0, 4.0])
@pytest.mark.parametrize("average", [True, False])
def test_predivide_postdivide_semantics(mesh, predivide, average):
    cfg = ReduceConfig(gradient_average=average,
                       gradient_predivide_factor=predivide)
    grads = jnp.ones((WORLD, 4), jnp.float32) * 2.0

    def step(g):
        return reduce_gradients(g[0], "data", cfg)

    out = shmap(mesh, step, (P("data"),), P())(grads)
    # sum over ranks = 16; average → post *f/world restores the mean
    # (/8 = 2); no average → NO post-scale (reference distributed.py:
    # 387-393 post-scales only when averaging), grads deliver at sum/f.
    expected = 2.0 if average else 16.0 / predivide
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_no_average_predivide_reference_parity(mesh):
    """``gradient_average=False`` + predivide ``f``: the reference's
    ``allreduce_bucket`` divides each grad by ``f`` BEFORE the
    all-reduce and applies no post-scale unless averaging
    (``apex/parallel/distributed.py:387-393``) — the delivered grads
    are ``sum(g_r)/f``, bit-matching a hand-rolled psum(g/f)."""
    f = 4.0
    cfg = ReduceConfig(gradient_average=False, gradient_predivide_factor=f)
    gvals = (jnp.arange(WORLD, dtype=jnp.float32) + 1.0)  # rank r: r+1

    def apex_step(g):
        return reduce_gradients(g[0], "data", cfg)

    def reference_step(g):
        return jax.lax.psum(g[0] / f, "data")

    got = shmap(mesh, apex_step, (P("data"),), P())(gvals)
    want = shmap(mesh, reference_step, (P("data"),), P())(gvals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(np.asarray(got),
                               float(gvals.sum()) / f, rtol=1e-6)


def test_fp32_wire_upcast(mesh):
    """allreduce_always_fp32: bf16 grads summed exactly over 8 ranks where a
    bf16 wire would round."""
    cfg = ReduceConfig(allreduce_always_fp32=True, gradient_average=False)
    # 1 + 1/256 is not representable after bf16 summation growth
    vals = (1.0 + jnp.arange(WORLD, dtype=jnp.float32) / 256.0)

    def step(v):
        g = v[0].astype(jnp.bfloat16)
        return reduce_gradients(g, "data", cfg).astype(jnp.float32)

    out = shmap(mesh, step, (P("data"),), P())(vals)
    # fp32 wire: result is bf16(round(exact fp32 sum)); exact sum = 8.109375
    exact = float(vals.sum())
    got = float(np.asarray(out))
    assert abs(got - exact) < 0.05


def test_sign_compression_opt_in(mesh):
    cfg = ReduceConfig(compression="sign", gradient_average=True)
    vals = jnp.asarray([-3.0, 5.0, -1.0, 2.0, 7.0, -2.0, 4.0, -8.0])

    def step(v):
        return reduce_gradients(v[0], "data", cfg)

    out = shmap(mesh, step, (P("data"),), P())(vals)
    expected = np.sign(np.asarray(vals)).sum() / WORLD
    np.testing.assert_allclose(np.asarray(out), expected)


def test_broadcast_param_sync(mesh):
    vals = jnp.arange(WORLD, dtype=jnp.float32) + 10.0

    def step(v):
        return broadcast(v[0], "data", root=3)

    out = shmap(mesh, step, (P("data"),), P())(vals)
    np.testing.assert_allclose(np.asarray(out), 13.0)


def test_ddp_with_amp_train_step(mesh):
    """amp O2 + DDP: per-device batches, synced updates → replicated params
    stay identical (the amp_master_params distributed test: rank0==rank1 and
    model==master.half())."""
    ddp = DistributedDataParallel(axis_name="data")
    a = amp.initialize(optimizer=optax.sgd(0.1), opt_level="O2", verbosity=0)
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = a.init(params)

    def loss_fn(p, x):
        return jnp.sum(p["w"] * x)

    step = amp.make_train_step(a, loss_fn, axis_name="data",
                               reduce_fn=ddp.reduce)

    x = jnp.arange(WORLD * 4, dtype=jnp.float32).reshape(WORLD, 4)
    def inner(s, xx):
        s2, metrics = step(s, xx[0])
        return s2, jax.lax.pmean(metrics["loss"], "data")

    sharded_step = shmap(mesh, inner, (P(), P("data")), (P(), P()))
    state2, mean_loss = sharded_step(state, x)

    # Expected grad = mean over ranks of x_r = column means
    expected_g = np.asarray(x).mean(axis=0)
    expected_w = 1.0 - 0.1 * expected_g
    np.testing.assert_allclose(np.asarray(state2.master_params["w"]),
                               expected_w, rtol=2e-2)
    # model params are the bf16 view of masters
    mp = a.model_params(state2)
    assert mp["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(mp["w"], np.float32),
                               expected_w, rtol=2e-2)


def test_reducer_manual_cadence(mesh):
    """Reducer: grads accumulate locally for 2 steps, reduced once
    (delay_allreduce / grad-accumulation semantics)."""
    red = Reducer(axis_name="data")
    ranks = jnp.arange(WORLD, dtype=jnp.float32)

    def step(r):
        acc = r[0] + r[0]  # two local "micro-batch" grads
        return red.reduce(acc)

    out = shmap(mesh, step, (P("data"),), P())(ranks)
    np.testing.assert_allclose(np.asarray(out), 2.0 * ranks.mean())
