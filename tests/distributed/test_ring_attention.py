"""Sequence-parallel attention tests on the 8-device CPU mesh.

Exactness contract: ring/ulysses attention over a sequence sharded across
the mesh must equal full single-device attention to float tolerance —
including causal masking, key padding masks, and gradients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.attention import attention, ring_attention, ulysses_attention
from apex_tpu.parallel import data_parallel_mesh

WORLD = 8
B, L, H, D = 2, 64, 8, 16   # L/W = 8 per device


@pytest.fixture(scope="module")
def mesh():
    return data_parallel_mesh()


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, L, H, D), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


def _reference(q, k, v, causal=False, kv_mask=None):
    # Pin the oracle to the jnp path: on hardware the auto-dispatching
    # attention() would route to the Pallas flash kernel, making this a
    # kernel-vs-kernel comparison instead of kernel-vs-jnp.
    return attention(q, k, v, axis_name=None, impl="jnp", causal=causal,
                     kv_mask=kv_mask)


def _run_sharded(mesh, fn, q, k, v, kv_mask=None):
    in_specs = [P(None, "data"), P(None, "data"), P(None, "data")]
    args = [q, k, v]
    if kv_mask is not None:
        in_specs.append(P(None, "data"))
        args.append(kv_mask)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=P(None, "data")))(*args)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(mesh, causal):
    q, k, v = _qkv()
    want = _reference(q, k, v, causal=causal)
    got = _run_sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "data",
                                             causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(mesh, causal):
    q, k, v = _qkv(1)
    want = _reference(q, k, v, causal=causal)
    got = _run_sharded(
        mesh, lambda q, k, v: ulysses_attention(q, k, v, "data",
                                                causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_key_padding_mask(mesh):
    q, k, v = _qkv(2)
    mask = jnp.asarray(np.random.RandomState(0).rand(B, L) > 0.3)
    want = _reference(q, k, v, kv_mask=mask)
    got = _run_sharded(
        mesh, lambda q, k, v, m: ring_attention(q, k, v, "data",
                                                kv_mask=m),
        q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_zero(mesh):
    q, k, v = _qkv(3)
    mask = jnp.zeros((B, L), bool)
    got = _run_sharded(
        mesh, lambda q, k, v, m: ring_attention(q, k, v, "data",
                                                kv_mask=m),
        q, k, v, kv_mask=mask)
    assert bool(jnp.isfinite(got).all())


def test_ring_gradients_match(mesh):
    q, k, v = _qkv(4)

    def loss_sharded(q, k, v):
        o = ring_attention(q, k, v, "data", causal=True)
        return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "data")

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    got = jax.jit(jax.shard_map(
        jax.grad(loss_sharded, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, "data"),) * 3,
        out_specs=(P(None, "data"),) * 3))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ring_bf16_inputs(mesh):
    q, k, v = _qkv(5, jnp.bfloat16)
    want = _reference(q, k, v)
    got = _run_sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "data"), q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=0.05, atol=0.05)


def test_ulysses_rejects_bad_head_count(mesh):
    q = k = v = jnp.zeros((B, L, 4, D))  # 4 heads, 8 devices
    with pytest.raises(Exception):
        _run_sharded(mesh,
                     lambda q, k, v: ulysses_attention(q, k, v, "data"),
                     q, k, v)
