"""Sequence-parallel attention tests on the 8-device CPU mesh.

Exactness contract: ring/ulysses attention over a sequence sharded across
the mesh must equal full single-device attention to float tolerance —
including causal masking, key padding masks, and gradients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.attention import attention, ring_attention, ulysses_attention
from apex_tpu.parallel import data_parallel_mesh
from apex_tpu.utils.jax_compat import shard_map

WORLD = 8
B, L, H, D = 2, 64, 8, 16   # L/W = 8 per device


@pytest.fixture(scope="module")
def mesh():
    # first 8 devices of the 16-device test platform (L/W = 8/device)
    return data_parallel_mesh(num_devices=8)


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, L, H, D), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


def _reference(q, k, v, causal=False, kv_mask=None):
    # Pin the oracle to the jnp path: on hardware the auto-dispatching
    # attention() would route to the Pallas flash kernel, making this a
    # kernel-vs-kernel comparison instead of kernel-vs-jnp.
    return attention(q, k, v, axis_name=None, impl="jnp", causal=causal,
                     kv_mask=kv_mask)


def _run_sharded(mesh, fn, q, k, v, kv_mask=None):
    in_specs = [P(None, "data"), P(None, "data"), P(None, "data")]
    args = [q, k, v]
    if kv_mask is not None:
        in_specs.append(P(None, "data"))
        args.append(kv_mask)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=P(None, "data")))(*args)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(mesh, causal):
    q, k, v = _qkv()
    want = _reference(q, k, v, causal=causal)
    got = _run_sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "data",
                                             causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(mesh, causal):
    q, k, v = _qkv(1)
    want = _reference(q, k, v, causal=causal)
    got = _run_sharded(
        mesh, lambda q, k, v: ulysses_attention(q, k, v, "data",
                                                causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_key_padding_mask(mesh):
    q, k, v = _qkv(2)
    mask = jnp.asarray(np.random.RandomState(0).rand(B, L) > 0.3)
    want = _reference(q, k, v, kv_mask=mask)
    got = _run_sharded(
        mesh, lambda q, k, v, m: ring_attention(q, k, v, "data",
                                                kv_mask=m),
        q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_zero(mesh):
    q, k, v = _qkv(3)
    mask = jnp.zeros((B, L), bool)
    got = _run_sharded(
        mesh, lambda q, k, v, m: ring_attention(q, k, v, "data",
                                                kv_mask=m),
        q, k, v, kv_mask=mask)
    assert bool(jnp.isfinite(got).all())


def test_ring_gradients_match(mesh):
    """Differentiated OUTSIDE the shard_map (the replicated-scalar-loss
    form, like the flash-grad test below): grad-of-psum placed inside
    the region is a jax-version semantic (legacy shard_map transposes
    it to a W-times-counted cotangent; the VMA API doesn't), while this
    form pins the package contract — ring backward == full-attention
    backward — identically on both."""
    q, k, v = _qkv(4)

    def sharded_loss(q, k, v):
        def inner(q, k, v):
            o = ring_attention(q, k, v, "data", causal=True)
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2),
                                "data")
        return shard_map(
            inner, mesh=mesh,
            in_specs=(P(None, "data"),) * 3, out_specs=P())(q, k, v)

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    got = jax.grad(sharded_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ring_bf16_inputs(mesh):
    q, k, v = _qkv(5, jnp.bfloat16)
    want = _reference(q, k, v)
    got = _run_sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "data"), q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=0.05, atol=0.05)


def test_ulysses_rejects_bad_head_count(mesh):
    q = k = v = jnp.zeros((B, L, 4, D))  # 4 heads, 8 devices
    with pytest.raises(Exception):
        _run_sharded(mesh,
                     lambda q, k, v: ulysses_attention(q, k, v, "data"),
                     q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_blocks_match_reference(mesh, causal):
    """Ring with the flash block engine == full jnp attention.

    On this CPU mesh the engine transparently substitutes its equivalent
    jnp math (interpret-mode pallas under shard_map trips a jax VMA
    limitation), so this pins the ring merge algebra — the branch
    selection, logsumexp-weighted merge, and masked-row conventions.  The
    compiled kernel-under-shard_map path is covered on hardware by
    test_ring_flash_kernel_on_tpu."""
    q, k, v = _qkv(5)
    want = _reference(q, k, v, causal=causal)
    got = _run_sharded(
        mesh, lambda q, k, v: ring_attention(q, k, v, "data",
                                             causal=causal, impl="flash"),
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_blocks_with_mask(mesh):
    q, k, v = _qkv(6)
    rng = np.random.RandomState(6)
    mask = jnp.asarray(rng.rand(B, L) > 0.3).at[:, 0].set(True)
    want = _reference(q, k, v, kv_mask=mask)
    got = _run_sharded(
        mesh, lambda q, k, v, m: ring_attention(q, k, v, "data",
                                                kv_mask=m, impl="flash"),
        q, k, v, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gradients_match_reference(mesh):
    """Gradients through the flash-block ring merge (differentiable lse).
    On CPU the jnp block engine stands in; the kernel dlse term is pinned
    by test_ring_flash_kernel_on_tpu on hardware."""
    q, k, v = _qkv(7)

    def sharded_loss(q, k, v):
        def inner(q, k, v):
            o = ring_attention(q, k, v, "data", causal=True, impl="flash")
            return jax.lax.psum(jnp.sum(jnp.sin(o)), "data")
        # check_rep=False (legacy jax only; a no-op on the VMA API): the
        # flash path's lax.switch trips "branches of cond produced
        # mismatched replication types" in the legacy checker, which jax
        # itself flags as a bug with this exact workaround.  Safe here:
        # grads are wrt sharded inputs only, where the unrewritten psum
        # transpose is correct.
        return shard_map(
            inner, mesh=mesh, in_specs=(P(None, "data"),) * 3,
            out_specs=P(), check_rep=False)(q, k, v)

    def ref_loss(q, k, v):
        return jnp.sum(jnp.sin(_reference(q, k, v, causal=True)))

    g = jax.grad(sharded_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_flash_blocks_match_reference(mesh):
    """impl="flash" forces the flash branch (its all_to_all layout swap);
    on this CPU mesh the engine substitutes equivalent jnp math, as in the
    ring flash tests."""
    q, k, v = _qkv(8)
    want = _reference(q, k, v, causal=True)
    got = _run_sharded(
        mesh, lambda q, k, v: ulysses_attention(q, k, v, "data",
                                                causal=True, impl="flash"),
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(jax.default_backend() == "cpu",
                    reason="compiled pallas under shard_map needs hardware")
def test_ring_flash_kernel_on_tpu():
    """Mosaic-compiled flash kernel inside shard_map on a 1-device mesh:
    exercises the vma-tagged out_shapes and the kernel dlse backward."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 256, 4, 64),
                          jnp.float32)

    def run(qq):
        return shard_map(
            lambda q: ring_attention(q, q, q, "data", causal=True,
                                     impl="flash"),
            mesh=mesh, in_specs=(P(None, "data"),),
            out_specs=P(None, "data"))(qq)

    out = jax.jit(run)(q)
    ref = _reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda q: jnp.sum(jax.jit(run)(q).astype(jnp.float32)))(q)
    assert bool(jnp.isfinite(g).all())


@pytest.mark.parametrize("impl", ["jnp", "flash", "ring", "ulysses"])
def test_dispatcher_forwards_impl_with_axis(mesh, impl):
    """attention() with an axis_name accepts every impl: ring/ulysses
    dispatch their path, flash/jnp select the ring block engine."""
    q, k, v = _qkv(9)
    want = _reference(q, k, v, causal=True)
    got = _run_sharded(
        mesh, lambda q, k, v: attention(q, k, v, axis_name="data",
                                        impl=impl, causal=True), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_dispatcher_rejects_unknown_impl():
    q = jnp.zeros((1, 8, 2, 4))
    with pytest.raises(ValueError):
        attention(q, q, q, impl="flsah")
    with pytest.raises(ValueError):
        attention(q, q, q, axis_name="data", impl="flsah")
