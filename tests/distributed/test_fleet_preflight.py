"""Cross-process SPMD preflight tests (real 2-process ``jax.distributed``).

The fleet failure mode this PR targets is a *hang*: one rank lowers a
different collective schedule (a sign-compressed bucket, a conditionally
skipped all-reduce) and the whole fleet wedges in the first mismatched
collective with no diagnosis.  Here two CPU-backend processes form a real
cluster and train a miniature DDP + amp-O2 step with the preflight barrier
enabled:

- the happy path proves the preflight passes AND the training itself is
  SPMD-consistent — reduced grads, agreeing scaler states, bit-identical
  final parameters across ranks (one digest covers all three);
- the seeded-divergence path gives rank 1 one extra collective and proves
  the fleet aborts before the first step with the differing op *named* in
  the error — instead of timing out.

Also here: the :func:`apex_tpu.parallel.multiproc.spawn` failure-surfacing
contract (a dying rank's stderr tail lands in the ``ClusterInitError``).
"""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])

#: the per-rank worker: build the DDP + amp-O2 train step, run the SPMD
#: preflight through ``initialize(preflight=...)``, then train 3 steps and
#: print a digest of the ENTIRE final state (params + masters + scaler) —
#: one line per rank the launcher can compare bit-for-bit.
WORKER = textwrap.dedent("""
    import hashlib
    import os
    import sys

    import jax
    jax.config.update("jax_platforms", "cpu")
    # the CPU backend only runs cross-process computations through the
    # gloo collectives implementation
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    import numpy as np

    from apex_tpu.parallel import multiproc

    _cache = {}

    def build():
        # runs AFTER cluster formation (initialize's preflight callable):
        # the global devices the mesh needs exist only now
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu import amp
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.parallel import DistributedDataParallel
        from apex_tpu.utils.jax_compat import shard_map

        mesh = Mesh(np.array(jax.devices()), ("data",))
        rank = jax.process_index()
        probe = os.environ.get("SEED_DIVERGENCE") == "1" and rank == 1
        params = {"w1": jax.random.normal(jax.random.PRNGKey(0), (8, 16)),
                  "w2": jax.random.normal(jax.random.PRNGKey(1), (16, 8))}

        def loss_fn(p, xb):
            h = jax.nn.relu(xb @ p["w1"])
            loss = jnp.mean(jnp.square(h @ p["w2"]))
            if probe:
                # the seeded divergence: rank 1 issues one extra
                # collective its peers never will (traced operand, so
                # nothing folds it away)
                extra = jax.lax.psum(jnp.sum(xb).astype(jnp.float32),
                                     "data")
                loss = loss + 0.0 * extra
            return loss

        ddp = DistributedDataParallel(axis_name="data")
        a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                           verbosity=0)
        state = a.init(params)
        step = amp.make_train_step(a, loss_fn, axis_name="data",
                                   reduce_fn=ddp.reduce)

        def inner(s, xb):
            s2, m = step(s, xb[0])
            return s2, jax.lax.pmean(m["loss"], "data")

        fn = jax.jit(shard_map(inner, mesh=mesh,
                               in_specs=(P(), P("data")),
                               out_specs=(P(), P())))
        n = jax.process_count()
        # every rank derives the same global batch, keeps its own shard
        xg = np.asarray(jax.random.normal(jax.random.PRNGKey(2),
                                          (n, 1, 4, 8)))
        state_g = multihost_utils.host_local_array_to_global_array(
            state, mesh, P())
        x_g = multihost_utils.host_local_array_to_global_array(
            xg[rank], mesh, P("data"))
        _cache.update(fn=fn, state=state_g, x=x_g, mesh=mesh)
        return fn.lower(state_g, x_g)

    try:
        rec = multiproc.initialize(preflight=build,
                                   preflight_label="ddp_o2_train")
    except multiproc.SpmdPreflightError as e:
        print("PREFLIGHT ABORT:", e, file=sys.stderr, flush=True)
        sys.exit(3)

    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    fn, state, x = _cache["fn"], _cache["state"], _cache["x"]
    for _ in range(3):
        state, loss = fn(state, x)
    state_l, loss_l = multihost_utils.global_array_to_host_local_array(
        (state, loss), _cache["mesh"], (P(), P()))
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state_l):
        h.update(np.asarray(leaf).tobytes())
    scale = float(np.asarray(state_l.scaler_states[0].loss_scale))
    print("RANK", jax.process_index(),
          "SCHED", rec["schedule_hash"][:12],
          "NCOLL", rec["n_collectives"],
          "SCALE", scale,
          "LOSS", float(np.asarray(loss_l)),
          "DIGEST", h.hexdigest(), flush=True)
""")


def _launch(tmp_path, extra_env=None):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ, WORLD_SIZE="2",
               PYTHONPATH=REPO_ROOT + ":" + os.environ.get("PYTHONPATH", ""))
    # drop the single-process test config so workers form their own cluster
    env.pop("XLA_FLAGS", None)
    env.pop("SEED_DIVERGENCE", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc", str(script)],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300)


@pytest.mark.skipif(
    os.environ.get("APEX_TPU_TEST_PLATFORM") not in (None, "cpu"),
    reason="local spawner test runs on the CPU backend")
def test_two_process_ddp_o2_trains_bit_identical_after_preflight(tmp_path):
    """Happy path: the preflight barrier passes, 3 real DDP + amp-O2
    steps run, and both ranks print the same schedule hash, scaler
    scale, loss, and full-state digest — grads were reduced and the
    replicas stayed bit-identical."""
    out = _launch(tmp_path)
    assert out.returncode == 0, (out.stdout, out.stderr)
    lines0 = [ln for ln in out.stdout.splitlines()
              if ln.startswith("RANK 0 ")]
    lines1 = [ln for ln in (tmp_path / "PROC_1.log").read_text().splitlines()
              if ln.startswith("RANK 1 ")]
    assert lines0 and lines1, (out.stdout, out.stderr)
    t0, t1 = lines0[0].split()[2:], lines1[0].split()[2:]
    # everything after "RANK <i>" must agree bit-for-bit across ranks:
    # schedule fingerprint, collective count, scaler state, loss, and the
    # sha256 over every leaf of the final AmpState
    assert t0 == t1, (lines0[0], lines1[0])
    # the preflight saw a real collective schedule (grad reduce + pmean)
    ncoll = int(t0[t0.index("NCOLL") + 1])
    assert ncoll >= 2, t0


@pytest.mark.skipif(
    os.environ.get("APEX_TPU_TEST_PLATFORM") not in (None, "cpu"),
    reason="local spawner test runs on the CPU backend")
def test_two_process_seeded_divergence_aborts_with_named_diff(tmp_path):
    """Rank 1 lowers one extra all-reduce: the preflight must abort the
    fleet (exit, not hang) and the launcher error must carry the named
    schedule diff from the dying rank's stderr."""
    out = _launch(tmp_path, {"SEED_DIVERGENCE": "1"})
    assert out.returncode == 1, (out.stdout, out.stderr)
    # the worker caught SpmdPreflightError and exited 3; spawn surfaced
    # that rank's stderr tail, which names the diverging op
    assert "exited with code 3" in out.stderr, out.stderr
    assert "SPMD preflight failed" in out.stderr, out.stderr
    assert "all-reduce" in out.stderr, out.stderr
    assert "ddp_o2_train" in out.stderr, out.stderr


def test_spawn_surfaces_failing_rank_stderr_tail(tmp_path, monkeypatch):
    """A rank that dies pre-barrier must be diagnosable from the
    launcher's exception alone: first failing rank, exit code, and the
    tail of its captured stderr."""
    from apex_tpu.parallel import multiproc

    script = tmp_path / "boom.py"
    script.write_text(
        "import sys\n"
        "print('device mask mismatch: the diagnosis', file=sys.stderr)\n"
        "sys.exit(7)\n")
    monkeypatch.chdir(tmp_path)
    with pytest.raises(multiproc.ClusterInitError) as ei:
        multiproc.spawn([str(script)], world_size=1)
    msg = str(ei.value)
    assert "rank 0 exited with code 7" in msg
    assert "the diagnosis" in msg
    assert "PROC_0.err" in msg
