"""Pallas-under-shard_map on real hardware (VERDICT r1 item 4).

The virtual-CPU distributed tier pins ``APEX_TPU_KERNELS=jnp`` because the
interpret-mode pallas evaluator has a VMA limitation under shard_map; this
module is the hardware half of that bargain: a FULL amp-O2 training step —
packed two-stage LAMB Pallas kernels, DDP gradient reduction, dynamic loss
scaling — Mosaic-compiled inside ``shard_map`` on a real TPU mesh
(1 device in this environment; the mesh axis is real either way).

Run with ``APEX_TPU_TEST_PLATFORM=axon`` (tools/onchip_run.py records the
result in ONCHIP_r{N}.json).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.jax_compat import shard_map

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="Mosaic-compiled pallas under shard_map needs hardware")


def test_pallas_train_step_under_shard_map(monkeypatch):
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    from apex_tpu import amp
    from apex_tpu.models.mlp import MLP, cross_entropy_loss
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.parallel import DistributedDataParallel

    n = min(len(jax.devices()), 8)
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    model = MLP(features=(128, 64))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64)))["params"]
    a = amp.initialize(optimizer=FusedLAMB(lr=1e-2), opt_level="O2",
                       verbosity=0)
    state = a.init(params)
    ddp = DistributedDataParallel(axis_name="data")

    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)

    inner = amp.make_train_step(a, loss_fn, axis_name="data",
                                reduce_fn=ddp.reduce)

    def train_step(state, xb, yb):
        state, m = inner(state, xb, yb)
        return state, jax.lax.pmean(m["loss"], "data")

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))

    x = jax.random.normal(jax.random.PRNGKey(1), (16 * n, 64))
    y = (jnp.arange(16 * n) % 10).astype(jnp.int32)
    losses = []
    for _ in range(8):
        state, loss = step(state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_pallas_multi_tensor_under_shard_map(monkeypatch):
    """The packed scale/l2norm kernels (SMEM overflow flag + per-chunk
    tables) compiled by Mosaic inside a shard_map region."""
    monkeypatch.setenv("APEX_TPU_KERNELS", "pallas")
    from apex_tpu.ops.multi_tensor import (
        multi_tensor_l2norm, multi_tensor_scale)

    n = min(len(jax.devices()), 8)
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    xs = [jax.random.normal(jax.random.PRNGKey(i), (4096 + i,))
          for i in range(3)]

    def body(*ts):
        outs, flag = multi_tensor_scale(4096, [list(ts)], 0.5)
        total, per = multi_tensor_l2norm(4096, [outs], per_tensor=True)
        return total, per, flag

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(), P(), P()),
        out_specs=(P(), P(), P())))
    total, per, flag = f(*xs)
    ref = np.array([np.linalg.norm(np.asarray(t) * 0.5) for t in xs])
    np.testing.assert_allclose(np.asarray(per), ref, rtol=1e-5)
    np.testing.assert_allclose(float(total), np.sqrt((ref ** 2).sum()),
                               rtol=1e-5)
    assert int(flag) == 0
