"""pjit auto-sharding training patterns as continuous tests — the TP and
FSDP slices of ``__graft_entry__.dryrun_multichip`` (tensor parallelism via
Megatron-style column/row NamedShardings; ZeRO-3-style param+moment
sharding) under pytest so regressions surface in CI, not only in the
driver's dry run.  SURVEY.md §7: the sharding spec IS the strategy; XLA
inserts the collectives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def _loss_fn(p, xb):
    h = jax.nn.relu(xb @ p["w1"])
    return jnp.mean(jnp.square(h @ p["w2"]))


def _sharded_state(a, params, shardings):
    state = a.init(params)
    return state._replace(
        master_params=jax.tree.map(
            lambda t, s: jax.device_put(t, s), state.master_params,
            shardings))


def _assert_trains(step, state, x, check_leaf):
    before = np.asarray(state.master_params["w1"])
    new_state, metrics = step(state, x)
    jax.block_until_ready(new_state)
    assert np.isfinite(float(metrics["loss"]))
    assert not np.allclose(np.asarray(new_state.master_params["w1"]),
                           before)
    check_leaf(new_state.master_params["w1"])
    return new_state


def test_tensor_parallel_megatron_shardings():
    """DP x TP: w1 column-sharded, w2 row-sharded over "model"; batch over
    "data"; amp O2 + FusedAdam; XLA inserts the all-reduces."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh or a pod slice)")
    devices = jax.devices()[:8]
    mesh = Mesh(np.array(devices).reshape(4, 2), ("data", "model"))
    d_in, d_hidden = 16, 32
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (d_in, d_hidden)),
        "w2": jax.random.normal(jax.random.PRNGKey(1), (d_hidden, d_in)),
    }
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    shardings = {"w1": NamedSharding(mesh, P(None, "model")),
                 "w2": NamedSharding(mesh, P("model", None))}
    state = _sharded_state(a, params, shardings)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (16, d_in)),
        NamedSharding(mesh, P("data")))
    step = jax.jit(amp.make_train_step(a, _loss_fn))

    def check(w1):
        # the update must preserve the TP layout (no silent gather)
        assert w1.sharding.spec == P(None, "model")

    state = _assert_trains(step, state, x, check)
    # second step reuses the compiled path
    _assert_trains(step, state, x, check)


def test_fsdp_zero3_param_and_moment_sharding():
    """FSDP/ZeRO-3: every param leaf AND its Adam moments shard over
    "data"; batch over the same axis; no manual collectives."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    devices = jax.devices()[:8]
    n = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    d_in, d_hidden = 8, 16 * n
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(3), (d_in, d_hidden)),
        "w2": jax.random.normal(jax.random.PRNGKey(4), (d_hidden, d_in)),
    }
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    state = a.init(params)
    shardings = {"w1": NamedSharding(mesh, P(None, "data")),
                 "w2": NamedSharding(mesh, P("data", None))}

    def put(path, leaf):
        key = jax.tree_util.keystr(path)
        for name, s in shardings.items():
            if name in key and getattr(leaf, "ndim", 0) == 2:
                return jax.device_put(leaf, s)
        return leaf

    # params AND moments (matched by path) shard; scalar counters replicate
    state = jax.tree_util.tree_map_with_path(put, state)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(5), (4 * n, d_in)),
        NamedSharding(mesh, P("data")))
    step = jax.jit(amp.make_train_step(a, _loss_fn))

    def check(w1):
        assert w1.sharding.spec == P(None, "data")

    state = _assert_trains(step, state, x, check)
    # moments kept their ZeRO-3 layout through the update
    m1 = state.opt_state.m["w1"]
    assert m1.sharding.spec == P(None, "data")
    _assert_trains(step, state, x, check)
