"""pjit auto-sharding training patterns as continuous tests — the TP and
FSDP slices of ``__graft_entry__.dryrun_multichip`` (tensor parallelism via
Megatron-style column/row NamedShardings; ZeRO-3-style param+moment
sharding) under pytest so regressions surface in CI, not only in the
driver's dry run.  SURVEY.md §7: the sharding spec IS the strategy; XLA
inserts the collectives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def _loss_fn(p, xb):
    h = jax.nn.relu(xb @ p["w1"])
    return jnp.mean(jnp.square(h @ p["w2"]))


def _sharded_state(a, params, shardings):
    state = a.init(params)
    return state._replace(
        master_params=jax.tree.map(
            lambda t, s: jax.device_put(t, s), state.master_params,
            shardings))


def _assert_trains(step, state, x, check_leaf):
    before = np.asarray(state.master_params["w1"])
    new_state, metrics = step(state, x)
    jax.block_until_ready(new_state)
    assert np.isfinite(float(metrics["loss"]))
    assert not np.allclose(np.asarray(new_state.master_params["w1"]),
                           before)
    check_leaf(new_state.master_params["w1"])
    return new_state


def test_tensor_parallel_megatron_shardings():
    """DP x TP: w1 column-sharded, w2 row-sharded over "model"; batch over
    "data"; amp O2 + FusedAdam; XLA inserts the all-reduces."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh or a pod slice)")
    devices = jax.devices()[:8]
    mesh = Mesh(np.array(devices).reshape(4, 2), ("data", "model"))
    d_in, d_hidden = 16, 32
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (d_in, d_hidden)),
        "w2": jax.random.normal(jax.random.PRNGKey(1), (d_hidden, d_in)),
    }
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    shardings = {"w1": NamedSharding(mesh, P(None, "model")),
                 "w2": NamedSharding(mesh, P("model", None))}
    state = _sharded_state(a, params, shardings)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (16, d_in)),
        NamedSharding(mesh, P("data")))
    step = jax.jit(amp.make_train_step(a, _loss_fn))

    def check(w1):
        # the update must preserve the TP layout (no silent gather)
        assert w1.sharding.spec == P(None, "model")

    state = _assert_trains(step, state, x, check)
    # second step reuses the compiled path
    _assert_trains(step, state, x, check)


def test_fsdp_zero3_param_and_moment_sharding():
    """FSDP/ZeRO-3: every param leaf AND its Adam moments shard over
    "data"; batch over the same axis; no manual collectives."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    devices = jax.devices()[:8]
    n = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    d_in, d_hidden = 8, 16 * n
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(3), (d_in, d_hidden)),
        "w2": jax.random.normal(jax.random.PRNGKey(4), (d_hidden, d_in)),
    }
    a = amp.initialize(optimizer=FusedAdam(lr=1e-2), opt_level="O2",
                       verbosity=0)
    state = a.init(params)
    shardings = {"w1": NamedSharding(mesh, P(None, "data")),
                 "w2": NamedSharding(mesh, P("data", None))}

    def put(path, leaf):
        key = jax.tree_util.keystr(path)
        for name, s in shardings.items():
            if name in key and getattr(leaf, "ndim", 0) == 2:
                return jax.device_put(leaf, s)
        return leaf

    # params AND moments (matched by path) shard; scalar counters replicate
    state = jax.tree_util.tree_map_with_path(put, state)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(5), (4 * n, d_in)),
        NamedSharding(mesh, P("data")))
    step = jax.jit(amp.make_train_step(a, _loss_fn))

    def check(w1):
        assert w1.sharding.spec == P(None, "data")

    state = _assert_trains(step, state, x, check)
    # moments kept their ZeRO-3 layout through the update
    m1 = state.opt_state.m["w1"]
    assert m1.sharding.spec == P(None, "data")
    _assert_trains(step, state, x, check)


def test_3d_composition_matches_single_device():
    """DP x TP x SP in one step (the ``dp_tp_sp_3d`` dryrun slice) must
    produce the SAME loss and updated master params as an unsharded
    single-device step on identical inputs — a stronger check than the
    dryrun's finite-loss: it catches wrong-axis psums, double-counted
    loss normalizers, and missing gradient reductions, the exact bug
    class 3-D composition invites."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh or a pod slice)")
    import __graft_entry__ as graft

    devices = jax.devices()[:8]
    step, args, _check = graft._build_dp_tp_sp(devices)
    out_sh = step(*args)
    jax.block_until_ready(out_sh)
    state_sh, loss_sh = out_sh

    # unsharded replica: same params/inputs (the builder's fixed seeds),
    # same math with full tensors and local attention
    from apex_tpu.attention import attention
    from apex_tpu.ops.rope import rope

    state0, x, positions = args
    E, nh = 16, 2
    B, L = x.shape[0], x.shape[1]
    hd = E // nh

    def loss_un(p, xb, pos):
        qkv = xb @ p["wqkv"].astype(xb.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], nh, hd)

        q = rope(heads(q), pos, 10000.0)
        k = rope(heads(k), pos, 10000.0)
        o = attention(q, k, heads(v), axis_name=None, causal=True)
        x2 = xb + o.reshape(xb.shape) @ p["wo"].astype(xb.dtype)
        h = jax.nn.relu(x2 @ p["w1"].astype(x2.dtype))
        y = h @ p["w2"].astype(h.dtype) + x2
        return jnp.sum(jnp.square(y).astype(jnp.float32)) / y.size

    from apex_tpu.optimizers import FusedAdam
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                       verbosity=0)
    # rebuild an identical unsharded state from the same master params
    state_un = a.init(jax.tree.map(np.asarray, state0.master_params))
    step_un = jax.jit(amp.make_train_step(a, loss_un))
    state_un, metrics_un = step_un(state_un, x, positions)

    # bf16 matmuls reassociate across the model/seq shards (fp32
    # accumulators, psum'd partials), so agreement is at the fp32
    # round-off of bf16-product sums
    np.testing.assert_allclose(float(loss_sh), float(metrics_un["loss"]),
                               rtol=1e-4)
    # Param agreement: Adam normalizes each element's update to ~lr, so
    # a NEAR-ZERO gradient element can flip sign under bf16
    # reassociation noise and land 2*lr away — bound by the step size
    # (atol 2.5e-3 > 2*lr=2e-3).  A sharding bug (wrong-axis psum,
    # double-counted normalizer) shifts whole tensors by O(1) and still
    # fails loudly.
    for (pa, la), (_pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(state_sh.master_params),
            jax.tree_util.tree_leaves_with_path(state_un.master_params)):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=2e-3, atol=2.5e-3,
            err_msg=jax.tree_util.keystr(pa))
