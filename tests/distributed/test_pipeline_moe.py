"""Pipeline- and expert-parallel tests on the virtual CPU mesh.

Both modes are beyond the reference (SURVEY.md section 2: apex has no
tp/pp/sp/ep), but complete the dp/tp/pp/sp/ep surface this framework
validates multi-device (conftest: 8 virtual CPU devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from apex_tpu.parallel.moe import moe_apply, top1_routing

D = 8


def _mesh(n, name):
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs), (name,))


def stage_fn(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def make_stage(key, d):
    kw, kb = jax.random.split(key)
    return {"w": jax.random.normal(kw, (d, d)) * 0.5,
            "b": jax.random.normal(kb, (d,)) * 0.1}


class TestPipeline:
    S = 4

    def setup_method(self, _):
        keys = jax.random.split(jax.random.PRNGKey(0), self.S)
        self.stages = [make_stage(k, D) for k in keys]
        self.stacked = stack_stage_params(self.stages)
        self.x = jax.random.normal(jax.random.PRNGKey(1), (16, D))

    def reference(self, stages, x):
        h = x
        for i in range(self.S):
            h = stage_fn(jax.tree.map(lambda l: l[i], stages), h)
        return h

    @pytest.mark.parametrize("n_micro", [4, 8])
    def test_forward_matches_sequential(self, n_micro):
        mesh = _mesh(self.S, "pipe")
        f = shard_map(
            lambda sp, x: pipeline_apply(stage_fn, sp, x, "pipe",
                                         n_microbatches=n_micro),
            mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())
        y = jax.jit(f)(self.stacked, self.x)
        ref = self.reference(self.stacked, self.x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_backward_matches_sequential(self):
        mesh = _mesh(self.S, "pipe")

        def loss_pp(sp, x):
            f = shard_map(
                lambda sp, x: pipeline_apply(stage_fn, sp, x, "pipe"),
                mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())
            return jnp.mean(f(sp, x) ** 2)

        def loss_ref(sp, x):
            return jnp.mean(self.reference(sp, x) ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(self.stacked, self.x)
        g_ref = jax.grad(loss_ref)(self.stacked, self.x)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_batch_divisibility_error(self):
        mesh = _mesh(self.S, "pipe")
        f = shard_map(
            lambda sp, x: pipeline_apply(stage_fn, sp, x, "pipe",
                                         n_microbatches=3),
            mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())
        with pytest.raises(ValueError, match="microbatch"):
            jax.eval_shape(f, self.stacked, self.x)


def expert_fn(p, x):
    return jax.nn.gelu(x @ p["wi"]) @ p["wo"]


def make_experts(key, n, d, hidden=16):
    k1, k2 = jax.random.split(key)
    return {"wi": jax.random.normal(k1, (n, d, hidden)) * 0.3,
            "wo": jax.random.normal(k2, (n, hidden, d)) * 0.3}


class TestMoE:
    RANKS, E_LOCAL = 4, 2

    def setup_method(self, _):
        E = self.RANKS * self.E_LOCAL
        self.experts = make_experts(jax.random.PRNGKey(0), E, D)
        self.router = jax.random.normal(jax.random.PRNGKey(1), (D, E))
        # tokens: (ranks * T_local, D)
        self.x = jax.random.normal(jax.random.PRNGKey(2),
                                   (self.RANKS * 32, D))

    def reference_shard(self, x_shard, capacity_factor):
        """Dense single-device evaluation of one rank's token shard with
        ALL experts local — what the all_to_all plumbing must reproduce."""
        t_local, d = x_shard.shape
        E = self.RANKS * self.E_LOCAL
        capacity = max(1, int(capacity_factor * t_local / E))
        logits = x_shard @ self.router
        dispatch, combine, aux = top1_routing(logits, capacity)
        sent = jnp.einsum("tec,td->ecd", dispatch, x_shard)
        out = jax.vmap(expert_fn)(self.experts, sent)
        y = jnp.einsum("tec,ecd->td", combine, out)
        return y, aux

    def test_no_drop_matches_per_token_reference(self):
        """Independent semantics check (no shared routing code): with
        capacity ample, y[t] == router_prob[t] * expert_fn(params[e_t], x[t])
        for every token."""
        mesh = _mesh(self.RANKS, "expert")
        f = shard_map(
            lambda ep, rw, x: moe_apply(expert_fn, ep, rw, x, "expert",
                                        capacity_factor=8.0),
            mesh=mesh, in_specs=(P("expert"), P(), P("expert")),
            out_specs=(P("expert"), P()))
        y, _ = jax.jit(f)(self.experts, self.router, self.x)
        logits = self.x @ self.router
        probs = jax.nn.softmax(logits, axis=-1)
        for t in range(0, self.x.shape[0], 7):
            e = int(jnp.argmax(logits[t]))
            one = jax.tree.map(lambda l: l[e], self.experts)
            ref = float(probs[t, e]) * expert_fn(one, self.x[t][None, :])[0]
            np.testing.assert_allclose(np.asarray(y[t]), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("capacity_factor", [8.0, 1.0])
    def test_matches_dense_reference(self, capacity_factor):
        # cf=8 -> nothing dropped; cf=1 -> capacity drops exercised
        mesh = _mesh(self.RANKS, "expert")
        f = shard_map(
            lambda ep, rw, x: moe_apply(expert_fn, ep, rw, x, "expert",
                                        capacity_factor=capacity_factor),
            mesh=mesh, in_specs=(P("expert"), P(), P("expert")),
            out_specs=(P("expert"), P()))
        y, aux = jax.jit(f)(self.experts, self.router, self.x)

        shards = self.x.reshape(self.RANKS, -1, D)
        refs = [self.reference_shard(s, capacity_factor) for s in shards]
        ref_y = jnp.concatenate([r[0] for r in refs])
        ref_aux = jnp.mean(jnp.stack([r[1] for r in refs]))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)

    def test_gradients_flow_to_all_experts(self):
        mesh = _mesh(self.RANKS, "expert")

        def loss(ep, rw, x):
            f = shard_map(
                lambda ep, rw, x: moe_apply(expert_fn, ep, rw, x, "expert",
                                            capacity_factor=8.0),
                mesh=mesh, in_specs=(P("expert"), P(), P("expert")),
                out_specs=(P("expert"), P()))
            y, aux = f(ep, rw, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(self.experts, self.router, self.x)
        for leaf in jax.tree.leaves(g):
            assert bool(jnp.isfinite(leaf).all())
        # every expert receives tokens under this router (checked above),
        # so every expert's weights must receive gradient
        per_expert = jnp.asarray(
            [float(jnp.abs(g["wi"][e]).max())
             for e in range(self.RANKS * self.E_LOCAL)])
        assert int((per_expert > 0).sum()) == self.RANKS * self.E_LOCAL, \
            per_expert


class TestShardedOverflowSkip:
    """finite_axes: with params sharded over a mesh axis, an overflow on ONE
    rank must skip the step on EVERY rank (globally consistent scaler
    trajectory) — the sharded-param extension of the reference's shared
    overflow buffer."""

    def test_one_rank_overflow_skips_all(self):
        import optax
        from apex_tpu import amp as amp_mod

        n = 4
        mesh = _mesh(n, "shard")
        a = amp_mod.initialize(optimizer=optax.sgd(0.1), opt_level="O2",
                               loss_scale=64.0, verbosity=0)
        params = {"w": jnp.ones((n, D))}
        state = a.init(params)

        def step(state, grads):
            new_state, info = a.apply_gradients(state, grads,
                                                finite_axes=("shard",))
            return new_state, info["overflow"]

        def spec_state(s):
            return jax.tree.map(
                lambda l: P("shard") if getattr(l, "ndim", 0) >= 1
                and l.shape[0] == n else P(), s)

        grads = jnp.zeros((n, D)).at[2, 0].set(jnp.inf)  # rank 2 only
        f = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=(spec_state(state), P("shard")),
            out_specs=(spec_state(state), P())))
        new_state, overflow = f(state, {"w": grads})
        assert bool(overflow)
        # every rank's param slice unchanged — including the finite ones
        np.testing.assert_array_equal(
            np.asarray(new_state.master_params["w"]),
            np.asarray(params["w"]))
