"""amp × Reducer grad-accumulation cadence on the 8-device CPU mesh
(VERDICT r2 item 6).

The reference's ``Reducer`` (``apex/parallel/distributed.py:94-131``) is the
manual-trigger reduction: users accumulate local grads for N micro-batches
and call ``reducer.reduce`` only on the boundary iteration, under amp's
scaled-loss loop.  Here the same cadence is expressed two ways — the manual
per-micro loop (stashed grads, one reduce, one ``apply_gradients``) and the
compiled ``make_train_step(accum_steps=N, reduce_fn=reducer.reduce)`` — and
both must match the plain every-step DDP run on the equivalent big batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.mlp import MLP, cross_entropy_loss
from apex_tpu.parallel import Reducer, data_parallel_mesh, pvary_params
from apex_tpu.utils.jax_compat import shard_map

WORLD = 8
N_MICRO = 2
BATCH = 4          # per-rank, per-micro
DIM, CLASSES = 8, 4
LR = 0.05


@pytest.fixture(scope="module")
def mesh():
    # first WORLD devices only: the platform carries 16 virtual devices
    # (the disaggregated-serving fleet topology); these WORLD=8-shaped
    # tests keep their original 8-wide mesh
    return data_parallel_mesh(num_devices=WORLD)


def _invariant_step(step):
    """Per-rank metrics (the local loss) are device-varying; pmean them
    so the shard_map out_specs can be fully replicated."""
    def wrapped(state, xr, yr):
        new_state, m = step(state, xr, yr)
        m = dict(m, loss=jax.lax.pmean(m["loss"], "data"))
        return new_state, m
    return wrapped


def _setup(seed=0):
    model = MLP(features=(16, CLASSES))
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, DIM)))["params"]
    rng = np.random.RandomState(seed)
    x = jnp.asarray(
        rng.randn(WORLD * N_MICRO * BATCH, DIM).astype(np.float32))
    y = jnp.asarray(rng.randint(0, CLASSES, WORLD * N_MICRO * BATCH))
    a = amp.initialize(optimizer=optax.sgd(LR), opt_level="O2",
                       verbosity=0)
    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)
    return a, params, x, y, loss_fn


def test_manual_reducer_cadence_matches_big_batch(mesh):
    """N_MICRO stashed micro-grads per rank, ONE reducer.reduce at the
    boundary, one apply_gradients — vs the single big-batch step whose
    loss is the mean of the per-micro means.  The manual path is the
    reference's steady-state Reducer loop under amp."""
    a, params, x, y, loss_fn = _setup()
    reducer = Reducer(axis_name="data")
    state0 = a.init(params)

    def manual(state, xr, yr):
        # xr: (N_MICRO*BATCH, DIM) on this rank
        params_c = pvary_params(a.model_params(state), "data")
        sstate = state.scaler_states[0]
        accum = None
        for i in range(N_MICRO):
            xb = xr[i * BATCH:(i + 1) * BATCH]
            yb = yr[i * BATCH:(i + 1) * BATCH]
            # a.run mirrors make_train_step's input casting (batch ->
            # bf16 under O2)
            g = jax.grad(lambda p: a.scale_loss(
                a.run(loss_fn, p, xb, yb) / N_MICRO, state))(params_c)
            if accum is None:
                accum, _ = a.scaler.unscale(g, sstate)
            else:
                accum, _ = a.scaler.unscale_with_stashed(g, accum, sstate)
        # boundary iteration: the ONE collective of the cadence
        reduced = reducer.reduce(accum)
        # grads are already unscaled: feed them as the stash with a zero
        # fresh-grad tree so apply_gradients' unscale adds nothing
        zeros = jax.tree.map(jnp.zeros_like, reduced)
        new_state, info = a.apply_gradients(state, zeros,
                                            stashed_grads=reduced)
        return new_state, info["overflow"]

    step = jax.jit(shard_map(
        manual, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=(P(), P())))
    acc_state, overflow = step(state0, x, y)
    assert not bool(overflow)

    # plain DDP big-batch reference: every-step reduce, same global batch
    big = jax.jit(shard_map(
        _invariant_step(amp.make_train_step(a, loss_fn, axis_name="data")),
        mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))
    big_state, m = big(state0, x, y)
    assert not bool(m["overflow"])

    for acc, ref in zip(jax.tree.leaves(acc_state.master_params),
                        jax.tree.leaves(big_state.master_params)):
        # bf16 micro-grads round differently from the one big backward
        # (the l0 grad-accum suite observes ~2e-4 absolute)
        np.testing.assert_allclose(np.asarray(acc), np.asarray(ref),
                                   rtol=1e-3, atol=2e-4)


def test_compiled_accum_with_reducer_matches_manual(mesh):
    """make_train_step(accum_steps=N, reduce_fn=reducer.reduce): the
    delay_allreduce economics as one jit — reduction fires once on the
    accumulated grads and must land on the same masters as the manual
    cadence."""
    a, params, x, y, loss_fn = _setup(seed=1)
    reducer = Reducer(axis_name="data")
    state0 = a.init(params)

    compiled = jax.jit(shard_map(
        _invariant_step(amp.make_train_step(
            a, loss_fn, axis_name="data", reduce_fn=reducer.reduce,
            accum_steps=N_MICRO)),
        mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))
    comp_state, m = compiled(state0, x, y)
    assert not bool(m["overflow"])

    big = jax.jit(shard_map(
        _invariant_step(amp.make_train_step(a, loss_fn, axis_name="data")),
        mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))
    big_state, _ = big(state0, x, y)

    for acc, ref in zip(jax.tree.leaves(comp_state.master_params),
                        jax.tree.leaves(big_state.master_params)):
        np.testing.assert_allclose(np.asarray(acc), np.asarray(ref),
                                   rtol=1e-3, atol=5e-5)


def test_reducer_cadence_overflow_on_one_rank_skips_globally(mesh):
    """An inf in one rank's micro-batch 0 must poison the reduced grads
    everywhere (inf rides the all-reduce) and skip the step globally —
    the amp x Reducer failure-detection composition."""
    a, params, x, y, loss_fn = _setup(seed=2)
    reducer = Reducer(axis_name="data")
    state0 = a.init(params)
    x_bad = x.at[0, 0].set(jnp.inf)      # rank 0, micro 0

    compiled = jax.jit(shard_map(
        _invariant_step(amp.make_train_step(
            a, loss_fn, axis_name="data", reduce_fn=reducer.reduce,
            accum_steps=N_MICRO)),
        mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))
    new_state, m = compiled(state0, x_bad, y)
    assert bool(m["overflow"])
    for old, new in zip(jax.tree.leaves(state0.master_params),
                        jax.tree.leaves(new_state.master_params)):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
    assert float(new_state.scaler_states[0].loss_scale) == 2.0 ** 15
