"""Casting-policy conformance: the port of the reference's
``tests/L0/run_amp/test_basic_casts.py`` (+ ``utils.py`` fixtures).

The reference's ``run_layer_test`` asserts the *output dtype string* of every
patched fn for fp16/fp32/fp64 inputs: whitelist -> HalfTensor, blacklist ->
FloatTensor, promote/passthrough -> match-the-widest-input, banned BCE raises
unless allowed (:14-21, 73-103).  Here the policy layer is
:mod:`apex_tpu.amp.ops`; the same matrix is asserted for every entry of the
:mod:`apex_tpu.amp.lists` tables, plus a table-integrity check that each
listed name actually exists in the ops namespace with the right wrapper kind
(the reference's auto-append consistency, ``tensor_overrides.py:55-62``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import lists, ops

HALF = jnp.bfloat16
O1 = amp.O1(half_dtype=HALF)


def r(*shape, dtype=jnp.float32, key=0):
    return jax.random.normal(jax.random.PRNGKey(key), shape).astype(dtype)


# Representative invocation per op: fn(dtype) -> output array.  Ops taking
# several floating args get them all at the probe dtype (the reference casts
# every input the same way, utils.py:1-21).
B, N, C = 4, 8, 16

HALF_CALLS = {
    "matmul": lambda dt: ops.matmul(r(B, N, dtype=dt), r(N, C, dtype=dt)),
    "dot": lambda dt: ops.dot(r(N, dtype=dt), r(N, dtype=dt)),
    "tensordot": lambda dt: ops.tensordot(r(B, N, dtype=dt),
                                          r(N, C, dtype=dt), 1),
    "einsum": lambda dt: ops.einsum("bn,nc->bc", r(B, N, dtype=dt),
                                    r(N, C, dtype=dt)),
    "dot_general": lambda dt: ops.dot_general(
        r(B, N, dtype=dt), r(N, C, dtype=dt),
        dimension_numbers=(((1,), (0,)), ((), ()))),
    "conv": lambda dt: ops.conv(
        r(1, 8, 8, 3, dtype=dt), r(3, 3, 3, C, dtype=dt),
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")),
    "conv_general_dilated": lambda dt: ops.conv_general_dilated(
        r(1, 8, 8, 3, dtype=dt), r(3, 3, 3, C, dtype=dt),
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")),
    "conv_transpose": lambda dt: ops.conv_transpose(
        r(1, 8, 8, 3, dtype=dt), r(3, 3, 3, C, dtype=dt),
        strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")),
    "linear": lambda dt: ops.linear(r(B, N, dtype=dt), r(N, C, dtype=dt),
                                    r(C, dtype=dt)),
    "prelu": lambda dt: ops.prelu(r(B, N, dtype=dt),
                                  jnp.asarray(0.25, dt)),
}

FP32_CALLS = {
    "exp": lambda dt: ops.exp(r(N, dtype=dt)),
    "expm1": lambda dt: ops.expm1(r(N, dtype=dt)),
    "log": lambda dt: ops.log(jnp.abs(r(N, dtype=dt)) + 0.5),
    "log1p": lambda dt: ops.log1p(jnp.abs(r(N, dtype=dt))),
    "log2": lambda dt: ops.log2(jnp.abs(r(N, dtype=dt)) + 0.5),
    "log10": lambda dt: ops.log10(jnp.abs(r(N, dtype=dt)) + 0.5),
    "pow": lambda dt: ops.pow(jnp.abs(r(N, dtype=dt)) + 0.5, 2.0),
    "reciprocal": lambda dt: ops.reciprocal(r(N, dtype=dt) + 2.0),
    "rsqrt": lambda dt: ops.rsqrt(jnp.abs(r(N, dtype=dt)) + 0.5),
    "sinh": lambda dt: ops.sinh(r(N, dtype=dt)),
    "cosh": lambda dt: ops.cosh(r(N, dtype=dt)),
    "tan": lambda dt: ops.tan(r(N, dtype=dt)),
    "acos": lambda dt: ops.acos(jnp.clip(r(N, dtype=dt), -0.9, 0.9)),
    "asin": lambda dt: ops.asin(jnp.clip(r(N, dtype=dt), -0.9, 0.9)),
    "erfinv": lambda dt: ops.erfinv(jnp.clip(r(N, dtype=dt), -0.9, 0.9)),
    "sum": lambda dt: ops.sum(r(N, dtype=dt)),
    "prod": lambda dt: ops.prod(r(N, dtype=dt)),
    "mean": lambda dt: ops.mean(r(N, dtype=dt)),
    "var": lambda dt: ops.var(r(N, dtype=dt)),
    "std": lambda dt: ops.std(r(N, dtype=dt)),
    "cumsum": lambda dt: ops.cumsum(r(N, dtype=dt)),
    "cumprod": lambda dt: ops.cumprod(r(N, dtype=dt)),
    "norm": lambda dt: ops.norm(r(N, dtype=dt)),
    "logsumexp": lambda dt: ops.logsumexp(r(N, dtype=dt)),
    "softmax": lambda dt: ops.softmax(r(B, N, dtype=dt)),
    "log_softmax": lambda dt: ops.log_softmax(r(B, N, dtype=dt)),
    "softmin": lambda dt: ops.softmin(r(B, N, dtype=dt)),
    "softplus": lambda dt: ops.softplus(r(N, dtype=dt)),
    "layer_norm": lambda dt: ops.layer_norm(r(B, N, dtype=dt), N,
                                            r(N, dtype=dt, key=1),
                                            r(N, dtype=dt, key=2)),
    "group_norm": lambda dt: ops.group_norm(r(B, C, dtype=dt), 4,
                                            r(C, dtype=dt, key=1),
                                            r(C, dtype=dt, key=2)),
    "batch_norm": lambda dt: ops.batch_norm(
        r(B, C, dtype=dt), jnp.zeros(C, dt), jnp.ones(C, dt),
        r(C, dtype=dt, key=1), r(C, dtype=dt, key=2)),
    "cross_entropy": lambda dt: ops.cross_entropy(
        r(B, N, dtype=dt), jnp.arange(B) % N),
    "nll_loss": lambda dt: ops.nll_loss(
        jax.nn.log_softmax(r(B, N, dtype=dt)), jnp.arange(B) % N),
    "l1_loss": lambda dt: ops.l1_loss(r(N, dtype=dt), r(N, dtype=dt, key=1)),
    "mse_loss": lambda dt: ops.mse_loss(r(N, dtype=dt),
                                        r(N, dtype=dt, key=1)),
    "smooth_l1_loss": lambda dt: ops.smooth_l1_loss(
        r(N, dtype=dt), r(N, dtype=dt, key=1)),
    "kl_div": lambda dt: ops.kl_div(
        jax.nn.log_softmax(r(B, N, dtype=dt)),
        jax.nn.softmax(r(B, N, dtype=dt, key=1))),
    "poisson_nll_loss": lambda dt: ops.poisson_nll_loss(
        r(N, dtype=dt), jnp.abs(r(N, dtype=dt, key=1))),
    "cosine_embedding_loss": lambda dt: ops.cosine_embedding_loss(
        r(B, N, dtype=dt), r(B, N, dtype=dt, key=1),
        jnp.ones(B, jnp.int32)),
}

PROMOTE_CALLS = {
    "add": lambda a, b: ops.add(a, b),
    "sub": lambda a, b: ops.sub(a, b),
    "mul": lambda a, b: ops.mul(a, b),
    "div": lambda a, b: ops.div(a, b + 2.0),
    "atan2": lambda a, b: ops.atan2(a, b + 2.0),
    "maximum": lambda a, b: ops.maximum(a, b),
    "minimum": lambda a, b: ops.minimum(a, b),
    "equal": lambda a, b: ops.equal(a, b),
    "greater": lambda a, b: ops.greater(a, b),
    "less": lambda a, b: ops.less(a, b),
}

COMPARISONS = {"equal", "greater", "less"}


def test_lists_and_ops_namespace_agree():
    """Table integrity: every listed name exists in the ops namespace with the
    wrapper kind its table prescribes (the reference's auto-append rule,
    ``tensor_overrides.py:55-62``, made an explicit invariant)."""
    for name in lists.HALF_OPS:
        assert getattr(ops, name).__amp_wrapped__ == "half", name
    for name in lists.FP32_OPS:
        assert getattr(ops, name).__amp_wrapped__ == "float", name
    for name in lists.PROMOTE_OPS:
        assert getattr(ops, name).__amp_wrapped__ == "promote", name
    for name in lists.SEQUENCE_PROMOTE_OPS:
        assert getattr(ops, name).__amp_wrapped__ == "sequence_promote", name
    for name in lists.BANNED_OPS:
        assert getattr(ops, name).__amp_wrapped__ == "banned", name
    # and the calls tables above cover the lists completely
    assert set(HALF_CALLS) == set(lists.HALF_OPS)
    assert set(FP32_CALLS) == set(lists.FP32_OPS)
    assert set(PROMOTE_CALLS) == set(lists.PROMOTE_OPS)


@pytest.mark.parametrize("name", sorted(HALF_CALLS))
@pytest.mark.parametrize("in_dtype", [jnp.float32, HALF])
def test_whitelist_to_half(name, in_dtype):
    """Whitelist fn x any float input -> half output (reference :73-79)."""
    with ops.cast_context(O1):
        out = HALF_CALLS[name](in_dtype)
    assert out.dtype == HALF, (name, out.dtype)


@pytest.mark.parametrize("name", sorted(FP32_CALLS))
@pytest.mark.parametrize("in_dtype", [jnp.float32, HALF])
def test_blacklist_to_float(name, in_dtype):
    """Blacklist fn x any float input -> fp32 output (reference :81-87)."""
    with ops.cast_context(O1):
        out = FP32_CALLS[name](in_dtype)
    assert out.dtype == jnp.float32, (name, out.dtype)


@pytest.mark.parametrize("name", sorted(PROMOTE_CALLS))
@pytest.mark.parametrize("dtypes", [(HALF, HALF), (jnp.float32, HALF),
                                    (HALF, jnp.float32),
                                    (jnp.float32, jnp.float32)])
def test_promote_widest(name, dtypes):
    """Promote fn -> widest input type; comparisons -> bool
    (reference test_promotion.py:12-42 covers the op set via CASTS)."""
    a, b = r(N, dtype=dtypes[0]), r(N, dtype=dtypes[1], key=1)
    with ops.cast_context(O1):
        out = PROMOTE_CALLS[name](a, b)
    if name in COMPARISONS:
        assert out.dtype == jnp.bool_
    else:
        expect = jnp.float32 if jnp.float32 in dtypes else HALF
        assert out.dtype == expect, (name, out.dtype)


@pytest.mark.parametrize("name", ["concatenate", "stack"])
def test_sequence_promote(name):
    fn = getattr(ops, name)
    with ops.cast_context(O1):
        out = fn([r(N, dtype=HALF), r(N, dtype=jnp.float32, key=1)])
        assert out.dtype == jnp.float32
        out = fn([r(N, dtype=HALF), r(N, dtype=HALF, key=1)])
        assert out.dtype == HALF


def test_passthrough_without_policy():
    """No active policy -> every op is a transparent passthrough
    (reference: unpatched torch behaves normally)."""
    x = r(B, N, dtype=jnp.float32)
    w = r(N, C, dtype=jnp.float32)
    assert ops.matmul(x, w).dtype == jnp.float32
    assert ops.softmax(x.astype(HALF)).dtype == HALF
    np.testing.assert_allclose(np.asarray(ops.matmul(x, w)),
                               np.asarray(jnp.matmul(x, w)), rtol=1e-6)


def test_banned_bce_raises_and_allow_banned():
    """BCE on probabilities raises on half input under the policy with the
    detailed message; fp32 inputs and disabled casts pass (reference
    :89-103, functional_overrides.py:67-77)."""
    probs = jnp.clip(jnp.abs(r(N, dtype=HALF)), 0.05, 0.95)
    targets = (r(N, dtype=jnp.float32, key=1) > 0).astype(jnp.float32)
    with ops.cast_context(O1):
        with pytest.raises(NotImplementedError, match="binary_cross_entropy"):
            ops.binary_cross_entropy(probs, targets)
        # fp32 inputs are allowed
        out = ops.binary_cross_entropy(probs.astype(jnp.float32), targets)
        assert out.dtype == jnp.float32
        # and disable_casts suspends the ban (reference handle.disable_casts)
        with ops.disable_casts():
            ops.binary_cross_entropy(probs, targets)


def test_half_values_match_fp32_reference():
    """Numerics sanity on top of the dtype matrix: the O1-cast matmul equals
    the fp32 matmul of pre-cast inputs (what the reference's cast cache
    test guards, test_cache.py:15-21 — grads/values must match an uncached
    reference; XLA CSE plays the cache's role here)."""
    x, w = r(B, N), r(N, C, key=1)
    with ops.cast_context(O1):
        y = ops.matmul(x, w)
    y_ref = jnp.matmul(x.astype(HALF), w.astype(HALF))
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(y_ref, np.float32))


@pytest.mark.parametrize("kind", ["half", "float", "promote"])
def test_train_eval_train_transitions_keep_grads_stable(kind):
    """Port of the cast-cache suite (``test_cache.py:31-96``): grads through
    a whitelist/blacklist/promote module must be identical across
    train -> eval -> train transitions and must match the explicitly
    pre-cast reference (the property the reference's cache-invalidation
    rules protect; here the policy layer is stateless and XLA CSE plays
    the cache's role, so the invariant is structural)."""
    w = r(N, C)
    x = r(B, N, key=1)

    def fwd(w):
        if kind == "half":
            y = ops.matmul(x, w)
        elif kind == "float":
            y = ops.softmax(ops.matmul(x, w))
        else:
            y = ops.add(jnp.matmul(x.astype(HALF), w.astype(HALF)),
                        jnp.float32(1.0))
        return jnp.sum(y.astype(jnp.float32) ** 2)

    grads = []
    for phase in ("train", "eval", "train"):
        if phase == "train":
            with ops.cast_context(O1):
                grads.append(jax.grad(fwd)(w))
        else:
            fwd(w)  # eval pass outside the policy must not perturb anything

    np.testing.assert_array_equal(np.asarray(grads[0], np.float32),
                                  np.asarray(grads[1], np.float32))

    # explicit-cast reference for the whitelist module (test_cache.py:15-21)
    if kind == "half":
        ref = jax.grad(lambda w: jnp.sum(
            jnp.matmul(x.astype(HALF), w.astype(HALF))
            .astype(jnp.float32) ** 2))(w)
        np.testing.assert_array_equal(np.asarray(grads[0], np.float32),
                                      np.asarray(ref, np.float32))


def test_conv_rejects_unsupported_rank():
    """Rank-2 input (zero spatial dims) must raise the explicit ValueError,
    not build a bogus NDHWC dimension-numbers string ("DHW"[-0:] == "DHW")."""
    with pytest.raises(ValueError, match="spatial"):
        ops.conv(jnp.ones((2, 3)), jnp.ones((3, 4)))
    with pytest.raises(ValueError, match="spatial"):
        ops.conv(jnp.ones((2, 3, 3, 3, 3, 3)), jnp.ones((3, 3, 3, 3, 3, 4)))
