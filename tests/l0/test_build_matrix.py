"""Build-matrix checks — the ext-vs-no-ext install axis.

The reference's CI compiled its five CUDA extensions against ~7 docker
images and separately pip-installed with and without extensions
(``tests/docker_extension_builds/run.sh``, ``tests/L1/common/run_test.sh``).
The analog here: the C++ host library must rebuild from scratch with the
in-tree Makefile, and the package must import and train with the native
layer disabled (``APEX_TPU_NATIVE=0``) and with either kernel path
(``APEX_TPU_KERNELS=jnp|pallas``) — every combination a user install can
land in.
"""

import os
import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[2]


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("make") is None,
                    reason="needs g++ and make")
def test_native_lib_rebuilds_from_scratch(tmp_path):
    """Fresh compile of csrc with the in-tree Makefile (the reference's
    per-image extension build), into an out-of-tree copy so the repo's
    own build products are untouched."""
    src = tmp_path / "csrc"
    shutil.copytree(REPO / "csrc", src, ignore=shutil.ignore_patterns(
        "*.so", "*.o"))
    out = subprocess.run(["make", "-C", str(src)], capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    # the Makefile places the library at ../apex_tpu/_native/ relative to
    # csrc (where the ctypes loader looks)
    built = list(tmp_path.rglob("*.so"))
    assert built, "make produced no shared library"


@pytest.mark.parametrize("env_overrides", [
    {"APEX_TPU_NATIVE": "0"},
    {"APEX_TPU_NATIVE": "0", "APEX_TPU_KERNELS": "jnp"},
    {"APEX_TPU_KERNELS": "pallas"},
])
def test_package_trains_in_every_install_mode(env_overrides, tmp_path):
    """Import + one amp train step in a fresh interpreter per mode (the
    reference literally pip-reinstalled apex with and without extensions
    and re-ran the harness, run_test.sh:1-150)."""
    script = tmp_path / "probe.py"
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp, optax\n"
        "import apex_tpu\n"
        "from apex_tpu import amp\n"
        "from apex_tpu._native import available\n"
        "import os\n"
        "if os.environ.get('APEX_TPU_NATIVE') == '0':\n"
        "    assert not available, 'native layer must be disabled'\n"
        "a = amp.initialize(optimizer=optax.sgd(0.1), opt_level='O2',\n"
        "                   verbosity=0)\n"
        "state = a.init({'w': jnp.ones((4, 4))})\n"
        "step = jax.jit(amp.make_train_step(\n"
        "    a, lambda p, x: jnp.sum((x @ p['w'].astype(jnp.float32))**2)))\n"
        "state, m = step(state, jnp.ones((2, 4)))\n"
        "assert float(m['loss']) > 0\n"
        "print('MODE-OK')\n")
    # start from a CLEAN install-mode state: an outer conformance-axis
    # APEX_TPU_KERNELS/NATIVE (e.g. PARITY.md row 25's jnp runs) must not
    # bleed into the parametrized combinations
    env = {k: v for k, v in os.environ.items()
           if k not in ("APEX_TPU_NATIVE", "APEX_TPU_KERNELS")}
    env["PYTHONPATH"] = f"{REPO}:" + env.get("PYTHONPATH", "")
    env.update(env_overrides)
    out = subprocess.run([sys.executable, str(script)], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-1000:])
    assert "MODE-OK" in out.stdout
