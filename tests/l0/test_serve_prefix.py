"""Cross-request prefix-cache KV sharing (serve/paged refcounted
content-addressed blocks + scheduler/engine/router admission).

The acceptance contracts: (a) allocator refcount/CoW invariants —
double free, share-then-evict, and fork-under-share all REFUSED, LRU
eviction order over refcount==0 only; (b) shared-prefix mixed streams
bitwise-equal to solo :func:`apex_tpu.models.generate.generate` —
greedy, sampled, and int8 KV, including through a preemption, a
copy-on-write fork of a fully-matched prompt, and a multi-turn
history reuse; (c) sharing actually SAVES work: fewer prefill chunks
dispatched than the sharing-off arm on the same stream; (d) the
disaggregated router admits prefix-hit requests straight to a decode
replica (no shipment) and the kill-busiest-replica chaos drill stays
bitwise under sharing; (e) the one-trace contract is untouched
(``trace_counts`` pins exactly as before; the CoW fork has its own
single-trace counter); (f) the V-side convert candidate from PR 6 is
resolved by a pin (structurally blocked at jax 0.4.37).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.models.generate import generate
from apex_tpu.obs.metrics import Registry
from apex_tpu.serve import (
    DisaggRouter,
    Request,
    RouterConfig,
    ServeConfig,
    ServeEngine,
)
from apex_tpu.serve.paged import (
    TRASH_BLOCK,
    BlockAllocator,
    PoolExhausted,
    chain_seed,
    chain_step,
    prefix_block_hashes,
)


# ---------------------------------------------------------------------------
# allocator property tests (no jax, no model)
# ---------------------------------------------------------------------------

def _h(i):
    return chain_step(chain_seed(4), [i, i, i, i])


def test_chain_hashes_cover_history_and_block_size():
    """Block identity is the CHAIN: equal token runs at different
    positions (or under different block sizes) never alias."""
    hs = prefix_block_hashes(list(range(8)), 4)
    assert len(hs) == 2                      # full blocks only
    assert prefix_block_hashes(list(range(7)), 4) == hs[:1]
    # same 4 tokens at positions 4..7 vs 0..3: different chain hash
    assert prefix_block_hashes([4, 5, 6, 7], 4)[0] != hs[1]
    # block-size is part of the seed
    assert prefix_block_hashes(list(range(8)), 8)[0] not in hs
    assert hs[0] == chain_step(chain_seed(4), [0, 1, 2, 3])
    assert hs[1] == chain_step(hs[0], [4, 5, 6, 7])


def test_allocator_refcount_share_free_invariants():
    a = BlockAllocator(8)                    # 7 usable
    b0 = a.alloc(3, "r0")
    assert TRASH_BLOCK not in b0
    a.register(b0[0], _h(0))
    a.register(b0[1], _h(1))
    # share increfs for another owner; refcount-1 private otherwise
    a.share(b0[0], "r1")
    assert a.refcount(b0[0]) == 2 and a.shared_count == 1
    with pytest.raises(ValueError, match="already held"):
        a.share(b0[0], "r1")                 # double-hold refused
    with pytest.raises(ValueError, match="not registered"):
        a.share(b0[2], "r1")                 # private blocks never share
    # r0's free decrefs; the block survives for r1
    a.free(b0, "r0")
    assert a.refcount(b0[0]) == 1
    with pytest.raises(ValueError, match="double free|not owned"):
        a.free([b0[0]], "r0")                # r0 no longer holds it
    # r1's free drops the last ref: registered -> cached, not free
    a.free([b0[0]], "r1")
    assert a.cached_count == 2 and a.refcount(b0[0]) == 0
    assert a.lookup(_h(0)) == b0[0]          # still matchable
    # the accounting invariant holds at every point
    assert a.free_count + a.live_count + a.cached_count == 7


def test_allocator_share_then_evict_refused():
    """A SHARED (live) block is never reclaimed: alloc raises
    PoolExhausted rather than stealing it — only refcount-0 cached
    blocks are eviction candidates."""
    a = BlockAllocator(4)                    # 3 usable
    blocks = a.alloc(3, "r0")
    for i, b in enumerate(blocks):
        a.register(b, _h(i))
    a.share(blocks[0], "r1")
    a.free(blocks, "r0")                     # b0 still live via r1
    assert a.cached_count == 2 and a.live_count == 1
    assert a.reclaimable_count == 2
    with pytest.raises(PoolExhausted):
        a.alloc(3, "r2")                     # would need the shared one
    # and the refusal reclaimed nothing
    assert a.cached_count == 2 and a.lookup(_h(0)) == blocks[0]


def test_allocator_fork_under_share_refused():
    """assert_writable refuses shared AND registered blocks — a write
    needs a private unregistered block (the copy-on-write rule)."""
    a = BlockAllocator(8)
    b = a.alloc(2, "r0")
    a.assert_writable(b[1], "r0")            # private: fine
    a.register(b[0], _h(0))
    with pytest.raises(ValueError, match="registered"):
        a.assert_writable(b[0], "r0")        # immutable once indexed
    a.share(b[0], "r1")
    with pytest.raises(ValueError, match="shared|registered"):
        a.assert_writable(b[0], "r1")
    with pytest.raises(ValueError, match="cannot write"):
        a.assert_writable(b[1], "r1")        # not the holder


def test_allocator_lru_reclaim_order_and_register_conflicts():
    a = BlockAllocator(5)                    # 4 usable
    blocks = a.alloc(4, "r0")
    for i, b in enumerate(blocks):
        a.register(b, _h(i))
    # free order defines LRU: blocks[2] parks first -> evicts first
    a.free([blocks[2]], "r0")
    a.free([blocks[0]], "r0")
    a.free([blocks[1]], "r0")
    got = a.alloc(2, "r1")
    assert got == [blocks[2], blocks[0]]     # least-recently-freed first
    assert a.cached_evictions == 2
    assert a.lookup(_h(2)) is None           # registration gone
    assert a.lookup(_h(1)) == blocks[1]      # survivor still indexed
    # register conflicts: same hash on another block -> False (first
    # registration canonical); same block, different hash -> raises
    assert a.register(got[0], _h(1)) is False
    assert not a.is_registered(got[0])
    assert a.register(got[0], _h(9)) is True
    with pytest.raises(ValueError, match="different chain hash"):
        a.register(got[0], _h(8))
    assert a.register(got[0], _h(9)) is True    # same-hash no-op
    with pytest.raises(ValueError, match="not live"):
        a.register(TRASH_BLOCK, _h(7))
    a.free([blocks[3]], "r0")
    with pytest.raises(ValueError, match="not live"):
        a.register(blocks[3], _h(7))         # register after free


# ---------------------------------------------------------------------------
# engine streams: bitwise parity under sharing
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def setup():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    params = a.model_params_from(params)      # bf16 serving layout
    rng = np.random.RandomState(42)
    system = rng.randint(0, cfg.vocab_size, (8,))   # 2 full blocks @ bs=4
    tails = [rng.randint(0, cfg.vocab_size, (n,)) for n in (3, 6, 1, 5)]
    return cfg, params, system, tails


SCFG = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                   max_blocks_per_slot=8, prefill_chunk=4)


def _solo(params, cfg, prompt, n, **kw):
    out = generate(params, cfg, jnp.asarray(np.asarray(prompt)[None]),
                   n, **kw)
    return np.asarray(out)[0, len(prompt):]


def test_shared_system_prompt_stream_bitwise_and_saves_chunks(setup):
    """The tentpole gate: 4 requests sharing one 8-token system prompt
    through 2 slots — every output bitwise-equal to solo generate(),
    prefix hits recorded, STRICTLY fewer prefill chunks than the
    sharing-off arm on the identical stream, and the one-trace
    contract untouched in both arms."""
    cfg, params, system, tails = setup

    def run(prefix_cache):
        import dataclasses
        scfg = dataclasses.replace(SCFG, prefix_cache=prefix_cache)
        eng = ServeEngine(params, cfg, scfg, registry=Registry())
        for i, t in enumerate(tails):
            eng.submit(Request(uid=f"r{i}",
                               prompt=np.concatenate([system, t]),
                               max_new_tokens=6))
        out = eng.run()
        chunks = eng.metrics.counter("serve_prefill_chunks_total").value
        return eng, out, chunks

    eng_on, out_on, chunks_on = run(True)
    eng_off, out_off, chunks_off = run(False)
    for i, t in enumerate(tails):
        p = np.concatenate([system, t])
        want = _solo(params, cfg, p, 6)
        np.testing.assert_array_equal(
            out_on[f"r{i}"], want,
            err_msg=f"r{i} diverged from solo under sharing")
        np.testing.assert_array_equal(out_off[f"r{i}"], want)
    # the perf claim, on the engine's own counters: the shared spans'
    # chunks were never dispatched
    assert chunks_on < chunks_off
    s = eng_on.sched
    assert s.prefix_probes == 4
    assert s.prefix_hits >= 3                # first request seeds
    assert s.prefix_hit_tokens > 0
    eng_on.metrics.flush()
    assert eng_on.metrics.gauge("serve_prefix_hit_rate").value > 0.5
    # drained: nothing shared, nothing live; the hot prefix is CACHED
    # (refcount 0, still matchable), not leaked
    assert s.allocator.live_count == 0
    assert s.allocator.shared_count == 0
    assert s.allocator.cached_count > 0
    assert eng_on.metrics.gauge("serve_prefix_shared_blocks").value == 0
    # trace pins: sharing is host-side page-table construction only
    assert eng_on.trace_counts == {"decode": 1, "prefill": 1,
                                   "sample1": 1}
    assert eng_off.trace_counts == {"decode": 1, "prefill": 1,
                                    "sample1": 1}
    # sharing-off engine has no prefix machinery in its catalog
    assert eng_off.sched._m_hit_rate is None


def test_full_prompt_match_forks_copy_on_write(setup):
    """A FULLY-matched aligned prompt re-dispatches exactly one token:
    the last matched block forks copy-on-write (one device copy, its
    own single trace), the rewrite lands in the private fork, and the
    stream is bitwise-equal to solo — the fork source stays registered
    for the next hit."""
    cfg, params, system, _tails = setup
    eng = ServeEngine(params, cfg, SCFG, registry=Registry())
    # 8 tokens = 2 full blocks at bs=4: an aligned full-match prompt
    eng.submit(Request(uid="a", prompt=system, max_new_tokens=6))
    out_a = eng.run()["a"]
    chunks_before = eng.metrics.counter(
        "serve_prefill_chunks_total").value
    eng.submit(Request(uid="b", prompt=system, max_new_tokens=6))
    out_b = eng.run()["b"]
    want = _solo(params, cfg, system, 6)
    np.testing.assert_array_equal(out_a, want)
    np.testing.assert_array_equal(out_b, want,
                                  err_msg="CoW fork diverged")
    m = eng.metrics
    assert m.counter("serve_prefix_cow_copies_total").value == 1
    # the full match dispatched ONE chunk (the n-1 re-dispatch), not
    # the prompt's two
    assert m.counter("serve_prefill_chunks_total").value \
        == chunks_before + 1
    # the CoW copy is its own executable with its own ONE trace — the
    # pinned trace_counts dict is untouched
    assert eng.cow_trace_count == 1
    assert eng.trace_counts == {"decode": 1, "prefill": 1,
                                "sample1": 1}
    assert eng.sched.allocator.live_count == 0


def test_sampled_and_multi_turn_reuse_bitwise(setup):
    """Sampling under sharing stays on the exact per-request PRNG
    chain (pinned against the sharing-off engine, the arm existing
    tests hold bitwise to solo), and a multi-turn follow-up (prompt =
    turn-1 prompt + its generated tokens + new user tokens) matches
    the DECODE-filled blocks the first turn registered at block
    boundaries — the greedy follow-up equals solo generate()."""
    import dataclasses
    cfg, params, system, tails = setup
    p1 = np.concatenate([system, tails[0]])          # 11 tokens

    def turn1(prefix_cache):
        scfg = dataclasses.replace(SCFG, prefix_cache=prefix_cache)
        eng = ServeEngine(params, cfg, scfg, registry=Registry())
        # two sampled same-prefix requests so the ON arm actually
        # shares (the second admission hits the first's blocks)
        eng.submit(Request(uid="s0", prompt=p1, max_new_tokens=8,
                           temperature=0.9, top_k=20, top_p=0.95,
                           seed=11))
        eng.submit(Request(uid="s1", prompt=np.concatenate(
            [system, tails[1]]), max_new_tokens=8, temperature=0.7,
            seed=3))
        return eng, eng.run()

    eng, out_on = turn1(True)
    _eng_off, out_off = turn1(False)
    for uid in ("s0", "s1"):
        np.testing.assert_array_equal(
            out_on[uid], out_off[uid],
            err_msg=f"{uid}: sampled stream diverged under sharing")
    # turn 2 reuses the whole turn-1 history + fresh tokens (greedy,
    # so solo generate() is the reference)
    p2 = np.concatenate([p1, out_on["s0"], tails[2], tails[2]])
    hits0 = eng.sched.prefix_hit_tokens
    eng.submit(Request(uid="t2", prompt=p2, max_new_tokens=5))
    out2 = eng.run()["t2"]
    np.testing.assert_array_equal(
        out2, _solo(params, cfg, p2, 5),
        err_msg="multi-turn reuse diverged from solo")
    # the follow-up matched PAST the prompt span of turn 1: generated
    # blocks registered at decode block boundaries are matchable too
    matched = eng.sched.prefix_hit_tokens - hits0
    assert matched >= 12                    # p1's 2 blocks + >=1 more


def test_preemption_under_sharing_stays_bitwise(setup):
    """The preemption drill replayed under sharing: block pressure
    evicts the youngest; its continuation re-probes the index (its own
    freed blocks are cached and matchable), and every request —
    evicted included — still equals its solo run."""
    cfg, params, system, tails = setup
    scfg = ServeConfig(num_slots=3, block_size=4, num_blocks=9,
                       max_blocks_per_slot=8, prefill_chunk=4)
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    reqs = [(system, 8), (np.concatenate([system[:4], tails[1]])[:8], 8),
            (np.concatenate([tails[1], tails[0]])[:6], 6)]
    for i, (p, n) in enumerate(reqs):
        eng.submit(Request(uid=f"r{i}", prompt=p, max_new_tokens=n))
    out = eng.run()
    assert eng.metrics.counter("serve_preemptions_total").value >= 1
    for i, (p, n) in enumerate(reqs):
        np.testing.assert_array_equal(
            out[f"r{i}"], _solo(params, cfg, p, n),
            err_msg=f"r{i} diverged through preemption under sharing")
    assert eng.sched.allocator.live_count == 0


def test_int8_kv_scale_pools_share_bitwise(setup):
    """int8 KV under sharing: the scale pools ride the same refcounts
    (a shared block's scales are the registered content too), the CoW
    fork copies them with the values, and the stream equals solo int8
    generate() bitwise."""
    cfg, params, system, tails = setup
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                       max_blocks_per_slot=8, prefill_chunk=4,
                       kv_dtype="int8")
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    p0 = np.concatenate([system, tails[0]])
    p1 = np.concatenate([system, tails[1]])
    eng.submit(Request(uid="a", prompt=p0, max_new_tokens=6))
    eng.submit(Request(uid="b", prompt=p1, max_new_tokens=6))
    out = eng.run()
    # b admitted the same boundary as a: no registration yet -> run a
    # third request AFTER the index is warm, plus a full-match CoW
    eng.submit(Request(uid="c", prompt=p1, max_new_tokens=6))
    eng.submit(Request(uid="d", prompt=system, max_new_tokens=6))
    out.update(eng.run())
    for uid, p in (("a", p0), ("b", p1), ("c", p1), ("d", system)):
        np.testing.assert_array_equal(
            out[uid], _solo(params, cfg, p, 6, kv_dtype="int8"),
            err_msg=f"{uid} diverged from solo int8 under sharing")
    assert eng.sched.prefix_hits >= 1
    assert eng.metrics.counter(
        "serve_prefix_cow_copies_total").value >= 1


# ---------------------------------------------------------------------------
# disaggregated fleet: straight-to-decode + chaos drill under sharing
# ---------------------------------------------------------------------------

def test_fleet_straight_to_decode_and_kill_busiest_drill(setup):
    """Fleet sharing end-to-end: a warm replica's index admits a
    same-prefix request STRAIGHT to decode (no prefill slice, no
    shipment — the shipment counter does not move), per-replica hit
    gauges mirror at the fleet boundary, and the kill-busiest-replica
    chaos drill replayed under sharing stays bitwise (rerouted
    continuations re-probe the survivors' indexes)."""
    cfg, params, system, tails = setup
    router = DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="ship"),
        registry=Registry())
    p0 = np.concatenate([system, tails[0]])
    router.submit(Request(uid="w", prompt=p0, max_new_tokens=6))
    out = router.run()                       # warm a replica's index
    m = router.metrics
    assert m.counter("serve_kv_shipments_total").value == 1
    assert m.counter("serve_prefix_direct_admissions_total").value == 0
    # same system prompt again: a replica holds the match -> straight
    # to decode, no second shipment
    p1 = np.concatenate([system, tails[1]])
    router.submit(Request(uid="x", prompt=p1, max_new_tokens=6))
    out.update(router.run())
    assert m.counter("serve_kv_shipments_total").value == 1
    assert m.counter("serve_prefix_direct_admissions_total").value == 1
    hit_rates = [m.gauge(f"serve_replica{i}_prefix_hit_rate").value
                 for i in range(2)]
    assert max(hit_rates) > 0                # the mirrored fleet gauge
    # now the chaos drill under sharing: a burst of shared-prefix
    # requests, kill the busiest replica mid-flight, drain
    news = (8, 6, 7)
    for i, n in enumerate(news):
        router.submit(Request(uid=f"k{i}",
                              prompt=np.concatenate([system, tails[i]]),
                              max_new_tokens=n))
    for _ in range(3):
        router.step()
    victim = max(router.replicas,
                 key=lambda r: r.eng.sched.n_active()).index
    router.kill_replica(victim)
    out.update(router.run())
    np.testing.assert_array_equal(out["w"], _solo(params, cfg, p0, 6))
    np.testing.assert_array_equal(out["x"], _solo(params, cfg, p1, 6))
    for i, n in enumerate(news):
        p = np.concatenate([system, tails[i]])
        np.testing.assert_array_equal(
            out[f"k{i}"], _solo(params, cfg, p, n),
            err_msg=f"k{i} diverged after the kill under sharing")
    # the prefill worker never shares (transient single slot)
    assert router.prefill.eng.scfg.prefix_cache is False
    assert router.prefill.eng.sched.prefix_probes == 0


# ---------------------------------------------------------------------------
# satellite 5: the V-side convert pin (jax 0.4.37 structural block)
# ---------------------------------------------------------------------------

def test_v_side_convert_pin():
    """Pins the resolution of the PR-6 V-side convert candidate in
    ``_attn_cached``: at jax 0.4.37 every expressible form of the f32
    x bf16 V contraction lowers with a materialized cache convert
    (einsum AND raw mixed-dtype dot_general), the DotAlgorithm API
    that would express mixed-operand accumulation raises, and the
    direct dot_general form is BITWISE-equal to the shipped einsum —
    the ready replacement for a jax whose lowering honors it.  If
    this test fails on a future jax bump, the block lifted: move
    ``_attn_cached``'s V contraction to the direct form."""
    import re
    B, Q, H, D, M = 1, 2, 2, 4, 8
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.standard_normal((B, H, Q, M)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, M, H, D)), jnp.bfloat16)
    dn = (((3,), (1,)), ((0, 1), (0, 2)))

    def ein(p, v):
        return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                          preferred_element_type=jnp.float32)

    def direct(p, v):
        out = jax.lax.dot_general(p, v, dimension_numbers=dn,
                                  preferred_element_type=jnp.float32)
        return jnp.transpose(out, (0, 2, 1, 3))

    np.testing.assert_array_equal(
        np.asarray(jax.jit(ein)(p, v)),
        np.asarray(jax.jit(direct)(p, v)))
    pat = re.compile(r"convert.*tensor<1x8x2x4xf32>")
    for fn in (ein, direct):
        txt = jax.jit(fn).lower(p, v).as_text()
        assert pat.search(txt), (
            "the V-side cache convert vanished from the lowering — "
            "the jax upgrade unblocked preferred_element_type on the "
            "V contraction; move _attn_cached to the direct "
            "dot_general form and retire this pin")
    with pytest.raises(Exception):
        alg = jax.lax.DotAlgorithm(
            lhs_precision_type=jnp.float32,
            rhs_precision_type=jnp.bfloat16,
            accumulation_type=jnp.float32)
        jax.jit(lambda p, v: jax.lax.dot_general(
            p, v, dimension_numbers=dn, precision=alg)).lower(p, v)
