"""Flash-attention kernel conformance (interpret mode on CPU; set
APEX_TPU_TEST_PLATFORM to run Mosaic-compiled on hardware).

The harness mirrors the multi-tensor fuzz style (SURVEY.md §4.1): kernel
output and gradients vs a pure-jnp oracle across causal/mask/dtype/odd-
length axes, with the masked-row and padding edge cases planted explicitly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas.flash_attention import flash_attention

B, L, H, D = 2, 384, 4, 64

# The oracle einsums run at precision="highest" so they are exact on TPU
# too; the kernel's MXU matmuls use the default f32 decomposition
# (bf16-multipass), which differs from a full-f32 oracle at the ~1e-2
# level after softmax renormalization — the same precision class as
# jax's own TPU flash kernel, hence the looser on-hardware tolerance.
_ON_CPU = jax.default_backend() == "cpu"
RTOL = 1e-5 if _ON_CPU else 2e-2
ATOL = 1e-5 if _ON_CPU else 2e-2
GTOL = 1e-4 if _ON_CPU else 2e-2


def ref_attn(q, k, v, causal=False, kv_mask=None):
    """jnp oracle; fully-masked rows emit zeros like the kernel."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision="highest") * scale
    neg = jnp.asarray(-1e30, jnp.float32)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, neg)
    if causal:
        tri = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
        s = jnp.where(tri[None, None], s, neg)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if kv_mask is not None:
        p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
    if causal:
        p = jnp.where(tri[None, None], p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", p / safe_l, v.astype(jnp.float32),
                     precision="highest")
    return out.astype(q.dtype)


def _qkv(dtype=jnp.float32, l=L, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, l, H, D).astype(np.float32)
                             ).astype(dtype)
    return mk(), mk(), mk()


def _check_grads(q, k, v, causal, mask, **flash_kwargs):
    """Gradients of a sin-sum loss through the kernel vs the jnp oracle."""
    def loss(fn):
        return lambda q, k, v: jnp.sum(
            jnp.sin(fn(q, k, v)).astype(jnp.float32))

    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, kv_mask=mask, **flash_kwargs)),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: ref_attn(
        q, k, v, causal=causal, kv_mask=mask)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=GTOL, atol=GTOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_mask", [False, True])
def test_forward_matches_reference(causal, use_mask):
    q, k, v = _qkv()
    mask = None
    if use_mask:
        rng = np.random.RandomState(1)
        mask = jnp.asarray(rng.rand(B, L) > 0.2).at[:, 0].set(True)
    out = flash_attention(q, k, v, causal=causal, kv_mask=mask,
                          block_q=128, block_k=128)
    ref = ref_attn(q, k, v, causal=causal, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_gradients_match_reference():
    q, k, v = _qkv()
    rng = np.random.RandomState(1)
    mask = jnp.asarray(rng.rand(B, L) > 0.2).at[:, 0].set(True)
    _check_grads(q, k, v, True, mask, block_q=128, block_k=128)


def test_odd_length_padding_and_bf16():
    q, k, v = _qkv(jnp.bfloat16, l=300)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = ref_attn(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16 and out.shape == q.shape
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_noncausal_padded_keys_do_not_attend():
    """Regression: with no kv_mask and a non-causal odd length, the
    zero-padded key columns must not enter the softmax (they ride the
    NEG_INF padding bias _prep builds — the fast no-bias kernel path is
    only legal when nothing is padded or causality hides the pad)."""
    q, k, v = _qkv(l=300, seed=3)
    out = flash_attention(q, k, v, causal=False, block_q=128, block_k=128)
    ref = ref_attn(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_explicit_block_override_warns():
    """Explicit sub-granularity block sizes are rounded up to Mosaic
    tile legality (block_k < 128 miscompiles on hardware); the caller
    asked for a specific blocking, so the adjustment must be audible
    (ADVICE r2)."""
    import warnings
    from apex_tpu.ops.pallas import flash_attention as fa
    fa._warn_block_override.cache_clear()
    q, k, v = _qkv(l=256)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        flash_attention(q, k, v, block_q=100, block_k=64)
    msgs = [str(w.message) for w in caught
            if "adjusted to" in str(w.message)]
    assert any("block_q=100 adjusted to 104" in m for m in msgs)
    assert any("block_k=64 adjusted to 128" in m for m in msgs)
    # Defaulted block sizes never warn.
    fa._warn_block_override.cache_clear()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        flash_attention(q, k, v)
    assert not [w for w in caught if "adjusted to" in str(w.message)]


def test_two_pass_backward_matches_reference(monkeypatch):
    """The long-context two-pass backward (dq + dkv kernels) is the
    fallback above the fused dq-partials budget; force it here (via the
    public env override) so both backward implementations keep gradient
    coverage."""
    monkeypatch.setenv("APEX_TPU_FLASH_FUSED_BWD_MAX_BYTES", "0")
    q, k, v = _qkv()
    rng = np.random.RandomState(1)
    mask = jnp.asarray(rng.rand(B, L) > 0.2).at[:, 0].set(True)
    _check_grads(q, k, v, True, mask, block_q=128, block_k=128)


def test_fully_masked_rows_emit_zeros():
    q, k, v = _qkv(l=256)
    mask = jnp.zeros((B, 256), bool).at[0].set(True)   # batch 1 all-masked
    out = flash_attention(q, k, v, kv_mask=mask, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out[1]), 0.0)
    assert bool(jnp.any(out[0] != 0.0))


def test_fully_masked_rows_zero_gradients():
    q, k, v = _qkv(l=256)
    mask = jnp.zeros((B, 256), bool).at[0].set(True)
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, kv_mask=mask, block_q=128, block_k=128)
        .astype(jnp.float32)))(q)
    np.testing.assert_allclose(np.asarray(g[1]), 0.0)


def test_dispatcher_uses_flash():
    from apex_tpu.attention import attention
    q, k, v = _qkv(l=256)
    out = attention(q, k, v, impl="flash", causal=True)
    ref = ref_attn(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_default_blocks_scale_with_length():
    """The block-size default switches to 1024 at L >= 2048 (per-step
    overhead amortization measured on chip); the selection logic is
    checked here, the numerics hardware-side below."""
    from apex_tpu.ops.pallas.flash_attention import _default_block
    cases = (
        (512, 512), (2047, 512), (2048, 1024), (4096, 1024), (16384, 1024),
        # 1024 blocks would pad 4608 -> 5120 (~23% extra quadratic work)
        # while 512 pads nothing: stay at 512.
        (4608, 512),
        # 4609 pads to 5120 under either block size: take the big block.
        (4609, 1024),
    )
    for l, expect in cases:
        assert _default_block(l) == expect, l


@pytest.mark.skipif(_ON_CPU, reason="interpret-mode 4096^2 attention is "
                    "prohibitively slow; run with APEX_TPU_TEST_PLATFORM")
def test_long_sequence_default_blocks_match_oracle():
    """L=4096 exercises the 1024-block default hot path on hardware:
    values must match the jnp oracle within the on-chip tolerance."""
    l = 4096
    q = jax.random.normal(jax.random.PRNGKey(0), (1, l, 2, 64), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (1, l, 2, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (1, l, 2, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = ref_attn(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=RTOL, atol=ATOL)


class TestRopeFused:
    """In-kernel rotary embedding (``rope=(cos, sin)``): q/k pass in
    unrotated and the kernel rotates VMEM blocks before the score
    matmul (and inverse-rotates dq/dk at emit).  Oracle: pre-rotate
    with :func:`apply_rope` and run the rope-free kernel — on CPU/fp32
    both paths do the identical fp32 rotation arithmetic, so
    tolerances stay at the kernel-parity level."""

    def _setup(self, l=L, dtype=jnp.float32, seed=0):
        from apex_tpu.ops.rope import rope_tables
        q, k, v = _qkv(dtype, l=l, seed=seed)
        pos = jnp.broadcast_to(jnp.arange(l)[None, :], (B, l))
        cos, sin = rope_tables(pos, D, 10000.0)
        return q, k, v, cos, sin

    def _oracle(self, q, k, v, cos, sin, **kw):
        from apex_tpu.ops.rope import apply_rope
        return flash_attention(apply_rope(q, cos, sin),
                               apply_rope(k, cos, sin), v, **kw)

    @pytest.mark.parametrize("use_mask", [False, True])
    def test_forward_and_grads_match_prerotated(self, use_mask):
        q, k, v, cos, sin = self._setup()
        mask = None
        if use_mask:
            rng = np.random.RandomState(1)
            mask = jnp.asarray(rng.rand(B, L) > 0.2).at[:, 0].set(True)
        kw = dict(causal=True, kv_mask=mask, block_q=128, block_k=128)
        out = flash_attention(q, k, v, rope=(cos, sin), **kw)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=RTOL, atol=ATOL)
        self._check_rope_grads(q, k, v, cos, sin, kw)

    def _check_rope_grads(self, q, k, v, cos, sin, kw, tol=GTOL):
        def loss(fn):
            return lambda q, k, v: jnp.sum(
                jnp.sin(fn(q, k, v)).astype(jnp.float32))

        gf = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, rope=(cos, sin), **kw)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: self._oracle(
            q, k, v, cos, sin, **kw)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=tol, atol=tol)

    def test_stream_mode_matches(self, monkeypatch):
        """Above the resident budget the tables stream per block; same
        numbers either way."""
        from apex_tpu.ops.pallas import flash_attention as fa
        q, k, v, cos, sin = self._setup()
        kw = dict(causal=True, block_q=128, block_k=128)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        monkeypatch.setattr(fa, "_ROPE_RESIDENT_MAX_BYTES", 0)
        out = flash_attention(q, k, v, rope=(cos, sin), **kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=RTOL, atol=ATOL)
        self._check_rope_grads(q, k, v, cos, sin, kw)

    def test_two_pass_backward_matches(self, monkeypatch):
        """The long-context two-pass backward rotates for the
        probability recompute and inverse-rotates dq/dk at emit too."""
        monkeypatch.setenv("APEX_TPU_FLASH_FUSED_BWD_MAX_BYTES", "0")
        q, k, v, cos, sin = self._setup()
        self._check_rope_grads(q, k, v, cos, sin,
                               dict(causal=True, block_q=128, block_k=128))

    def test_bhld_layout(self):
        q, k, v, cos, sin = self._setup()
        kw = dict(causal=True, block_q=128, block_k=128)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        qh, kh, vh = (jnp.moveaxis(t, 1, 2) for t in (q, k, v))
        out = flash_attention(qh, kh, vh, layout="bhld", rope=(cos, sin),
                              **kw)
        np.testing.assert_allclose(np.asarray(jnp.moveaxis(out, 1, 2)),
                                   np.asarray(ref), rtol=RTOL, atol=ATOL)

    def test_odd_length_bf16(self):
        """Sequence padding: zero-padded table rows rotate the (already
        zero) padded q/k rows to zero; bf16 tables add the same rounding
        class as bf16 q/k storage."""
        q, k, v, cos, sin = self._setup(l=300, dtype=jnp.bfloat16, seed=3)
        kw = dict(causal=True, block_q=128, block_k=128)
        out = flash_attention(q, k, v, rope=(cos, sin), **kw)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=3e-2, atol=3e-2)

    def test_cross_attention_rejected(self):
        q, k, v, cos, sin = self._setup()
        with pytest.raises(ValueError, match="self-attention"):
            flash_attention(q, k[:, :128], v[:, :128], rope=(cos, sin))

    def test_fp32_defaults_capped_at_512(self, monkeypatch):
        """fp32 + rope caps *defaulted* blocks at 512 (1024-blocks blow
        the scoped-VMEM limit in the fused backward — measured on the
        O0 L2048 train step); explicit requests pass through."""
        from apex_tpu.ops.pallas import flash_attention as fa
        seen = []
        real = fa._flash

        def spy(q, k, v, bias, cos_t, sin_t, scale, causal, bq, bk,
                has_bias, rope_mode, layout):
            seen.append((bq, bk, rope_mode))
            return real(q, k, v, bias, cos_t, sin_t, scale, causal, bq,
                        bk, has_bias, rope_mode, layout)

        monkeypatch.setattr(fa, "_flash", spy)
        l = 2048
        rng = np.random.RandomState(0)
        mk = lambda: jnp.asarray(rng.randn(1, l, 1, D).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        pos = jnp.broadcast_to(jnp.arange(l)[None, :], (1, l))
        from apex_tpu.ops.rope import rope_tables
        cos, sin = rope_tables(pos, D, 10000.0)
        fa.flash_attention(q, k, v, causal=True, rope=(cos, sin))
        assert seen[-1][:2] == (512, 512)
        # bf16 keeps the length-scaled default
        fa.flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                           v.astype(jnp.bfloat16), causal=True,
                           rope=(cos, sin))
        assert seen[-1][:2] == (1024, 1024)
        # no rope: fp32 keeps the 1024 default (unchanged behavior)
        fa.flash_attention(q, k, v, causal=True)
        assert seen[-1][:2] == (1024, 1024)
        assert seen[-1][2] is None

    def test_rope_under_shard_map_fallback(self):
        """Off-TPU, a varying-under-shard_map q routes to the jnp
        fallback (interpreter VMA limitation); with rope it must rotate
        out-of-kernel via apply_rope_tables and still match the
        pre-rotated oracle — the data-parallel GPT step hits exactly
        this path in the CPU dryruns."""
        import numpy as onp
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.utils.jax_compat import shard_map
        # 2-way data mesh on CPU (8 virtual devices); on the one-chip
        # TPU a 1-device mesh still compiles flash+rope under shard_map
        # (the kernel path — hardware coverage the fallback test line
        # can't get), so the test adapts instead of skipping.
        devs = jax.devices()[:min(2, len(jax.devices()))]
        q, k, v, cos, sin = self._setup(l=256)
        kw = dict(causal=True, block_q=128, block_k=128)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        mesh = Mesh(onp.array(devs), ("data",))

        def fwd(q, k, v, cos, sin):
            return flash_attention(q, k, v, rope=(cos, sin), **kw)

        out = shard_map(
            fwd, mesh=mesh,
            in_specs=(P("data"), P("data"), P("data"), P("data"),
                      P("data")),
            out_specs=P("data"))(q, k, v, cos, sin)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=GTOL, atol=GTOL)

    def test_dispatcher_passthrough_and_seq_parallel_rejection(self):
        from apex_tpu.attention import attention
        q, k, v, cos, sin = self._setup(l=256)
        kw = dict(causal=True, block_q=128, block_k=128)
        ref = self._oracle(q, k, v, cos, sin, **kw)
        out = attention(q, k, v, impl="flash", causal=True,
                        block_q=128, block_k=128, rope=(cos, sin))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=RTOL, atol=ATOL)
        # jnp local path rotates out-of-kernel, same convention
        out_jnp = attention(q, k, v, impl="jnp", causal=True,
                            rope=(cos, sin))
        np.testing.assert_allclose(np.asarray(out_jnp), np.asarray(ref),
                                   rtol=GTOL, atol=GTOL)
        with pytest.raises(ValueError, match="axis_name"):
            attention(q, k, v, axis_name="seq", rope=(cos, sin))
        # cross-attention + rope raises the same clear error on the jnp
        # fallback as on the kernel path
        with pytest.raises(ValueError, match="self-attention"):
            attention(q, k[:, :128], v[:, :128], impl="jnp",
                      rope=(cos, sin))


@pytest.mark.parametrize("bq,bk", [(64, 128), (256, 128), (128, 256)])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_mask", [False, True])
def test_unequal_blocks_fuzz(bq, bk, causal, use_mask):
    """Sweep the (causal x has_bias x block-shape) kernel dispatch matrix
    with UNEQUAL q/k blocks: the straddle predicate, the exp-underflow
    masked-entry zeroing, and the no-bias fast path must all hold when
    a block can contain rows with zero visible keys (bq > bk) or keys
    spanning several diagonals (bk > bq).  Forward and gradients vs the
    jnp oracle; L=192 pads to lcm(bq, bk).  (Block sizes must be legal
    post-round-up — block_k below 128 is silently raised to 128, so
    bq > bk regimes use bq = 256.)"""
    l = 192
    q, k, v = _qkv(l=l, seed=7)
    mask = None
    if use_mask:
        rng = np.random.RandomState(2)
        mask = jnp.asarray(rng.rand(B, l) > 0.3).at[:, 0].set(True)

    out = flash_attention(q, k, v, causal=causal, kv_mask=mask,
                          block_q=bq, block_k=bk)
    ref = ref_attn(q, k, v, causal=causal, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    _check_grads(q, k, v, causal, mask, block_q=bq, block_k=bk)
