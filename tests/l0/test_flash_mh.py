"""Multi-head BLHD-native flash kernel + head-major layout conformance.

``flash_mh`` is kept as a documented experiment (measured slower than
the BHLD kernel on v5e — see its module docstring); its numerics stay
pinned here.  The production head-major pieces — ``flash_attention(
layout="bhld")``, the ``_QKVProj``/``_OutProj`` Dense-compatible
projections, and the MXU rope spelling — are what BERT's fast path
runs, and they are pinned against the reference spellings exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas.flash_attention import _jnp_attention, \
    flash_attention
from apex_tpu.ops.pallas.experimental.flash_mh import flash_attention_mh

B, L, H, D = 2, 256, 4, 64
SCALE = 1.0 / 8.0
# On real hardware the MXU computes fp32 dots via bf16 passes (default
# precision); interpret mode on CPU is exact fp32 — same tolerance split
# as tests/l0/test_flash_attention.py.
_ON_CPU = jax.default_backend() == "cpu"
RTOL = 2e-5 if _ON_CPU else 2e-2
ATOL = 2e-5 if _ON_CPU else 2e-2


def _qkv(l=L, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (B, l, H, D), jnp.float32),
            jax.random.normal(kk, (B, l, H, D), jnp.float32),
            jax.random.normal(kv, (B, l, H, D), jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.experimental
def test_mh_forward_matches_reference(causal):
    q, k, v = _qkv()
    out, lse = flash_attention_mh(q, k, v, causal=causal, block_q=128,
                                  block_k=128, return_lse=True)
    ref, rlse = _jnp_attention(q, k, v, causal=causal, kv_mask=None,
                               scale=SCALE, return_lse=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse),
                               rtol=RTOL, atol=ATOL)


@pytest.mark.experimental
def test_mh_padded_mask_and_grads():
    q, k, v = _qkv(l=200, seed=1)          # padding active
    mask = jnp.asarray(np.random.RandomState(1).rand(B, 200) > 0.2
                       ).at[:, 0].set(True)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            f(q, k, v, kv_mask=mask, block_q=128, block_k=128) ** 2)

    got = jax.grad(loss(flash_attention_mh), (0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(_jnp_attention(
            q, k, v, causal=False, kv_mask=mask, scale=SCALE) ** 2),
        (0, 1, 2))(q, k, v)
    for g, r in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=max(RTOL, 1e-4),
                                   atol=max(ATOL, 1e-4))


def test_bhld_layout_matches_blhd():
    """flash_attention(layout='bhld') == the blhd result transposed —
    forward, lse, and gradients (the production head-major path)."""
    q, k, v = _qkv(seed=2)
    qh, kh, vh = (jnp.moveaxis(t, 1, 2) for t in (q, k, v))
    out_b = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    out_h, lse_h = flash_attention(qh, kh, vh, causal=True, block_q=128,
                                   block_k=128, layout="bhld",
                                   return_lse=True)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(out_h, 1, 2)),
                               np.asarray(out_b), rtol=1e-6, atol=1e-6)
    _, lse_b = flash_attention(q, k, v, causal=True, block_q=128,
                               block_k=128, return_lse=True)
    np.testing.assert_array_equal(np.asarray(lse_h), np.asarray(lse_b))

    g_b = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128) ** 2))(q)
    g_h = jax.grad(lambda qh: jnp.sum(flash_attention(
        qh, kh, vh, causal=True, block_q=128, block_k=128,
        layout="bhld") ** 2))(qh)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(g_h, 1, 2)),
                               np.asarray(g_b), rtol=1e-6, atol=1e-6)


def test_attention_dispatcher_bhld_routes_and_falls_back():
    """attention(layout='bhld'): honors impl='jnp' (head-major in/out via
    the jnp math), rejects sequence-parallel axes, and matches the blhd
    dispatch numerically."""
    from apex_tpu.attention import attention
    q, k, v = _qkv(seed=5)
    qh, kh, vh = (jnp.moveaxis(t, 1, 2) for t in (q, k, v))
    out_jnp = attention(qh, kh, vh, impl="jnp", causal=True,
                        layout="bhld")
    want = attention(q, k, v, impl="jnp", causal=True)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(out_jnp, 1, 2)),
                               np.asarray(want), rtol=1e-5, atol=1e-5)
    out_auto = attention(qh, kh, vh, causal=True, layout="bhld")
    # auto-dispatch hits the Pallas kernel on hardware: platform tols
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(out_auto, 1, 2)),
                               np.asarray(want), rtol=max(RTOL, 1e-4),
                               atol=max(ATOL, 1e-4))
    with pytest.raises(ValueError, match="bhld"):
        attention(qh, kh, vh, axis_name="seq", layout="bhld")


def test_bhld_cross_attention_falls_back():
    q, k, v = _qkv(seed=3)
    qh = jnp.moveaxis(q, 1, 2)
    kh = jnp.moveaxis(k, 1, 2)[:, :, :128]
    vh = jnp.moveaxis(v, 1, 2)[:, :, :128]
    out = flash_attention(qh, kh, vh, layout="bhld")
    ref = _jnp_attention(q, k[:, :128], v[:, :128], causal=False,
                         kv_mask=None, scale=SCALE)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(out, 1, 2)),
                               np.asarray(ref), rtol=RTOL, atol=ATOL)


def test_rope_mxu_matches_concat_spelling():
    from apex_tpu.models.gpt import (apply_rope, apply_rope_mxu,
                                     rope_tables)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, L, H, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(L)[None, :], (B, L))
    cos, sin = rope_tables(positions, D, 10000.0)
    want = apply_rope(x, cos, sin)                       # (B, L, H, D)
    xh = jnp.moveaxis(x, 1, 2)
    cos_h = jnp.moveaxis(jnp.concatenate([cos, cos], -1), 1, 2)
    sin_h = jnp.moveaxis(jnp.concatenate([sin, sin], -1), 1, 2)
    got = jnp.moveaxis(apply_rope_mxu(xh, cos_h, sin_h), 1, 2)
    # exact on both backends: the rotation matmul runs precision=highest
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_head_major_projections_match_dense_split():
    """_QKVProj/_OutProj: identical params to Dense(3E)/Dense(E) and
    identical math to the split+reshape spelling — the checkpoint/param
    compatibility BERT's fast path relies on."""
    from apex_tpu.layers import Dense
    from apex_tpu.layers import HeadMajorOutProj as _OutProj, \
        HeadMajorQKVProj as _QKVProj
    E, Hh = 64, 4
    Dh = E // Hh
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, E), jnp.float32)
    proj = _QKVProj(E, Hh)
    params = proj.init(jax.random.PRNGKey(1), x)["params"]
    assert params["kernel"].shape == (E, 3 * E)
    assert params["bias"].shape == (3 * E,)
    qkv_h = proj.apply({"params": params}, x)            # (3, B, H, L, D)
    dense = Dense(3 * E)
    ref = dense.apply({"params": params}, x)             # (B, L, 3E)
    q, k, v = jnp.split(ref, 3, axis=-1)
    for i, t in enumerate((q, k, v)):
        want = jnp.moveaxis(t.reshape(2, 16, Hh, Dh), 1, 2)
        np.testing.assert_allclose(np.asarray(qkv_h[i]), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    out = _OutProj(E, Hh)
    oparams = out.init(jax.random.PRNGKey(2), qkv_h[0])["params"]
    assert oparams["kernel"].shape == (E, E)
    got = out.apply({"params": oparams}, qkv_h[0])
    want = Dense(E).apply(
        {"params": oparams},
        jnp.moveaxis(qkv_h[0], 1, 2).reshape(2, 16, E))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
