"""apex_tpu.quant coverage (ISSUE 9): fp8 round-trip bounds, the
delayed-scaling state machine under jit, the int8 KV cache's
write/read fidelity and bitwise determinism, the O4 opt level
end-to-end, and the DurableCheckpointManager round trip of an O4
``AmpState`` (amax history restores bitwise, including onto a
reshaped mesh)."""

import os
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from apex_tpu import amp, checkpoint  # noqa: E402
from apex_tpu.models.generate import generate  # noqa: E402
from apex_tpu.models.gpt import GPTModel, gpt_tiny  # noqa: E402
from apex_tpu.models.mlp import MLP, cross_entropy_loss  # noqa: E402
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from apex_tpu.quant import fp8, int8  # noqa: E402
from apex_tpu.resilience import DurableCheckpointManager  # noqa: E402


# ---------------------------------------------------------------------------
# fp8: round-trip error bounds
# ---------------------------------------------------------------------------

def test_fp8_e4m3_round_trip_bound():
    """e4m3 has 3 mantissa bits: for values inside the scaled range the
    relative round-trip error is bounded by 2^-4 (half an ulp of the
    3-bit significand) plus the subnormal floor."""
    x = jax.random.normal(jax.random.PRNGKey(0), (512,), jnp.float32)
    amax = float(jnp.max(jnp.abs(x)))
    scale = jnp.float32(fp8.fp8_max(fp8.FP8_E4M3) / amax)
    back = fp8.dequantize(fp8.quantize(x, scale), scale)
    rel = np.abs(np.asarray(back) - np.asarray(x)) / \
        np.maximum(np.abs(np.asarray(x)), 1e-6)
    assert float(rel.max()) <= 2.0 ** -4 + 1e-3


def test_fp8_e5m2_round_trip_bound():
    """e5m2: 2 mantissa bits -> relative error bound 2^-3."""
    x = jax.random.normal(jax.random.PRNGKey(1), (512,), jnp.float32)
    amax = float(jnp.max(jnp.abs(x)))
    scale = jnp.float32(fp8.fp8_max(fp8.FP8_E5M2) / amax)
    back = fp8.dequantize(fp8.quantize(x, scale, fp8.FP8_E5M2), scale)
    rel = np.abs(np.asarray(back) - np.asarray(x)) / \
        np.maximum(np.abs(np.asarray(x)), 1e-6)
    assert float(rel.max()) <= 2.0 ** -3 + 1e-3


def test_fp8_quantize_saturates_not_inf():
    """Values beyond the representable range clip to fp8_max — never
    inf/nan (the loss scaler owns overflow semantics, not the cast)."""
    q = fp8.quantize(jnp.asarray([1e9, -1e9]), jnp.float32(1.0))
    back = np.asarray(fp8.dequantize(q, jnp.float32(1.0)))
    assert np.all(np.isfinite(back))
    assert back[0] == fp8.fp8_max(fp8.FP8_E4M3)
    assert back[1] == -fp8.fp8_max(fp8.FP8_E4M3)


def test_scaled_matmul_matches_f32_within_operand_rounding():
    """The native-fp8 dot (operands cast to fp8, f32 accumulation via
    preferred_element_type) must match the f32 product of the ROUNDED
    operands exactly — the only error is operand rounding."""
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (16, 32), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (32, 8), jnp.float32)
    sx = jnp.float32(64.0)
    sw = jnp.float32(128.0)
    got = fp8.scaled_matmul(x, w, sx, sw, out_dtype=jnp.float32)
    xr = fp8.dequantize(fp8.quantize(x, sx), sx)
    wr = fp8.dequantize(fp8.quantize(w, sw), sw)
    want = np.asarray(xr) @ np.asarray(wr)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6,
                               atol=1e-6)


def test_qdq_ste_gradient_passes_through_unrounded():
    """Straight-through: d/dx of sum(qdq_ste(x)) is exactly ones — no
    e4m3 rounding of the cotangent (the fp8-double-quantize regression
    the lint caught on the first O4 lane)."""
    x = jax.random.normal(jax.random.PRNGKey(4), (32,), jnp.float32)
    g = jax.grad(lambda v: jnp.sum(fp8.qdq_ste(v, jnp.float32(8.0))
                                   * 3.0))(x)
    np.testing.assert_array_equal(np.asarray(g), np.full((32,), 3.0,
                                                         np.float32))


def test_bwd_qdq_rounds_cotangent_to_e5m2():
    """bwd_qdq is identity forward; its backward rounds the cotangent
    onto the e5m2 grid at the given scale."""
    x = jnp.zeros((64,), jnp.float32)
    cot = jax.random.normal(jax.random.PRNGKey(5), (64,), jnp.float32)
    _, vjp = jax.vjp(lambda v: fp8.bwd_qdq(v, jnp.float32(16.0)), x)
    (got,) = vjp(cot)
    want = fp8.qdq(cot, jnp.float32(16.0), fp8.FP8_E5M2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert not np.array_equal(np.asarray(got), np.asarray(cot))


# ---------------------------------------------------------------------------
# delayed scaling: state transitions under jit
# ---------------------------------------------------------------------------

def test_delayed_scaling_roll_and_derivation_under_jit():
    """The rolled history is newest-first, the derived scale reflects
    the window max, and the whole transition jits (pure pytree)."""
    st = fp8.init_delayed_scaling(4)
    roll = jax.jit(lambda s, a: fp8.record_amax(s, a, fp8.FP8_E4M3))
    st = roll(st, jnp.float32(2.0))
    st = roll(st, jnp.float32(8.0))
    st = roll(st, jnp.float32(4.0))
    np.testing.assert_array_equal(np.asarray(st.amax_history),
                                  [4.0, 8.0, 2.0, 0.0])
    assert float(st.scale) == pytest.approx(448.0 / 8.0)
    # the 8.0 falls off the 4-deep window after 3 more rolls: the
    # scale re-derives from the surviving max (4.0)
    st = roll(st, jnp.float32(1.0))
    st = roll(st, jnp.float32(1.0))
    st = roll(st, jnp.float32(1.0))
    assert float(st.scale) == pytest.approx(448.0 / 4.0)


def test_delayed_scale_is_one_step_behind():
    """The DELAYED contract: the scale in the state never reflects an
    amax that was not yet rolled in — quantizing step t's tensor uses
    a scale derived from steps <= t-1."""
    st = fp8.init_delayed_scaling(4)
    assert float(st.scale) == 1.0            # warmup: nothing recorded
    st = fp8.record_amax(st, jnp.float32(100.0), fp8.FP8_E4M3)
    # the scale NOW reflects 100.0 — for the NEXT step's quantize
    assert float(st.scale) == pytest.approx(4.48)


def test_nonfinite_amax_records_as_zero():
    """An overflowed (scaler-skipped) backward's inf/nan amax must not
    poison the window — it records as 0 (no range information)."""
    st = fp8.init_delayed_scaling(4)
    st = fp8.record_amax(st, jnp.float32(2.0), fp8.FP8_E4M3)
    st = fp8.record_amax(st, jnp.float32(np.inf), fp8.FP8_E4M3)
    st = fp8.record_amax(st, jnp.float32(np.nan), fp8.FP8_E4M3)
    np.testing.assert_array_equal(np.asarray(st.amax_history),
                                  [0.0, 0.0, 2.0, 0.0])
    assert float(st.scale) == pytest.approx(224.0)
    assert np.isfinite(float(st.scale))


def test_rescale_events_count_shrinking_scales():
    old = fp8.init_train_state(4)
    old = fp8.update_train_state(old, jnp.float32(1.0), jnp.float32(1.0),
                                 jnp.float32(1.0))
    bigger = fp8.update_train_state(old, jnp.float32(64.0),
                                    jnp.float32(1.0), jnp.float32(1.0))
    assert int(fp8.rescale_events(old, bigger)) == 1   # input shrank


# ---------------------------------------------------------------------------
# O4 end to end
# ---------------------------------------------------------------------------

def _mlp_setup():
    model = MLP(features=(32,))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 28, 28, 1),
                          jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    def loss_fn(p, xb, yb):
        return cross_entropy_loss(model.apply({"params": p}, xb), yb)
    return params, loss_fn, (x, y)


def test_resolve_o4_properties():
    p = amp.resolve("O4")
    assert p.fp8 and p.opt_level == "O4"
    assert p.master_weights and p.is_dynamic_loss_scale
    assert p.fp8_dtype_fwd == jnp.float8_e4m3fn
    assert p.fp8_dtype_bwd == jnp.float8_e5m2
    with pytest.raises(ValueError, match="O4"):
        amp.resolve("O5")


def test_fp8_lists_shape():
    from apex_tpu.amp import lists
    assert "matmul" in lists.FP8_OPS and "conv" in lists.FP8_OPS
    assert "softmax" in lists.FP8_DENY_OPS
    assert not set(lists.FP8_OPS) & set(lists.FP8_DENY_OPS)


def test_o4_train_step_trains_and_reports_fp8_metrics():
    params, loss_fn, batch = _mlp_setup()
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O4",
                       verbosity=0)
    state = a.init(params)
    assert state.fp8_state is not None
    step = jax.jit(amp.make_train_step(a, loss_fn), donate_argnums=0)
    losses = []
    for _ in range(8):
        state, m = step(state, *batch)
        losses.append(float(m["loss"]))
        assert "fp8_amax_saturation" in m and "fp8_rescales" in m
    assert losses[-1] < losses[1]           # skip the overflow step 0
    # the delayed scales moved off their unit init
    assert float(state.fp8_state.input.scale) != 1.0
    # the program really contains fp8 quantizes
    txt = jax.jit(amp.make_train_step(a, loss_fn),
                  donate_argnums=0).lower(state, *batch).as_text()
    assert "f8E4M3" in txt and "f8E5M2" in txt


def test_o4_matches_o1_loss_first_steps():
    """fp8 operand rounding must not derail mnist-scale optimization:
    after a few identical-batch steps the O4 loss tracks O1 within a
    coarse band (the convergence harness's o4_mnist lane is the full
    curve version)."""
    params, loss_fn, batch = _mlp_setup()
    finals = {}
    for lvl in ("O1", "O4"):
        a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level=lvl,
                           verbosity=0)
        state = a.init(params)
        step = jax.jit(amp.make_train_step(a, loss_fn))
        for _ in range(6):
            state, m = step(state, *batch)
        finals[lvl] = float(m["loss"])
    assert finals["O4"] <= finals["O1"] * 1.25 + 0.05


def test_fp8_deny_ops_enforced_for_prelu():
    """prelu is a HALF op but sits in FP8_DENY_OPS (pointwise, not a
    contraction): under a live O4 trace its operands must NOT quantize
    and its inputs must not pollute the amax collector."""
    from apex_tpu.amp import ops as amp_ops

    p4 = amp.resolve("O4")
    st = fp8.init_train_state(4)
    x = jax.random.normal(jax.random.PRNGKey(9), (16,), jnp.float32)
    alpha = jnp.float32(0.25)
    with amp_ops.cast_context(p4):
        with amp_ops.fp8_trace(st) as tr:
            got = amp_ops.prelu(x, alpha)
            n_amax = len(tr.amaxes["input"]) + len(tr.amaxes["weight"])
    want = jnp.where(x.astype(jnp.bfloat16) >= 0,
                     x.astype(jnp.bfloat16),
                     jnp.bfloat16(0.25) * x.astype(jnp.bfloat16))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert n_amax == 0
    # a contraction through the same context DOES quantize + record
    with amp_ops.cast_context(p4):
        with amp_ops.fp8_trace(st) as tr:
            amp_ops.matmul(jnp.ones((4, 4)), jnp.ones((4, 4)))
            assert len(tr.amaxes["input"]) == 1
            assert len(tr.amaxes["weight"]) == 1


def test_o4_bare_run_degrades_to_half_cast():
    """Amp.run without a train step (no fp8 trace context) must not
    crash — it degrades to the O2-style half cast, documented."""
    params, loss_fn, batch = _mlp_setup()
    a = amp.initialize(opt_level="O4", verbosity=0)
    out = a.run(loss_fn, a.model_params_from(params), *batch)
    assert np.isfinite(float(out))


# ---------------------------------------------------------------------------
# int8 KV cache
# ---------------------------------------------------------------------------

def test_int8_weight_quantization_per_channel():
    w = jax.random.normal(jax.random.PRNGKey(6), (32, 8), jnp.float32)
    q, s = int8.quantize_int8(w, axis=0)
    assert q.dtype == jnp.int8 and s.shape == (1, 8)
    back = int8.dequantize_int8(q, s)
    # per-channel absmax: error bounded by half a quantization step
    step = np.asarray(s)
    assert np.all(np.abs(np.asarray(back) - np.asarray(w))
                  <= 0.5 * step + 1e-7)


def test_quantize_kv_per_position_scales():
    kv = jax.random.normal(jax.random.PRNGKey(7), (2, 5, 3, 4),
                           jnp.bfloat16)
    q, s = int8.quantize_kv(kv)
    assert q.shape == kv.shape and q.dtype == jnp.int8
    assert s.shape == (2, 5) and s.dtype == jnp.float32
    back = np.asarray(q, np.float32) * np.asarray(s)[..., None, None]
    err = np.abs(back - np.asarray(kv, np.float32))
    assert float(err.max()) <= 0.5 * float(np.asarray(s).max()) + 1e-6
    # zero vectors quantize to zeros with unit scale (no div-by-zero)
    qz, sz = int8.quantize_kv(jnp.zeros((1, 2, 3, 4)))
    assert np.all(np.asarray(qz) == 0) and np.all(np.asarray(sz) == 1.0)


@pytest.fixture(scope="module")
def tiny_lm():
    """Tiny GPT BRIEFLY TRAINED on a periodic token sequence, in the
    bf16 serving layout.  A random-init model's near-uniform logits
    flip argmax on ulp-level perturbations — that tests tie-breaking,
    not the cache format; a model with real margins is what the
    documented token-match tolerance is a statement about (the
    pysrc-trained rate is the convergence artifact's
    ``int8_kv_decode`` lane)."""
    from apex_tpu.models.gpt import train_toy_lm

    cfg, params, ids = train_toy_lm()
    prompt = jnp.asarray(ids[:2, :8], jnp.int32)
    return cfg, params, prompt


def test_int8_kv_decode_matches_dense_within_tolerance(tiny_lm):
    """Greedy decode with the int8 KV cache vs the dense cache: token
    match rate at the documented tolerance (>= 0.9; the convergence
    artifact records the trained-model rate)."""
    cfg, params, prompt = tiny_lm
    dense = np.asarray(generate(params, cfg, prompt, 12))
    q = np.asarray(generate(params, cfg, prompt, 12, kv_dtype="int8"))
    match = float(np.mean(dense[:, 8:] == q[:, 8:]))
    assert match >= 0.9


def test_int8_kv_decode_bitwise_deterministic(tiny_lm):
    cfg, params, prompt = tiny_lm
    a = np.asarray(generate(params, cfg, prompt, 12, kv_dtype="int8"))
    b = np.asarray(generate(params, cfg, prompt, 12, kv_dtype="int8"))
    np.testing.assert_array_equal(a, b)


def test_generate_rejects_unknown_kv_dtype(tiny_lm):
    cfg, params, prompt = tiny_lm
    with pytest.raises(ValueError, match="kv_dtype"):
        generate(params, cfg, prompt, 4, kv_dtype="int4")


def test_serve_engine_int8_kv_matches_solo(tiny_lm):
    """The serve engine's int8-KV path (paged pools + scale pools)
    produces the same greedy stream as solo int8 generate, stays on
    ONE decode trace, and reports the admission-time quantization
    error gauge."""
    from apex_tpu.obs.metrics import Registry
    from apex_tpu.serve import Request, ServeConfig, ServeEngine

    cfg, params, prompt = tiny_lm
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=11,
                       max_blocks_per_slot=5, prefill_chunk=4,
                       kv_dtype="int8")
    assert scfg.int8_kv
    eng = ServeEngine(params, cfg, scfg, registry=Registry())
    eng.submit(Request(uid="a", prompt=np.asarray(prompt[0]),
                       max_new_tokens=6))
    eng.submit(Request(uid="b", prompt=np.asarray(prompt[1][:5]),
                       max_new_tokens=6))
    outs = eng.run()
    solo = np.asarray(generate(params, cfg, prompt[0][None], 6,
                               kv_dtype="int8"))[0, 8:]
    np.testing.assert_array_equal(outs["a"], solo)
    assert eng.trace_counts["decode"] == 1
    eng.metrics.flush()
    err = eng.metrics.gauge("serve_kv_quant_error").value
    assert 0.0 < err < 0.1


# ---------------------------------------------------------------------------
# DurableCheckpointManager round trip of an O4 AmpState
# ---------------------------------------------------------------------------

def _o4_state():
    params, loss_fn, batch = _mlp_setup()
    a = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O4",
                       verbosity=0)
    state = a.init(params)
    step = jax.jit(amp.make_train_step(a, loss_fn))
    for _ in range(3):
        state, _m = step(state, *batch)
    return a, state, loss_fn, batch


def test_o4_ampstate_durable_round_trip(tmp_path):
    """Save/restore the full O4 AmpState through the durable layer:
    every leaf — amax histories and derived scales included — restores
    BITWISE, and training continues identically."""
    a, state, loss_fn, batch = _o4_state()
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(3, state)
    mgr.wait()
    template = a.init(jax.tree.map(jnp.zeros_like,
                                   state.master_params))
    restored, _step = mgr.restore(template)
    for (pa, la), (_pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(restored),
            jax.tree_util.tree_leaves_with_path(state)):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=jax.tree_util.keystr(pa))
    # continue: one more step from saved vs restored is bitwise equal
    step = jax.jit(amp.make_train_step(a, loss_fn))
    s1, m1 = step(state, *batch)
    s2, m2 = step(restored, *batch)
    np.testing.assert_array_equal(
        np.asarray(s1.fp8_state.input.amax_history),
        np.asarray(s2.fp8_state.input.amax_history))
    assert float(m1["loss"]) == float(m2["loss"])


@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="needs 4 devices (virtual CPU mesh)")
def test_o4_ampstate_restores_onto_reshaped_mesh(tmp_path):
    """The O4 state saved with masters sharded on a 4-device mesh
    restores bitwise onto a 2-device mesh — fp8_state leaves (scalars
    + tiny histories, replicated) ride the same full-gather +
    template-placement path as everything else, no special case."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    a, state, _loss_fn, _batch = _o4_state()

    def put(state, n):
        mesh = Mesh(np.array(jax.devices()[:n]), ("data",))

        def place(path, leaf):
            if getattr(leaf, "ndim", 0) == 2 and leaf.shape[0] % n == 0:
                return jax.device_put(
                    leaf, NamedSharding(mesh, P("data", None)))
            return leaf
        return jax.tree_util.tree_map_with_path(place, state)

    sharded = put(state, 4)
    mgr = DurableCheckpointManager(str(tmp_path))
    mgr.save(1, sharded)
    mgr.wait()
    template = put(a.init(jax.tree.map(jnp.zeros_like,
                                       state.master_params)), 2)
    restored, _step = mgr.restore(template)
    for (pa, la), (_pb, lb) in zip(
            jax.tree_util.tree_leaves_with_path(restored),
            jax.tree_util.tree_leaves_with_path(state)):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=jax.tree_util.keystr(pa))
    np.testing.assert_array_equal(
        np.asarray(restored.fp8_state.grad.amax_history),
        np.asarray(state.fp8_state.grad.amax_history))


def test_o4_checkpoint_state_dict_round_trip():
    """checkpoint.state_dict/load_state_dict carry fp8_state; a
    pre-fp8 template (fp8_state=None) keeps matching old payloads."""
    a, state, _loss_fn, _batch = _o4_state()
    d = checkpoint.state_dict(state)
    assert "fp8_state" in d
    template = a.init(jax.tree.map(jnp.zeros_like, state.master_params))
    restored, _extras = checkpoint.load_state_dict(template, d)
    np.testing.assert_array_equal(
        np.asarray(restored.fp8_state.weight.amax_history),
        np.asarray(state.fp8_state.weight.amax_history))


def test_committed_convergence_r06_records_quant_lanes():
    """The committed round-6 convergence artifact carries both quant
    lanes, green, schema-valid (gate hygiene re-validates in tier-1)."""
    import json
    doc = json.loads((REPO / "CONVERGENCE_r06.json").read_text())
    assert doc["all_ok"]
    assert doc["o4_mnist"]["ok"]
    assert doc["o4_mnist"]["o4_final"] <= \
        doc["o4_mnist"]["o1_final"] * (1 + doc["o4_mnist"]["band"]) + 0.05
    assert doc["int8_kv_decode"]["ok"]
    assert doc["int8_kv_decode"]["token_match_rate"] >= 0.9
    assert doc["int8_kv_decode"]["bitwise_deterministic"]


def test_pre_fp8_checkpoint_warm_starts_into_o4_template():
    """Restoring an O2-era checkpoint (no fp8_state key) into an O4
    template keeps the template's FRESH delayed-scaling state while
    masters/scalers restore — the O2->O4 warm-start path."""
    params, loss_fn, batch = _mlp_setup()
    a2 = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O2",
                        verbosity=0)
    d = checkpoint.state_dict(a2.init(params))
    del d["fp8_state"]                       # a pre-fp8 payload
    a4 = amp.initialize(optimizer=FusedAdam(lr=1e-3), opt_level="O4",
                        verbosity=0)
    restored, _extras = checkpoint.load_state_dict(a4.init(params), d)
    assert restored.fp8_state is not None
    assert float(restored.fp8_state.input.scale) == 1.0   # fresh
    np.testing.assert_array_equal(
        np.asarray(restored.master_params["AmpDense_0"]["kernel"]),
        np.asarray(d["master_params"]["AmpDense_0"]["kernel"]))
