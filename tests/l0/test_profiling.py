"""Tracing/profiling utility tests (SURVEY.md §5.1 port)."""

import glob
import os

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.utils import (
    annotate,
    nvtx_range,
    profiler_start,
    profiler_stop,
    range_pop,
    range_push,
)


def test_nvtx_range_inside_jit():
    @jax.jit
    def f(x):
        with nvtx_range("hot_section"):
            return x * 2.0

    assert float(f(jnp.float32(3.0))) == 6.0
    # the named scope must land in the HLO metadata (kept in debug
    # info; Lowered.as_text grew its debug_info kwarg after this jax —
    # the MLIR module's debug asm is the version-stable spelling)
    low = jax.jit(_scoped).lower(jnp.float32(1.0))
    hlo = low.compiler_ir(dialect="stablehlo").operation.get_asm(
        enable_debug_info=True)
    assert "scoped_region" in hlo


def _scoped(x):
    with nvtx_range("scoped_region"):
        return x + 1.0


def test_range_push_pop_balanced():
    range_push("outer")
    range_push("inner")
    range_pop()
    range_pop()
    range_pop()  # extra pop is a no-op, like nvtx


def test_annotate_decorator():
    @annotate()
    def my_fn(x):
        return x + 1

    assert my_fn(1) == 2
    assert my_fn.__name__ == "my_fn"


@pytest.mark.slow        # capture-heavy (ROADMAP item 6); the FAST
# capture smoke lives in tests/l0/test_obs.py (capture_dir fixture +
# test_real_capture_parses_with_op_times: one tiny capture, parsed)
def test_profiler_capture(tmp_path):
    logdir = str(tmp_path / "trace")
    profiler_start(logdir)
    x = jnp.ones((8, 8))
    jax.block_until_ready(jnp.dot(x, x))
    profiler_stop()
    # a trace event file must exist under the plugin directory
    produced = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced)
    # idempotent stop
    profiler_stop()
