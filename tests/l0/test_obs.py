"""apex_tpu.obs — unified runtime telemetry (ISSUE 7).

Contracts under test: (a) registry semantics — get-or-create
instruments, kind safety, host fast path vs deferred device values;
(b) the 1-step-lag resolution contract (a deferred value is never
fetched before ``lag`` ticks, tracers are rejected outright);
(c) histogram quantile correctness against numpy percentiles and the
windowed (``since=``) reads bench relies on; (d) Prometheus/JSON
export goldens; (e) spans land in HLO metadata and time into the
registry; (f) the xplane library — one REAL capture parsed per module
(the fast capture smoke), the chrome-trace fallback pinned on a
synthetic fixture, and all profile tools importing the ONE parser;
(g) the OBS / DECODE_PROFILE schemas, their acceptance bars, and the
committed artifacts; (h) the instrumentation-overhead smoke; (i) the
``tools/profile_decode.py`` CPU-xplane smoke whose bucket names match
DECODE_DECOMPOSE.
"""

import glob
import gzip
import json
import math
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.obs import metrics as obs_metrics
from apex_tpu.obs import spans, xplane
from apex_tpu.analysis import decode_decompose, decode_profile
from apex_tpu.analysis import obs as obs_schema

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_registry_get_or_create_and_kind_safety():
    reg = obs_metrics.Registry()
    c1 = reg.counter("x_total", "help text")
    assert reg.counter("x_total") is c1
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x_total")
    c1.inc()
    c1.inc(2.5)
    assert c1.value == 3.5
    g = reg.gauge("g")
    g.set(1.0)
    g.set(-2.0)
    assert g.value == -2.0
    # array observations: counter sums, gauge means
    c1.inc(np.asarray([1.0, 1.0]))
    assert c1.value == 5.5
    g.set(np.asarray([2.0, 4.0]))
    assert g.value == 3.0


def test_histogram_quantiles_match_numpy():
    """Dense linear buckets + interpolation track numpy percentiles to
    within one bucket width."""
    reg = obs_metrics.Registry()
    h = reg.histogram("lat", buckets=np.arange(0.01, 1.01, 0.01))
    rng = np.random.default_rng(0)
    data = rng.uniform(0.0, 1.0, 2000)
    h.observe(data)
    assert h.count == 2000
    for q in (0.5, 0.9, 0.99):
        want = float(np.quantile(data, q))
        assert abs(h.quantile(q) - want) <= 0.02, (q, h.quantile(q), want)


def test_histogram_windowed_quantile_and_empty():
    """``quantile(q, since=state())`` isolates one measurement window —
    how bench.py reads per-load-level p50/p99 off a long-lived
    engine."""
    reg = obs_metrics.Registry()
    h = reg.histogram("lat", buckets=(0.1, 0.2, 0.4, 0.8))
    h.observe([0.05, 0.05, 0.05])           # old window: fast steps
    mark = h.state()
    assert math.isnan(h.quantile(0.5, since=mark))   # empty window
    h.observe([0.3, 0.3, 0.3, 0.3])         # new window: slower steps
    assert h.quantile(0.25) <= 0.1          # all-time p25: a fast step
    assert 0.2 <= h.quantile(0.5, since=mark) <= 0.4  # window: slow
    assert h.quantile(0.25, since=mark) >= 0.2        # no fast steps
    # in the window
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_rejects_bad_buckets():
    reg = obs_metrics.Registry()
    with pytest.raises(ValueError, match="increasing"):
        reg.histogram("bad", buckets=(1.0, 0.5))


# ---------------------------------------------------------------------------
# the lag contract
# ---------------------------------------------------------------------------

def test_deferred_values_resolve_with_exactly_one_step_lag():
    reg = obs_metrics.Registry(lag=1, resolve_every=1)
    g = reg.gauge("loss")
    g.set(jnp.float32(7.0))                 # device value: deferred
    assert g.value == 0.0 and reg.pending_groups == 1
    reg.tick()                              # seals; still within lag
    assert g.value == 0.0 and reg.pending_groups == 1
    g.set(jnp.float32(9.0))
    reg.tick()                              # first group now ripe
    assert g.value == 7.0
    reg.flush()
    assert g.value == 9.0 and reg.pending_groups == 0


def test_deferred_resolution_batches_but_never_under_lag():
    """resolve_every batches the device fetch; a value still never
    resolves earlier than ``lag`` ticks after it was recorded."""
    reg = obs_metrics.Registry(lag=1, resolve_every=3)
    c = reg.counter("n")
    for i in range(3):
        c.inc(jnp.float32(1.0))
        reg.tick()
        assert c.value == 0.0               # 3 sealed, none past batch
    c.inc(jnp.float32(1.0))
    reg.tick()                              # 4 sealed: 3 ripe -> fetch
    assert c.value == 3.0
    reg.flush()
    assert c.value == 4.0


def test_tracer_recording_is_an_error():
    reg = obs_metrics.Registry()
    g = reg.gauge("inside")

    @jax.jit
    def f(x):
        g.set(x)                            # recording a tracer: bug
        return x

    with pytest.raises(TypeError, match="never inside"):
        f(jnp.float32(1.0))


def test_discard_pending_drops_abandoned_timeline():
    reg = obs_metrics.Registry(lag=1, resolve_every=1)
    c = reg.counter("n")
    c.inc(jnp.float32(5.0))
    reg.discard_pending()
    reg.flush()
    assert c.value == 0.0


def test_instrument_step_wraps_and_lags():
    reg = obs_metrics.Registry()
    calls = []

    def step(state, x):
        calls.append(x)
        return state + 1, {"loss": jnp.float32(0.5),
                           "overflow": jnp.asarray(False)}

    wrapped = obs_metrics.instrument_step(step, registry=reg)
    s = 0
    for i in range(3):
        s, m = wrapped(s, i)
    assert s == 3 and len(calls) == 3
    assert reg.counter("train_steps_total").value == 3.0
    assert reg.histogram("train_step_dispatch_seconds").count == 3
    reg.flush()
    assert reg.gauge("train_loss").value == 0.5
    assert reg.counter("train_overflows_total").value == 0.0


def test_instrument_step_records_fp8_metrics_lagged():
    """The O4 regime's telemetry (amax-saturation gauge,
    overflow-to-rescale counter) rides the SAME deferred/lag machinery
    as loss/overflow — device values recorded at the step boundary,
    resolved by tick/flush, never a fresh host sync."""
    reg = obs_metrics.Registry()

    def step(state, x):
        return state + 1, {"loss": jnp.float32(0.1),
                           "overflow": jnp.asarray(False),
                           "fp8_amax_saturation": jnp.float32(0.97),
                           "fp8_rescales": jnp.asarray(2, jnp.int32)}

    wrapped = obs_metrics.instrument_step(step, registry=reg)
    s = 0
    for i in range(3):
        s, _m = wrapped(s, i)
    reg.flush()
    assert reg.gauge("train_fp8_amax_saturation").value ==         jnp.float32(0.97)
    assert reg.counter("train_fp8_rescales_total").value == 6.0


# ---------------------------------------------------------------------------
# export goldens
# ---------------------------------------------------------------------------

def _golden_registry():
    reg = obs_metrics.Registry()
    c = reg.counter("req_total", "requests served")
    c.inc(3)
    h = reg.histogram("lat_seconds", "step latency",
                      buckets=(0.1, 1.0))
    h.observe([0.05, 0.5, 5.0])
    return reg


def test_prometheus_export_golden():
    assert _golden_registry().to_prometheus() == (
        "# HELP lat_seconds step latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1.0"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_sum 5.55\n"
        "lat_seconds_count 3\n"
        "# HELP req_total requests served\n"
        "# TYPE req_total counter\n"
        "req_total 3\n")


def test_json_export_golden():
    assert _golden_registry().snapshot() == {"metrics": [
        {"name": "lat_seconds", "type": "histogram",
         "help": "step latency",
         "buckets": {"0.1": 1, "1.0": 2, "+Inf": 3},
         "sum": 5.55, "count": 3},
        {"name": "req_total", "type": "counter",
         "help": "requests served", "value": 3.0},
    ]}


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_paths_and_timing():
    reg = obs_metrics.Registry()
    assert spans.current_path() == ""
    with spans.span("serve", registry=reg):
        with spans.span("decode_step", registry=reg):
            assert spans.current_path() == "serve/decode_step"
        assert spans.current_path() == "serve"
    assert spans.current_path() == ""
    h = reg.histogram(spans.metric_name("serve/decode_step"))
    assert h.count == 1 and h.sum > 0


def test_span_lands_in_hlo_metadata_not_default_lowering():
    """Inside jit a span contributes metadata ONLY: the scope shows in
    the debug-info asm and the compiled module, while the default
    lowered text — what every analysis pass parses — is unchanged."""
    reg = obs_metrics.Registry()

    def f(x):
        with spans.span("obs_probe/region", registry=reg):
            return x * 2.0 + 1.0

    low = jax.jit(f).lower(jnp.float32(1.0))
    assert "obs_probe" not in low.as_text()
    dbg = low.compiler_ir(dialect="stablehlo").operation.get_asm(
        enable_debug_info=True)
    assert "obs_probe/region" in dbg
    # tracing suppressed the wall-clock observation (trace time is
    # compile cost, not runtime)
    assert reg.histogram(
        spans.metric_name("obs_probe/region")).count == 0


def test_traced_span_decorator():
    reg = obs_metrics.Registry()

    @spans.traced_span("my/step", registry=reg)
    def fn(x):
        return x + 1

    assert fn(1) == 2
    assert reg.histogram(spans.metric_name("my/step")).count == 1


# ---------------------------------------------------------------------------
# xplane library: one real capture (the fast capture smoke) + fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def capture_dir(tmp_path_factory):
    """One REAL profiler capture of a tiny jitted program, shared by
    the parser tests (also the fast replacement for the slow-marked
    capture case in test_profiling.py)."""
    logdir = str(tmp_path_factory.mktemp("trace"))
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    f(x).block_until_ready()
    with jax.profiler.trace(logdir):
        for _ in range(2):
            r = f(x)
        r.block_until_ready()
    import time
    time.sleep(0.5)
    return logdir


def test_real_capture_parses_with_op_times(capture_dir):
    t = xplane.op_times(capture_dir)
    assert t.total_ps > 0
    assert t.by_op                      # op-level events present
    # CPU captures have no device plane: the host XLA executor lines
    # carry the per-instruction events (or, without the tsl proto,
    # the chrome-trace fallback)
    assert t.source in ("xplane-device", "xplane-host", "trace-json")
    by_name, by_cat, total = xplane.parse_xplane(capture_dir)
    assert total == t.total_ps and by_name == t.by_op
    assert xplane.step_markers(capture_dir) == []   # no Steps on CPU


def test_profile_tools_share_the_one_parser():
    """ISSUE 7 satellite: the three xplane-parsing tools (plus
    d64_decompose) import apex_tpu.obs.xplane — no private copies."""
    import profile_step
    assert profile_step.parse_xplane is xplane.parse_xplane
    src_ca = (REPO / "tools" / "conv_attrib.py").read_text()
    src_fr = (REPO / "tools" / "fusion_roofline.py").read_text()
    assert "from apex_tpu.obs.xplane import parse_xplane" in src_ca
    assert "from apex_tpu.obs.xplane import parse_xplane" in src_fr
    for src in (src_ca, src_fr):
        assert "xplane_pb2" not in src   # the copies are gone


def _write_trace_json(tmp_path, events):
    p = tmp_path / "plugins" / "profile" / "x"
    p.mkdir(parents=True)
    with gzip.open(p / "t.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def test_chrome_trace_fallback_device_planes_pinned(tmp_path):
    """The lossy chrome-trace path (behavior pinned when the copies
    were deleted): device-plane 'XLA Ops' events aggregate; host and
    non-op threads are ignored when a device plane produced data."""
    meta = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "thread_name", "pid": 9, "tid": 1,
         "args": {"name": "tf_XLAEigen/1"}},
    ]
    events = meta + [
        {"ph": "X", "pid": 1, "tid": 2, "name": "%fusion.1 = f32[8]",
         "dur": 2.0, "args": {"hlo_category": "fusion"}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "dot.3", "dur": 1.0,
         "args": {"hlo_category": "convolution"}},
        {"ph": "X", "pid": 9, "tid": 1, "name": "dot.9", "dur": 5.0},
        {"ph": "X", "pid": 1, "tid": 3, "name": "ignored", "dur": 9.0},
    ]
    by_name, by_cat, total = xplane.parse_trace_json(
        _write_trace_json(tmp_path, events))
    assert total == int(3.0 * 1e6)          # us -> ps
    assert by_name == {"fusion.1": 2_000_000, "dot.3": 1_000_000}
    assert by_cat == {"fusion": 2_000_000, "convolution": 1_000_000}


def test_chrome_trace_fallback_host_lines_when_no_device(tmp_path):
    """XLA:CPU captures have no device plane — the tf_XLA* executor
    lines are harvested instead, infra events filtered."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "thread_name", "pid": 9, "tid": 1,
         "args": {"name": "tf_XLAEigen/1"}},
        {"ph": "X", "pid": 9, "tid": 1, "name": "dot.9", "dur": 5.0},
        {"ph": "X", "pid": 9, "tid": 1,
         "name": "ThreadpoolListener::Record", "dur": 4.0},
    ]
    by_name, _, total = xplane.parse_trace_json(
        _write_trace_json(tmp_path, events))
    assert by_name == {"dot.9": 5_000_000} and total == 5_000_000


def test_bucket_op_times_classifies_and_fills_all_buckets():
    table = xplane.bucket_op_times(
        {"dot.1": 100, "copy.2": 50, "weird.3": 25},
        classify=lambda n: {"dot.1": "kv_read",
                            "copy.2": "kv_write"}.get(n),
        buckets=["kv_read", "kv_write", "sampling"])
    assert table["bucket_ps"] == {"kv_read": 100, "kv_write": 50,
                                  "sampling": 0, "other": 25}
    assert table["total_ps"] == 175 and table["matched_ps"] == 150
    assert table["fractions"]["other"] == round(25 / 175, 4)


# ---------------------------------------------------------------------------
# schemas + committed artifacts
# ---------------------------------------------------------------------------

def test_profile_bucket_vocabulary_pinned_to_decompose():
    """decode_profile duplicates BUCKETS (gate_hygiene loads each
    schema standalone); the two vocabularies must never drift."""
    assert decode_profile.BUCKETS == decode_decompose.BUCKETS


def _valid_obs_doc():
    return {
        "round": 1, "platform": "cpu",
        "overhead": {"steps": 40, "bare_s": 0.5, "instrumented_s": 0.5,
                     "overhead_pct": 0.4},
        "syncs": {"clean": True,
                  "lanes": {"serve_step": {"host_callbacks": 0,
                                           "static_scalars": 0,
                                           "errors": 0}}},
        "export": {"metrics": [{"name": "x", "type": "counter"}]},
    }


def test_obs_schema_accepts_valid_and_enforces_bars():
    assert obs_schema.validate_obs(_valid_obs_doc()) == []
    over = _valid_obs_doc()
    over["overhead"]["overhead_pct"] = 1.7
    assert any("budget" in p for p in obs_schema.validate_obs(over))
    dirty = _valid_obs_doc()
    dirty["syncs"]["lanes"]["serve_step"]["host_callbacks"] = 2
    problems = obs_schema.validate_obs(dirty)
    assert any("hazard" in p for p in problems)
    unclean = _valid_obs_doc()
    unclean["syncs"]["clean"] = False
    assert any("contradiction" in p
               for p in obs_schema.validate_obs(unclean))
    empty = _valid_obs_doc()
    empty["export"] = {"metrics": []}
    assert any("export" in p for p in obs_schema.validate_obs(empty))


def test_decode_profile_schema_accepts_valid_and_rejects_drift():
    doc = {
        "round": 1, "platform": "cpu",
        "config": {"batch": 8, "prefill": 64, "new_tokens": 32},
        "method": "xplane-capture",
        "capture": {"iters": 2, "total_ps": 1000, "source": "xplane"},
        "device_time_ps": {k: 10 for k in decode_profile.BUCKETS},
        "device_time_fractions": {
            k: round(1 / 7, 4) for k in decode_profile.BUCKETS},
        "coverage": round(1 - 1 / 7, 4),
        "verdict": "smoke",
    }
    assert decode_profile.validate_profile(doc) == []
    drifted = dict(doc, device_time_ps=dict(doc["device_time_ps"],
                                            bogus_bucket=5))
    assert any("vocabulary" in p
               for p in decode_profile.validate_profile(drifted))
    empty = dict(doc, capture={"iters": 2, "total_ps": 0,
                               "source": "xplane"})
    assert any("empty capture" in p
               for p in decode_profile.validate_profile(empty))
    noverdict = dict(doc, verdict="  ")
    assert any("verdict" in p
               for p in decode_profile.validate_profile(noverdict))


def test_committed_obs_and_profile_artifacts_validate():
    """The committed OBS_r01 / DECODE_PROFILE_r01 are the schemas'
    reference instances — and OBS_r01 is the acceptance record: the
    measured instrumentation overhead under 1% and the clean syncs
    table over the instrumented serve + train lanes."""
    import gate_hygiene
    assert gate_hygiene._validate_obs(str(REPO)) == []
    assert gate_hygiene._validate_profiles(str(REPO)) == []
    with open(REPO / "OBS_r01.json") as f:
        doc = json.load(f)
    assert doc["overhead"]["overhead_pct"] < 1.0
    assert doc["syncs"]["clean"] is True
    assert "serve_step" in doc["syncs"]["lanes"]
    names = {m["name"] for m in doc["export"]["metrics"]}
    assert {"serve_decode_step_seconds", "serve_tokens_total",
            "train_steps_total"} <= names
    with open(REPO / "DECODE_PROFILE_r01.json") as f:
        prof = json.load(f)
    assert set(prof["device_time_ps"]) == set(decode_decompose.BUCKETS)


# ---------------------------------------------------------------------------
# overhead smoke + the profile_decode CPU-xplane smoke
# ---------------------------------------------------------------------------

def test_instrumentation_overhead_smoke():
    """The chaos_run-style measurement at (reduced) bench-smoke scale:
    the deterministic per-step instrument cost must sit far under the
    step time.  The committed OBS_r01.json pins the real <1% number;
    this smoke allows noise headroom so a loaded CI box cannot flake
    it."""
    import obs_report
    out = obs_report.measure_overhead(steps=10, reps=2, calls=300)
    assert out["bare_s"] > 0 and out["instrument_us_per_step"] > 0
    assert out["overhead_pct"] < 5.0, out


def test_profile_decode_cpu_xplane_smoke(tmp_path):
    """Acceptance: tools/profile_decode.py captures the decode program
    on this backend, buckets device time via obs.xplane into the
    DECODE_DECOMPOSE bucket names, and emits a schema-valid
    document."""
    import profile_decode
    doc = profile_decode.profile(batch=1, prefill=8, new_tokens=8,
                                 tiny=True, iters=1,
                                 logdir=str(tmp_path / "trace"))
    assert decode_profile.validate_profile(doc) == []
    assert set(doc["device_time_ps"]) == set(decode_decompose.BUCKETS)
    assert doc["capture"]["total_ps"] > 0
    assert doc["capture"]["step_ps"] > 0      # the while-body was found
    assert doc["device_time_fractions"]["host_sync"] == 0.0
    # the decode loop's time concentrates in the real buckets, not
    # "other" — the classifier understands the program
    assert doc["coverage"] >= 0.5
