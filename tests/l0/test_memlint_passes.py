"""The memory / cost / syncs lint passes (ISSUE 4 tentpole).

Each pass must FIRE on a tiny crafted violating program — a known
dropped donation, a known io_callback, a known oversized temp against
a small budget, a known static-scalar retrace hazard — with the exact
finding code pinned, and stay QUIET (error-free) on clean programs.
Everything runs on CPU-jitted programs: the whole point of the memlint
passes is that XLA's ``memory_analysis()`` / ``cost_analysis()`` and
the callback/alias text are available without a TPU.
"""

import functools
import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from apex_tpu import analysis  # noqa: E402
from apex_tpu.analysis import cost as cost_mod  # noqa: E402
from apex_tpu.analysis import memlint  # noqa: E402
from apex_tpu.analysis import memory as memory_mod  # noqa: E402


def _codes(report, pass_name, severity=None):
    return [f.op for f in report.by_pass(pass_name)
            if severity is None or f.severity == severity]


# ---------------------------------------------------------------------------
# memory
# ---------------------------------------------------------------------------

def test_memory_dropped_donation_is_error():
    """A donated arg with no same-shaped output: the compiled alias
    table omits it, and the memory pass reports the compiled OUTCOME
    (the donation pass reports the request — both fire)."""
    def g(x):
        return (x[:2] * 2.0).sum()

    rep = analysis.analyze(g, jnp.ones((128, 128)), donate_argnums=(0,),
                           passes=("memory",))
    assert not rep.ok
    errs = [f for f in rep.by_pass("memory") if f.severity == "error"]
    assert [f.op for f in errs] == ["donation-dropped"]
    assert errs[0].bytes == 128 * 128 * 4


def test_memory_budget_violation_fires_on_oversized_temp():
    """A matmul's temp buffers push the static peak over a deliberately
    tiny budget — the ``hbm-budget`` error carries the peak bytes."""
    def f(x):
        return (x @ x.T).sum()

    x = jnp.ones((256, 256), jnp.float32)
    rep = analysis.analyze(f, x, passes=("memory",),
                           options={"memory": {"budget_bytes": 1024}})
    assert not rep.ok
    errs = [f for f in rep.errors if f.op == "hbm-budget"]
    assert len(errs) == 1
    assert errs[0].bytes > 1024              # the recorded peak
    # the same program inside a sane budget is clean
    rep2 = analysis.analyze(f, x, passes=("memory",),
                            options={"memory":
                                     {"budget_bytes": 1 << 30}})
    assert rep2.ok


def test_memory_honored_donation_quiet_with_alias_table():
    def f(x):
        return x * 2.0

    rep = analysis.analyze(f, jnp.ones((64, 64)), donate_argnums=(0,),
                           passes=("memory",))
    assert rep.ok
    infos = rep.by_pass("memory")
    table = [f for f in infos if f.op == "donation-alias"]
    assert len(table) == 1 and "1/1" in table[0].message
    peak = [f for f in infos if f.op == "peak-hbm"]
    assert peak and peak[0].bytes > 0


def test_memory_pass_skips_uncompiled():
    rep = analysis.analyze(lambda x: x + 1.0, jnp.ones((4,)),
                           passes=("memory",), compile=False)
    assert rep.ok
    assert "skipped" in rep.by_pass("memory")[0].message


def test_memory_stats_peak_formula():
    """peak = args + outputs + temps − aliased, per device."""
    step = jax.jit(lambda w: w * 2.0, donate_argnums=(0,))
    compiled = step.lower(jnp.ones((64, 64))).compile()
    stats = memory_mod.memory_stats(compiled)
    assert stats["peak_hbm_bytes"] == (
        stats["argument_bytes"] + stats["output_bytes"]
        + stats["temp_bytes"] - stats["alias_bytes"])
    assert stats["alias_bytes"] == 64 * 64 * 4   # the honored donation


# ---------------------------------------------------------------------------
# syncs
# ---------------------------------------------------------------------------

def test_syncs_io_callback_on_step_path_is_error():
    from jax.experimental import io_callback

    def f(x):
        y = io_callback(lambda v: np.asarray(v),
                        jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y * 2.0

    rep = analysis.analyze(f, jnp.ones((4,)), passes=("syncs",))
    assert not rep.ok
    assert _codes(rep, "syncs", "error") == ["host-callback"]
    # the lowering-only fallback classifies from StableHLO attributes
    rep2 = analysis.analyze(f, jnp.ones((4,)), passes=("syncs",),
                            compile=False)
    assert not rep2.ok
    assert _codes(rep2, "syncs", "error") == ["host-callback"]


def test_syncs_debug_print_warns_not_gates():
    def f(x):
        jax.debug.print("x={x}", x=x)
        return x * 1.5

    rep = analysis.analyze(f, jnp.ones((4,)), passes=("syncs",))
    assert rep.ok, rep.format()   # warning, not error
    assert "debug-callback" in _codes(rep, "syncs", "warning")


def test_syncs_pure_callback_warns():
    def f(x):
        y = jax.pure_callback(lambda v: np.asarray(v),
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    rep = analysis.analyze(f, jnp.ones((4,)), passes=("syncs",))
    assert rep.ok
    assert "pure-callback" in _codes(rep, "syncs", "warning")


def test_syncs_infeed_crafted_hlo_is_error():
    hlo = ("ENTRY %main (t: token[]) -> f32[4] {\n"
           "  %infeed = ((f32[4]{0}), token[]) infeed(token[] %t)\n"
           "}\n")
    ctx = analysis.PassContext(stablehlo_text="", hlo_text=hlo)
    out = analysis.PASSES["syncs"](ctx)
    errs = [f for f in out if f.severity == "error"]
    assert errs and "infeed" in errs[0].message


def test_syncs_static_scalar_retrace_warns():
    @functools.partial(jax.jit, static_argnums=(1,))
    def f(x, n):
        return x * n

    rep = analysis.analyze(f, jnp.ones((8,)), 3, passes=("syncs",))
    assert rep.ok    # warning: legitimate shape statics exist
    warns = [f for f in rep.by_pass("syncs")
             if f.op == "static-scalar"]
    assert len(warns) == 1 and "recompiles" in warns[0].message
    assert "arg1=3" in warns[0].message   # exact attribution


def test_syncs_static_scalar_mixed_with_dynamic_is_not_misattributed():
    """A static int ALONGSIDE a dynamically-passed Python float: the
    traced signature cannot say which is which, so the finding names
    the candidate set at info severity — never a warning pointing at
    the dynamic arg alone."""
    @functools.partial(jax.jit, static_argnums=(0,))
    def f(n, x, s):
        return x * n * s

    rep = analysis.analyze(f, 4, jnp.ones((8,)), 2.0,
                           passes=("syncs",))
    hits = [f for f in rep.by_pass("syncs")
            if f.op == "static-scalar"]
    assert len(hits) == 1 and hits[0].severity == "info"
    assert "cannot say which" in hits[0].message
    assert "arg0=4" in hits[0].message and "arg2=2.0" in hits[0].message


def test_syncs_nonnumeric_static_does_not_misattribute_dynamic_float():
    """The real static is a mode STRING; the Python float is dynamic.
    The exact-attribution branch must not fire (it would name the
    dynamic float as static while the same run reports it weak-typed
    traced)."""
    @functools.partial(jax.jit, static_argnums=(1,))
    def f(scale, mode):
        return scale * (2.0 if mode == "mul" else 0.5)

    rep = analysis.analyze(f, 0.5, "mul", passes=("syncs",))
    hits = [f for f in rep.by_pass("syncs")
            if f.op == "static-scalar"]
    assert len(hits) == 1 and hits[0].severity == "info"
    assert "cannot say which" in hits[0].message
    # no warning-severity claim that arg0 is static
    assert not [f for f in rep.by_pass("syncs")
                if f.severity == "warning"]


def test_syncs_weak_scalar_info_and_clean_program_quiet():
    rep = analysis.analyze(lambda x, s: x * s, jnp.ones((8,)), 2.5,
                           passes=("syncs",))
    assert rep.ok
    assert "weak-scalar" in _codes(rep, "syncs", "info")
    # arrays-only program: nothing to say
    rep2 = analysis.analyze(lambda x: x * 2.0, jnp.ones((8,)),
                            passes=("syncs",))
    assert rep2.ok and not rep2.findings


def test_syncs_inplace_read_race_info():
    rep = analysis.analyze(lambda x: x * 2.0, jnp.ones((32, 32)),
                           donate_argnums=(0,), passes=("syncs",))
    assert rep.ok
    infos = [f for f in rep.by_pass("syncs")
             if f.op == "inplace-read-race"]
    assert len(infos) == 1 and infos[0].bytes == 32 * 32 * 4


# ---------------------------------------------------------------------------
# cost
# ---------------------------------------------------------------------------

def test_cost_pass_records_flops_and_bytes():
    rep = analysis.analyze(lambda a, b: (a @ b).sum(),
                           jnp.ones((64, 64)), jnp.ones((64, 64)),
                           passes=("cost",))
    assert rep.ok
    codes = _codes(rep, "cost")
    assert "flops" in codes and "hbm-bytes" in codes


def test_cost_roofline_expectation_math():
    exp = cost_mod.roofline_expectation(
        flops=1e6, hbm_bytes=1e6, peak_flops=100e12,
        peak_hbm_bytes_per_s=1e12)
    assert exp["intensity_flops_per_byte"] == 1.0
    assert exp["bound"] == "bandwidth"
    assert exp["ceiling_flops_per_s"] == 1e12
    assert exp["ceiling_util"] == pytest.approx(0.01)
    exp2 = cost_mod.roofline_expectation(
        flops=1e9, hbm_bytes=1.0, peak_flops=100e12,
        peak_hbm_bytes_per_s=1e12)
    assert exp2["bound"] == "compute" and exp2["ceiling_util"] == 1.0


def test_cost_floor_above_ceiling_is_error():
    doc = {"hbm_gbps_peak": 819.0,
           "kernels": {"k": {"gbps": 400.0, "roofline_frac": 0.49}}}
    out = cost_mod.audit_kernel_artifact(doc, "KERNELBENCH_rX.json",
                                         floors={"k": 1.2})
    assert [f.op for f in out] == ["floor-above-ceiling"]
    assert all(f.severity == "error" for f in out)
    # floors at/below the ceiling are fine
    assert not cost_mod.audit_kernel_artifact(doc, "x",
                                              floors={"k": 0.5})


def test_cost_measured_above_ceiling_is_error():
    doc = {"hbm_gbps_peak": 819.0,
           "kernels": {"k": {"gbps": 900.0, "roofline_frac": 1.1}}}
    out = cost_mod.audit_kernel_artifact(doc, "KERNELBENCH_rX.json")
    assert len(out) == 2
    assert {f.op for f in out} == {"measured-above-ceiling"}


def test_cost_bench_artifact_hfu_below_mfu_is_error():
    doc = {"parsed": {"configs": {
        "good": {"mfu": 0.5, "hfu": 0.55},
        "bad_mfu": {"mfu": 1.3, "hfu": 1.3},
        "bad_hfu": {"mfu": 0.5, "hfu": 0.3},
        "zero_hfu": {"mfu": 0.5, "hfu": 0.0}}}}  # broken counter
    out = cost_mod.audit_bench_artifact(doc, "BENCH_rX.json",
                                        mfu_floors={"good": 0.45})
    msgs = " | ".join(f.message for f in out)
    assert len(out) == 3 and "bad_mfu" in msgs and "bad_hfu" in msgs
    assert "zero_hfu" in msgs   # hfu=0.0 must not slip the falsy guard


def test_cost_audit_floor_artifacts_over_dir(tmp_path):
    (tmp_path / "KERNELBENCH_r03.json").write_text(json.dumps(
        {"hbm_gbps_peak": 819.0,
         "kernels": {"k": {"gbps": 1000.0, "roofline_frac": 1.2}}}))
    (tmp_path / "KERNELBENCH_r02.json").write_text(json.dumps(
        {"hbm_gbps_peak": 819.0,
         "kernels": {"k": {"gbps": 100.0, "roofline_frac": 0.1}}}))
    out = cost_mod.audit_floor_artifacts(str(tmp_path))
    errs = [f for f in out if f.severity == "error"]
    assert len(errs) == 2        # only the NEWEST round is audited
    assert all("r03" in f.message for f in errs)
    # clean dir: single info verdict
    clean = cost_mod.audit_floor_artifacts(str(tmp_path / "nowhere"))
    assert len(clean) == 1 and clean[0].severity == "info"


def test_cost_audit_floors_fail_without_artifacts(tmp_path):
    """The floor tables are artifact-independent: an impossible floor
    (>1.0) must fail even when no KERNELBENCH/BENCH file loads — a
    corrupt newest round must never launder it through a clean
    verdict."""
    out = cost_mod.audit_floor_artifacts(
        str(tmp_path), kernel_floors={"k": 1.5}, mfu_floors={"c": 2.0})
    errs = [f for f in out if f.severity == "error"]
    assert len(errs) == 2
    assert all(f.op == "floor-above-ceiling" for f in errs)
    # an unreadable newest artifact is a coverage WARNING, never the
    # affirmative clean verdict
    (tmp_path / "KERNELBENCH_r09.json").write_text("{truncated")
    (tmp_path / "BENCH_r09.json").write_text("not json")
    out2 = cost_mod.audit_floor_artifacts(str(tmp_path))
    warns = [f for f in out2 if f.severity == "warning"]
    assert len(warns) == 2
    assert any("KERNELBENCH_r09" in f.message for f in warns)
    assert not any("sit under the cost-model ceilings" in f.message
                   for f in out2)


def test_repo_committed_artifacts_pass_calibration():
    """The repo's own committed KERNELBENCH/BENCH artifacts and
    published floor tables must sit under the cost-model ceilings —
    the 'floors must sit under the ceiling' rule, enforced."""
    sys.path.insert(0, str(REPO / "tools"))
    import kernel_bench
    out = cost_mod.audit_floor_artifacts(
        str(REPO), kernel_floors=kernel_bench.KERNEL_FLOORS)
    errs = [f for f in out if f.severity == "error"]
    assert not errs, [f.message for f in errs]


# ---------------------------------------------------------------------------
# one-lowering sharing (the analyze double-lowering fix)
# ---------------------------------------------------------------------------

def test_mixed_pass_list_shares_one_context():
    """Compiled-evidence passes (memory/cost) and lowering-only passes
    (policy, constant-capture) run from ONE analyze call — a single
    lowering and a single compilation feed every pass."""
    def fwd(w, x):
        h = jnp.matmul(x, w).astype(jnp.bfloat16)
        return jax.nn.softmax(h, axis=-1).astype(jnp.float32).sum()

    w = jnp.ones((8, 8), jnp.float32)
    x = jnp.ones((4, 8), jnp.float32)
    rep = analysis.analyze(fwd, w, x,
                           passes=("constant-capture", "memory", "cost",
                                   "policy"))
    # policy fires from the shared stablehlo text while memory/cost
    # read the shared executable
    assert any(f.pass_name == "policy" and f.severity == "error"
               for f in rep.findings)
    assert any(f.op == "peak-hbm" for f in rep.by_pass("memory"))
    assert any(f.op == "flops" for f in rep.by_pass("cost"))


def test_build_context_carries_executable_and_outputs():
    lowered = jax.jit(lambda x: (x * 2, x.sum())).lower(
        jnp.ones((4, 4)))
    ctx = analysis.build_context(lowered)
    assert ctx.compiled is not None and ctx.hlo_text
    assert [o.nbytes for o in ctx.outputs] == [64, 4]
    ctx2 = analysis.build_context(lowered, compile=False)
    assert ctx2.compiled is None and ctx2.hlo_text is None


def test_derived_tables_memoized_per_context():
    """The alias set / kept map / donation table are parsed from the
    HLO text once per lowering, however many passes consume them —
    repeated calls return the SAME object from the context memo."""
    from apex_tpu.analysis import donation as donation_mod
    from apex_tpu.analysis import memory as memory_mod

    lowered = jax.jit(lambda s, x: (s + x, x.sum()),
                      donate_argnums=(0,)).lower(
        jnp.ones((16, 16)), jnp.ones((16, 16)))
    ctx = analysis.build_context(lowered)
    t1 = memory_mod.donation_table(ctx)
    t2 = memory_mod.donation_table(ctx)
    assert t1 is t2 and t1 and t1[0]["aliased"]
    assert donation_mod.kept_index_map(ctx) \
        is donation_mod.kept_index_map(ctx)
    assert donation_mod.aliased_parameter_set(ctx) \
        is donation_mod.aliased_parameter_set(ctx)
    # a second context has its own memo — no cross-lowering bleed
    ctx2 = analysis.build_context(
        jax.jit(lambda x: x * 2).lower(jnp.ones((4,))))
    assert memory_mod.donation_table(ctx2) == []


# ---------------------------------------------------------------------------
# memlint schema
# ---------------------------------------------------------------------------

def _valid_doc():
    return {"round": 1, "platform": "cpu", "budget_bytes": None,
            "lanes": {"mlp_o1_train": {
                "ok": True, "peak_hbm_bytes": 123,
                "breakdown": {"argument_bytes": 100},
                "donation": [{"arg": "w", "bytes": 4, "aliased": True}],
                "cost": {"flops": 1.0, "hbm_bytes": 2.0},
                "findings": {"info": 3}}},
            "multichip": {"n_devices": 8,
                          "slices": {"fsdp": {"ok": True,
                                              "hbm_bytes_per_device": 9}}}}


def test_memlint_schema_accepts_valid_doc():
    assert memlint.validate_memlint(_valid_doc()) == []


@pytest.mark.parametrize("mutate,needle", [
    (lambda d: d.pop("lanes"), "lanes"),
    (lambda d: d.pop("round"), "round"),
    (lambda d: d["lanes"]["mlp_o1_train"].pop("peak_hbm_bytes"),
     "peak_hbm_bytes"),
    (lambda d: d["lanes"]["mlp_o1_train"].update(peak_hbm_bytes=-1),
     "peak_hbm_bytes"),
    (lambda d: d["lanes"]["mlp_o1_train"].update(
        donation=[{"nope": 1}]), "donation"),
    (lambda d: d["lanes"]["mlp_o1_train"].update(
        cost={"flops": "fast"}), "hbm_bytes"),
    (lambda d: d.update(multichip={"n_devices": 8}), "multichip"),
])
def test_memlint_schema_rejects(mutate, needle):
    doc = _valid_doc()
    mutate(doc)
    problems = memlint.validate_memlint(doc)
    assert problems and any(needle in p for p in problems), problems


def test_memlint_file_validator_and_repo_artifact(tmp_path):
    p = tmp_path / "MEMLINT_r09.json"
    p.write_text('{"round": ')
    assert any("unreadable" in m
               for m in memlint.validate_memlint_file(str(p)))
    committed = REPO / "MEMLINT_r01.json"
    assert committed.exists(), "MEMLINT_r01.json must be committed"
    assert memlint.validate_memlint_file(str(committed)) == []
    doc = json.loads(committed.read_text())
    # acceptance: all four families + the decode lanes, each with the
    # full static memory/cost story
    for family in ("mlp", "resnet", "gpt", "bert"):
        assert f"{family}_o1_train" in doc["lanes"]
        assert f"{family}_o2_train" in doc["lanes"]
    assert "decode_b1" in doc["lanes"] and "decode_b2" in doc["lanes"]
    for lane in doc["lanes"].values():
        assert lane["peak_hbm_bytes"] > 0
        assert lane["cost"].get("flops", 0) > 0
    assert doc["calibration"]["ok"] is True
    # the multichip table carries per-device HBM for the live slices
    slices = doc["multichip"]["slices"]
    assert any(rec.get("hbm_bytes_per_device") for rec in
               slices.values())
