"""Graph-lint subsystem coverage (:mod:`apex_tpu.analysis`).

Each pass must (a) FIRE on a crafted violating program — a dropped
donation, a large replicated param on the 8-device mesh, over-budget
collective bytes, a captured weight-sized constant, an escaped 16-bit
softmax — and (b) stay QUIET on the clean in-tree model families'
O1 train steps (``tools/graph_lint.py``, the continuously-enforced
version of the "statically checkable guarantees" story).  Parser pins
on crafted HLO/StableHLO spellings keep the text walks trustworthy, and
the compat surfaces (``amp.audit``, ``__graft_entry__._collective_audit``)
are pinned by their own pre-existing suites.
"""

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import analysis  # noqa: E402
from apex_tpu.analysis import Finding, Report  # noqa: E402

from apex_tpu.utils.jax_compat import shard_map as _shard_map


def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def test_dropped_donation_fires_with_wasted_bytes():
    """A donated arg with no same-shaped output cannot alias: the pass
    must report it as an error carrying the wasted buffer size."""
    def g(x, y):
        return (x[:2] * 2.0).sum() + y.sum()

    x = jnp.ones((128, 128), jnp.float32)
    y = jnp.ones((8,), jnp.float32)
    rep = analysis.analyze(g, x, y, donate_argnums=(0, 1),
                           passes=("donation",))
    assert not rep.ok
    errs = [f for f in rep.by_pass("donation") if f.severity == "error"]
    assert {f.bytes for f in errs} == {128 * 128 * 4, 8 * 4}
    assert all("dropped" in f.message for f in errs)


def test_honored_donation_is_quiet():
    def f(x):
        return x * 2.0

    rep = analysis.analyze(f, jnp.ones((64, 64)), donate_argnums=(0,),
                           passes=("donation",))
    assert rep.ok and not rep.findings


def test_no_donation_declared_is_quiet():
    rep = analysis.analyze(lambda x: x + 1.0, jnp.ones((4,)),
                           passes=("donation",))
    assert rep.ok and not rep.findings


def test_pruned_unused_arg_does_not_shift_donation_numbering():
    """jit prunes unused args (keep_unused=False), renumbering the
    compiled parameters — an honored donation AFTER a pruned arg must
    not be misreported as dropped; the pruned donated arg itself is a
    vacuous-donation warning, not an error."""
    def f(unused, y):
        return y * 2.0

    rep = analysis.analyze(f, jnp.ones((16, 16)), jnp.ones((8, 8)),
                           donate_argnums=(1,), passes=("donation",))
    assert rep.ok and not rep.findings
    rep2 = analysis.analyze(f, jnp.ones((16, 16)), jnp.ones((8, 8)),
                            donate_argnums=(0, 1), passes=("donation",))
    assert rep2.ok   # dead-arg donation warns, never gates
    warns = rep2.by_pass("donation")
    assert len(warns) == 1 and warns[0].severity == "warning"
    assert "pruned" in warns[0].message


def test_async_all_gather_spelling_is_seen():
    """XLA's latency-hiding scheduler emits big gathers as tuple-shaped
    ``all-gather-start`` — the replication check must see those too."""
    hlo = (
        "HloModule jit_f, is_scheduled=true, num_partitions=8\n"
        "ENTRY %main (p0: f32[128,64]) -> f32[1024,64] {\n"
        "  %p0 = f32[128,64]{1,0} parameter(0), "
        "sharding={devices=[8,1]<=[8]}\n"
        "  %ag-start = (f32[128,64]{1,0}, f32[1024,64]{1,0}) "
        "all-gather-start(f32[128,64]{1,0} %p0), dimensions={0}\n"
        "  ROOT %ag-done = f32[1024,64]{1,0} all-gather-done("
        "(f32[128,64]{1,0}, f32[1024,64]{1,0}) %ag-start)\n"
        "}\n")
    ctx = analysis.PassContext(stablehlo_text="", hlo_text=hlo)
    out = analysis.PASSES["sharding"](ctx, min_bytes=1024)
    gathers = [f for f in out if f.op == "all-gather"]
    assert len(gathers) == 1 and gathers[0].bytes == 1024 * 64 * 4


def test_sharded_donation_without_compile_is_not_misreported():
    """A sharded donated arg lowers as ``jax.buffer_donor`` (aliasing
    decided at compile time) with a sharding attr whose quoted value
    embeds braces — the lowering-only fallback must report it as
    inconclusive (info), never as a dropped-donation error; compiling
    resolves it to an honored alias."""
    mesh = mesh8()
    w = jax.device_put(jnp.ones((256, 64), jnp.float32),
                       NamedSharding(mesh, P("data", None)))
    step = jax.jit(lambda w: w * 2.0, donate_argnums=(0,))
    rep = analysis.analyze(step, w, passes=("donation",), compile=False)
    assert rep.ok, rep.format()
    infos = rep.by_pass("donation")
    assert len(infos) == 1 and infos[0].severity == "info"
    assert "buffer_donor" in infos[0].message
    rep2 = analysis.analyze(step, w, passes=("donation",), compile=True)
    assert rep2.ok and not rep2.findings


def test_sharded_dropped_donation_errors_when_compiled():
    """When the executable honored ZERO donations its header has no
    alias table at all — that absence is authoritative evidence of a
    drop, not a reason to fall back to inconclusive lowering markers."""
    mesh = mesh8()
    w = jax.device_put(jnp.ones((256, 64), jnp.float32),
                       NamedSharding(mesh, P("data", None)))
    step = jax.jit(lambda w: (w[:2] * 2.0).sum(), donate_argnums=(0,))
    rep = analysis.analyze(step, w, passes=("donation",), compile=True)
    assert not rep.ok
    assert rep.errors[0].bytes == 256 * 64 * 4
    assert "compiled executable" in rep.errors[0].message


def test_ambiguous_arg_numbering_degrades_to_info():
    """If the kept-arg inference (a private jax attribute) disagrees
    with the lowered signature's arg count, the pass must refuse to
    guess instead of emitting false dropped-donation errors."""
    from apex_tpu.analysis.core import ArgInfo
    args = tuple(ArgInfo(i, f"[{i}]", (4,), "float32", 16,
                         donated=(i == 1), kept=True)
                 for i in range(3))   # claims 3 kept ...
    stablehlo = ("func.func public @main(%arg0: tensor<4xf32>, "
                 "%arg1: tensor<4xf32>) -> (tensor<4xf32>) {")  # ... sig has 2
    ctx = analysis.PassContext(stablehlo_text=stablehlo, args=args)
    out = analysis.PASSES["donation"](ctx)
    assert len(out) == 1 and out[0].severity == "info"
    assert "ambiguous" in out[0].message


def test_hlo_alias_table_parser():
    # the compiled executable's header is the ground truth the pass reads
    hlo = ("HloModule jit_f, input_output_alias={ {0}: (0, {}, "
           "may-alias), {2}: (3, {}, must-alias) }, "
           "entry_computation_layout={...}")
    from apex_tpu.analysis.donation import aliased_parameters
    assert aliased_parameters(hlo) == {0, 3}
    assert aliased_parameters("HloModule jit_f") == set()


# ---------------------------------------------------------------------------
# sharding
# ---------------------------------------------------------------------------

def test_replicated_large_param_fires():
    mesh = mesh8()
    w = jax.device_put(jnp.ones((256, 64), jnp.float32),
                       NamedSharding(mesh, P()))
    xb = jax.device_put(jnp.ones((16, 256), jnp.float32),
                        NamedSharding(mesh, P("data")))

    def loss(w, xb):
        return jnp.sum(jnp.square(xb @ w))

    rep = analysis.analyze(loss, w, xb, passes=("sharding",),
                           options={"sharding": {"min_bytes": 1024}})
    hits = [f for f in rep.by_pass("sharding")
            if "replicated" in f.message]
    assert hits and hits[0].bytes == 256 * 64 * 4
    assert hits[0].severity == "warning"   # no intent declared
    assert rep.ok


def test_replicated_against_intent_is_error():
    mesh = mesh8()
    w = jax.device_put(jnp.ones((256, 64), jnp.float32),
                       NamedSharding(mesh, P()))
    xb = jax.device_put(jnp.ones((16, 256), jnp.float32),
                        NamedSharding(mesh, P("data")))

    def loss(w, xb):
        return jnp.sum(jnp.square(xb @ w))

    # the intent mapping an FSDP/TP layout would declare for w
    rep = analysis.analyze(
        loss, w, xb, passes=("sharding",),
        options={"sharding": {"min_bytes": 1024,
                              "intended": {"[0]": P("data", None)}}})
    assert not rep.ok
    assert any("intent declares" in f.message for f in rep.errors)


def test_sharded_params_are_quiet():
    mesh = mesh8()
    w = jax.device_put(jnp.ones((256, 64), jnp.float32),
                       NamedSharding(mesh, P("data", None)))

    def loss(w):
        return jnp.sum(jnp.square(w))   # elementwise: no gather needed

    rep = analysis.analyze(loss, w, passes=("sharding",),
                           options={"sharding": {"min_bytes": 1024}})
    assert rep.ok and not rep.by_pass("sharding")


def test_single_device_program_is_quiet():
    rep = analysis.analyze(lambda x: (x @ x.T).sum(),
                           jnp.ones((512, 512)), passes=("sharding",),
                           options={"sharding": {"min_bytes": 1024}})
    assert rep.ok and not rep.findings


def test_intended_specs_helper_builds_the_intent_mapping():
    from apex_tpu.parallel import intended_specs
    mesh = mesh8()
    tree = {"w1": NamedSharding(mesh, P("data", None)),
            "w2": P(None, "data"),
            "bias": P()}
    out = intended_specs(tree)
    assert set(out) == {"['w1']", "['w2']"}   # replicated intent dropped
    assert out["['w1']"] == P("data", None)


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def test_over_budget_collective_bytes_fires():
    mesh = mesh8()

    def step(x):
        return jax.lax.psum(x.sum(axis=0), "data")

    sm = jax.jit(_shard_map(step, mesh=mesh, in_specs=(P("data"),),
                            out_specs=P()))
    x = jnp.ones((8, 128), jnp.float32)
    rep = analysis.analyze(sm, x, passes=("collectives",),
                           options={"collectives":
                                    {"budget": {"total": 0}}})
    assert not rep.ok
    err = rep.errors[0]
    assert err.op == "total" and err.bytes and err.bytes > 0
    # the same program inside its budget passes, with the volume recorded
    rep2 = analysis.analyze(sm, x, passes=("collectives",),
                            options={"collectives":
                                     {"budget": {"total": 1 << 20}}})
    assert rep2.ok
    infos = rep2.by_pass("collectives")
    assert any(f.op == "all-reduce" and f.count == 1 for f in infos)


def test_per_kind_budget_and_async_tally():
    from apex_tpu.analysis import collective_table
    hlo = """
  %all-reduce.1 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %p), to_apply=%add
  %ag-start = (f32[4]{0}, f32[32]{0}) all-gather-start(f32[4]{0} %x), dimensions={0}
  %ag-done = f32[32]{0} all-gather-done((f32[4]{0}, f32[32]{0}) %ag-start)
"""
    table = collective_table(hlo)
    assert table["all-reduce"] == {"count": 1, "bytes": 8 * 16 * 4,
                                   "sync": 1, "async": 0,
                                   "channels": [], "replica_groups": [],
                                   "global_ids": 0}
    assert table["all-gather"] == {"count": 1, "bytes": 32 * 4,
                                   "sync": 0, "async": 1,
                                   "channels": [], "replica_groups": [],
                                   "global_ids": 0}
    ctx = analysis.PassContext(stablehlo_text="", hlo_text=hlo)
    out = analysis.PASSES["collectives"](
        ctx, budget={"all-reduce": 4, "all-gather": 1 << 20})
    errs = [f for f in out if f.severity == "error"]
    assert len(errs) == 1 and errs[0].op == "all-reduce"


# ---------------------------------------------------------------------------
# constant capture
# ---------------------------------------------------------------------------

def test_captured_weight_sized_constant_fires():
    big = jax.random.normal(jax.random.PRNGKey(0), (512, 640))

    def h(x):
        return x @ big   # closed over: baked into the jaxpr

    rep = analysis.analyze(h, jnp.ones((4, 512)),
                           passes=("constant-capture",), compile=False)
    assert not rep.ok
    err = rep.errors[0]
    assert err.bytes == 512 * 640 * 4 and err.dtype == "f32"


def test_splat_and_small_constants_are_quiet():
    zeros = jnp.zeros((512, 640))          # splat: scalar + broadcast
    small = jax.random.normal(jax.random.PRNGKey(1), (8, 8))

    def h(x):
        return (x @ zeros) * small.sum()

    rep = analysis.analyze(h, jnp.ones((4, 512)),
                           passes=("constant-capture",), compile=False)
    assert rep.ok and not rep.findings


def test_passed_as_argument_is_quiet():
    big = jax.random.normal(jax.random.PRNGKey(0), (512, 640))
    rep = analysis.analyze(lambda x, w: x @ w, jnp.ones((4, 512)), big,
                           passes=("constant-capture",), compile=False)
    assert rep.ok and not rep.findings


# ---------------------------------------------------------------------------
# policy (via the pass API; the legacy amp.audit surface has its own suite)
# ---------------------------------------------------------------------------

def test_policy_pass_flags_escaped_softmax():
    def escaped(w, x):
        h = jnp.matmul(x, w).astype(jnp.bfloat16)
        return jax.nn.softmax(h, axis=-1).astype(jnp.float32).sum()

    w = jnp.ones((8, 8), jnp.float32)
    x = jnp.ones((4, 8), jnp.float32)
    rep = analysis.analyze(escaped, w, x, passes=("policy",),
                           compile=False)
    assert not rep.ok
    assert any(f.op == "exponential" and f.dtype == "bf16"
               for f in rep.errors)


# ---------------------------------------------------------------------------
# report plumbing
# ---------------------------------------------------------------------------

def test_report_shapes_and_merge():
    f1 = Finding("donation", "error", "m1", bytes=4)
    f2 = Finding("policy", "info", "m2")
    rep = Report((f1,), ("donation",)).merged(
        Report((f2,), ("policy",)))
    assert not rep.ok and rep.passes == ("donation", "policy")
    d = rep.to_dict()
    assert d["counts"] == {"error": 1, "info": 1}
    assert d["findings"][0]["pass"] == "donation"
    assert "FAIL" in rep.format() and "m1" in rep.format()
    with pytest.raises(ValueError):
        Finding("x", "fatal", "bad severity")
    with pytest.raises(KeyError):
        analysis.run_passes(analysis.PassContext(""), passes=("nope",))


# ---------------------------------------------------------------------------
# the clean in-tree families (the CLI's continuously-enforced guarantee)
# ---------------------------------------------------------------------------

#: bert/gpt/resnet compiles cost 12-17s each on a 2-vCPU tier-1 box —
#: slow-marked so the tier-1 wall clock stays inside its timeout; the
#: mlp lane keeps the guarantee continuously enforced.
HEAVY_FAMILIES = ("resnet", "gpt", "bert")


def _marks_for(name):
    return (pytest.mark.slow,) if name in HEAVY_FAMILIES else ()


@pytest.mark.parametrize("family",
                         [pytest.param(f, id=f, marks=_marks_for(f))
                          for f in ["mlp", "resnet", "gpt", "bert"]])
def test_in_tree_family_train_step_lints_clean(family):
    import graph_lint
    report = graph_lint.lint_family(family)
    assert report.ok, report.format()
    # the guarantee is meaningful only if every pass actually ran
    assert set(graph_lint.ALL_PASSES) <= set(report.passes)


def test_cli_main_runs_selected_family(capsys):
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--lanes", "o1"]) == 0
    out = capsys.readouterr().out
    assert '"lane": "mlp_o1"' in out and '"ok": true' in out


# ---------------------------------------------------------------------------
# ISSUE 4: strict mode + every in-tree entry point lints clean
# ---------------------------------------------------------------------------

def test_cli_strict_mode_memory_budget_enforced(capsys):
    """Tier-1 strict-mode run over the smallest family: the memlint
    passes execute with the v5e 16 GiB device budget ARMED (bare
    ``--memory-budget``), so every tier-1 run proves the memory/cost/
    syncs passes fire on a real lane and the lane fits the chip."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp",
                            "--lanes", "o1,o2,decode",
                            "--memory-budget"]) == 0
    out = capsys.readouterr().out
    assert '"lane": "mlp_o1"' in out and '"lane": "mlp_o2"' in out
    assert '"lane": "decode_b1"' in out   # decode dispatch through main()
    for line in out.splitlines():
        rec = json.loads(line)
        assert {"memory", "cost", "syncs"} <= set(rec["passes"])
        assert rec["ok"], rec


def test_cli_serve_lane_dispatch_and_skip(capsys):
    """``--lanes serve`` dispatches the serve lane through main() —
    proven cheaply via the policy-pass skip path (no build, no
    compile; the serve lane linting CLEAN under the full pass matrix
    is the serve_step entry-point test below)."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--passes", "policy",
                            "--lanes", "o1,serve"]) == 0
    captured = capsys.readouterr()
    assert "serve_step" not in captured.out     # skipped, not ok:true
    assert "skipped: no requested pass applies" in captured.err
    with pytest.raises(SystemExit):             # typo'd lane refused
        graph_lint.main(["--lanes", "serv"])


def test_cli_memory_budget_violation_fails_exit_code(capsys):
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--lanes", "o1",
                            "--memory-budget", "1KiB"]) == 1
    out = capsys.readouterr().out
    assert '"hbm-budget"' in out


def test_parse_bytes_forms():
    import graph_lint
    assert graph_lint.parse_bytes("1048576") == 1 << 20
    assert graph_lint.parse_bytes("16GiB") == 16 << 30
    assert graph_lint.parse_bytes("512MiB") == 512 << 20
    assert graph_lint.parse_bytes("2GB") == 2 * 10**9
    with pytest.raises(ValueError):
        graph_lint.parse_bytes("lots")


def test_cli_emit_json_rejects_partial_modes(tmp_path):
    """--emit-json commits the full-matrix artifact; a restricted
    --passes or --no-compile run must be refused, never silently
    overridden into a partial document."""
    import graph_lint
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", str(tmp_path / "M_r99.json"),
                         "--no-compile"])
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", str(tmp_path / "M_r99.json"),
                         "--passes", "donation"])
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", str(tmp_path / "M_r99.json"),
                         "--families", "mlp"])
    with pytest.raises(SystemExit):
        graph_lint.main(["--emit-json", str(tmp_path / "M_r99.json"),
                         "--lanes", "o1"])
    assert not (tmp_path / "M_r99.json").exists()


def test_cli_emit_json_defaults_budget_armed(monkeypatch, tmp_path):
    """--emit-json without --memory-budget arms the v5e default — a
    regeneration must never quietly replace a budget-gated round with
    an unarmed one."""
    import graph_lint
    seen = {}

    def fake_emit(path, families, memory_budget=None, verbose=False):
        seen["budget"] = memory_budget
        return 0

    monkeypatch.setattr(graph_lint, "emit_memlint", fake_emit)
    assert graph_lint.main(
        ["--emit-json", str(tmp_path / "M_r99.json")]) == 0
    from apex_tpu.analysis.memory import V5E_HBM_BYTES
    assert seen["budget"] == V5E_HBM_BYTES


def test_cli_no_compile_rejects_armed_budget():
    """--memory-budget + --no-compile: the budget gate can't run
    without the compiled executable — refuse the combination rather
    than exit 0 having asserted nothing."""
    import graph_lint
    with pytest.raises(SystemExit):
        graph_lint.main(["--families", "mlp", "--lanes", "o1",
                         "--no-compile", "--memory-budget", "1KiB"])


def test_memory_pass_uncompiled_armed_budget_warns():
    """analyze(compile=False) with budget_bytes armed: the skip is a
    WARNING naming the unasserted gate, not a bare info."""
    from apex_tpu import analysis
    rep = analysis.analyze(lambda x: x * 2, jnp.ones((4,)),
                           compile=False, passes=("memory",),
                           options={"memory": {"budget_bytes": 1024}})
    skips = rep.by_pass("memory")
    assert len(skips) == 1 and skips[0].severity == "warning"
    assert "asserted NOTHING" in skips[0].message
    # without a budget the same skip stays informational
    rep2 = analysis.analyze(lambda x: x * 2, jnp.ones((4,)),
                            compile=False, passes=("memory",))
    assert rep2.by_pass("memory")[0].severity == "info"


def test_cli_zero_applicable_passes_fails(capsys):
    """``--passes policy --lanes o2``: policy only applies to O1
    forwards, so every selected lane would run ZERO passes — the
    lint-nothing-and-pass class the --lanes guard exists to stop must
    fail here too."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--passes", "policy",
                            "--lanes", "o2"]) == 1
    captured = capsys.readouterr()
    assert "ran zero passes" in captured.err


def test_cli_policy_only_with_default_lanes_still_passes(capsys):
    """``--passes policy`` without ``--lanes``: the default lane list
    includes decode lanes that can't host the policy pass — those are
    SKIPPED (never printed as ok), while the O1 lane runs policy and
    the invocation exits 0 (the pre-PR behavior)."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp",
                            "--passes", "policy"]) == 0
    captured = capsys.readouterr()
    assert '"lane": "mlp_o1"' in captured.out
    assert "decode_b1" not in captured.out      # no ok:true for a skip
    assert "skipped: no requested pass applies" in captured.err


def test_multichip_slice_table_refuses_missing_mesh(monkeypatch):
    """Fewer CPU devices than the virtual mesh needs (backend
    initialized before XLA_FLAGS could act): fail loudly rather than
    commit wrong per-device numbers under an n_devices: 8 header."""
    import graph_lint
    one = jax.devices("cpu")[:1]
    monkeypatch.setattr(graph_lint.jax, "devices",
                        lambda *a, **k: one)
    with pytest.raises(RuntimeError, match="need 8 CPU devices"):
        graph_lint.multichip_slice_table(8)


#: every in-tree lint entry point: the four families at both opt
#: levels plus the decode lanes — the parametrized "runs clean over
#: every example entry point" guarantee (the ResNet-50 ``entry()``
#: forward is the slow-marked flagship below).  The heavy-family lanes
#: carry the ``slow`` mark (tier-1 budget); mlp + decode stay tier-1.
def _entry_param(name, opt_level):
    return pytest.param(name, opt_level,
                        id=f"{name}_{opt_level}" if opt_level else name,
                        marks=_marks_for(name))


ENTRY_POINTS = ([_entry_param(f, o)
                 for f in ["mlp", "resnet", "gpt", "bert"]
                 for o in ["O1", "O2"]]
                + [_entry_param("decode_b1", None),
                   _entry_param("decode_b2", None),
                   _entry_param("serve_step", None),
                   # the disaggregated fleet's split steps: the prefill
                   # worker's chunk program stays tier-1 (a new program
                   # class); the replica-shaped decode lane duplicates
                   # serve_step's program class at another geometry and
                   # rides the slow lane (tier-1 budget)
                   _entry_param("serve_prefill", None),
                   pytest.param("serve_decode", None, id="serve_decode",
                                marks=(pytest.mark.slow,)),
                   # the speculative-decoding verifier: a NEW program
                   # class (b×(k+1) multi-token verify + on-device
                   # acceptance), so it rides tier-1 like serve_step
                   _entry_param("serve_verify", None)])


@pytest.mark.parametrize("name,opt_level", ENTRY_POINTS)
def test_every_entry_point_lints_clean(name, opt_level):
    import graph_lint
    if opt_level is None:
        if name in graph_lint.SERVE_PREFILL_LANES:
            lint = graph_lint.lint_serve_prefill
        elif name in graph_lint.SERVE_VERIFY_LANES:
            lint = graph_lint.lint_serve_verify
        elif name in graph_lint.SERVE_LANES:
            lint = graph_lint.lint_serve
        else:
            lint = graph_lint.lint_decode
        report = lint(
            name, memory_budget=graph_lint.memory_mod.V5E_HBM_BYTES)
    else:
        report = graph_lint.lint_family(
            name, opt_level=opt_level,
            memory_budget=graph_lint.memory_mod.V5E_HBM_BYTES)
    assert report.ok, report.format()
    assert any(f.op == "peak-hbm" for f in report.by_pass("memory"))


@pytest.mark.slow
def test_flagship_entry_forward_lints_clean():
    """``__graft_entry__.entry()`` — the ResNet-50 bf16 forward the
    driver compiles — through the full non-policy pass list."""
    sys.path.insert(0, str(REPO))
    import __graft_entry__ as graft
    fwd, args = graft.entry()
    rep = analysis.analyze(
        fwd, *args,
        passes=("donation", "collectives", "constant-capture",
                "memory", "cost", "syncs"),
        options={"memory": {"budget_bytes": 16 << 30},
                 "collectives": {"budget": {"total": 0}}})
    assert rep.ok, rep.format()


# ---------------------------------------------------------------------------
# ISSUE 9: the O4 (fp8) train lane and the int8-KV decode lane
# ---------------------------------------------------------------------------

def test_cli_o4_lane_full_matrix_clean(capsys):
    """The fp8 regime's train step — delayed-scaling state donated in
    AmpState, e4m3/e5m2 quantizes in the program — lints clean under
    the FULL pass matrix with the memory budget armed: donation covers
    the fp8 leaves, the syncs pass proves the instrumented-metrics
    design added no host sync, and the precision pass carries the
    three fp8 rules."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--lanes", "o4",
                            "--memory-budget"]) == 0
    out = capsys.readouterr().out
    rec = json.loads([line for line in out.splitlines()
                      if '"lane": "mlp_o4"' in line][0])
    assert rec["ok"]
    assert {"donation", "memory", "syncs", "precision"} \
        <= set(rec["passes"])


def test_cli_decode_kv8_lane_dispatch(capsys):
    """``--lanes decode`` dispatches the int8-KV lane alongside the
    dense ones (cheap lowering-only precision run)."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp", "--lanes", "decode",
                            "--passes", "precision"]) == 0
    out = capsys.readouterr().out
    assert '"lane": "decode_b1_kv8"' in out
    rec = json.loads([line for line in out.splitlines()
                      if '"lane": "decode_b1_kv8"' in line][0])
    assert rec["ok"]


def test_decode_lanes_table_carries_kv8():
    import graph_lint
    assert graph_lint.DECODE_LANES["decode_b1_kv8"][3] == "int8"
    assert "o4" in graph_lint.TRAIN_LANES


# ---------------------------------------------------------------------------
# ISSUE 10: the export-compat pass rides the lint CLI too
# ---------------------------------------------------------------------------

def test_cli_export_compat_pass_clean(capsys):
    """``--passes export-compat`` over the train + serve lanes: the
    lanes the AOT export pipeline serializes lint serializable
    (lowering-only — the pass reads StableHLO text, so the CLI skips
    the per-lane compile exactly like the precision-only mode)."""
    import graph_lint
    assert graph_lint.main(["--families", "mlp",
                            "--passes", "export-compat",
                            "--lanes", "o1,serve"]) == 0
    out = capsys.readouterr().out
    assert '"lane": "mlp_o1"' in out and '"lane": "serve_step"' in out
    for line in out.splitlines():
        rec = json.loads(line)
        assert rec["ok"] and rec["passes"] == ["export-compat"]
