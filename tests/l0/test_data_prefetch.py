"""apex_tpu.data — the host→device prefetch pipeline (VERDICT r3 #4).

Correctness pins for the overlapped input pipeline: ordering and
completeness, pytree batches, the on-device transform, the lookahead
contract (the source IS consumed ahead — that's the overlap), sharding
placement on a multi-device mesh, and the reference-shaped
``DataPrefetcher.next()`` sentinel protocol
(``reference examples/imagenet/main_amp.py:256-290``).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.data import DataPrefetcher, prefetch_to_device


def _batches(n, start=0):
    for i in range(start, start + n):
        yield {"x": np.full((4, 8), i, np.float32),
               "y": np.full((4,), i, np.int32)}


def test_order_and_completeness():
    out = list(prefetch_to_device(_batches(7), lookahead=2))
    assert len(out) == 7
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["x"]),
                                      np.full((4, 8), i, np.float32))
        np.testing.assert_array_equal(np.asarray(b["y"]),
                                      np.full((4,), i, np.int32))


def test_fewer_batches_than_lookahead():
    assert len(list(prefetch_to_device(_batches(1), lookahead=4))) == 1
    assert list(prefetch_to_device(_batches(0), lookahead=2)) == []


def test_transform_runs_on_device_arrays():
    def normalize(b):
        return {"x": b["x"] / 2.0, "y": b["y"]}

    out = list(prefetch_to_device(_batches(3), lookahead=2,
                                  transform=normalize))
    np.testing.assert_allclose(np.asarray(out[2]["x"]),
                               np.full((4, 8), 1.0, np.float32))


def test_uint8_normalize_pattern():
    # the intended usage: uint8 over the wire, fp32 on device
    def src():
        yield np.arange(16, dtype=np.uint8).reshape(4, 4), \
            np.zeros((4,), np.int32)

    def normalize(b):
        x, y = b
        return x.astype(jnp.float32) / 255.0, y

    (x, y), = list(prefetch_to_device(src(), transform=normalize))
    assert x.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(x)[0, 1], 1 / 255.0)


def test_lookahead_consumes_source_ahead():
    """The whole point: while the consumer holds batch 0, the source
    must already have produced ``lookahead`` more — that production is
    what overlaps the step's compute."""
    produced = []

    def recording(n):
        for i in range(n):
            produced.append(i)
            yield np.full((2,), i, np.float32)

    gen = prefetch_to_device(recording(6), lookahead=3)
    first = next(gen)
    np.testing.assert_array_equal(np.asarray(first), [0.0, 0.0])
    # 0..2 were produced to fill the queue, and pulling one batch
    # produced one more
    assert produced == [0, 1, 2, 3]


def test_lookahead_must_be_positive():
    with pytest.raises(ValueError, match="lookahead"):
        next(prefetch_to_device(_batches(2), lookahead=0))


def test_sharding_places_leaves_on_mesh():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs the multi-device virtual mesh")
    mesh = Mesh(np.array(devs[:4]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    out = list(prefetch_to_device(_batches(2), lookahead=2,
                                  sharding=sharding))
    for b in out:
        assert b["x"].sharding.is_equivalent_to(sharding, b["x"].ndim)


def test_data_prefetcher_sentinel_protocol():
    pf = DataPrefetcher(_batches(2))
    seen = 0
    batch = pf.next()
    while batch is not None:
        seen += 1
        batch = pf.next()
    assert seen == 2
    assert pf.next() is None  # stays exhausted


def test_data_prefetcher_is_iterable():
    assert len(list(DataPrefetcher(_batches(3)))) == 3
