"""tools/serve_scenarios.py — the scenario-matrix harness.

The committed SCENARIO_r*.json's schema validity and gate verdict are
pinned by ``tests/l0/test_gate_hygiene.py`` (the artifact is gate
memory).  Here: the cell driver emits schema-shaped records whose
gates derive from their own numbers, the committed matrix covers the
contexts the roadmap names, and the 32k-context cell runs (slow
lane)."""

import copy
import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

import serve_scenarios  # noqa: E402

from apex_tpu import amp  # noqa: E402
from apex_tpu.analysis.scenario import validate_scenario  # noqa: E402
from apex_tpu.models import GPTModel, gpt_tiny  # noqa: E402
from apex_tpu.serve import truncated_draft  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(
        opt_level="O2", verbosity=0).model_params_from(params)
    ids = np.asarray((np.arange(8 * 32).reshape(8, 32) * 7) % 16,
                     np.int32)
    return cfg, params, ids


def test_run_cell_records_are_schema_shaped(tiny):
    """One spec-off/spec-on cell pair at a tiny shape: both records
    carry the schema's numbers and a gate DERIVED from them, and a
    document assembled from them (replicated to the matrix minimum)
    validates clean."""
    cfg, params, ids = tiny
    draft = truncated_draft(params, cfg, 1)
    knobs = dict(context=32, new_tokens=4, num_slots=2,
                 arrival="steady", sampling="greedy", kv8=False,
                 churn=False, spec_k=2)
    reqs = serve_scenarios._requests(ids, 32, 4, 4, "greedy")
    off = serve_scenarios.run_cell(cfg, params, draft, list(reqs),
                                   spec=False, **knobs)
    on = serve_scenarios.run_cell(cfg, params, draft, list(reqs),
                                  spec=True, **knobs)
    assert off["retraces"] == 1 and on["retraces"] == 1
    assert on["config"]["spec"] and not off["config"]["spec"]
    assert "acceptance_rate" in on
    cells, ab = {}, []
    for i in range(5):
        o, s = copy.deepcopy(off), copy.deepcopy(on)
        cells[f"c{i}"], cells[f"c{i}_spec"] = o, s
        ab.append({"on": f"c{i}_spec", "off": f"c{i}",
                   "tokens_per_step_on": s["tokens_per_step"],
                   "tokens_per_step_off": o["tokens_per_step"],
                   "spec_wins": s["tokens_per_step"]
                   > o["tokens_per_step"],
                   "gated": i == 0})
    cells_ok = all(c["gate"]["ok"] for c in cells.values())
    ab_ok = all(r["spec_wins"] for r in ab if r["gated"])
    doc = {"round": 1, "platform": "cpu", "model": "gpt_tiny",
           "gate_k": serve_scenarios.GATE_K, "cells": cells, "ab": ab,
           "gate": {"cells_ok": cells_ok, "ab_ok": ab_ok,
                    "ok": cells_ok and ab_ok}}
    assert validate_scenario(doc) == []


def test_cell_matrix_covers_contexts_and_axes():
    """The committed matrix names the roadmap's axes: contexts
    128-2048, burst + steady arrivals, a mixed-sampling cell, a churn
    cell, a kv8 cell — and ``--full`` adds the 32k slow cell."""
    base = serve_scenarios.cell_matrix(full=False)
    contexts = {k["context"] for _, k, _ in base}
    assert {128, 512, 2048} <= contexts
    assert 32768 not in contexts
    assert any(k["arrival"] == "burst" for _, k, _ in base)
    assert any(k["sampling"] == "mixed" for _, k, _ in base)
    assert any(k["churn"] for _, k, _ in base)
    assert any(k["kv8"] for _, k, _ in base)
    gated = [g for _, _, g in base if g]
    assert len(gated) >= 3       # the steady greedy pairs are gated
    full = serve_scenarios.cell_matrix(full=True)
    assert any(k["context"] == 32768 for _, k, _ in full)


def test_chat_cell_reuses_history_and_churn_pins_sharing_off(tiny):
    """The multi-turn chat cell's second turn resubmits each request's
    own prompt + streamed reply, so the content index must HIT (prompt
    blocks registered at arm, reply blocks at decode boundaries) and
    the cell records its prefix counters; a churn cell pins sharing
    OFF — the repeated training-stream prompts would dedupe and absorb
    the engineered block shortage — so its record carries NO prefix
    block."""
    cfg, params, ids = tiny
    draft = truncated_draft(params, cfg, 1)
    knobs = dict(context=32, new_tokens=4, num_slots=2,
                 arrival="steady", sampling="greedy", kv8=False,
                 spec=False, spec_k=2)
    reqs = serve_scenarios._requests(ids, 16, 4, 2, "greedy")
    chat = serve_scenarios.run_cell(cfg, params, draft, list(reqs),
                                    churn=False, chat=True, **knobs)
    assert chat["prefix"]["probes"] >= 4     # both turns probe
    assert chat["prefix"]["hits"] >= 2       # every turn-2 admission
    assert chat["prefix"]["hit_rate"] > 0
    assert chat["gate"]["retrace_ok"], chat

    reqs = serve_scenarios._requests(ids, 16, 4, 2, "greedy")
    churn = serve_scenarios.run_cell(cfg, params, draft, list(reqs),
                                     churn=True, chat=False, **knobs)
    assert "prefix" not in churn


def test_committed_artifact_round_trips_the_tool_gate():
    """The committed r01 carries the tool's own derived verdict: the
    gated A/B rows all won (tokens/step strictly greater with spec
    on) — the speculative latency win as committed gate memory."""
    arts = sorted(REPO.glob("SCENARIO_r*.json"))
    assert arts, "SCENARIO_r01.json must be committed"
    doc = json.loads(arts[-1].read_text())
    gated = [r for r in doc["ab"] if r["gated"]]
    assert gated and all(r["spec_wins"] for r in gated)
    specs = [c for c in doc["cells"].values() if c["config"]["spec"]]
    assert specs and all(c["acceptance_rate"] > 0 for c in specs)


@pytest.mark.slow
def test_32k_cell_runs_and_gates(tiny):
    """The 32k-context cell (slow lane): a whole-pool-reach page
    table, 512 prefill chunks, and the same tail/retrace gate as
    every other cell."""
    cfg, params, ids = tiny
    draft = truncated_draft(params, cfg, 1)
    name, knobs, _g = next(c for c in serve_scenarios.cell_matrix(True)
                           if c[1]["context"] == 32768)
    knobs = dict(knobs)
    num_slots = knobs.pop("num_slots")
    n_requests = knobs.pop("n_requests")
    block_size = knobs.pop("block_size")
    reqs = serve_scenarios._requests(ids, knobs["context"],
                                     knobs["new_tokens"], n_requests,
                                     knobs["sampling"])
    rec = serve_scenarios.run_cell(cfg, params, draft, reqs,
                                   num_slots=num_slots,
                                   block_size=block_size,
                                   spec=False, spec_k=2, **knobs)
    assert rec["config"]["context"] == 32768
    assert rec["gate"]["retrace_ok"], rec
    # the measuring window opens AFTER the first (compile) step, so
    # it sees new_tokens minus the prefill sample and that first step
    assert rec["decode_tokens"] >= 1 and rec["decode_steps"] >= 1
    assert rec["tokens_per_step"] >= 1.0
