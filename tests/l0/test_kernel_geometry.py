"""Block-geometry selector units + numerics parity at ragged sizes.

The round-6 retune (ISSUE 2) changed HOW the streaming kernels move
memory — bigger selected row blocks, multi-chunk grid steps, masked
ragged tails — while the element math must stay exactly what it was
(the L1 conformance contract).  These tests pin that at the shapes the
geometry machinery makes interesting: rows not divisible by the chosen
block, the ``ADAM_PAD`` boundary, single-tile tensors, and chunk counts
that leave an empty/ragged tail block.  All run in interpret mode (the
CPU tier); the same grids compile under Mosaic on chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas import geometry
from apex_tpu.ops.pallas.adam_kernel import (
    ADAM_PAD,
    adam_geometry,
    adam_tree_geometry,
    packed_adam,
    packed_adam_tree,
)
from apex_tpu.ops.pallas.lamb_kernels import (
    packed_lamb_stage1,
    packed_lamb_stage2,
    stage1_geometry,
)


# ---------------------------------------------------------------------------
# Selector units


def test_select_block_rows_budget_bound():
    # adam-like row cost: 1024 lanes * 30 B/elem-row stream total
    br = geometry.select_block_rows(1 << 16, row_bytes=30 * 1024)
    assert br == 128   # 2*128*30720 = 7.5 MiB <= 8 MiB; 256 would blow it
    # a tighter budget steps down the ladder, never below the tile floor
    assert geometry.select_block_rows(1 << 16, row_bytes=30 * 1024,
                                      budget=1 << 20) == 16
    assert geometry.select_block_rows(1 << 16, row_bytes=1 << 30) == 8


def test_select_block_rows_clamps_to_data():
    # 24 rows: the block covers the data (rounded to the tile multiple),
    # not the budget's 128 — no giant masked block for tiny inputs
    assert geometry.select_block_rows(24, row_bytes=30 * 1024) == 16
    assert geometry.select_block_rows(4, row_bytes=4096,
                                      multiple_of=16) == 16


def test_select_chunks_per_block_caps():
    # VMEM-bound, unroll-capped, and never more than the chunks
    assert geometry.select_chunks_per_block(1000, 8, 3584) == 8
    assert geometry.select_chunks_per_block(3, 8, 3584) == 3
    assert geometry.select_chunks_per_block(1000, 8, 3584,
                                            max_unroll=4) == 4
    assert geometry.select_chunks_per_block(1000, 512, 3584,
                                            budget=1 << 20) == 1


def test_vmem_budget_env_override(monkeypatch):
    monkeypatch.setenv("APEX_TPU_VMEM_BUDGET_MB", "2")
    assert geometry.vmem_budget() == 2 * 1024 * 1024
    monkeypatch.setenv("APEX_TPU_VMEM_BUDGET_MB", "not-a-number")
    assert geometry.vmem_budget() == geometry.DEFAULT_VMEM_BUDGET


def test_adam_geometry_ragged_grid():
    # 3*ADAM_PAD = 24 rows of 1024 lanes; selected block 16 -> ceil grid
    g = adam_geometry(3 * ADAM_PAD, with_copy=True)
    assert (g.block_rows, g.grid) == (16, 2)
    # override (the autotune axis) is honored verbatim
    g = adam_geometry(3 * ADAM_PAD, with_copy=True, block_rows=8)
    assert (g.block_rows, g.grid) == (8, 3)


# ---------------------------------------------------------------------------
# Numerics parity at ragged/odd sizes (interpret mode)


def _adam_ref(p, m, v, g, *, step_size, beta1, beta2, eps, scale,
              weight_decay, eps_mode):
    g32 = g / scale + weight_decay * p
    m2 = beta1 * m + (1.0 - beta1) * g32
    v2 = beta2 * v + (1.0 - beta2) * g32 * g32
    denom = jnp.sqrt(v2 + eps) if eps_mode == 1 else jnp.sqrt(v2) + eps
    return p - step_size * m2 / denom, m2, v2


@pytest.mark.parametrize("n_pads", [1, 3, 17])
def test_packed_adam_ragged_rows_match_reference(n_pads):
    """n_pads=3/17 leave rows not divisible by the selected block (the
    masked-tail path); n_pads=1 is the single-block floor.  Geometry
    must not change a single element vs the jnp recurrence."""
    n = ADAM_PAD * n_pads
    rng = np.random.RandomState(n_pads)
    p, m, v, g = (jnp.asarray(rng.rand(n).astype(np.float32)) + 0.1
                  for _ in range(4))
    kw = dict(step_size=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, scale=2.0,
              weight_decay=0.01, eps_mode=1)
    got = packed_adam(p, m, v, g, p_copy_dtype=jnp.bfloat16, **kw)
    ref = jax.jit(lambda *a: _adam_ref(*a, **kw))(p, m, v, g)
    for r, o in zip(ref, got[:3]):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=1e-6, atol=1e-7)
    assert got[3].dtype == jnp.bfloat16


def test_packed_adam_block_override_is_pure_geometry():
    """Every swept block size produces identical bits — the autotune
    knob can never change numerics."""
    n = ADAM_PAD * 5   # 40 rows: ragged under 16/32, exact under 8
    rng = np.random.RandomState(0)
    p, m, v, g = (jnp.asarray(rng.randn(n).astype(np.float32))
                  for _ in range(4))
    kw = dict(step_size=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, scale=1.0,
              weight_decay=0.0, eps_mode=0)
    base = packed_adam(p, m, v, g, block_rows=8, **kw)
    for br in (16, 32, 64):
        got = packed_adam(p, m, v, g, block_rows=br, **kw)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_adam_donate_matches_undonated():
    n = ADAM_PAD * 2
    rng = np.random.RandomState(1)
    p, m, v, g = (jnp.asarray(rng.randn(n).astype(np.float32))
                  for _ in range(4))
    kw = dict(step_size=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, scale=1.0,
              weight_decay=0.01, eps_mode=1)
    plain = packed_adam(p, m, v, g, **kw)
    aliased = packed_adam(p, m, v, g, donate=True, **kw)
    for a, b in zip(plain, aliased):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("n_chunks", [1, 3, 8, 13])
def test_packed_adam_tree_ragged_chunks_match_reference(n_chunks):
    """The whole-tree kernel across chunk counts that leave an empty
    tail (8 % K == 0), a ragged tail (3, 13), and a single-tile buffer
    (1) — against the standalone jnp recurrence with per-chunk step
    sizes riding the (padded) SMEM table.  Tolerance is one ulp: the
    standalone reference and the kernel sit in different jit graphs, so
    XLA's FMA contraction may differ — the BIT-identity contract is the
    driver-level test (test_fused_adam.py::
    test_packed_tree_update_bitwise_matches_per_leaf), where both paths
    share the surrounding graph."""
    chunk = 1024
    n = chunk * n_chunks
    rng = np.random.RandomState(n_chunks)
    p, m, v, g = (jnp.asarray(rng.randn(n).astype(np.float32))
                  for _ in range(4))
    steps = jnp.asarray(rng.rand(n_chunks).astype(np.float32)) * 1e-2
    kw = dict(beta1=0.9, beta2=0.999, eps=1e-8, scale=128.0,
              weight_decay=0.01, eps_mode=0, chunk_size=chunk)
    got = packed_adam_tree(p, m, v, g, steps, **kw)

    @jax.jit
    def ref(p, m, v, g, steps):
        b1, b2 = jnp.float32(0.9), jnp.float32(0.999)
        om1 = jnp.float32(1.0 - 0.9)
        om2 = jnp.float32(1.0 - 0.999)
        g2 = g / jnp.float32(128.0) + jnp.float32(0.01) * p
        m2 = b1 * m + om1 * g2
        v2 = b2 * v + om2 * g2 * g2
        denom = jnp.sqrt(v2) + jnp.float32(1e-8)
        step_el = jnp.repeat(steps, chunk)
        return p - step_el * m2 / denom, m2, v2

    for r, o in zip(ref(p, m, v, g, steps), got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=2e-7, atol=1e-9)
    # the multi-chunk unroll actually engaged where it can
    geom = adam_tree_geometry(n, chunk)
    assert geom.chunks_per_block == min(n_chunks, 8)


@pytest.mark.parametrize("n_chunks", [1, 5, 16])
def test_lamb_stage1_fused_norms_match_separate_pass(n_chunks):
    """with_norms must return exactly the per-chunk partial sums the
    separate packed_sumsq_per_chunk pass produced (same block sums, one
    read earlier) AND identical u/m/v to the norm-less kernel."""
    from apex_tpu.ops.pallas.multi_tensor_kernels import (
        packed_sumsq_per_chunk)

    chunk = 1024
    n = chunk * n_chunks
    rng = np.random.RandomState(n_chunks + 7)
    g, p, m, v = (jnp.asarray(rng.randn(n).astype(np.float32))
                  for _ in range(4))
    decay = jnp.asarray(rng.rand(n_chunks).astype(np.float32)) * 0.1
    kw = dict(beta1=0.9, beta2=0.999, eps=1e-6, inv_scale=0.5,
              bc1=0.9, bc2=0.99, chunk_size=chunk)
    u0, m0, v0 = packed_lamb_stage1(g, p, m, v, decay, **kw)
    u1, m1, v1, psq, usq = packed_lamb_stage1(g, p, m, v, decay,
                                              with_norms=True, **kw)
    for a, b in zip((u0, m0, v0), (u1, m1, v1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert psq.shape == usq.shape == (n_chunks,)
    np.testing.assert_allclose(
        np.asarray(psq), np.asarray(packed_sumsq_per_chunk(p, chunk)),
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(usq), np.asarray(packed_sumsq_per_chunk(u1, chunk)),
        rtol=1e-6)


def test_lamb_stage2_ragged_chunks_match_reference():
    chunk = 1024
    for n_chunks in (1, 3, 11):
        n = chunk * n_chunks
        rng = np.random.RandomState(n_chunks)
        p = jnp.asarray(rng.randn(n).astype(np.float32))
        u = jnp.asarray(rng.randn(n).astype(np.float32))
        ratio = jnp.asarray(rng.rand(n_chunks).astype(np.float32)) * 1e-2
        new_p, copy = packed_lamb_stage2(p, u, ratio, chunk_size=chunk,
                                         p_copy_dtype=jnp.bfloat16)
        ref = p - jnp.repeat(ratio, chunk) * u
        np.testing.assert_allclose(np.asarray(new_p), np.asarray(ref),
                                   rtol=1e-6, atol=1e-7)
        assert copy.dtype == jnp.bfloat16


def test_stage1_geometry_tables_padded_to_grid():
    # 13 chunks at K=8: grid 2, table slots 16 — the padded tail is how
    # the masked last block stays inside the SMEM tables
    geom = stage1_geometry(13 * 1024, 1024)
    assert geom.chunks_per_block == 8 and geom.grid == 2
    assert geom.grid * geom.chunks_per_block == 16


@pytest.mark.parametrize("rows", [1, 7, 16, 100, 129])
def test_layernorm_forward_ragged_rows_match_jnp(rows):
    """Forward at row counts straddling the selected block (including
    a single row and block+1): selected geometry + masked tail must
    reproduce the jnp reference statistics exactly as before."""
    from apex_tpu.ops.pallas.layer_norm_kernels import _forward

    n2 = 256
    rng = np.random.RandomState(rows)
    x = jnp.asarray(rng.randn(rows, n2).astype(np.float32))
    w = jnp.asarray(rng.rand(n2).astype(np.float32)) + 0.5
    b = jnp.asarray(rng.randn(n2).astype(np.float32))
    y, mean, inv = _forward(x, w, b, 1e-5, True)
    assert y.shape == (rows, n2) and mean.shape == (rows, 1)

    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=1, keepdims=True)
    ref = (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # block override is pure geometry here too
    y2, _, _ = _forward(x, w, b, 1e-5, True, block_rows=16)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))
