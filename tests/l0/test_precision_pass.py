"""Precision-flow pass coverage (:mod:`apex_tpu.analysis.precision`).

Each finding class must (a) FIRE on a seeded violating program — a
deliberately bf16-accumulating long reduce, an f16-accumulating dot, a
dropped master-weight cast, a mis-ordered unscale — with the documented
finding id, and (b) stay QUIET on the correct spellings and on the real
model families' O1/O2 train lanes (the continuously-enforced half of
the paper's "numerically safe by policy" contract; ISSUE 5).  The
shared dtype-dataflow walker (:mod:`apex_tpu.analysis.dflow`) and the
PRECLINT artifact schema (:mod:`apex_tpu.analysis.preclint`) are pinned
here too.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import amp, analysis  # noqa: E402
from apex_tpu.analysis import dflow  # noqa: E402
from apex_tpu.analysis.precision import precision_report  # noqa: E402
from apex_tpu.analysis.preclint import (validate_preclint,  # noqa: E402
                                        validate_preclint_file)


def _run(fn, *args, policy=None):
    return analysis.analyze(fn, *args, passes=("precision",),
                            compile=False, policy=policy)


def _ops(report):
    return [f.op for f in report.findings]


# ---------------------------------------------------------------------------
# seeded violations fire with the documented finding ids
# ---------------------------------------------------------------------------

def test_seeded_bf16_long_reduce_fires():
    """A raw lax.reduce accumulating 4096 elements in bf16 is exactly
    the Kalamkar §3 failure — jnp.sum would have upcast; the seeded
    program skips that on purpose."""
    def f(x):
        return jax.lax.reduce(x, jnp.bfloat16(0), jax.lax.add, (0,))

    rep = _run(f, jnp.ones((4096,), jnp.bfloat16))
    errs = [f_ for f_ in rep.findings if f_.op == "low-precision-reduce"]
    assert len(errs) == 1 and errs[0].severity == "error"
    assert errs[0].count == 4096 and errs[0].dtype == "bf16"


def test_short_bf16_reduce_is_quiet():
    """Sub-threshold 16-bit reduce-adds (the AD backward emits them for
    small batch axes) lose a few ulps at most — must not fire."""
    def f(x):
        return jax.lax.reduce(x, jnp.bfloat16(0), jax.lax.add, (0,))

    rep = _run(f, jnp.ones((8,), jnp.bfloat16))
    assert rep.ok and _ops(rep) == ["precision-summary"]


def test_f16_accumulating_dot_fires():
    def f(a, b):
        return a @ b

    rep = _run(f, jnp.ones((8, 8), jnp.float16), jnp.ones((8, 8), jnp.float16))
    errs = [f_ for f_ in rep.findings if f_.op == "half-accum-matmul"]
    assert len(errs) == 1 and errs[0].severity == "error"
    assert "f32 accumulation" in errs[0].message


def test_narrowed_accumulator_dot_fires():
    """f32 operands with preferred_element_type=bf16: the accumulator
    itself is narrowed below the operands."""
    def f(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.bfloat16)

    rep = _run(f, jnp.ones((8, 8)), jnp.ones((8, 8)))
    assert any(f_.op == "half-accum-matmul" and f_.severity == "error"
               for f_ in rep.findings)


def test_bf16_dot_default_precision_is_clean():
    """bf16 x bf16 -> bf16 is the CORRECT O1/O2 matmul spelling: the MXU
    accumulates it in f32 by hardware contract — flagging it would fail
    every correct program."""
    def f(a, b):
        return a @ b

    rep = _run(f, jnp.ones((8, 8), jnp.bfloat16),
               jnp.ones((8, 8), jnp.bfloat16))
    assert rep.ok and _ops(rep) == ["precision-summary"]


def test_double_round_warns():
    def f(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    rep = _run(f, jnp.ones((512,), jnp.float32))
    warns = [f_ for f_ in rep.findings if f_.op == "double-round"]
    assert len(warns) == 1 and warns[0].severity == "warning"
    assert warns[0].count == 512


def test_returned_bf16_value_is_not_double_round():
    """A 16-bit value that LEAVES the program is a real use the
    consumer table doesn't record — an O2 step returning bf16 params
    alongside an f32-derived metric must not warn."""
    def f(x):
        y = x.astype(jnp.bfloat16)
        return y, y.astype(jnp.float32) + 1.0

    rep = _run(f, jnp.ones((512,), jnp.float32))
    assert not any(f_.op == "double-round" for f_ in rep.findings)


def test_useful_downcast_is_not_double_round():
    """A bf16 value actually CONSUMED in bf16 (here by a dot) lost its
    mantissa for a reason — no finding."""
    def f(x, w):
        return x.astype(jnp.bfloat16) @ w

    rep = _run(f, jnp.ones((512, 16), jnp.float32),
               jnp.ones((16, 4), jnp.bfloat16))
    assert not any(f_.op == "double-round" for f_ in rep.findings)


def test_dropped_master_weight_cast_fires():
    """ISSUE seed: a bf16 'master_params' leaf under the O2 policy is
    the exact failure f32 masters exist to prevent."""
    props = amp.initialize(opt_level="O2", verbosity=0).properties
    state = {"master_params": {"w": jnp.ones((4,), jnp.bfloat16)},
             "opt_state": {"m": jnp.zeros((4,), jnp.float32)}}

    def f(state, x):
        return jnp.sum(state["master_params"]["w"].astype(jnp.float32) * x
                       + state["opt_state"]["m"])

    rep = _run(f, state, jnp.ones(4), policy=props)
    errs = [f_ for f_ in rep.findings if f_.op == "master-weight-dtype"]
    assert len(errs) == 1 and errs[0].severity == "error"
    assert errs[0].dtype == "bfloat16"


def test_bf16_moment_fires_and_f32_masters_clean():
    props = amp.initialize(opt_level="O2", verbosity=0).properties
    state = {"master_params": {"w": jnp.ones((4,), jnp.float32)},
             "opt_state": {"m": jnp.zeros((4,), jnp.bfloat16)}}

    def f(state, x):
        return jnp.sum(state["master_params"]["w"] * x
                       + state["opt_state"]["m"].astype(jnp.float32))

    rep = _run(f, state, jnp.ones(4), policy=props)
    errs = [f_ for f_ in rep.findings if f_.op == "master-weight-dtype"]
    assert len(errs) == 1 and "optimizer moment" in errs[0].message

    clean = {"master_params": {"w": jnp.ones((4,), jnp.float32)},
             "opt_state": {"m": jnp.zeros((4,), jnp.float32)}}
    rep = _run(f, clean, jnp.ones(4), policy=props)
    assert not any(f_.op == "master-weight-dtype" for f_ in rep.findings)


def test_o1_no_masters_policy_does_not_gate_arg_dtypes():
    """Under O1 (no master copies resolved) a 16-bit leaf that happens
    to be NAMED master_params is not a contract violation."""
    props = amp.initialize(opt_level="O1", verbosity=0).properties
    state = {"master_params": {"w": jnp.ones((4,), jnp.bfloat16)}}

    def f(state, x):
        return jnp.sum(state["master_params"]["w"].astype(jnp.float32) * x)

    rep = _run(f, state, jnp.ones(4), policy=props)
    assert not any(f_.op == "master-weight-dtype" for f_ in rep.findings)


def test_misordered_unscale_fires():
    """ISSUE seed: scaled gradients reaching the returned update — the
    unscale never dominated the use."""
    def bad(params, box, x):
        g = jax.grad(
            lambda p: jnp.sum((x @ p) ** 2) * box["loss_scale"])(params)
        return params - 0.1 * g           # update integrates SCALED grads

    rep = _run(bad, jnp.ones((4, 2)), {"loss_scale": jnp.float32(1024.0)},
               jnp.ones((3, 4)))
    errs = [f_ for f_ in rep.findings if f_.op == "unscaled-grad-use"]
    assert errs and all(f_.severity == "error" for f_ in errs)


def test_correct_scale_placement_is_clean_and_counted():
    def good(params, box, x):
        s = box["loss_scale"]
        g = jax.grad(lambda p: jnp.sum((x @ p) ** 2) * s)(params)
        return params - 0.1 * (g / s)     # unscale dominates the update

    lowered = analysis.lower_quiet(
        jax.jit(good), jnp.ones((4, 2)),
        {"loss_scale": jnp.float32(1024.0)}, jnp.ones((3, 4)))
    ctx = analysis.build_context(lowered, compile=False)
    findings, stats = precision_report(ctx)
    assert not any(f.severity == "error" for f in findings)
    assert stats["scale_args"] == 1
    assert stats["scale_applied"] >= 1 and stats["unscaled"] >= 1


def test_unapplied_loss_scale_warns():
    """Unscaling gradients that were never scaled is the placement
    contract violated in the other direction."""
    def f(params, box, x):
        g = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(params)
        return params - 0.1 * (g / box["loss_scale"])

    rep = _run(f, jnp.ones((4, 2)), {"loss_scale": jnp.float32(1024.0)},
               jnp.ones((3, 4)))
    assert any(f_.op == "loss-scale-unused" and f_.severity == "warning"
               for f_ in rep.findings)


def test_o3_demotes_dtype_findings_to_info():
    """O3 is the documented "speed of light, unsafe" level: the dtype
    findings stay visible but must not fail a lane that opted out."""
    props = amp.initialize(opt_level="O3", verbosity=0).properties

    def f(x):
        return jax.lax.reduce(x, jnp.bfloat16(0), jax.lax.add, (0,))

    rep = _run(f, jnp.ones((4096,), jnp.bfloat16), policy=props)
    finds = [f_ for f_ in rep.findings if f_.op == "low-precision-reduce"]
    assert finds and all(f_.severity == "info" for f_ in finds)
    assert rep.ok


# ---------------------------------------------------------------------------
# the dflow walker's SSA view (parser pins on crafted StableHLO)
# ---------------------------------------------------------------------------

_CRAFTED = """\
module @jit_f {
  func.func public @main(%arg0: tensor<4x8xf32> {jax.result_info = ""}, %arg1: tensor<8xbf16>) -> (tensor<8xbf16>) {
    %0 = stablehlo.constant dense<1.0> : tensor<4x8xf32>
    %1 = stablehlo.add %arg0, %0 : tensor<4x8xf32>
    %2 = stablehlo.reduce(%1 init: %cst) applies stablehlo.add across dimensions = [0] : (tensor<4x8xf32>, tensor<f32>) -> tensor<8xf32>
    %3 = stablehlo.convert %2 : (tensor<8xf32>) -> tensor<8xbf16>
    %4:2 = stablehlo.while(%iterArg = %3, %iterArg_0 = %arg1) : tensor<8xbf16>, tensor<8xbf16>
     cond {
      stablehlo.return %c : tensor<i1>
    } do {
      %5 = stablehlo.multiply %iterArg, %iterArg_0 : tensor<8xbf16>
      stablehlo.return %5, %iterArg_0 : tensor<8xbf16>, tensor<8xbf16>
    }
    return %4#0 : tensor<8xbf16>
  }
}
"""


def test_dflow_parses_ops_types_and_regions():
    funcs = dflow.parse_module(_CRAFTED)
    main = dflow.main_func(funcs)
    assert main is not None and main.name == "main"
    assert main.args == [("%arg0", "4x8xf32"), ("%arg1", "8xbf16")]
    by_name = {}
    for op in main.ops:
        by_name.setdefault(op.name, op)
    red = by_name["reduce"]
    assert red.result_elem == "f32" and red.reduce_dims() == (4,)
    assert red.reduced_elems() == 4
    conv = by_name["convert"]
    assert conv.operand_elems()[0] == "f32" and conv.result_elem == "bf16"
    # while-header bindings recorded as aliases; region returns attributed
    wh = by_name["while"]
    assert main.resolve("%iterArg") == "%3"
    assert ("%5", "%iterArg_0") in wh.region_returns
    # the outer func return is separated from the region returns
    assert len(main.returns) == 1
    assert main.returns[0].operands == ("%4#0",)


def test_dflow_use_counts_and_consumers():
    funcs = dflow.parse_module(_CRAFTED)
    main = funcs["main"]
    assert main.use_count["%arg0"] == 1
    assert any(op.name == "convert" for op in main.consumers["%2"])


# ---------------------------------------------------------------------------
# real lanes lint clean (the committed-artifact guarantee, enforced live)
# ---------------------------------------------------------------------------

#: bert/gpt/resnet model builds + lowerings cost 10s+ each on the
#: 2-vCPU tier-1 box — slow-marked like the graph-lint lanes; mlp keeps
#: the guarantee continuously enforced at both opt levels.
HEAVY_FAMILIES = ("resnet", "gpt", "bert")


def _marks_for(name):
    return (pytest.mark.slow,) if name in HEAVY_FAMILIES else ()


@pytest.mark.parametrize("family",
                         [pytest.param(f, id=f, marks=_marks_for(f))
                          for f in ["mlp", "resnet", "gpt", "bert"]])
@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_family_train_lane_precision_clean(family, opt_level):
    import graph_lint
    rep = graph_lint.lint_family(family, passes=("precision",),
                                 compile=False, opt_level=opt_level)
    assert rep.ok, rep.format()
    assert rep.passes == ("precision",) or "precision" in rep.passes
    summary = [f for f in rep.findings if f.op == "precision-summary"]
    # the clean verdict is meaningful only with evidence the pass looked
    assert summary and "0 matmul" not in summary[0].message


# ---------------------------------------------------------------------------
# PRECLINT artifact schema + committed round
# ---------------------------------------------------------------------------

def _lane(ok=True, errors=0):
    return {"ok": ok,
            "findings": {"error": errors, "info": 1},
            "checked": {k: 0 for k in ("dots", "reduces", "converts",
                                       "collectives", "scale_args",
                                       "scale_applied", "unscaled")}}


def test_committed_preclint_artifact_is_schema_valid():
    assert validate_preclint_file(str(REPO / "PRECLINT_r01.json")) == []


def test_preclint_schema_rejects_malformed_documents():
    assert validate_preclint("not a dict")
    assert any("lanes" in p for p in validate_preclint(
        {"round": 1, "platform": "cpu", "half_dtype": "bfloat16",
         "lanes": {}}))
    doc = {"round": 1, "platform": "cpu", "half_dtype": "bfloat16",
           "lanes": {"mlp_o1_train": _lane()}}
    assert validate_preclint(doc) == []
    # missing counters
    bad = {**doc, "lanes": {"x": {"ok": True, "findings": {},
                                  "checked": {"dots": 1}}}}
    assert validate_preclint(bad)


def test_preclint_schema_rejects_contradictory_verdict():
    """ok=True with error findings (or the reverse) is internally
    inconsistent — the verdict must be derivable from the document."""
    doc = {"round": 1, "platform": "cpu", "half_dtype": "bfloat16",
           "lanes": {"mlp_o1_train": _lane(ok=True, errors=2)}}
    assert any("contradicts" in p for p in validate_preclint(doc))
    doc["lanes"]["mlp_o1_train"] = _lane(ok=False, errors=0)
    assert any("contradicts" in p for p in validate_preclint(doc))


# ---------------------------------------------------------------------------
# the fp8 contract (ISSUE 9): each rule fires on a seeded bug and stays
# quiet on the correct delayed-scaling spelling and the real O4 lanes
# ---------------------------------------------------------------------------

def _fp8_errs(rep):
    return [f.op for f in rep.findings if f.severity == "error"]


def test_seeded_same_step_scale_fires():
    """Quantizing with a scale derived from THIS step's amax — the
    anti-pattern delayed scaling exists to forbid."""
    def bad(x):
        amax = jnp.max(jnp.abs(x))
        s = 448.0 / jnp.maximum(amax, 1e-30)
        q = jnp.clip(x * s, -448., 448.).astype(jnp.float8_e4m3fn)
        return (q.astype(jnp.float32) / s).sum()

    rep = _run(bad, jnp.ones((64,)))
    assert "fp8-same-step-scale" in _fp8_errs(rep)


def test_seeded_double_quantize_fires():
    """Dequantize-then-requantize through a pure value chain: two
    roundings, two composed scales."""
    def bad(x, s1, s2):
        q1 = jnp.clip(x * s1, -448., 448.).astype(jnp.float8_e4m3fn)
        d = q1.astype(jnp.float32) / s1
        q2 = jnp.clip(d * s2, -448., 448.).astype(jnp.float8_e4m3fn)
        return (q2.astype(jnp.float32) / s2).sum()

    rep = _run(bad, jnp.ones((64,)), jnp.float32(2.0), jnp.float32(3.0))
    assert "fp8-double-quantize" in _fp8_errs(rep)


def test_seeded_amax_unrecorded_fires_under_fp8_policy():
    """Under the O4 policy, quantizing without ever rolling an amax
    into the carried state leaves the delayed scale free-running."""
    from apex_tpu.quant import fp8 as fp8_lib

    def bad(x, scale):
        q = fp8_lib.quantize(x, scale)
        return (q.astype(jnp.float32) / scale).sum()

    rep = _run(bad, jnp.ones((64,)), jnp.float32(2.0),
               policy=amp.resolve("O4"))
    assert "fp8-amax-unrecorded" in _fp8_errs(rep)


def test_correct_delayed_scaling_spelling_is_quiet():
    """quantize with the CARRIED scale + record_amax flowing to the
    output: the in-tree spelling, clean under the O4 policy."""
    from apex_tpu.quant import fp8 as fp8_lib

    def good(x, state):
        q = fp8_lib.quantize(x, state.scale)
        y = (q.astype(jnp.float32) / state.scale).sum()
        new = fp8_lib.record_amax(state, fp8_lib.tensor_amax(x),
                                  fp8_lib.FP8_E4M3)
        return y, new

    rep = _run(good, jnp.ones((64,)), fp8_lib.init_delayed_scaling(4),
               policy=amp.resolve("O4"))
    assert rep.ok, rep.format()


def test_int8_kv_quantization_is_exempt():
    """The int8 KV format's per-write dynamic scale is the documented
    design — converts target i8, so no fp8 rule may fire."""
    def int8_write(k):
        amax = jnp.max(jnp.abs(k), axis=(-2, -1))
        s = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.rint(k / s[..., None, None]),
                     -127, 127).astype(jnp.int8)
        return q, s

    rep = _run(int8_write, jnp.ones((2, 4, 3, 8), jnp.bfloat16))
    assert rep.ok, rep.format()
    assert not [f for f in rep.findings if f.op.startswith("fp8-")]


@pytest.mark.parametrize("family",
                         [pytest.param(f, id=f, marks=_marks_for(f))
                          for f in ["mlp", "resnet", "gpt", "bert"]])
def test_family_o4_train_lane_precision_clean(family):
    """The real fp8 regime — every family's full O4 train step (qdq
    operand quantization, e5m2 cotangent rounding, history roll in the
    donated state) — lints clean, with f8-quantize evidence counted."""
    import graph_lint
    rep = graph_lint.lint_family(family, passes=("precision",),
                                 compile=False, opt_level="O4")
    assert rep.ok, rep.format()
    summary = [f for f in rep.findings if f.op == "precision-summary"]
    assert summary
    import re
    m = re.search(r"(\d+) f8 quantize", summary[0].message)
    assert m and int(m.group(1)) > 0, summary[0].message


def test_decode_kv8_lane_precision_clean():
    """The int8-KV decode lane (quantize-on-write, fused dequant) under
    the precision pass — the static half of the kv8 bench config."""
    import graph_lint
    rep = graph_lint.lint_decode("decode_b1_kv8", passes=("precision",),
                                 compile=False)
    assert rep.ok, rep.format()


def test_committed_preclint_r02_covers_quant_lanes():
    """The regenerated round-2 artifact records the fp8 regime: every
    family's O4 lane clean WITH f8-quantize evidence, plus the int8-KV
    decode lane."""
    import json as _json
    path = REPO / "PRECLINT_r02.json"
    assert validate_preclint_file(str(path)) == []
    doc = _json.loads(path.read_text())
    for fam in ("mlp", "resnet", "gpt", "bert"):
        lane = doc["lanes"][f"{fam}_o4_train"]
        assert lane["ok"]
        assert lane["checked"].get("fp8_quantizes", 0) > 0
    assert doc["lanes"]["decode_b1_kv8"]["ok"]


def test_amax_unrecorded_not_masked_by_softmax_max():
    """Every transformer has a numerical-stability max-reduce flowing
    into the loss; the reachability check seeds from ABS-fed reduces
    only, so a dropped history-roll still fires through a softmax."""
    from apex_tpu.quant import fp8 as fp8_lib

    def bad(x, scale):
        q = fp8_lib.quantize(x, scale)
        logits = (q.astype(jnp.float32) / scale)
        return jax.nn.softmax(logits).sum()   # softmax max reaches out

    rep = _run(bad, jnp.ones((8, 8)), jnp.float32(2.0),
               policy=amp.resolve("O4"))
    assert "fp8-amax-unrecorded" in _fp8_errs(rep)
