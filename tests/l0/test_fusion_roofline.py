"""Parser units for tools/fusion_roofline.py (the RN50 roofline audit).

The tool's conclusions (ROOFLINE_RN50_r04.json: the b256 step is
HBM-bound, MFU ceiling ~0.35) hang on its HLO accounting, so the shape/
byte/FLOP extraction is pinned here against a hand-written HLO snippet
with the wrinkles that broke earlier drafts: tuple-valued fusion outputs
whose type strings contain spaces and layout parens (``T(8,128)``),
operands resolved per-computation, duplicate operands counted once, and
the analytic conv-FLOP formula."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))

from fusion_roofline import _shape_bytes, parse_step  # noqa: E402

HLO = """\
HloModule test

%fused_computation.1 (param_0: bf16[8,16,16,64], param_1: bf16[1,1,64,32]) -> (f32[32], bf16[8,16,16,32]) {
  %param_0.1 = bf16[8,16,16,64]{3,0,2,1:T(8,128)(2,1)} parameter(0)
  %param_1.1 = bf16[1,1,64,32]{2,3,1,0:T(8,128)(2,1)} parameter(1)
  %conv.1 = bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)} convolution(%param_0.1, %param_1.1), window={size=1x1}, dim_labels=b01f_01io->b01f, metadata={op_name="test/conv"}
  %cvt.1 = f32[8,16,16,32]{3,0,2,1:T(8,128)} convert(%conv.1)
  %c0 = f32[] constant(0)
  %red.1 = f32[32]{0:T(256)} reduce(%cvt.1, %c0), dimensions={0,1,2}, to_apply=%add_comp
  ROOT %tup = (f32[32]{0:T(256)}, bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)}) tuple(%red.1, %conv.1)
}

ENTRY %main (p0: bf16[8,16,16,64], p1: bf16[1,1,64,32]) -> bf16[8,16,16,32] {
  %p0 = bf16[8,16,16,64]{3,0,2,1:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[1,1,64,32]{2,3,1,0:T(8,128)(2,1)} parameter(1)
  %big_fusion.7 = (f32[32]{0:T(256)S(1)}, bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)}) fusion(%p0, %p1), kind=kOutput, calls=%fused_computation.1, metadata={op_name="test/convfusion"}
  %gte.1 = bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)} get-tuple-element(%big_fusion.7), index=1
  %dup.1 = bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)} add(%gte.1, %gte.1)
  ROOT %out.1 = bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)} copy(%dup.1)
}
"""


def test_shape_bytes_tuple_and_layout_parens():
    t = ("(f32[32]{0:T(256)S(1)}, "
         "bf16[8,16,16,32]{3,0,2,1:T(8,128)(2,1)})")
    assert _shape_bytes(t) == 32 * 4 + 8 * 16 * 16 * 32 * 2
    assert _shape_bytes("pred[]{:T(512)}") == 1


def test_parse_step_tuple_fusion_record():
    rec = parse_step(HLO)
    # tuple-output fusion (type string with spaces + layout parens) must
    # produce a record — earlier drafts dropped exactly these, silently
    # excluding every conv mega-fusion from the audit
    f = rec["big_fusion.7"]
    assert f["read_b"] == (8 * 16 * 16 * 64 * 2) + (64 * 32 * 2)
    assert f["write_b"] == 32 * 4 + 8 * 16 * 16 * 32 * 2
    # 2 * out(8*16*16*32) * window(1*1) * Cin(64)
    assert f["conv_flops"] == 2.0 * 8 * 16 * 16 * 32 * 64
    assert f["meta"] == "test/convfusion"


GRAD_HLO = """\
HloModule grads

ENTRY %main (p0: bf16[8,16,16,64], p1: bf16[3,3,1,64], p2: bf16[8,14,14,32]) -> f32[3,3,64,32] {
  %p0 = bf16[8,16,16,64]{3,0,2,1:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[3,3,1,64]{2,3,1,0:T(8,128)(2,1)} parameter(1)
  %p2 = bf16[8,14,14,32]{3,0,2,1:T(8,128)(2,1)} parameter(2)
  %dw.1 = bf16[8,16,16,64]{3,0,2,1:T(8,128)(2,1)} convolution(%p0, %p1), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f, feature_group_count=64, metadata={op_name="test/depthwise"}
  %kg.1 = f32[3,3,64,32]{3,2,1,0:T(8,128)} convolution(%p0, %p2), window={size=14x14}, dim_labels=f01b_i01o->01bf, metadata={op_name="test/kernelgrad"}
  ROOT %out.1 = f32[3,3,64,32]{3,2,1,0:T(8,128)} copy(%kg.1)
}
"""


def test_conv_flops_contract_over_rhs_i_dim():
    rec = parse_step(GRAD_HLO)
    # depthwise (feature_group_count=64): per-output contraction is the
    # rhs i dim = 1, NOT the lhs f dim = 64 — reading lhs f overcounts
    # by the group count
    assert rec["dw.1"]["conv_flops"] == 2.0 * (8 * 16 * 16 * 64) * 9 * 1
    # kernel-grad conv (labels f01b_i01o): contraction is over batch,
    # surfaced as the rhs i dim = 8
    assert (rec["kg.1"]["conv_flops"]
            == 2.0 * (3 * 3 * 64 * 32) * (14 * 14) * 8)


def test_parse_step_duplicate_operands_counted_once():
    rec = parse_step(HLO)
    add = rec["dup.1"]
    assert add["read_b"] == 8 * 16 * 16 * 32 * 2  # gte.1 once, not twice
    assert add["conv_flops"] == 0.0
    # bookkeeping ops never become records
    assert "gte.1" not in rec and "p0" not in rec and "tup" not in rec
