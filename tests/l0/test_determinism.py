"""Seeded-bug fixtures for the bitwise-determinism lint
(:mod:`apex_tpu.analysis.determinism`) and its committed artifact.

Every per-lane rule id gets a minimal program built to trip it AND a
clean twin that differs only in the one property the rule checks — so
a rule that goes quiet (regression) or noisy (false positive) fails
here, not in a committed DETLINT round.  The comparator tests pin the
sweep's headline claim — the ``_attn_cached`` b1-vs-b8 suspect is
mechanically CLEARED with positionally identical reduction-signature
streams — on the real decode lowerings, and the artifact tests hold
the committed ``DETLINT_r01.json`` to the contradiction-rejecting
schema plus its recorded verdicts.
"""

import json
import shutil
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import analysis                            # noqa: E402
from apex_tpu.analysis import determinism, detlint       # noqa: E402
from apex_tpu.models.generate import (                   # noqa: E402
    greedy_argmax, pin_logits)
from apex_tpu.parallel.moe import top1_routing           # noqa: E402


def _findings(fn, *args):
    text = jax.jit(fn).lower(*args).as_text()
    return determinism.determinism_findings(text)


def _error_ids(findings):
    return sorted({f.op for f in findings if f.severity == "error"})


def _counter(findings, op):
    return sum(f.count for f in findings
               if f.severity == "info" and f.op == op)


_X = jnp.ones((4, 8), jnp.float32)
_W = jnp.ones((8, 16), jnp.float32)


# ---------------------------------------------------------------------------
# the rule lists cannot drift
# ---------------------------------------------------------------------------

def test_rule_lists_pinned_equal():
    """detlint.py mirrors the rule ids so gate_hygiene stays
    stdlib-only; this pin is what keeps the mirror honest."""
    assert tuple(determinism.RULES) == tuple(detlint.RULES)
    assert len(set(determinism.RULES)) == 5
    assert tuple(determinism.LANE_RULES) == tuple(detlint.LANE_RULES)
    assert detlint.PAIR_RULE == "det-lane-shape-variant"


def test_pass_registered():
    assert "determinism" in analysis.PASSES


# ---------------------------------------------------------------------------
# det-tie-argmax: raw float argmax/top-k vs the greedy_argmax form
# ---------------------------------------------------------------------------

def test_tie_argmax_fires_on_raw_argmax():
    f = _findings(lambda x: jnp.argmax(x, -1), _X)
    assert "det-tie-argmax" in _error_ids(f)


def test_tie_argmax_fires_on_top_k():
    f = _findings(lambda x: jax.lax.top_k(x, 3), _X)
    assert "det-tie-argmax" in _error_ids(f)


def test_tie_argmax_quiet_on_greedy_argmax():
    f = _findings(lambda x: greedy_argmax(x), _X)
    assert _error_ids(f) == []
    # and not by vacuum: the reductions were walked
    assert _counter(f, "det-epilogue-sites") == 0


def test_tie_argmax_key_perturbed_draw_is_legal():
    """jax.random.categorical is gumbel-noise + argmax: the argmax
    operand derives from a random-bits expansion, so a ulp tie-flip is
    just a different legal sample — info, not error."""
    key = jax.random.PRNGKey(0)
    f = _findings(lambda k, l: jax.random.categorical(k, l), key, _X)
    assert "det-tie-argmax" not in _error_ids(f)
    assert _counter(f, "det-epilogue-sites") >= 1


# ---------------------------------------------------------------------------
# det-multi-materialize: a value both returned and argmax'd, unpinned
# ---------------------------------------------------------------------------

def test_multi_materialize_fires_on_shared_unpinned_logits():
    def seed(x, w):
        logits = x @ w          # ONE binding: both uses share the value
        return logits.argmax(-1), logits
    ids = _error_ids(_findings(seed, _X, _W))
    assert "det-multi-materialize" in ids
    assert "det-tie-argmax" in ids


def test_multi_materialize_quiet_under_pin_logits():
    def clean(x, w):
        logits = pin_logits(x @ w)
        return greedy_argmax(logits), logits
    f = _findings(clean, _X, _W)
    assert _error_ids(f) == []
    assert _counter(f, "det-barriers") >= 1


# ---------------------------------------------------------------------------
# det-scatter-order: non-provably-disjoint scatter windows
# ---------------------------------------------------------------------------

_BUF = jnp.zeros((16, 8), jnp.float32)
_IDX = jnp.array([1, 3, 5], jnp.int32)
_UPD = jnp.ones((3, 8), jnp.float32)


def test_scatter_order_fires_on_unguarded_indices():
    f = _findings(lambda b, i, u: b.at[i].set(u), _BUF, _IDX, _UPD)
    assert "det-scatter-order" in _error_ids(f)
    assert _counter(f, "det-scatter-sites") == 1


def test_scatter_order_quiet_on_trash_guard():
    """The serving pool's form: masked rows route to a sacrificial
    index, so colliding writes statically land in the trash block."""
    mask = jnp.array([True, True, False])
    f = _findings(lambda b, i, u, m: b.at[jnp.where(m, i, 15)].set(u),
                  _BUF, _IDX, _UPD, mask)
    assert _error_ids(f) == []
    assert _counter(f, "det-scatter-sites") == 1


def test_scatter_order_quiet_on_unique_indices():
    f = _findings(
        lambda b, u: b.at[jnp.arange(3)].set(u, unique_indices=True),
        _BUF, _UPD)
    assert _error_ids(f) == []


# ---------------------------------------------------------------------------
# det-prng-reuse: one key feeding two independent expansions
# ---------------------------------------------------------------------------

def test_prng_reuse_fires_on_shared_key():
    key = jax.random.PRNGKey(0)
    f = _findings(lambda k: jax.random.normal(k, (4,))
                  + jax.random.uniform(k, (4,)), key)
    assert "det-prng-reuse" in _error_ids(f)
    assert _counter(f, "det-rng-calls") >= 2


def test_prng_reuse_quiet_after_split():
    key = jax.random.PRNGKey(0)

    def clean(k):
        k1, k2 = jax.random.split(k)
        return jax.random.normal(k1, (4,)) + jax.random.uniform(k2, (4,))
    f = _findings(clean, key)
    assert "det-prng-reuse" not in _error_ids(f)


# ---------------------------------------------------------------------------
# the MoE router rides the greedy_argmax form (the fixed raw-argmax site)
# ---------------------------------------------------------------------------

def test_moe_router_lints_clean():
    logits = jnp.ones((8, 4), jnp.float32)
    f = _findings(lambda lg: top1_routing(lg, capacity=4)[0], logits)
    assert "det-tie-argmax" not in _error_ids(f)


def test_moe_router_raw_argmax_twin_would_fire():
    """The before-image of the fix: the same router with a raw
    jnp.argmax tie-break trips the rule, so the greedy_argmax swap in
    top1_routing is load-bearing, not decorative."""
    def raw_router(lg):
        probs = jax.nn.softmax(lg, axis=-1)
        return jnp.argmax(probs, axis=-1)
    f = _findings(raw_router, jnp.ones((8, 4), jnp.float32))
    assert "det-tie-argmax" in _error_ids(f)


# ---------------------------------------------------------------------------
# the comparator, pinned on the real decode lanes (the _attn_cached
# b1-vs-b8 suspect: mechanically cleared)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def decode_pair_texts():
    import det_lint
    return (det_lint.lane_text("decode", (1, 8, 8, None)),
            det_lint.lane_text("decode", (8, 8, 8, None)))


def test_decode_b1_b8_signatures_cleared(decode_pair_texts):
    ta, tb = decode_pair_texts
    sa = determinism.reduction_signatures(ta)
    sb = determinism.reduction_signatures(tb)
    assert sa, "decode_b1 recorded no float reductions (vacuum)"
    res = determinism.compare_signatures("decode_b1", sa,
                                         "decode_b8", sb)
    assert res["verdict"] == "cleared"
    assert res["positional"] is True
    assert res["variants"] == []


def test_decode_lanes_lint_clean(decode_pair_texts):
    for text in decode_pair_texts:
        f = determinism.determinism_findings(text)
        assert _error_ids(f) == []


def test_signature_diff_detects_an_injected_variant(decode_pair_texts):
    """The comparator cannot be cleared-by-construction: perturbing one
    stream flips the verdict."""
    ta, _ = decode_pair_texts
    sa = determinism.reduction_signatures(ta)
    sb = list(sa) + [("dot", (999,), ("f32", "f32", "f32"))]
    res = determinism.compare_signatures("a", sa, "b", sb)
    assert res["verdict"] == "variant"
    assert res["positional"] is False
    assert any(v["dims"] == [999] for v in res["variants"])


# ---------------------------------------------------------------------------
# the committed artifact: schema-valid, verdicts as documented
# ---------------------------------------------------------------------------

_ARTIFACT = REPO / "DETLINT_r01.json"


def _load_artifact():
    return json.loads(_ARTIFACT.read_text())


def test_committed_detlint_exists_and_validates():
    assert _ARTIFACT.exists(), "DETLINT_r01.json must be committed"
    assert detlint.validate_detlint_file(str(_ARTIFACT)) == []


def test_committed_detlint_gate_and_verdicts():
    doc = _load_artifact()
    assert doc["gate"]["ok"] is True
    assert doc["rules"] == list(detlint.RULES)
    # the _attn_cached suspect: cleared with positional evidence
    pair = doc["pairs"]["decode_b1|decode_b8"]
    assert pair["verdict"] == "cleared"
    assert pair["positional"] is True
    assert pair["signatures"]["decode_b1"]  # evidence, not a claim
    # the kv8 tolerance class: a variant, documented
    kv8 = doc["pairs"]["decode_b1|decode_b1_kv8"]
    assert kv8["verdict"] == "variant"
    assert kv8["expected"] is True and kv8["reason"].strip()
    # spec's step-vs-verify contract holds
    assert doc["pairs"]["serve_step|serve_verify"]["verdict"] == "cleared"


# ---------------------------------------------------------------------------
# the schema rejects contradictions (the gate_hygiene enforcement path)
# ---------------------------------------------------------------------------

def test_schema_rejects_ok_contradicting_findings():
    doc = _load_artifact()
    doc["lanes"]["decode_b1"]["findings"]["det-tie-argmax"] = 3
    assert any("contradicts" in p
               for p in detlint.validate_detlint(doc))


def test_schema_rejects_clean_by_vacuum():
    doc = _load_artifact()
    lane = doc["lanes"]["decode_b1"]
    lane["checked"] = {k: 0 for k in lane["checked"]}
    assert any("examined nothing" in p
               for p in detlint.validate_detlint(doc))


def test_schema_rejects_fabricated_cleared_verdict():
    doc = _load_artifact()
    kv8 = doc["pairs"]["decode_b1|decode_b1_kv8"]
    kv8["verdict"] = "cleared"          # signatures still diverge
    assert any("contradicts the recorded signatures" in p
               for p in detlint.validate_detlint(doc))


def test_schema_rejects_suppressed_variant_list():
    doc = _load_artifact()
    doc["pairs"]["decode_b1|decode_b1_kv8"]["variants"] = []
    assert any("disagree" in p for p in detlint.validate_detlint(doc))


def test_schema_rejects_expected_variant_without_reason():
    doc = _load_artifact()
    doc["pairs"]["decode_b1|decode_b1_kv8"].pop("reason")
    assert any("reason" in p for p in detlint.validate_detlint(doc))


def test_schema_rejects_gate_contradiction():
    doc = _load_artifact()
    doc["gate"]["lanes_clean"] = 0
    assert any("gate.lanes_clean" in p
               for p in detlint.validate_detlint(doc))


def test_schema_rejects_stale_waiver():
    doc = _load_artifact()
    doc["lanes"]["decode_b1"]["waivers"] = {
        "det-tie-argmax": "documented"}
    assert any("stale waiver" in p
               for p in detlint.validate_detlint(doc))


def test_gate_hygiene_validates_detlints(tmp_path):
    """gate_hygiene's stdlib-only loader path: a tampered artifact in a
    checkout fails the hygiene gate with a named problem."""
    import gate_hygiene
    (tmp_path / "apex_tpu" / "analysis").mkdir(parents=True)
    shutil.copy(REPO / "apex_tpu" / "analysis" / "detlint.py",
                tmp_path / "apex_tpu" / "analysis" / "detlint.py")
    doc = _load_artifact()
    doc["gate"]["ok"] = False           # contradicts the clean records
    (tmp_path / "DETLINT_r01.json").write_text(json.dumps(doc))
    problems = gate_hygiene._validate_detlints(str(tmp_path))
    assert problems and "DETLINT_r01.json" in problems[0]


# ---------------------------------------------------------------------------
# partial-config emits are refused, not silently committed
# ---------------------------------------------------------------------------

def _refuses(argv):
    import graph_lint
    with pytest.raises(SystemExit) as e:
        graph_lint.main(argv)
    assert e.value.code == 2


def test_graph_lint_refuses_detlint_with_lanes(tmp_path):
    out = str(tmp_path / "DETLINT_r09.json")
    _refuses(["--emit-json", out, "--lanes", "decode"])
    assert not Path(out).exists()


def test_graph_lint_refuses_detlint_with_foreign_passes(tmp_path):
    _refuses(["--emit-json", str(tmp_path / "DETLINT_r09.json"),
              "--passes", "precision"])


def test_graph_lint_refuses_detlint_with_families(tmp_path):
    _refuses(["--emit-json", str(tmp_path / "DETLINT_r09.json"),
              "--families", "gpt"])


def test_graph_lint_refuses_detlint_with_budget(tmp_path):
    _refuses(["--emit-json", str(tmp_path / "DETLINT_r09.json"),
              "--passes", "determinism", "--memory-budget", "1.0"])


def test_kernel_bench_refuses_detlint_name(tmp_path):
    import kernel_bench
    out = str(tmp_path / "DETLINT_r09.json")
    with pytest.raises(SystemExit) as e:
        kernel_bench.main(["--out", out, "--tiny"])
    assert e.value.code == 2
    assert not Path(out).exists()


# ---------------------------------------------------------------------------
# the timeline ingests the family (a committed round can't go unseen)
# ---------------------------------------------------------------------------

def test_timeline_adapter_ingests_detlint():
    from apex_tpu.analysis import timeline
    assert "DETLINT" in timeline.ADAPTERS
    rows = timeline.ADAPTERS["DETLINT"](_load_artifact(), None)
    metrics = {(c, m) for c, m, _v in rows}
    assert ("lane:decode_b1", "lint_clean") in metrics
    assert ("pair:decode_b1|decode_b8", "cleared") in metrics
    assert ("gate", "lanes_clean_frac") in metrics
    assert ("gate", "pairs_ok_frac") in metrics
