"""LARC conformance vs a hand-computed reference (``apex/parallel/LARC.py``
semantics: adaptive lr = trust·‖p‖/(‖g‖+wd·‖p‖+ε), clip vs scale modes,
weight decay folded into the grad, untouched grads where either norm is 0).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.optimizers import LARC, larc

LR = 0.1
TRUST = 0.02
WD = 0.01
EPS = 1e-8


def _ref_scaled(g, p, clip):
    p_norm = np.linalg.norm(p)
    g_norm = np.linalg.norm(g)
    if p_norm == 0 or g_norm == 0:
        return g
    adaptive = TRUST * p_norm / (g_norm + WD * p_norm + EPS)
    rate = min(adaptive / LR, 1.0) if clip else adaptive
    return (g + WD * p) * rate


def test_clip_mode_matches_reference():
    rng = np.random.RandomState(0)
    params = {"a": rng.randn(5, 3).astype(np.float32),
              "b": rng.randn(7).astype(np.float32)}
    grads = {"a": rng.randn(5, 3).astype(np.float32),
             "b": rng.randn(7).astype(np.float32)}
    tx = larc(LR, trust_coefficient=TRUST, clip=True, eps=EPS,
              weight_decay=WD)
    out, _ = tx.update(jax.tree.map(jnp.asarray, grads),
                       tx.init(params), jax.tree.map(jnp.asarray, params))
    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]),
                                   _ref_scaled(grads[k], params[k], True),
                                   rtol=1e-5, atol=1e-6)


def test_scale_mode_matches_reference():
    rng = np.random.RandomState(1)
    p = rng.randn(4, 4).astype(np.float32)
    g = rng.randn(4, 4).astype(np.float32)
    tx = larc(LR, trust_coefficient=TRUST, clip=False, eps=EPS,
              weight_decay=WD)
    out, _ = tx.update({"p": jnp.asarray(g)}, tx.init({"p": p}),
                       {"p": jnp.asarray(p)})
    np.testing.assert_allclose(np.asarray(out["p"]),
                               _ref_scaled(g, p, False),
                               rtol=1e-5, atol=1e-6)


def test_zero_norm_leaves_grad_untouched():
    tx = larc(LR, weight_decay=WD)
    g = jnp.ones((3,))
    out, _ = tx.update({"p": g}, tx.init({"p": jnp.zeros((3,))}),
                       {"p": jnp.zeros((3,))})
    np.testing.assert_allclose(np.asarray(out["p"]), np.ones(3))
    out2, _ = tx.update({"p": jnp.zeros((3,))}, tx.init({"p": g}),
                        {"p": g})
    np.testing.assert_allclose(np.asarray(out2["p"]), np.zeros(3))


def test_larc_wrapped_sgd_trains():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(64, 8).astype(np.float32))
    w_true = jnp.asarray(rng.randn(8, 1).astype(np.float32))
    y = x @ w_true
    params = {"w": jnp.zeros((8, 1), jnp.float32)}
    tx = LARC(optax.sgd(LR), LR, weight_decay=WD)
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        def loss(p):
            return jnp.mean(jnp.square(x @ p["w"] - y))
        l, g = jax.value_and_grad(loss)(params)
        updates, state2 = tx.update(g, state, params)
        return optax.apply_updates(params, updates), state2, l

    first = None
    for _ in range(50):
        params, state, l = step(params, state)
        first = float(l) if first is None else first
    assert float(l) < 0.5 * first
