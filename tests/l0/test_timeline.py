"""The longitudinal perf timeline (``apex_tpu/analysis/timeline.py`` +
``tools/perf_timeline.py``).

Contracts under test: (a) the adapter registry ingests every committed
artifact family and an unknown family is a LINT error, not a silent
coverage hole; (b) the statistical-band regression rule and its
attribution — a synthetic artifact set with a planted drop between
rounds yields exactly one regression row naming the planted round and
the commits between the two rounds' artifact commits; (c) the schema's
contradiction rejection (fabricated rows, suppressed rows, self-citing
gate verdicts, stale coverage); (d) the committed ``TIMELINE_r01.json``
is schema-valid against THIS checkout and mechanically rediscovers the
two known tpu-heads regressions (gpt / bert_lamb between r04 and r05,
VERDICT r5 weak #1) with the documented suspect commits in range.
"""

import copy
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu.analysis import timeline  # noqa: E402


# ---------------------------------------------------------------------------
# naming + ingestion
# ---------------------------------------------------------------------------

def test_parse_artifact_name():
    assert timeline.parse_artifact_name("BENCH_r05.json") == \
        ("BENCH", 5, "")
    assert timeline.parse_artifact_name("INCIDENT_r02_wedge.json") == \
        ("INCIDENT", 2, "_wedge")
    assert timeline.parse_artifact_name("ROOFLINE_RN50_r04.json") == \
        ("ROOFLINE_RN50", 4, "")
    assert timeline.parse_artifact_name("BASELINE.json") is None
    assert timeline.parse_artifact_name("SCALING_SWEEP.json") is None


def test_every_committed_family_has_an_adapter():
    """The staleness lint's premise: THIS checkout's committed
    round-numbered artifacts all have registered adapters, and the
    ingest covers them all with rows."""
    out = timeline.ingest_repo(str(REPO))
    assert out["unknown"] == [], out["unknown"]
    assert out["unreadable"] == [], out["unreadable"]
    fams = set(out["coverage"])
    for expect in ("BENCH", "KERNELBENCH", "MEMLINT", "PRECLINT",
                   "SCENARIO", "SERVE_DISAGG", "TRACE", "OBS",
                   "EXPORT", "CONVERGENCE", "DECODE_PROFILE",
                   "DECODE_DECOMPOSE", "BENCH_VARIANCE", "FLEETLINT",
                   "PREFIXCACHE", "TRAINFLEET", "KERNLINT"):
        assert expect in fams, f"{expect} not ingested ({fams})"
    assert all(rec["files"] for rec in out["coverage"].values())
    assert sum(rec["rows"] for rec in out["coverage"].values()) > 100


def test_fleetlint_adapter_rows():
    """FLEETLINT rounds chart per-lane consistency (1.0 = every rank
    compiled the same collective schedule), the lane's collective count,
    and the gate's inconsistent-lane total — a regression on any of them
    is a fleet-wide deadlock risk appearing in the timeline."""
    rank = {"schedule_hash": "a" * 64, "opcode_hash": "b" * 64,
            "n_collectives": 4}
    doc = {"round": 1, "platform": "cpu", "n_ranks": 8,
           "lanes": {"ddp_o1_train": {"compare": "schedule",
                                      "consistent": True,
                                      "ranks": {"0": dict(rank),
                                                "1": dict(
                                                    rank,
                                                    n_collectives=3)},
                                      "mismatches": []}},
           "gate": {"ok": True, "inconsistent_lanes": 0}}
    rows = timeline.ADAPTERS["FLEETLINT"](doc, {})
    assert ("ddp_o1_train", "consistent", 1.0) in rows
    assert ("ddp_o1_train", "n_collectives", 4.0) in rows
    assert ("gate", "inconsistent_lanes", 0.0) in rows


def test_kernlint_adapter_rows():
    """KERNLINT rounds chart each kernel's clean verdict as 1.0/0.0,
    its total finding count, and the gate's clean fraction — a kernel
    regressing into findings (or a waiver papering over them) drops a
    charted value, not just prose."""
    rules = ["pallas-parallel-race", "pallas-vmem-overflow"]
    doc = {"round": 1, "platform": "cpu", "budget_mb": 16.0,
           "rules": rules,
           "kernels": {
               "fused_adam": {"ok": True, "configs": 2, "calls": 3,
                              "findings": {r: 0 for r in rules}},
               "layer_norm": {"ok": False, "configs": 4, "calls": 6,
                              "findings": {"pallas-vmem-overflow": 2}}},
           "gate": {"ok": False, "kernels_clean": 1,
                    "kernels_total": 2}}
    rows = timeline.ADAPTERS["KERNLINT"](doc, {})
    assert ("kernel:fused_adam", "lint_clean", 1.0) in rows
    assert ("kernel:fused_adam", "rule_findings", 0.0) in rows
    assert ("kernel:layer_norm", "lint_clean", 0.0) in rows
    assert ("kernel:layer_norm", "rule_findings", 2.0) in rows
    assert ("gate", "kernels_clean_frac", 0.5) in rows


def test_prefixcache_adapter_rows():
    """PREFIXCACHE rounds chart both arms' deterministic counts plus
    the hit-rate headline — a round where sharing quietly dispatches
    MORE prefill tokens (or the hit rate collapses) shows up as a
    timeline regression, not a silent rot."""
    doc = {"round": 1, "platform": "cpu",
           "sharing": {"prefill_chunks": 5,
                       "prefill_tokens_dispatched": 33,
                       "peak_live_blocks": 10,
                       "admitted_requests_per_block": 0.4,
                       "p50_ms": 1.9, "p99_ms": 3.2, "retraces": 1,
                       "prefix": {"hit_rate": 0.75, "hit_tokens": 31,
                                  "cow_copies": 1,
                                  "shared_blocks_peak": 4}},
           "baseline": {"prefill_tokens_dispatched": 64,
                        "peak_live_blocks": 16,
                        "admitted_requests_per_block": 0.25}}
    rows = timeline.ADAPTERS["PREFIXCACHE"](doc, {})
    assert ("sharing", "prefill_tokens_dispatched", 33.0) in rows
    assert ("baseline", "prefill_tokens_dispatched", 64.0) in rows
    assert ("sharing", "admitted_requests_per_block", 0.4) in rows
    assert ("prefix", "hit_rate", 0.75) in rows
    assert ("prefix", "hit_tokens", 31.0) in rows


def test_trainfleet_adapter_rows():
    """TRAINFLEET rounds chart the chaos drill's wall clock, generation
    count, per-recovery steps-lost, and the bitwise verdicts as
    1.0/0.0 — a round where recovery quietly loses more steps (or a
    bitwise flag drops to 0) is a timeline regression, not prose."""
    doc = {"round": 1, "platform": "cpu", "wall_s": 51.0,
           "generations": [{"gen": 0}, {"gen": 1}, {"gen": 2}],
           "recoveries": [
               {"reason": "shrink", "steps_lost": 3},
               {"reason": "regrow", "steps_lost": 1}],
           "bitwise": {"shrink_matches_uninterrupted": True,
                       "regrow_matches_uninterrupted": True,
                       "final_cross_rank_identical": False},
           "gate": {"ok": False}}
    rows = timeline.ADAPTERS["TRAINFLEET"](doc, {})
    assert ("drill", "wall_s", 51.0) in rows
    assert ("drill", "generations", 3.0) in rows
    assert ("shrink", "steps_lost", 3.0) in rows
    assert ("regrow", "steps_lost", 1.0) in rows
    assert ("bitwise", "final_cross_rank_identical", 0.0) in rows
    assert ("bitwise", "shrink_matches_uninterrupted", 1.0) in rows
    assert ("gate", "ok", 0.0) in rows


def test_unknown_family_is_a_lint_error(tmp_path):
    """A committed family with no adapter must refuse the build — the
    mechanism that keeps the timeline from silently going stale."""
    (tmp_path / "NEWFAMILY_r01.json").write_text('{"x": 1}')
    out = timeline.ingest_repo(str(tmp_path))
    assert out["unknown"] == ["NEWFAMILY_r01.json"]
    import perf_timeline
    with pytest.raises(ValueError, match="NEWFAMILY"):
        perf_timeline.build_timeline(str(tmp_path), gated=[])


def test_unreadable_artifact_excluded_from_coverage(tmp_path):
    """A corrupt committed artifact must NOT be vouched for: it stays
    out of the coverage table (so the staleness lint flags the doc
    against the checkout) and the tool refuses to build over it."""
    (tmp_path / "KERNELBENCH_r01.json").write_text(_bench_artifact(
        {}))          # readable (empty kernels -> zero rows)
    (tmp_path / "KERNELBENCH_r02.json").write_text('{"trunc')
    out = timeline.ingest_repo(str(tmp_path))
    assert out["coverage"]["KERNELBENCH"]["files"] == \
        ["KERNELBENCH_r01.json"]
    assert any("KERNELBENCH_r02" in u for u in out["unreadable"])
    # a timeline claiming that coverage is STALE vs the checkout
    doc = {"round": 1, "bands": {"default": 0.03},
           "series": {"BENCH|c|tok_s": {
               "family": "BENCH", "config": "c", "metric": "tok_s",
               "points": [{"round": 1, "value": 1.0}]}},
           "regressions": [], "coverage": out["coverage"],
           "gate": {"regressions": 0, "ok": True}}
    problems = timeline.validate_timeline(doc, repo_dir=str(tmp_path))
    assert any("STALE" in p and "KERNELBENCH_r02" in p
               for p in problems)
    import perf_timeline
    with pytest.raises(ValueError, match="unreadable"):
        perf_timeline.build_timeline(str(tmp_path), gated=[])


def test_bench_adapter_reconstructs_truncated_round():
    """BENCH_r05's tail is truncated past its configs map; the adapter
    reconstructs each rate as prev x (1 + recorded delta) — the
    artifact's own regression deltas are the recoverable witness."""
    rows = timeline.ingest_repo(str(REPO))["rows"]
    by = {(r["family"], r["round"], r["config"], r["metric"]):
          r["value"] for r in rows}
    r4 = by[("BENCH", 4, "gpt_small_tpu_heads_o2", "tok_s")]
    r5 = by[("BENCH", 5, "gpt_small_tpu_heads_o2", "tok_s")]
    assert r4 == 139660.56
    assert r5 == pytest.approx(r4 * (1 - 0.0323), rel=1e-6)


# ---------------------------------------------------------------------------
# the band rule
# ---------------------------------------------------------------------------

def _series(values, family="BENCH", config="c", metric="tok_s"):
    key = timeline.series_key(family, config, metric)
    return {key: {"family": family, "config": config, "metric": metric,
                  "points": [{"round": i + 1, "value": v,
                              "commit": None}
                             for i, v in enumerate(values)]}}


def test_detect_regressions_band_rule():
    s = _series([100.0, 104.0, 100.9])     # -3.0% vs best: inside band
    key = next(iter(s))
    assert timeline.detect_regressions(s, [key],
                                       default_band=0.03) == []
    s = _series([100.0, 104.0, 100.0])     # -3.8% vs best: crosses
    rows = timeline.detect_regressions(s, [key], default_band=0.03)
    assert len(rows) == 1
    row = rows[0]
    assert row["best_round"] == 2 and row["drop_round"] == 3
    assert row["from_round"] == 2
    assert row["drop_frac"] == pytest.approx(0.0385, abs=1e-3)
    # per-series band overrides the default
    assert timeline.detect_regressions(
        s, [key], bands={key: 0.05}, default_band=0.03) == []
    # a recovered series (newest back above band) never rows
    s = _series([100.0, 90.0, 99.0])
    assert timeline.detect_regressions(s, [key],
                                       default_band=0.03) == []
    # ungated series never row
    assert timeline.detect_regressions(s, [], default_band=0.03) == []


def test_first_drop_round_named():
    """The row names the FIRST round that fell below the band, not
    just the newest."""
    s = _series([100.0, 95.0, 94.0, 93.0])
    key = next(iter(s))
    rows = timeline.detect_regressions(s, [key], default_band=0.03)
    assert rows[0]["drop_round"] == 2       # 95 < 100*0.97
    assert rows[0]["from_round"] == 1
    assert rows[0]["newest_round"] == 4


# ---------------------------------------------------------------------------
# seeded-regression attribution (satellite: the planted-drop test)
# ---------------------------------------------------------------------------

def _git(repo, *args):
    subprocess.run(["git", "-C", str(repo), "-c", "user.email=t@t",
                    "-c", "user.name=t", *args], check=True,
                   capture_output=True)


def _bench_artifact(configs):
    return json.dumps({"parsed": {"metric": "m", "value": 1.0,
                                  "unit": "u", "configs": configs}})


def test_seeded_regression_attribution(tmp_path):
    """A synthetic artifact set with a planted drop between rounds
    yields EXACTLY ONE regression row naming the planted round and
    the commits between the two round tags."""
    try:
        _git(tmp_path, "init", "-q")
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("git unavailable")
    import perf_timeline

    (tmp_path / "BENCH_r01.json").write_text(_bench_artifact(
        {"cfg_a": {"tok_s": 1000.0}, "cfg_b": {"tok_s": 500.0}}))
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "round 1 artifact")
    # the suspect: a code commit BETWEEN the two round tags
    (tmp_path / "kernel.py").write_text("# the perf-relevant change\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "the suspect change")
    suspect = subprocess.run(
        ["git", "-C", str(tmp_path), "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True, check=True).stdout.strip()
    # round 2: cfg_a planted -10%, cfg_b steady
    (tmp_path / "BENCH_r02.json").write_text(_bench_artifact(
        {"cfg_a": {"tok_s": 900.0}, "cfg_b": {"tok_s": 501.0}}))
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-q", "-m", "round 2 artifact")

    gated = [timeline.series_key("BENCH", c, "tok_s")
             for c in ("cfg_a", "cfg_b")]
    doc = perf_timeline.build_timeline(str(tmp_path), gated=gated)
    assert len(doc["regressions"]) == 1
    row = doc["regressions"][0]
    assert row["series"] == timeline.series_key("BENCH", "cfg_a",
                                                "tok_s")
    assert row["drop_round"] == 2 and row["from_round"] == 1
    assert row["drop_frac"] == pytest.approx(0.10, abs=1e-4)
    suspects = [s["commit"] for s in row["suspects"]]
    assert suspect in suspects, (suspect, suspects)
    # ... and the emitted document validates against its own repo
    assert timeline.validate_timeline(doc,
                                      repo_dir=str(tmp_path)) == []


# ---------------------------------------------------------------------------
# schema contradiction classes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def committed_doc():
    # the NEWEST committed round: the one gate_hygiene holds to
    # coverage-completeness against this checkout
    newest = max(REPO.glob("TIMELINE_r*.json"))
    with open(newest) as f:
        return json.load(f)


def test_committed_timeline_validates(committed_doc):
    assert timeline.validate_timeline(committed_doc,
                                      repo_dir=str(REPO)) == []


def test_committed_timeline_rediscovers_known_regressions(
        committed_doc):
    """The acceptance bar: the committed round's regression table
    independently rediscovers the gpt/bert tpu-heads drops between
    r04 and r05, with VERDICT's suspects in the attributed range."""
    rows = {r["series"]: r for r in committed_doc["regressions"]}
    gpt = rows["BENCH|gpt_small_tpu_heads_o2|tok_s"]
    bert = rows["BENCH|bert_large_tpu_heads_lamb_o2|seq_s"]
    for row in (gpt, bert):
        assert row["drop_round"] == 5 and row["from_round"] == 4
        suspects = [s["commit"] for s in row["suspects"]]
        # the two suspects VERDICT r5 named by hand
        assert "90d60d2" in suspects      # prefill-flash
        assert "02a761d" in suspects      # mt-aliasing
    assert gpt["drop_frac"] == pytest.approx(0.0323, abs=1e-3)
    assert committed_doc["gate"] == {"regressions": 2, "ok": False}
    # the kv8 seed is reported as UNMEASURED, not passed off as a floor
    assert "gpt_small_tpu_decode_kv8" in \
        committed_doc["provisional_floors"]


def test_fabricated_regression_rejected(committed_doc):
    bad = copy.deepcopy(committed_doc)
    bad["regressions"][0]["series"] = "BENCH|resnet50_o2|img_s"
    problems = timeline.validate_timeline(bad)
    assert any("never cross" in p for p in problems)


def test_suppressed_regression_rejected(committed_doc):
    bad = copy.deepcopy(committed_doc)
    bad["regressions"] = []
    bad["gate"] = {"regressions": 0, "ok": True}
    problems = timeline.validate_timeline(bad)
    assert any("suppressed regression" in p for p in problems)


def test_self_citing_gate_rejected(committed_doc):
    bad = copy.deepcopy(committed_doc)
    bad["gate"]["ok"] = True
    problems = timeline.validate_timeline(bad)
    assert any("CONTRADICTORY verdict: gate.ok" in p
               for p in problems)
    bad2 = copy.deepcopy(committed_doc)
    bad2["gate"]["regressions"] = 99
    assert any("gate.regressions" in p
               for p in timeline.validate_timeline(bad2))


def test_tampered_values_rejected(committed_doc):
    """A regression row whose stated values disagree with the series
    it cites is contradictory."""
    bad = copy.deepcopy(committed_doc)
    bad["regressions"][0]["best_value"] += 10.0
    problems = timeline.validate_timeline(bad)
    assert any("CONTRADICTORY record" in p for p in problems)


def test_tampered_from_round_rejected(committed_doc):
    """from_round defines the suspect-commit attribution range; a row
    claiming a different range than the cited series derives is
    contradictory like every other field."""
    bad = copy.deepcopy(committed_doc)
    bad["regressions"][0]["from_round"] = 1
    problems = timeline.validate_timeline(bad)
    assert any("from_round" in p for p in problems)


def test_stale_coverage_rejected(tmp_path, committed_doc):
    """A committed artifact absent from the coverage table invalidates
    the timeline when judged against the checkout — a new family or
    round cannot land without refreshing the timeline."""
    # judged against a dir with one extra committed family file
    (tmp_path / "KERNELBENCH_r99.json").write_text("{}")
    problems = timeline.validate_timeline(committed_doc,
                                          repo_dir=str(tmp_path))
    assert any("STALE timeline" in p and "KERNELBENCH_r99" in p
               for p in problems)
    # internal-only validation of the same doc stays clean
    assert timeline.validate_timeline(committed_doc) == []
