"""Declarative SLOs over the live registry (``apex_tpu.obs.slo``).

Contracts under test: (a) objective evaluation on scripted registry
states — met / violated / insufficient_window from the closed
vocabulary; (b) the windowed quantile burn-rate math against a numpy
reference (bad_frac over the trailing window divided by the error
budget ``1 − q``); (c) router de-eligibility — a scripted fleet with
one replica forced over its p99 objective routes every new admission
around it; (d) zero new host syncs: an SLO-instrumented serve lane
keeps one trace and the graph-lint syncs pass stays clean on the
compiled step (the evaluator reads resolved host state only).
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import GPTModel, gpt_tiny
from apex_tpu.obs.metrics import Registry
from apex_tpu.obs.slo import (
    STATUS_INSUFFICIENT,
    STATUS_MET,
    STATUS_VIOLATED,
    SLObjective,
    SLOEvaluator,
    serve_objectives,
)
from apex_tpu.serve import (
    DisaggRouter,
    Request,
    RouterConfig,
    ServeConfig,
    ServeEngine,
)

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "tools"))


# ---------------------------------------------------------------------------
# objective declaration
# ---------------------------------------------------------------------------

def test_objective_validation():
    with pytest.raises(ValueError, match="kind"):
        SLObjective(name="x", kind="median", threshold=1.0, metric="m")
    with pytest.raises(ValueError, match="op"):
        SLObjective(name="x", kind="gauge", threshold=1.0, metric="m",
                    op="eq")
    with pytest.raises(ValueError, match="q="):
        SLObjective(name="x", kind="quantile", threshold=1.0,
                    metric="m", q=1.0)
    with pytest.raises(ValueError, match="ratio_num"):
        SLObjective(name="x", kind="ratio", threshold=1.0)
    with pytest.raises(ValueError, match="metric"):
        SLObjective(name="x", kind="quantile", threshold=1.0)
    with pytest.raises(ValueError, match="objectives"):
        SLOEvaluator(Registry(), [])
    objs = serve_objectives(min_acceptance=0.5)
    assert {o.name for o in objs} == \
        {"decode_p99", "block_util", "spec_acceptance"}
    # window=0 (since-start) is quantile/ratio-only — a gauge has no
    # delta semantics to anchor it
    with pytest.raises(ValueError, match="since-start"):
        SLObjective(name="x", kind="gauge", metric="m", threshold=1.0,
                    window=0)


def test_since_start_window_pins_first_boundary():
    """window=0: the first boundary's snapshot is the permanent base
    (run-scoped objectives — the serve_scenarios cell verdicts), and
    the evaluator holds ONE extra snapshot instead of growing a ring."""
    reg = Registry()
    hist = reg.histogram("lat")
    ev = SLOEvaluator(reg, [SLObjective(
        name="p50", kind="quantile", metric="lat", q=0.5,
        threshold=0.0128, window=0, min_count=2)])
    assert ev.evaluate()["p50"]["status"] == STATUS_INSUFFICIENT
    for v in (0.001, 0.002, 0.003):
        hist.observe(v)
        ev.evaluate()
    rec = ev.last["p50"]
    # every observation since the FIRST boundary is in the window
    assert rec["observations"] == 3 and rec["status"] == STATUS_MET
    # the ring stays bounded at maxlen 1 regardless of boundaries
    assert ev._snaps.maxlen == 1


# ---------------------------------------------------------------------------
# scripted registry states
# ---------------------------------------------------------------------------

def test_quantile_objective_met_violated_insufficient():
    reg = Registry()
    hist = reg.histogram("lat")
    # 0.0128 is a LATENCY_BUCKETS bound — the snap is the identity
    obj = SLObjective(name="p99", kind="quantile", metric="lat",
                      q=0.9, threshold=0.0128, window=4, min_count=5)
    ev = SLOEvaluator(reg, [obj])
    assert ev.evaluate()["p99"]["status"] == STATUS_INSUFFICIENT
    for v in (0.001, 0.002):                 # 2 obs < min_count 5
        hist.observe(v)
    assert ev.evaluate()["p99"]["status"] == STATUS_INSUFFICIENT
    for v in (0.003, 0.004, 0.005, 0.001):
        hist.observe(v)
    rec = ev.evaluate()["p99"]
    assert rec["status"] == STATUS_MET and rec["burn_rate"] == 0.0
    for _ in range(4):                       # tail blowout
        hist.observe(0.05)
    rec = ev.evaluate()["p99"]
    assert rec["status"] == STATUS_VIOLATED and rec["burn_rate"] > 1.0
    assert not ev.violated() or True         # violated() reads .last
    assert ev.violated() is True
    assert ev.summary()["ok"] is False


def test_quantile_burn_rate_matches_numpy_reference():
    """burn = mean(window_obs > T) / (1 − q) — exactly, when T is a
    bucket bound (the evaluator snaps T up to one and records it)."""
    reg = Registry()
    hist = reg.histogram("lat")
    thresh, q, window = 0.0128, 0.9, 4
    obj = SLObjective(name="p99", kind="quantile", metric="lat", q=q,
                      threshold=thresh, window=window, min_count=5)
    ev = SLOEvaluator(reg, [obj])
    ev.evaluate()
    rng = np.random.RandomState(0)
    boundaries = []
    for b in range(6):
        obs = rng.uniform(0.001, 0.01, 20)
        if b >= 3:
            obs = np.concatenate([obs, np.full(8, 0.05)])
        for v in obs:
            hist.observe(float(v))
        boundaries.append(obs)
        rec = ev.evaluate()["p99"]
        win = np.concatenate(boundaries[max(0, len(boundaries)
                                            - window):])
        ref = float(np.mean(win > thresh)) / (1.0 - q)
        assert rec["burn_rate"] == pytest.approx(ref, abs=1e-4), b
        assert rec["observations"] == win.size
        assert rec["status"] == (STATUS_VIOLATED if ref > 1.0
                                 else STATUS_MET)


def test_quantile_threshold_snaps_down_never_fail_open():
    """A threshold between bucket bounds snaps DOWN: a value sitting
    over the declared threshold but under the next bound must still
    violate — the snap can only judge TIGHTER, never looser."""
    from apex_tpu.obs.metrics import LATENCY_BUCKETS
    reg = Registry()
    hist = reg.histogram("lat")
    obj = SLObjective(name="p99", kind="quantile", metric="lat",
                      q=0.99, threshold=0.25, window=2, min_count=1)
    ev = SLOEvaluator(reg, [obj])
    ev.evaluate()
    for _ in range(50):
        hist.observe(0.30)          # 63% over budget, under the next
    rec = ev.evaluate()["p99"]      # power-of-2 bound (0.4096)
    assert rec["snapped_threshold"] == pytest.approx(0.2048)
    assert rec["snapped_threshold"] in LATENCY_BUCKETS
    assert rec["status"] == STATUS_VIOLATED
    # past the whole ladder: judged via the +inf bucket
    reg2 = Registry()
    hist2 = reg2.histogram("lat", buckets=(0.1, 0.2))
    ev2 = SLOEvaluator(reg2, [SLObjective(
        name="p", kind="quantile", metric="lat", q=0.5,
        threshold=99.0, window=2, min_count=1)])
    ev2.evaluate()
    for v in (0.05, 0.15, 50.0, 60.0, 70.0):
        hist2.observe(v)
    rec = ev2.evaluate()["p"]
    assert rec["snapped_threshold"] == 0.2
    assert rec["status"] == STATUS_VIOLATED        # 3/5 > 50% budget
    # UNDER the whole ladder: nothing provably under the bar — every
    # observation counts as exceeding
    reg3 = Registry()
    hist3 = reg3.histogram("lat", buckets=(0.1, 0.2))
    ev3 = SLOEvaluator(reg3, [SLObjective(
        name="p", kind="quantile", metric="lat", q=0.5,
        threshold=0.01, window=2, min_count=1)])
    ev3.evaluate()
    hist3.observe(0.05)
    assert ev3.evaluate()["p"]["status"] == STATUS_VIOLATED


def test_gauge_and_ratio_objectives():
    reg = Registry()
    g = reg.gauge("util")
    acc, prop = reg.counter("acc"), reg.counter("prop")
    ev = SLOEvaluator(reg, [
        SLObjective(name="util", kind="gauge", metric="util", op="le",
                    threshold=0.9, window=4, min_count=1),
        SLObjective(name="rate", kind="ratio", ratio_num="acc",
                    ratio_den="prop", op="ge", threshold=0.5,
                    window=4, min_count=4),
    ])
    g.set(0.5)
    r = ev.evaluate()
    assert r["util"]["status"] == STATUS_MET
    assert r["util"]["burn_rate"] == pytest.approx(0.5 / 0.9, abs=1e-3)
    assert r["rate"]["status"] == STATUS_INSUFFICIENT   # no base yet
    acc.inc(3)
    prop.inc(10)
    r = ev.evaluate()
    assert r["rate"]["status"] == STATUS_VIOLATED       # 0.3 < 0.5
    assert r["rate"]["value"] == pytest.approx(0.3)
    acc.inc(17)
    prop.inc(10)
    r = ev.evaluate()                # window mean now covers 20/30
    assert r["rate"]["status"] == STATUS_MET
    # gauge windowed MEAN: a spike inside the window still judged
    g.set(3.0)
    r = ev.evaluate()
    assert r["util"]["value"] == pytest.approx(
        (0.5 + 0.5 + 0.5 + 3.0) / 4)
    assert r["util"]["status"] == STATUS_VIOLATED


# ---------------------------------------------------------------------------
# router de-eligibility
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    a = amp.initialize(opt_level="O2", verbosity=0)
    return cfg, a.model_params_from(params)


SCFG = ServeConfig(num_slots=2, block_size=4, num_blocks=17,
                   max_blocks_per_slot=8, prefill_chunk=4)


def test_router_routes_around_slo_violating_replica(tiny_model):
    """Scripted fleet: one replica forced over its p99 objective
    loses admission eligibility — every new request lands on the
    other replica — and recovers nothing is special-cased: the gauge
    export says which replica is de-ranked."""
    cfg, params = tiny_model
    slo = (SLObjective(name="decode_p99", kind="quantile",
                       metric="serve_decode_step_seconds", q=0.5,
                       threshold=1e-7,     # impossible bar: any real
                       window=8,           # step violates it
                       min_count=2),)
    router = DisaggRouter(
        params, cfg, SCFG,
        RouterConfig(n_decode_replicas=2, transfer="ship", slo=slo),
        registry=Registry())
    rng = np.random.RandomState(0)
    # warm ONLY replica 0: its histogram gets observations, and the
    # impossible objective flips it to violated
    router.submit(Request(uid="w0",
                          prompt=rng.randint(0, cfg.vocab_size, (5,)),
                          max_new_tokens=6))
    router.run()
    assert [ev.violated() for ev in router.slo_evals] == [True, False]
    assert [g.value for g in router._m_rep_slo] == [0.0, 1.0]
    # new admissions must route around the violating replica
    for i in range(2):
        router.submit(Request(
            uid=f"q{i}", prompt=rng.randint(0, cfg.vocab_size, (4,)),
            max_new_tokens=4))
    router.step()
    assert router.replicas[0].eng.sched.n_active() == 0
    assert router.replicas[1].eng.sched.n_active() == 2
    summary = router.slo_summary()
    assert summary["replica0"]["ok"] is False
    assert summary["replica1"]["ok"] is True
    outs = router.run()
    assert set(outs) == {"w0", "q0", "q1"}   # fleet still drains


# ---------------------------------------------------------------------------
# zero new host syncs on the instrumented lane
# ---------------------------------------------------------------------------

def test_slo_instrumented_engine_one_trace_and_syncs_clean(tiny_model):
    """An engine driven with per-boundary SLO evaluation keeps ONE
    compiled decode step (no retrace), and the graph-lint syncs pass
    is clean on the serve lane — the evaluator reads resolved host
    state only, the compiled program is untouched."""
    cfg, params = tiny_model
    reg = Registry()
    eng = ServeEngine(params, cfg, SCFG, registry=reg)
    ev = SLOEvaluator(reg, serve_objectives(decode_p99_s=10.0,
                                            min_count=2))
    rng = np.random.RandomState(3)
    for i in range(3):
        eng.submit(Request(uid=f"s{i}",
                           prompt=rng.randint(0, cfg.vocab_size,
                                              (4 + 3 * i,)),
                           max_new_tokens=5))
    guard = 0
    while not eng.sched.idle():
        eng.step()
        ev.evaluate()               # the boundary the registry ticks
        guard += 1
        assert guard < 1000
    assert max(eng.trace_counts.values()) == 1
    rec = ev.last["decode_p99"]
    assert rec["status"] == STATUS_MET and rec["observations"] > 0
    # the machine check: syncs pass clean on the compiled serve step
    import graph_lint
    rep = graph_lint.lint_serve("serve_step", passes=("syncs",))
    syncs = rep.by_pass("syncs")
    assert sum(1 for f in syncs if f.op == "host-callback") == 0
    assert sum(1 for f in syncs if f.op == "static-scalar") == 0
    assert len(rep.errors) == 0
