"""Continuous profiler + drift sentinel (apex_tpu.obs.contprof),
the shared step classifiers (apex_tpu.obs.stepclass), the
PROFILE_DRIFT schema's contradiction rejection, and the HTTP
exposition endpoint.

The sentinel tests are scripted (pure windows through the ONE rule in
apex_tpu/analysis/profile_drift.py); the capture tests run a real
jax.profiler window around a live tiny serve engine — the XLA:CPU
``tf_XLA*`` xplane fallback is what makes that possible in tier-1.
"""

import json
import sys
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from apex_tpu import amp  # noqa: E402
from apex_tpu.analysis import decode_profile  # noqa: E402
from apex_tpu.analysis import profile_drift as pd  # noqa: E402
from apex_tpu.models.gpt import GPTModel, gpt_tiny  # noqa: E402
from apex_tpu.obs import contprof, stepclass  # noqa: E402
from apex_tpu.obs import metrics as obs_metrics  # noqa: E402
from apex_tpu.obs.exposition import MetricsServer  # noqa: E402
from apex_tpu.obs.flight import FlightRecorder  # noqa: E402
from apex_tpu.resilience import incidents as incidents_lib  # noqa: E402
from apex_tpu.serve import Request, ServeConfig, ServeEngine  # noqa: E402

BAND = 0.05
BASE = {"fractions": {"param_read": 0.1, "kv_read": 0.6,
                      "kv_write": 0.05, "attention": 0.02,
                      "sampling": 0.15, "host_sync": 0.0,
                      "other": 0.08},
        "step_wall_s": 0.003, "source": "test"}


def _frac(**over):
    f = dict(BASE["fractions"])
    for k, v in over.items():
        f[k] = v
    return f


def _windows(specs):
    """specs: [(fractions, wall), ...] -> schema-shaped windows with
    re-derivable out_of_band lists."""
    return [{"index": i, "fractions": fr, "step_wall_s": w,
             "out_of_band": pd.out_of_band(fr, w, BASE, BAND)}
            for i, (fr, w) in enumerate(specs)]


# ---------------------------------------------------------------------------
# vocabulary pins
# ---------------------------------------------------------------------------

def test_bucket_vocabularies_pinned_equal():
    """The duplicated tuples (stdlib schema modules are loaded
    standalone by gate_hygiene) must never drift apart."""
    assert stepclass.DECODE_BUCKETS == decode_profile.BUCKETS
    assert stepclass.DECODE_BUCKETS == pd.DECODE_BUCKETS
    assert stepclass.TRAIN_BUCKETS == pd.TRAIN_BUCKETS
    assert pd.KINDS["serve-decode"] == pd.DECODE_BUCKETS
    assert pd.KINDS["train"] == pd.TRAIN_BUCKETS


# ---------------------------------------------------------------------------
# the sentinel rule (scripted — no capture)
# ---------------------------------------------------------------------------

def test_sentinel_catches_seeded_drift_in_exactly_k_windows():
    sent = contprof.DriftSentinel(baseline=dict(BASE), band=BAND, k=3)
    drifted = _frac(kv_read=0.75, sampling=0.0)
    specs = [(_frac(), 0.003)] * 2 + [(drifted, 0.003)] * 4
    for w in _windows(specs):
        sent.observe(w)
    assert len(sent.drifts) == 1        # latched: no re-confirmation
    d = sent.drifts[0]
    # first out-of-band window is index 2; k=3 -> confirmed at 4
    assert d["window"] == 4
    assert d["bucket"] == "kv_read"
    assert d["windows_out"] == 3


def test_sentinel_quiet_on_in_band_noise_and_isolated_spikes():
    sent = contprof.DriftSentinel(baseline=dict(BASE), band=BAND, k=2)
    spike = _frac(kv_read=0.7, sampling=0.05)
    specs = [(_frac(kv_read=0.62, sampling=0.13), 0.0031),
             (spike, 0.003),            # isolated spike: no confirm
             (_frac(kv_read=0.58, other=0.1), 0.0029),
             (spike, 0.003),            # another isolated spike
             (_frac(), 0.003)]
    for w in _windows(specs):
        sent.observe(w)
    assert sent.drifts == []
    assert not sent.drifting


def test_sentinel_wall_regression_and_recovery_resets_gauge():
    reg = obs_metrics.Registry()
    sent = contprof.DriftSentinel(baseline=dict(BASE), band=BAND, k=2,
                                  registry=reg)
    slow = (_frac(), 0.004)             # +33% wall, fractions in band
    for w in _windows([slow, slow]):
        sent.observe(w)
    assert len(sent.drifts) == 1
    assert sent.drifts[0]["bucket"] == "step_wall"
    assert reg.gauge("serve_profile_drift").value == 1.0
    assert sent.drifting
    sent.observe(_windows([(_frac(), 0.003)])[0])   # recovery
    assert reg.gauge("serve_profile_drift").value == 0.0
    assert not sent.drifting


def test_sentinel_matches_schema_replay():
    """The online machine and the validator's replay are the same
    rule: scripted windows produce identical verdicts."""
    sent = contprof.DriftSentinel(baseline=dict(BASE), band=BAND, k=2)
    rng = np.random.RandomState(3)
    specs = []
    for i in range(12):
        kv = 0.6 + (0.12 if 4 <= i < 8 else rng.uniform(-0.03, 0.03))
        specs.append((_frac(kv_read=round(kv, 4)),
                      round(0.003 * rng.uniform(0.98, 1.02), 6)))
    windows = _windows(specs)
    for w in windows:
        sent.observe(w)
    derived = pd.replay_sentinel(windows, BASE, BAND, 2)
    assert [(d["window"], d["bucket"]) for d in sent.drifts] == \
        [(d["window"], d["bucket"]) for d in derived]


def test_sentinel_first_window_seeds_baseline():
    sent = contprof.DriftSentinel(baseline=None, band=BAND, k=2)
    w0 = {"index": 0, "fractions": _frac(), "step_wall_s": 0.003}
    sent.observe(w0)
    assert sent.baseline["source"] == "first-window"
    assert w0["out_of_band"] == []
    w1 = {"index": 1, "fractions": _frac(kv_read=0.8, sampling=0.0),
          "step_wall_s": 0.003}
    sent.observe(w1)
    assert [e["metric"] for e in w1["out_of_band"]] == \
        ["kv_read", "sampling"]


def test_sentinel_rejects_k1_and_bad_band():
    with pytest.raises(ValueError, match="k="):
        contprof.DriftSentinel(k=1)
    with pytest.raises(ValueError, match="band"):
        contprof.DriftSentinel(k=2, band=1.5)


def test_confirmed_drift_writes_incident_and_flight_tail(tmp_path):
    """The incident is schema-valid, names the bucket, and embeds the
    flight tail whose last events include the drift note."""
    fr = FlightRecorder(capacity=32)
    path = str(tmp_path / "drift_incident.json")
    sent = contprof.DriftSentinel(baseline=dict(BASE), band=BAND, k=2,
                                  flight=fr, incident_path=path)
    drifted = _frac(kv_read=0.8, sampling=0.0)
    windows = _windows([(drifted, 0.003)] * 2)
    windows[1]["top_ops"] = [
        {"op": "fusion.7", "ps": 999, "bucket": "kv_read"},
        {"op": "broadcast.1", "ps": 10, "bucket": "other"}]
    for w in windows:
        sent.observe(w)
    assert len(sent.incidents) == 1
    rec = sent.incidents[0]
    assert rec["status"] == "profile-drift"
    assert "kv_read" in rec["summary"]
    # top offending ops filtered to the drifting bucket
    assert rec["drift"]["top_ops"] == [
        {"op": "fusion.7", "ps": 999, "bucket": "kv_read"}]
    # the flight tail contains the drift event
    kinds = [e["kind"] for e in rec["flight"]["events"]]
    assert "profile_drift" in kinds
    # the written artifact validates against the incident schema
    assert Path(path).exists()
    assert incidents_lib.validate_incident_file(path) == []


def test_drift_objective_is_a_valid_slo():
    obj = contprof.drift_objective()
    assert obj.kind == "gauge"
    assert obj.metric == "serve_profile_drift"


# ---------------------------------------------------------------------------
# the train classifier (fixture-pinned)
# ---------------------------------------------------------------------------

_TRAIN_HLO = """\
HloModule jit_step

%fused_bwd (p: f32[8,8]) -> f32[8,8] {
  %m = f32[8,8] multiply(f32[8,8] %p, f32[8,8] %p), metadata={op_name="jit(step)/jit(main)/transpose(jvp(MLP))/mul"}
  ROOT %r = f32[8,8] add(f32[8,8] %m, f32[8,8] %m), metadata={op_name="jit(step)/jit(main)/jvp(MLP)/add"}
}

ENTRY %main (a: f32[8,8]) -> f32[8,8] {
  %fwd.1 = f32[8,8] dot(f32[8,8] %a, f32[8,8] %a), metadata={op_name="jit(step)/jit(main)/jvp(MLP)/dot_general"}
  %bwd.1 = f32[8,8] dot(f32[8,8] %a, f32[8,8] %a), metadata={op_name="jit(step)/jit(main)/transpose(jvp(MLP))/dot_general"}
  %mixed.1 = f32[8,8] fusion(f32[8,8] %a), kind=kLoop, calls=%fused_bwd
  %opt.1 = f32[8,8] add(f32[8,8] %a, f32[8,8] %a), metadata={op_name="jit(step)/jit(main)/cond/branch_1_fun/add"}
  %unscale.1 = f32[8,8] multiply(f32[8,8] %a, f32[8,8] %a), metadata={op_name="jit(step)/jit(main)/amp_unscale/mul"}
  %grad-ar = f32[8,8] all-reduce(f32[8,8] %bwd.1), to_apply=%fused_bwd, metadata={op_name="jit(step)/jit(main)/transpose(jvp(MLP))/psum"}
  %plain.1 = f32[8,8] add(f32[8,8] %a, f32[8,8] %a), metadata={op_name="jit(step)/jit(main)/convert_element_type"}
  ROOT %out = f32[8,8] add(f32[8,8] %opt.1, f32[8,8] %plain.1)
}
"""


def test_train_classifier_fixture():
    """The pinned vocabulary contract: jvp -> fwd, transpose(jvp ->
    bwd (winning over fwd inside a mixed fusion), cond/amp_unscale ->
    optimizer, collective opcode -> collectives (winning over its bwd
    scope), unscoped -> other, host_gap never classified."""
    clf = stepclass.TrainStepClassifier(_TRAIN_HLO)
    assert clf("fwd.1") == "fwd"
    assert clf("bwd.1") == "bwd"
    assert clf("mixed.1") == "bwd"          # precedence is the pin
    assert clf("opt.1") == "optimizer"
    assert clf("unscale.1") == "optimizer"
    assert clf("grad-ar") == "collectives"
    assert clf("plain.1") is None           # -> other
    assert "host_gap" not in set(clf.buckets.values())
    assert {"fwd.1", "bwd.1", "mixed.1", "opt.1"} <= clf.step_ops()


def test_train_classifier_on_real_compiled_step():
    """The real amp mlp train step classifies non-trivially: forward,
    backward, AND optimizer ops all present (the graph_lint lowering
    profile_step's --train-buckets lane uses)."""
    sys.path.insert(0, str(REPO / "tools"))
    import graph_lint
    step, args, _ = graph_lint.build_train_step("mlp", opt_level="O2")
    state, *batch = args
    txt = step.lower(state, *batch).compile().as_text()
    clf = stepclass.TrainStepClassifier(txt)
    got = set(clf.buckets.values())
    assert {"fwd", "bwd", "optimizer"} <= got
    assert "host_gap" not in got


# ---------------------------------------------------------------------------
# live capture: one profiled serve session (module-scoped — compiles
# one tiny engine, captures two real windows)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def profiled_session():
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(opt_level="O2",
                            verbosity=0).model_params_from(params)
    scfg = ServeConfig(num_slots=2, block_size=16, num_blocks=17,
                       max_blocks_per_slot=8, prefill_chunk=16)
    reg = obs_metrics.Registry()
    eng = ServeEngine(params, cfg, scfg, registry=reg)
    sent = contprof.DriftSentinel(band=0.25, k=2, registry=reg)
    pcfg = contprof.ContProfConfig(capture_every=5, capture_steps=2,
                                   warmup_steps=2, max_windows=2,
                                   max_overhead_pct=None)
    prof = contprof.serve_profiler(eng, config=pcfg, sentinel=sent)
    rng = np.random.RandomState(0)
    for i in range(2):
        eng.submit(Request(uid=f"s{i}",
                           prompt=rng.randint(0, cfg.vocab_size, (8,)),
                           max_new_tokens=20))
    steps = 0
    while not eng.sched.idle() and steps < 40:
        eng.step()
        steps += 1
    prof.abort_window()
    return eng, prof, sent, reg, steps


def test_capture_windows_parse_and_classify(profiled_session):
    _eng, prof, _sent, _reg, _steps = profiled_session
    assert len(prof.windows) == 2
    for w in prof.windows:
        assert w["source"] in ("xplane-host", "xplane-device",
                               "trace-json")
        assert w["total_ps"] > 0
        # the live executable's instruction names resolve against the
        # separately-lowered classifier: real attribution, not all-
        # "other"
        assert w["matched_frac"] > 0.3
        assert w["fractions"]["kv_read"] > 0.0
        assert abs(sum(w["fractions"].values()) - 1.0) < 0.02
        assert w["top_ops"]


def test_profiled_steps_excluded_from_latency_histogram(
        profiled_session):
    """The gate-exclusion contract: every step inside a capture
    window lands in serve_profiled_step_seconds, NOT in the
    histogram bench/SLO judge — and the two partitions cover every
    decode step exactly."""
    _eng, prof, _sent, reg, steps = profiled_session
    gated = reg.histogram("serve_decode_step_seconds").count
    profiled = reg.histogram("serve_profiled_step_seconds").count
    captured = sum(w["steps"] for w in prof.windows) \
        + sum(w["steps"] for w in prof.discarded)
    assert profiled == captured
    assert profiled >= 4                   # 2 windows x 2 steps
    assert gated + profiled == steps
    assert reg.counter("serve_profile_windows_total").value == \
        len(prof.windows)


def test_sentinel_saw_session_windows(profiled_session):
    _eng, prof, sent, _reg, _steps = profiled_session
    assert sent.baseline is not None
    assert sent.baseline["source"] == "first-window"
    replay = pd.replay_sentinel(prof.windows, sent.baseline,
                                sent.band, sent.k)
    assert [(d["window"], d["bucket"]) for d in sent.drifts] == \
        [(d["window"], d["bucket"]) for d in replay]


def test_serve_classifier_buckets_real_program(profiled_session):
    eng, _prof, _sent, _reg, _steps = profiled_session
    clf = contprof.serve_classifier_builder(eng)()
    got = set(clf.buckets.values())
    assert {"kv_read", "kv_write", "param_read", "sampling"} <= got


def test_capture_lock_skips_colliding_window():
    """A profiler whose window comes due while another holds the
    process-global tracer SKIPS (counted), never queues."""
    prof = contprof.ContinuousProfiler(
        config=contprof.ContProfConfig(capture_every=3,
                                       capture_steps=2,
                                       warmup_steps=0))
    assert contprof._capture_lock.acquire(blocking=False)
    try:
        opened = prof.step_begin()
    finally:
        contprof._capture_lock.release()
    assert opened is False
    assert prof.skipped_windows == 1
    assert not prof.in_window


def test_suppress_aborts_window_and_restarts_cadence():
    prof = contprof.ContinuousProfiler(
        config=contprof.ContProfConfig(capture_every=4,
                                       capture_steps=2,
                                       warmup_steps=1))
    assert prof.step_begin() is False      # warmup
    assert prof.step_begin() is True       # window opens (real trace)
    assert prof.in_window
    prof.suppress()
    assert not prof.in_window
    # the lock is released and a full interval must elapse again
    assert contprof._capture_lock.acquire(blocking=False)
    contprof._capture_lock.release()
    assert prof.step_begin() is False      # warmup restarted


def test_throttle_reanchors_next_window_a_full_interval_out():
    """After the auto-throttle widens the interval, the next window
    must start the FULL new interval after the window that proved it
    was needed — never at the next multiple of an absolute cadence
    grid (which could come almost immediately and run ~2x over the
    budget the throttle just enforced)."""
    prof = contprof.ContinuousProfiler(
        config=contprof.ContProfConfig(
            capture_every=20, capture_steps=2, warmup_steps=0,
            max_overhead_pct=1.0))
    # a window that opened at step 20 and cost 0.36 s against a 1 s
    # step wall needs a 36-step interval
    prof._step = 21
    prof._win_start_step = 20
    prof._next_start = 40                   # the pre-throttle anchor
    prof._throttle({"capture_s": 0.36, "parse_s": 0.0,
                    "sentinel_s": 0.0, "step_wall_s": 1.0})
    assert prof.effective_every == 36
    assert prof._next_start == 20 + 36     # not 36 (the old grid)


def test_close_path_failure_degrades_to_discarded_window():
    """A failing capture stop/parse must DEGRADE (discarded window,
    lock released), never propagate into the loop the profiler
    watches — and later steps must go back to the gated histogram."""
    class BrokenParse(contprof.ContinuousProfiler):
        def _parse_window(self):
            raise OSError("capture dir vanished")

    prof = BrokenParse(
        config=contprof.ContProfConfig(capture_every=4,
                                       capture_steps=1,
                                       warmup_steps=0))
    assert prof.step_begin() is True     # real trace opens
    w = prof.step_end(0.001)             # parse raises inside
    assert w is not None and "discarded" in w
    assert "parse failed" in w["discarded"]
    assert len(prof.discarded) == 1 and not prof.windows
    assert not prof.in_window
    assert contprof._capture_lock.acquire(blocking=False)
    contprof._capture_lock.release()
    assert prof.step_begin() is False    # back to the gated path


def test_obs_schema_rejects_zero_step_wall_contprof():
    """A contprof lane with step_wall_ms = 0 must be invalid — an inf
    'derived' overhead would make the re-derivation check vacuous."""
    from apex_tpu.analysis import obs as obs_schema
    doc = json.loads((REPO / "OBS_r03.json").read_text())
    assert obs_schema.validate_obs(doc) == []
    doc["contprof"]["step_wall_ms"] = 0
    assert any("step_wall_ms must be > 0" in p
               for p in obs_schema.validate_obs(doc))


def test_classifier_builder_drops_closure_and_captures_avals():
    """The train builder captures only ShapeDtypeStruct avals (never
    the live state/batch arrays — gigabytes on a real model), and the
    profiler drops the builder closure after its one build."""
    @jax.jit
    def stepf(s, x):
        return s * 2.0, {"loss": (s * x).sum()}

    state = jnp.ones((4,))
    batch = (jnp.arange(4, dtype=jnp.float32),)
    builder = contprof.train_classifier_builder(stepf, state, batch)
    cells = jax.tree_util.tree_leaves(
        [c.cell_contents for c in builder.__closure__])
    arrays = [c for c in cells if isinstance(c, jax.Array)]
    assert not arrays, f"builder closure pins live arrays: {arrays}"
    prof = contprof.ContinuousProfiler(
        buckets=contprof.TRAIN_BUCKETS, classifier_builder=builder)
    assert prof._classifier() is not None
    assert prof._builder is None            # closure released
    # "has a source" must survive the release, so run_resilient never
    # supplies (and pins) a second closure
    assert prof.has_classifier_builder


# ---------------------------------------------------------------------------
# schema: contradiction classes
# ---------------------------------------------------------------------------

def _valid_doc():
    clean = _windows([(_frac(kv_read=0.61), 0.003),
                      (_frac(kv_read=0.59), 0.0031)])
    drifted = _frac(kv_read=0.8, sampling=0.0)
    seeded_w = _windows([(_frac(), 0.003),
                         (drifted, 0.003), (drifted, 0.003)])
    return {
        "round": 1, "platform": "cpu", "kind": "serve-decode",
        "config": {}, "band": {"value": BAND, "source": "test"},
        "k": 2,
        "sessions": {
            "clean": {"baseline": dict(BASE), "windows": clean,
                      "drifts": [], "quiet": True},
            "seeded": {"baseline": dict(BASE), "windows": seeded_w,
                       "seed": {"bucket": "kv_read", "factor": 2.0,
                                "from_window": 1},
                       "drifts": pd.replay_sentinel(
                           seeded_w, BASE, BAND, 2),
                       "quiet": False},
        },
        "gate": {"clean_quiet": True, "seeded_caught": True,
                 "ok": True},
        "note": "test doc",
    }


def test_schema_valid_doc_passes():
    doc = _valid_doc()
    assert pd.validate_profile_drift(doc) == []
    drifts = doc["sessions"]["seeded"]["drifts"]
    assert [(d["window"], d["bucket"]) for d in drifts] == \
        [(2, "kv_read")]


def test_schema_rejects_quiet_verdict_over_out_of_band_run():
    doc = _valid_doc()
    doc["sessions"]["seeded"]["drifts"] = []
    doc["sessions"]["seeded"]["quiet"] = True
    doc["gate"]["seeded_caught"] = False
    doc["gate"]["ok"] = False
    problems = pd.validate_profile_drift(doc)
    assert any("CONTRADICTORY" in p and "replaying" in p
               for p in problems)


def test_schema_rejects_invented_drift():
    doc = _valid_doc()
    doc["sessions"]["clean"]["drifts"] = [
        {"window": 1, "bucket": "kv_read", "windows_out": 2}]
    doc["sessions"]["clean"]["quiet"] = False
    problems = pd.validate_profile_drift(doc)
    assert any("CONTRADICTORY" in p and "clean" in p
               for p in problems)


def test_schema_rejects_lying_out_of_band_list():
    """A window whose recorded excursion list contradicts its own
    recorded fractions is invalid — in BOTH directions."""
    doc = _valid_doc()
    doc["sessions"]["seeded"]["windows"][1]["out_of_band"] = []
    problems = pd.validate_profile_drift(doc)
    assert any("derive" in p and "out_of_band" in p
               for p in problems)


def test_schema_rejects_fabricated_excursion_numbers():
    """An excursion naming the RIGHT metric but carrying invented
    value/baseline/delta numbers (a dramatized or minimized drift) is
    the same fabrication class as a lying metric list — the numbers
    must re-derive from the recorded fractions too."""
    doc = _valid_doc()
    exc = doc["sessions"]["seeded"]["windows"][1]["out_of_band"]
    assert exc, "fixture window must be out of band"
    exc[0]["delta"] = round(exc[0]["delta"] * 10, 4)   # dramatized
    problems = pd.validate_profile_drift(doc)
    assert any("re-deriving from the recorded fractions" in p
               for p in problems)


def test_schema_rejects_gate_contradiction():
    doc = _valid_doc()
    doc["gate"]["ok"] = False
    problems = pd.validate_profile_drift(doc)
    assert any("gate.ok" in p for p in problems)


def test_schema_rejects_drift_not_naming_seeded_bucket():
    doc = _valid_doc()
    doc["sessions"]["seeded"]["seed"]["bucket"] = "attention"
    problems = pd.validate_profile_drift(doc)
    assert any("name the bucket" in p for p in problems)


def test_schema_rejects_k1_and_unknown_bucket():
    doc = _valid_doc()
    doc["k"] = 1
    assert any("k must be >= 2" in p
               for p in pd.validate_profile_drift(doc))
    doc = _valid_doc()
    doc["sessions"]["clean"]["windows"][0]["fractions"]["flops"] = 0.1
    assert any("unknown buckets" in p
               for p in pd.validate_profile_drift(doc))


def test_committed_profile_drift_artifact():
    """The committed PROFILE_DRIFT_r01.json is the schema's reference
    instance: valid, both lanes present, gate green."""
    arts = sorted(REPO.glob("PROFILE_DRIFT_r*.json"))
    assert arts, "PROFILE_DRIFT_r01.json must be committed"
    doc = json.loads(arts[-1].read_text())
    assert pd.validate_profile_drift(doc) == []
    assert doc["gate"]["ok"] is True
    assert doc["sessions"]["clean"]["quiet"] is True
    seeded = doc["sessions"]["seeded"]
    assert seeded["drifts"][0]["bucket"] == seeded["seed"]["bucket"]


def test_committed_obs_r03_contprof_lane():
    """The committed OBS round carries the contprof overhead lane
    under budget and the contprof-instrumented serve lane in its
    clean syncs table."""
    arts = sorted(REPO.glob("OBS_r*.json"))
    doc = json.loads(arts[-1].read_text())
    cp = doc.get("contprof")
    assert cp is not None, "newest OBS round must carry the lane"
    assert cp["overhead_pct"] <= 1.0
    assert "serve_step_contprof" in doc["syncs"]["lanes"]
    assert doc["syncs"]["clean"] is True


# ---------------------------------------------------------------------------
# timeline adapter
# ---------------------------------------------------------------------------

def test_timeline_adapter_ingests_profile_drift():
    from apex_tpu.analysis import timeline
    assert "PROFILE_DRIFT" in timeline.ADAPTERS
    rows = timeline.ADAPTERS["PROFILE_DRIFT"](_valid_doc(), {})
    by = {(c, m): v for c, m, v in rows}
    assert by[("clean", "drifts")] == 0.0
    assert by[("seeded", "drifts")] == 1.0
    assert by[("seeded", "windows")] == 3.0
    assert ("seeded:last_window", "kv_read") in by


# ---------------------------------------------------------------------------
# exposition endpoint
# ---------------------------------------------------------------------------

def test_metrics_server_smoke():
    reg = obs_metrics.Registry()
    reg.counter("serve_tokens_total", "t").inc(5)
    reg.histogram("serve_decode_step_seconds", "h").observe(0.002)
    rep = obs_metrics.Registry()
    rep.counter("serve_tokens_total", "t").inc(7)
    rep.gauge("serve_block_utilization", "u").set(0.5)
    srv = MetricsServer(registry=reg,
                        fleet_registries={"replica0": reg,
                                          "replica1": rep})
    host, port = srv.start()
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=5) as r:
                return r.read().decode()
        body = get("/metrics")
        assert "# TYPE serve_tokens_total counter" in body
        assert "serve_tokens_total 5" in body
        assert "serve_decode_step_seconds_bucket" in body
        fleet = get("/fleet")
        assert "serve_tokens_total 12" in fleet   # counters SUM
        assert "# gauge-table" in fleet
        assert "replica1" in fleet
        assert get("/healthz").strip() == "ok"
        with pytest.raises(urllib.error.HTTPError):
            get("/nope")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# router wiring (no captures: cadence far beyond the stream)
# ---------------------------------------------------------------------------

def test_router_contprof_wiring_and_drift_deranking():
    from apex_tpu.serve import DisaggRouter, RouterConfig
    cfg = gpt_tiny()
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    params = amp.initialize(opt_level="O2",
                            verbosity=0).model_params_from(params)
    scfg = ServeConfig(num_slots=2, block_size=4, num_blocks=9,
                       max_blocks_per_slot=4, prefill_chunk=4)
    rcfg = RouterConfig(
        n_decode_replicas=2, transfer="recompute",
        contprof=contprof.ContProfConfig(capture_every=10_000,
                                         capture_steps=2))
    router = DisaggRouter(params, cfg, scfg, rcfg,
                          registry=obs_metrics.Registry())
    assert len(router.profilers) == 2
    # staggered phases: fleet windows never collide on the
    # process-global tracer
    phases = [p.config.phase for p in router.profilers]
    assert len(set(phases)) == 2
    # each replica's own registry carries the sentinel gauge
    for rep in router.replicas:
        assert "serve_profile_drift" in rep.eng.metrics._instruments
    # a confirmed-unrecovered drift DE-RANKS the replica: admission
    # prefers the clean one even when the drifted one is emptier
    router.sentinels[0]._active = True
    req = Request(uid="r", prompt=np.zeros(4, np.int32),
                  max_new_tokens=4)
    pick = router._pick_replica(req)
    assert pick is router.replicas[1]
    # ...but a fleet whose every replica drifted still serves
    router.sentinels[1]._active = True
    assert router._pick_replica(req) is not None
    # killing a replica mid-window must abort ITS open capture —
    # a dead replica steps no more, so a held capture lock would
    # silently stop fleet-wide profiling for the rest of the run
    p0 = router.profilers[0]
    p0._next_start = 2
    assert p0.step_begin() is False     # warmup
    assert p0.step_begin() is True      # real trace opens
    assert p0.in_window
    router.kill_replica(0)
    assert not p0.in_window
    assert contprof._capture_lock.acquire(blocking=False)
    contprof._capture_lock.release()


# ---------------------------------------------------------------------------
# run_resilient integration (train vocabulary, real capture)
# ---------------------------------------------------------------------------

def test_run_resilient_with_train_profiler():
    sys.path.insert(0, str(REPO / "tools"))
    import chaos_run

    from apex_tpu.resilience import run_resilient
    from apex_tpu.resilience.loop import ResilienceConfig
    _a, step_fn, state0, batch_fn = chaos_run.build_workload(
        0, features=(32, 32), batch=16, d_in=16)
    reg = obs_metrics.Registry()
    sent = contprof.DriftSentinel(band=0.5, k=2, name="train",
                                  registry=reg)
    prof = contprof.train_profiler(
        config=contprof.ContProfConfig(capture_every=4,
                                       capture_steps=2,
                                       warmup_steps=2, max_windows=1,
                                       max_overhead_pct=None),
        sentinel=sent, registry=reg)
    result = run_resilient(step_fn, state0, batch_fn, num_steps=10,
                           config=ResilienceConfig(
                               watchdog_timeout_s=120.0),
                           registry=reg, profiler=prof)
    assert result.steps_completed == 10
    assert len(prof.windows) == 1
    w = prof.windows[0]
    assert set(w["fractions"]) == set(stepclass.TRAIN_BUCKETS)
    named = sum(w["fractions"][b] for b in
                ("fwd", "bwd", "optimizer", "collectives"))
    assert named > 0.0                  # real attribution happened
    assert not prof.in_window           # nothing leaked
    assert contprof._capture_lock.acquire(blocking=False)
    contprof._capture_lock.release()
